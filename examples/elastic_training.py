"""Fault-tolerant async training: retries, elastic workers, watchdog.

The reference inherited Spark task retry — which silently replays a
partition against the live PS (SURVEY.md §5 "semantic hazard").  This
pipeline demonstrates the rebuilt fault story on the faithful host-PS
arm: a chaos hook stalls one worker (caught by the liveness watchdog)
and permanently breaks another — its first attempts consume the retry
budget (each retry re-pulls and re-runs, at-most-once per commit),
then it dies and is tolerated elastically while the survivors finish.

Run:  python examples/elastic_training.py
      python examples/elastic_training.py --workers 6 --kill-worker 5
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup, report


def main():
    parser = make_parser(__doc__, rows=2048, epochs=2, batch_size=16,
                         workers=4, window=2, learning_rate=5e-3)
    parser.add_argument("--kill-worker", type=int, default=3,
                        help="worker id to hard-kill mid-run")
    parser.add_argument("--stall-worker", type=int, default=1,
                        help="worker id to stall once (transient)")
    parser.add_argument("--compression", default=None,
                        metavar="CODEC",
                        help="compress commits on the wire: int8, "
                             "bfloat16, topk[:frac] (error-feedback "
                             "corrected)")
    args = parse_args_and_setup(parser)
    if args.checkpoint_dir or args.resume:
        raise SystemExit(
            "fidelity='host' (this demo's arm) cannot checkpoint "
            "racing threads; use an emulated-fidelity example")
    for name in ("kill_worker", "stall_worker"):
        if not 0 <= getattr(args, name) < args.workers:
            raise SystemExit(
                f"--{name.replace('_', '-')} {getattr(args, name)} "
                f"out of range for --workers {args.workers}")
    rounds = args.rows // (args.workers * args.batch_size) // args.window
    if rounds < 3:
        raise SystemExit(
            f"only {rounds} rounds/worker/epoch — need >= 3 for the "
            f"chaos schedule (raise --rows or lower --batch-size)")

    import time

    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import ADAG

    data = datasets.synthetic_classification(args.rows, (8,), 4,
                                             seed=args.seed)
    cfg = model_config("mlp", (8,), num_classes=4, hidden=(32,))

    chaos = {"stalled": False, "tripped": False}

    def injector(w, epoch, r):
        if (w == args.stall_worker and epoch == 0 and r == 1
                and not chaos["stalled"]):
            chaos["stalled"] = True
            print(f"[chaos] stalling worker {w} for 2s")
            time.sleep(2.0)
        if w == args.kill_worker and (epoch > 0 or r >= 2):
            # permanent: every attempt fails, so the retry budget
            # exhausts and the worker dies (tolerated elastically)
            if not chaos["tripped"]:
                chaos["tripped"] = True
                print(f"[chaos] hard-killing worker {w}")
            raise RuntimeError(f"injected hard failure on worker {w}")

    t = ADAG(cfg, fidelity="host", num_workers=args.workers,
             communication_window=args.window,
             batch_size=args.batch_size, num_epoch=args.epochs,
             learning_rate=args.learning_rate, worker_optimizer="adam",
             worker_retries=2, max_worker_failures=1,
             worker_timeout=0.5, fault_injector=injector,
             compression=args.compression,
             profile_dir=args.profile_dir)
    t.train(data)
    if args.compression:
        wire = t.history["commit_wire_bytes"][-1]
        raw = t.history["commit_raw_bytes"][-1]
        print(f"[wire] {wire/1e6:.2f} MB committed vs {raw/1e6:.2f} MB "
              f"raw ({wire/max(raw,1):.0%})")

    failures = t.history.get("worker_failures", [[]])[-1]
    retries = t.history.get("worker_round_retries", [[]])[-1]
    detected = t.history.get("detected_idle_workers", [[]])[-1]
    print(f"[elastic] worker failures tolerated: {failures}")
    print(f"[elastic] round retries (worker, epoch, round): {retries}")
    print(f"[elastic] watchdog detections: {detected} "
          "(the first entry may reflect JIT warmup, not chaos)")
    metrics = evaluate_model(t.model, t.trained_variables, data)
    report("elastic_training", t, metrics,
           failures=len(failures), retries=len(retries))


if __name__ == "__main__":
    main()

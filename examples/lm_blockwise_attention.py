"""Long-context LM on ONE chip with blockwise (flash-style) attention.

The sequence-parallel example (`lm_seq_parallel.py`) scales T across a
mesh; this one scales T on a single device: `TransformerLM(
blockwise_attn=True)` runs the ring path's q-chunked online-softmax
locally (no collectives), so neither the forward nor the backward ever
materializes the [T, T] attention matrix — measured +41% tokens/s over
dense attention at T=2048 on the v5e (PERF.md §13 addendum).  The
hand-written Pallas kernels (`flash_attn=True`, ops/attention)
run the same algorithm as one Mosaic kernel per pass and are faster
still (PERF.md §17).  Trains a tiny LM with all three attentions on
the same data and checks they reach the same loss (same function).

Run:  python examples/lm_blockwise_attention.py
      python examples/lm_blockwise_attention.py --seq-len 256
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup


def main():
    parser = make_parser(__doc__, rows=256, epochs=3, batch_size=16,
                         learning_rate=3e-3)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--vocab-size", type=int, default=64)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--q-chunk", type=int, default=32,
                        help="q block length (bounds the transient "
                             "logits to [q_chunk, T])")
    args = parse_args_and_setup(parser)
    from distkeras_tpu.profiling import profiler_trace

    with profiler_trace(args.profile_dir):
        _run(args)


def _run(args):
    import json

    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import SingleTrainer

    data = datasets.lm_synth(args.rows, seq_len=args.seq_len,
                             vocab_size=args.vocab_size, seed=0)

    def train(attn: str):
        cfg = model_config(
            "transformer_lm", (args.seq_len,), input_dtype="int32",
            vocab_size=args.vocab_size, num_layers=args.layers,
            d_model=args.d_model, num_heads=4,
            max_len=args.seq_len, dtype="float32",
            blockwise_attn=attn == "blockwise",
            flash_attn=attn == "flash",
            attn_q_chunk=args.q_chunk if attn == "blockwise" else None)
        t = SingleTrainer(cfg, loss="sparse_categorical_crossentropy",
                          worker_optimizer="adam",
                          learning_rate=args.learning_rate,
                          batch_size=args.batch_size,
                          num_epoch=args.epochs, seed=args.seed)
        t.train(data)
        return [round(x, 4) for x in t.history["epoch_loss"]]

    dense = train("dense")
    block = train("blockwise")
    flash = train("flash")
    print(json.dumps({
        "example": "lm_blockwise_attention",
        "seq_len": args.seq_len,
        "dense_epoch_loss": dense,
        "blockwise_epoch_loss": block,
        "flash_epoch_loss": flash,
    }))
    # same function, same data, same seed: curves agree to numerics
    assert np.allclose(dense, block, rtol=2e-2, atol=2e-2), (dense,
                                                             block)
    assert np.allclose(dense, flash, rtol=2e-2, atol=2e-2), (dense,
                                                             flash)
    assert block[-1] < block[0]


if __name__ == "__main__":
    main()

"""Pipeline-parallel LM training through the trainer surface.

``SyncTrainer(pipeline_stages=S)`` trains a TransformerLM dp x pp over
a ``(workers, stage)`` mesh: the layer stack (``scan_blocks`` stacked
form) is sharded one slice per stage and driven through the GPipe
microbatch schedule (``parallel.pipeline``), with activations hopping
stages over ppermute.  Contrast ``examples/pipeline_moe.py``, which
drives the raw ``pipeline_apply`` primitive on a synthetic stage
function — this is the same schedule carrying a real model through the
normal Trainer API, including loss parity with the unpipelined run.

Run:  python examples/pipeline_lm.py --devices 8
      python examples/pipeline_lm.py --devices 8 --stages 4 --workers 2
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup


def main():
    parser = make_parser(__doc__, rows=512, epochs=2, batch_size=8,
                         workers=2, learning_rate=1e-3)
    parser.add_argument("--stages", type=int, default=4)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--microbatches", type=int, default=None)
    args = parse_args_and_setup(parser)
    from distkeras_tpu.profiling import profiler_trace

    with profiler_trace(args.profile_dir):
        _run(args)


def _run(args):
    import json

    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.trainers import SyncTrainer

    data = datasets.lm_synth(args.rows, seq_len=args.seq_len,
                             vocab_size=128, seed=args.seed)
    spec = model_config("transformer_lm", (args.seq_len,),
                        input_dtype="int32", vocab_size=128,
                        num_layers=args.layers, d_model=args.d_model,
                        num_heads=4, max_len=args.seq_len,
                        dtype="float32", scan_blocks=True)
    kw = dict(batch_size=args.batch_size, num_epoch=args.epochs,
              learning_rate=args.learning_rate,
              worker_optimizer="adam",
              loss="sparse_categorical_crossentropy", seed=args.seed,
              checkpoint_dir=args.checkpoint_dir)

    # identical init for both arms -> the losses must match
    import jax
    import jax.numpy as jnp

    v0 = ModelSpec.from_config(spec).build().init(
        jax.random.key(args.seed + 7),
        jnp.zeros((2, args.seq_len), jnp.int32))

    pp = SyncTrainer(spec, num_workers=args.workers,
                     pipeline_stages=args.stages,
                     pipeline_microbatches=args.microbatches, **kw)
    pp.train(data, initial_variables=v0, resume_from=args.resume)

    ref = SyncTrainer(spec, num_workers=args.workers,
                      **{**kw, "checkpoint_dir": None})
    ref.train(data, initial_variables=v0)

    pp_losses = [round(x, 4) for x in pp.history["epoch_loss"]]
    ref_losses = [round(x, 4) for x in ref.history["epoch_loss"]]
    print(json.dumps({
        "example": "pipeline_lm",
        "mesh": f"(workers={pp.num_workers}, stages={args.stages})",
        "pipelined_epoch_loss": pp_losses,
        "unpipelined_epoch_loss": ref_losses,
        "max_abs_diff": round(max(abs(a - b) for a, b in
                                  zip(pp_losses, ref_losses)), 5),
    }))
    assert np.isfinite(pp_losses).all()


if __name__ == "__main__":
    main()

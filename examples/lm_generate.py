"""Train a tiny LM, then SERVE it: KV-cache autoregressive generation.

The reference predates autoregressive serving (its predictors are one
batched forward per partition — SURVEY.md §3.3); the rebuild's LM
family completes the loop: train with any trainer, then
``models.generate`` — one prompt pass fills every layer's KV cache,
each new token is a T=1 step inside ``lax.scan``, the whole generation
one compiled XLA program.

The synthetic LM task (``datasets.lm_synth``) is next-token prediction
on structured sequences, so after a few epochs greedy continuations
should follow the learned structure; the demo asserts the decode path
is exact (cached greedy == naive re-forward loop) and prints both
sampled and greedy continuations.

Run:  python examples/lm_generate.py
      python examples/lm_generate.py --temperature 0.8 --top-k 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup


def main():
    parser = make_parser(__doc__, rows=512, epochs=4, batch_size=32,
                         learning_rate=3e-3)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--vocab-size", type=int, default=64)
    parser.add_argument("--prompt-len", type=int, default=8)
    parser.add_argument("--new-tokens", type=int, default=24)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--kv-heads", type=int, default=None,
                        help="GQA: K/V heads (divides 4); shrinks "
                             "the serving KV cache by the group "
                             "factor — 5.8-9x measured per-step "
                             "decode cost (PERF.md §18 addendum)")
    parser.add_argument("--kv-dtype", default=None,
                        choices=["int8"],
                        help="int8-quantized KV cache (+31% measured "
                             "decode throughput at MHA scale)")
    args = parse_args_and_setup(parser)
    from distkeras_tpu.profiling import profiler_trace

    with profiler_trace(args.profile_dir):
        _run(args)


def _run(args):
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import ModelSpec, generate, model_config
    from distkeras_tpu.trainers import SingleTrainer

    data = datasets.lm_synth(args.rows, seq_len=args.seq_len,
                             vocab_size=args.vocab_size, seed=0)
    cfg = model_config(
        "transformer_lm", (args.seq_len,), input_dtype="int32",
        vocab_size=args.vocab_size, num_layers=2, d_model=64,
        num_heads=4, max_len=args.seq_len, dtype="float32",
        num_kv_heads=args.kv_heads, kv_cache_dtype=args.kv_dtype)
    trainer = SingleTrainer(cfg, loss="sparse_categorical_crossentropy",
                            worker_optimizer="adam",
                            learning_rate=args.learning_rate,
                            batch_size=args.batch_size,
                            num_epoch=args.epochs, seed=args.seed)
    trainer.train(data)
    variables = trainer.trained_variables

    model = ModelSpec.from_config(cfg).build()
    prompt = np.asarray(data["features"][:2, :args.prompt_len],
                        np.int32)
    greedy = generate(model, variables, prompt,
                      max_new_tokens=args.new_tokens)

    # Decode-path correctness by teacher forcing: ONE full forward
    # over the generated sequence must score every generated token
    # within a small logit tolerance of its context's argmax.  (Not
    # bitwise vs a re-forward loop: the KV-cache attention and the
    # dense attention reduce in different orders, and the synthetic
    # task trains into near-ties — a 0.006-logit gap was measured to
    # flip a token on the v5e.  Bitwise equality IS asserted where
    # numerics are exact: tests/test_generate.py on the CPU backend.)
    logits = np.asarray(model.apply(variables, greedy)
                        .astype(jnp.float32))
    gen = np.asarray(greedy)
    # int8 cache: decode logits carry the quantization error bound,
    # so the teacher-forced gap tolerance widens accordingly
    tol = 0.05 if args.kv_dtype is None else 0.5
    for i in range(args.prompt_len, gen.shape[1]):
        step = logits[:, i - 1]
        gap = step.max(-1) - step[np.arange(len(gen)), gen[:, i]]
        assert (gap <= tol).all(), (i, gap)

    # beam decoding: report both sequences' teacher-forced log-probs
    # (beam typically scores higher; the guarantee is not strict once
    # greedy's prefix can be pruned mid-search, so this reports
    # rather than asserts)
    from distkeras_tpu.models import beam_search

    beam, beam_scores = beam_search(model, variables, prompt,
                                    max_new_tokens=args.new_tokens,
                                    num_beams=4)

    def seq_logprob(seq):
        lg = np.asarray(model.apply(variables, seq)
                        .astype(jnp.float32))
        lp = np.asarray(jax.nn.log_softmax(lg, axis=-1))
        t0 = args.prompt_len
        return sum(lp[np.arange(len(seq)), i - 1, np.asarray(seq)[:, i]]
                   for i in range(t0, seq.shape[1]))

    out = {"example": "lm_generate",
           "epoch_loss": [round(x, 4)
                          for x in trainer.history["epoch_loss"]],
           "prompt": prompt[0].tolist(),
           "greedy": np.asarray(greedy)[0, args.prompt_len:].tolist(),
           "beam": np.asarray(beam)[0, args.prompt_len:].tolist(),
           "beam_scores": [round(float(s), 3) for s in
                           np.asarray(beam_scores)],
           "greedy_logprob": [round(float(x), 3)
                              for x in seq_logprob(greedy)],
           "beam_logprob": [round(float(x), 3)
                            for x in seq_logprob(jnp.asarray(beam))],
           "decode_teacher_forced": True}
    if args.temperature > 0:
        sampled = generate(model, variables, prompt,
                           max_new_tokens=args.new_tokens,
                           temperature=args.temperature,
                           top_k=args.top_k, rng=jax.random.key(7))
        out["sampled"] = np.asarray(
            sampled)[0, args.prompt_len:].tolist()
    print(json.dumps(out))
    assert trainer.history["epoch_loss"][-1] < \
        trainer.history["epoch_loss"][0]


if __name__ == "__main__":
    main()

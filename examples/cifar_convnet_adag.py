"""CIFAR-10 ConvNet + ADAG — BASELINE.md row 2.

Pipeline: synthetic CIFAR-shaped data -> ADAG (the reference's flagship
async trainer) over a worker mesh -> predict -> accuracy, with per-round
staleness telemetry printed at the end (observability the reference
lacked, SURVEY.md §5).

Run:  python examples/cifar_convnet_adag.py --devices 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report,
                     resolve_platform_defaults)


def main():
    # lr: 0.02 diverges with the adam worker optimizer on this config
    # (loss explodes past the init value); 2e-3 converges.
    parser = make_parser(__doc__, rows=None, epochs=None, batch_size=16,
                         workers=4, window=2, learning_rate=2e-3)
    add_data_option(parser)
    args = parse_args_and_setup(parser)
    resolve_platform_defaults(args, rows=(512, 2048), epochs=(1, 2))

    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import ADAG

    data = load_dataset(
        args,
        lambda: datasets.cifar10_synth(args.rows, seed=args.seed + 1))
    cfg = model_config("convnet", (32, 32, 3), num_classes=10,
                       widths=(16, 32), dense=64)
    trainer = ADAG(cfg, num_workers=args.workers,
                   communication_window=args.window,
                   batch_size=args.batch_size, num_epoch=args.epochs,
                   learning_rate=args.learning_rate,
                   worker_optimizer="adam", seed=args.seed,
                   checkpoint_dir=args.checkpoint_dir,
                   profile_dir=args.profile_dir)
    variables = trainer.train(data, resume_from=args.resume)

    metrics = evaluate_model(trainer.model, variables, data,
                             batch_size=256)
    stal = np.asarray(trainer.history["staleness"])
    print(f"[cifar_adag] staleness per commit: mean {stal.mean():.2f}, "
          f"max {stal.max()} over {stal.size} commits")
    report("cifar_convnet_adag", trainer, metrics,
           staleness_mean=round(float(stal.mean()), 3))


if __name__ == "__main__":
    main()

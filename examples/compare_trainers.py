"""Run every trainer on the same data and compare accuracy + time.

The closest analogue of the reference's MNIST workflow notebook, whose
punchline was a table of training time and accuracy per trainer
(SURVEY.md §4 "example notebooks as integration tests", §6 README
plots).

Run:  python examples/compare_trainers.py --devices 8
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup)


def main():
    # sgd @ 0.05 is the PARITY.md-validated setup: the async family's
    # summed delta commits want plain-sgd scale (adam-scaled deltas
    # overshoot the center, making async look falsely broken)
    parser = make_parser(__doc__, rows=4096, epochs=2, batch_size=32,
                         workers=4, window=2, learning_rate=0.05)
    add_data_option(parser)
    args = parse_args_and_setup(parser)

    from distkeras_tpu import trainers
    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config

    data = load_dataset(
        args, lambda: datasets.mnist_synth(args.rows,
                                           seed=args.seed))
    cfg = model_config("mlp", (28, 28, 1), num_classes=10, hidden=(64,))
    # plain-sgd workers (the Trainer default; EAMSGD keeps its own
    # nesterov default) — adam-scaled deltas overshoot the PS center
    common = dict(learning_rate=args.learning_rate,
                  batch_size=args.batch_size, num_epoch=args.epochs,
                  seed=args.seed, profile_dir=args.profile_dir)
    dist = dict(num_workers=args.workers,
                communication_window=args.window)
    # elastic family: the paper's stability condition couples alpha =
    # lr * rho; rescale the flag by the same 0.02/0.05 ratio the
    # parity script uses so --learning-rate drives every run
    elastic = {**common, "learning_rate": args.learning_rate * 0.4}
    # DOWNPOUR commits the RAW window-summed delta (no normalization —
    # that omission is what ADAG fixes), so its stable lr scales like
    # 1/(workers*window); DynSGD scales commits by 1/(staleness+1) but
    # not by the window, so it wants ~1/window.  Measured on this
    # config: downpour 0.05 -> chance, 0.05/8 -> 0.85; dynsgd 0.05 ->
    # 0.30, 0.025 -> 0.81.
    # ADAG window-normalizes but still sums W commits per round, so it
    # wants ~1/workers (measured: 0.05 -> 0.59, 0.0125 -> 0.92).
    downpour = {**common, "learning_rate":
                args.learning_rate / (args.workers * args.window)}
    adag = {**common,
            "learning_rate": args.learning_rate / args.workers}
    dynsgd = {**common,
              "learning_rate": args.learning_rate / args.window}

    runs = {
        "single": trainers.SingleTrainer(cfg, **common),
        "sync": trainers.SyncTrainer(cfg, num_workers=args.workers,
                                     **common),
        "downpour": trainers.DOWNPOUR(cfg, **dist, **downpour),
        "adag": trainers.ADAG(cfg, **dist, **adag),
        "aeasgd": trainers.AEASGD(cfg, rho=2.5, **dist, **elastic),
        # EAMSGD = the elastic law + its default Nesterov workers
        "eamsgd": trainers.EAMSGD(cfg, rho=2.5, **dist, **elastic),
        "dynsgd": trainers.DynSGD(cfg, **dist, **dynsgd),
    }

    rows = []
    for name, trainer in runs.items():
        variables = trainer.train(data)
        acc = evaluate_model(trainer.model, variables, data,
                             batch_size=256)["accuracy"]
        rows.append({"trainer": name, "accuracy": round(acc, 4),
                     "time_s": round(trainer.training_time, 2),
                     "final_loss": round(
                         float(trainer.history["epoch_loss"][-1]), 4)})
        print(f"{name:>9}: accuracy {acc:.4f}  "
              f"time {trainer.training_time:6.2f}s  "
              f"loss {rows[-1]['final_loss']:.4f}")
    print(json.dumps({"config": "compare_trainers", "runs": rows}))


if __name__ == "__main__":
    main()

"""Streaming inference — train a model, then serve an event stream.

The reference's Kafka notebook consumed a message stream and ran the
trained Keras model per batch (SURVEY.md §2.1 Examples).  Here the
stream is any Python iterable (plug a Kafka/PubSub consumer in its
place); StreamingPredictor micro-batches rows onto ONE compiled forward
shape, so a long-running stream never recompiles.

Run:  python examples/streaming_inference.py
      python examples/streaming_inference.py --flush-every 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup


def main():
    parser = make_parser(__doc__, rows=2048, epochs=2, batch_size=32,
                         learning_rate=3e-3)
    parser.add_argument("--stream-rows", type=int, default=500)
    parser.add_argument("--flush-every", type=int, default=None,
                        help="flush a non-full micro-batch after this "
                             "many consumed rows (latency bound)")
    args = parse_args_and_setup(parser)

    import time

    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.streaming import StreamingPredictor
    from distkeras_tpu.trainers import SingleTrainer

    cfg = model_config("mlp", (16,), num_classes=4, hidden=(32,))
    data = datasets.synthetic_classification(args.rows, (16,), 4,
                                             seed=args.seed)
    t = SingleTrainer(cfg, worker_optimizer="adam",
                      learning_rate=args.learning_rate,
                      batch_size=args.batch_size,
                      num_epoch=args.epochs,
                      profile_dir=args.profile_dir)
    variables = t.train(data)
    print(f"[streaming] trained: epoch loss "
          f"{t.history['epoch_loss'][0]:.3f} -> "
          f"{t.history['epoch_loss'][-1]:.3f}")

    rng = np.random.default_rng(args.seed + 1)

    def event_stream(n):
        """Stand-in for a Kafka consumer loop."""
        for i in range(n):
            yield {"event_id": i,
                   "features": rng.normal(size=(16,)).astype(
                       np.float32)}

    sp = StreamingPredictor(cfg, variables, batch_size=64,
                            flush_every=args.flush_every,
                            output="class")
    start = time.time()
    n_out = 0
    classes = np.zeros(4, np.int64)
    for row in sp.predict_stream(event_stream(args.stream_rows)):
        n_out += 1
        classes[int(row["prediction"])] += 1
    dt = time.time() - start
    print(f"[streaming] {n_out} events in {dt:.2f}s "
          f"({n_out / dt:.0f} events/s), class histogram "
          f"{classes.tolist()}")
    import json

    print(json.dumps({"config": "streaming_inference",
                      "events": n_out,
                      "events_per_s": round(n_out / dt, 1),
                      "class_histogram": classes.tolist()}))


if __name__ == "__main__":
    main()

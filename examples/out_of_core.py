"""Out-of-core training: stream npz shard files through a PS trainer.

The reference scaled past host RAM by construction — Spark partitions
streamed through executors (SURVEY.md §1 L0).  The rebuild's
equivalent is ``Dataset.from_npz_shards``: a ``ShardedDataset`` that
keeps only shard-file metadata in memory and materializes one shard at
a time, so host peak memory is one shard, not the dataset.  This
example writes a sharded dataset to disk, trains ADAG by streaming it
(shard order reshuffled every epoch), and cross-checks the result
against the fully in-memory run.

Run:  python examples/out_of_core.py --devices 8
      python examples/out_of_core.py --shards 8 --rows 16384
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup


def main():
    parser = make_parser(__doc__, rows=8192, epochs=3,
                         learning_rate=0.05)
    parser.add_argument("--shards", type=int, default=4,
                        help="number of npz shard files to write")
    parser.add_argument("--shard-dir", default=None,
                        help="where to write shards (default: tmpdir)")
    args = parse_args_and_setup(parser)
    from distkeras_tpu.profiling import profiler_trace

    with profiler_trace(args.profile_dir):
        _run(args)


def _run(args):
    import json
    import tempfile

    import numpy as np

    from distkeras_tpu.data import Dataset, datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import ADAG

    shard_dir = args.shard_dir or tempfile.mkdtemp(prefix="dkt_shards_")
    full = datasets.synthetic_classification(args.rows, (16,), 8,
                                             seed=args.seed)
    full.to_npz_shards(str(Path(shard_dir) / "part"),
                       rows_per_shard=max(1, args.rows // args.shards))
    sharded = Dataset.from_npz_shards(str(Path(shard_dir) / "part-*.npz"))
    print(f"wrote {sharded.num_shards} shards, {len(sharded)} rows, "
          f"columns {sharded.column_names}")

    cfg = model_config("mlp", (16,), num_classes=8, hidden=(64,))
    kw = dict(num_workers=args.workers,
              communication_window=args.window,
              batch_size=args.batch_size, num_epoch=args.epochs,
              learning_rate=args.learning_rate,
              seed=args.seed,
              checkpoint_dir=args.checkpoint_dir)

    streamed = ADAG(cfg, **kw)
    streamed.train(sharded, resume_from=args.resume)
    acc_s = evaluate_model(streamed.model, streamed.trained_variables,
                           full, batch_size=512)["accuracy"]

    in_memory = ADAG(cfg, **{**kw, "checkpoint_dir": None})
    in_memory.train(full)
    acc_m = evaluate_model(in_memory.model,
                           in_memory.trained_variables, full,
                           batch_size=512)["accuracy"]

    print(json.dumps({
        "example": "out_of_core_adag",
        "shards": sharded.num_shards,
        "streamed_epoch_loss": [round(x, 4) for x in
                                streamed.history["epoch_loss"]],
        "streamed_accuracy": round(float(acc_s), 4),
        "in_memory_accuracy": round(float(acc_m), 4),
        "dropped_tail_batches": streamed.history.get(
            "dropped_tail_batches", []),
        "skipped_segment_rows": streamed.history.get(
            "skipped_segment_rows", []),
    }))
    assert np.isfinite(streamed.history["epoch_loss"]).all()


if __name__ == "__main__":
    main()

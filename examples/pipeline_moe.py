"""Pipeline + expert parallelism primitives, end to end.

The last two of the five parallelism forms (SURVEY.md §2.3 — neither
exists in the reference): a GPipe microbatch pipeline over a ``stage``
mesh axis, and a Switch-style MoE with all_to_all token dispatch over
an ``expert`` axis.  Each trains a small regression and reports losses
plus EP routing telemetry.

Run:  python examples/pipeline_moe.py --devices 8
      python examples/pipeline_moe.py --devices 8 --steps 50
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import make_parser, parse_args_and_setup


def main():
    parser = make_parser(__doc__)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--d-model", type=int, default=16)
    args = parse_args_and_setup(parser)
    from distkeras_tpu.profiling import profiler_trace

    with profiler_trace(args.profile_dir):
        _run(args)


def _run(args):
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.parallel import (init_moe_params, moe_apply,
                                        moe_pspecs, pipeline_apply)
    from distkeras_tpu.utils import shard_map

    n_dev = len(jax.devices())
    d = args.d_model
    rng = np.random.default_rng(args.seed)

    # ---- pipeline: n_dev stages, tanh-dense each, fit a random map --
    mesh = Mesh(np.asarray(jax.devices()), ("stage",))
    params = {
        "w": jnp.asarray(rng.normal(scale=0.4, size=(n_dev, d, d)),
                         jnp.float32),
        "b": jnp.zeros((n_dev, d), jnp.float32)}
    x = jnp.asarray(rng.normal(size=(32, d)), jnp.float32)
    tgt = jnp.asarray(np.tanh(np.asarray(x) @ rng.normal(
        scale=0.3, size=(d, d))), jnp.float32)

    def stage_fn(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    pipe_loss = shard_map(
        lambda p, x, t: jnp.mean(
            (pipeline_apply(stage_fn, p, x, axis_name="stage",
                            num_microbatches=4) - t) ** 2),
        mesh=mesh, in_specs=(P("stage"), P(), P()), out_specs=P())
    pp_losses = _fit(pipe_loss, params, x, tgt, args.steps, optax, jax)
    print(f"[pipeline] {n_dev} stages, 4 microbatches: loss "
          f"{pp_losses[0]:.4f} -> {pp_losses[-1]:.4f}")

    # ---- MoE: 2 experts/device, all_to_all dispatch ----------------
    mesh_e = Mesh(np.asarray(jax.devices()), ("expert",))
    mp = init_moe_params(jax.random.key(args.seed), d, 2 * d,
                         num_experts=2 * n_dev)
    xe = jnp.asarray(rng.normal(size=(n_dev * 16, d)), jnp.float32)
    te = jnp.asarray(np.sin(np.asarray(xe)), jnp.float32)

    def moe_loss(p, x, t):
        out, aux = moe_apply(p, x, axis_name="expert",
                             capacity_factor=2.0)
        return (lax.pmean(jnp.mean((out - t) ** 2), "expert")
                + 0.01 * aux.load_balance_loss)

    moe_sharded = shard_map(
        moe_loss, mesh=mesh_e,
        in_specs=(moe_pspecs("expert"), P("expert"),
                  P("expert")),
        out_specs=P())
    ep_losses = _fit(moe_sharded, mp, xe, te, args.steps, optax, jax)
    print(f"[moe] {2 * n_dev} experts on {n_dev} devices: loss "
          f"{ep_losses[0]:.4f} -> {ep_losses[-1]:.4f}")

    print(json.dumps({
        "config": "pipeline_moe", "devices": n_dev,
        "pipeline_loss": [round(pp_losses[0], 5),
                          round(pp_losses[-1], 5)],
        "moe_loss": [round(ep_losses[0], 5), round(ep_losses[-1], 5)],
    }))


def _fit(loss_fn, params, x, tgt, steps, optax, jax):
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, s, x, t):
        loss, g = jax.value_and_grad(loss_fn)(p, x, t)
        upd, s = tx.update(g, s)
        return optax.apply_updates(p, upd), s, loss

    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, x, tgt)
        losses.append(float(loss))
    return losses


if __name__ == "__main__":
    main()

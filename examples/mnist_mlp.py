"""MNIST MLP — the reference's canonical workflow (BASELINE.md row 1).

Pipeline: synthetic MNIST-shaped data -> SingleTrainer (or any trainer
via --trainer) -> sharded batch inference -> accuracy.  The analogue of
the reference's MNIST workflow notebook, which ran every trainer on the
same data and compared accuracies (SURVEY.md §4).

Run:  python examples/mnist_mlp.py
      python examples/mnist_mlp.py --trainer adag --devices 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report)

TRAINERS = ("single", "sync", "downpour", "adag", "aeasgd", "eamsgd",
            "dynsgd")


def main():
    parser = make_parser(__doc__, rows=4096, epochs=3, batch_size=64,
                         learning_rate=3e-3)
    parser.add_argument("--trainer", choices=TRAINERS, default="single")
    add_data_option(parser)
    args = parse_args_and_setup(parser)

    from distkeras_tpu import trainers
    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config

    data = load_dataset(
        args, lambda: datasets.mnist_synth(args.rows, seed=args.seed))
    holdout, train = data.shard(4, 0), data.shard(4, 1).concat(
        data.shard(4, 2)).concat(data.shard(4, 3))
    cfg = model_config("mlp", (28, 28, 1), num_classes=10, hidden=(64,))

    common = dict(worker_optimizer="adam",
                  learning_rate=args.learning_rate,
                  batch_size=args.batch_size, num_epoch=args.epochs,
                  seed=args.seed, checkpoint_dir=args.checkpoint_dir,
                  profile_dir=args.profile_dir)
    dist = dict(num_workers=args.workers,
                communication_window=args.window)
    name = args.trainer
    if name == "single":
        trainer = trainers.SingleTrainer(cfg, **common)
    elif name == "sync":
        trainer = trainers.SyncTrainer(cfg, num_workers=args.workers,
                                       **common)
    else:
        cls = {"downpour": trainers.DOWNPOUR, "adag": trainers.ADAG,
               "aeasgd": trainers.AEASGD, "eamsgd": trainers.EAMSGD,
               "dynsgd": trainers.DynSGD}[name]
        trainer = cls(cfg, **dist, **common)

    variables = trainer.train(train, resume_from=args.resume)
    metrics = {
        "train_accuracy": evaluate_model(
            trainer.model, variables, train, batch_size=256)["accuracy"],
        "holdout_accuracy": evaluate_model(
            trainer.model, variables, holdout,
            batch_size=256)["accuracy"],
    }
    report(f"mnist_mlp/{name}", trainer, metrics)


if __name__ == "__main__":
    main()

"""Criteo Wide&Deep — BASELINE.md row 5: the full ETL pipeline.

The config that exercises the columnar transformer surface (the
reference's Spark-ML-style ETL, SURVEY.md §3.4): min-max scale the dense
counts, hash-bucket the categorical strings, assemble a feature matrix,
train Wide&Deep with DOWNPOUR, batch-predict, evaluate accuracy.

Run:  python examples/criteo_widedeep.py --devices 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report,
                     timed)


def main():
    parser = make_parser(__doc__, rows=4096, epochs=3, batch_size=32,
                         workers=4, window=2, learning_rate=0.01)
    parser.add_argument("--num-dense", type=int, default=4)
    parser.add_argument("--num-categorical", type=int, default=6)
    parser.add_argument("--buckets", type=int, default=50)
    add_data_option(parser,
                    required=("dense", "label",
                              "c0..c{num_categorical-1}"))
    args = parse_args_and_setup(parser)

    from distkeras_tpu.data import (
        AssembleTransformer,
        HashBucketTransformer,
        MinMaxTransformer,
        Pipeline,
        datasets,
    )
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models import model_config
    from distkeras_tpu.predictors import ModelPredictor
    from distkeras_tpu.trainers import DOWNPOUR

    nd, nc = args.num_dense, args.num_categorical
    data = load_dataset(
        args,
        lambda: datasets.criteo_synth(args.rows, num_dense=nd,
                                      num_categorical=nc,
                                      vocab_size=100,
                                      seed=args.seed + 4),
        required=("dense", "label")
        + tuple(f"c{j}" for j in range(nc)))
    with timed("criteo_etl"):
        etl = Pipeline(
            [MinMaxTransformer("dense")]
            + [HashBucketTransformer(f"c{j}", args.buckets)
               for j in range(nc)]
            + [AssembleTransformer(
                ["dense"] + [f"c{j}_bucket" for j in range(nc)])])
        table = etl.fit_transform(data)

    cfg = model_config("wide_deep", (nd + nc,), num_dense=nd,
                       num_categorical=nc, vocab_size=args.buckets,
                       embed_dim=8, deep=(32, 16), num_classes=2)
    trainer = DOWNPOUR(cfg, num_workers=args.workers,
                       communication_window=args.window,
                       batch_size=args.batch_size,
                       num_epoch=args.epochs,
                       learning_rate=args.learning_rate,
                       worker_optimizer="adam", seed=args.seed,
                       checkpoint_dir=args.checkpoint_dir,
                       profile_dir=args.profile_dir)
    variables = trainer.train(table, resume_from=args.resume)

    with timed("criteo_predict"):
        scored = ModelPredictor(trainer.model, variables,
                                output="class",
                                batch_size=256).predict(table)
    acc = AccuracyEvaluator("prediction", "label").evaluate(scored)
    report("criteo_widedeep_downpour", trainer, {"accuracy": acc})


if __name__ == "__main__":
    main()

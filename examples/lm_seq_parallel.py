"""Long-context LM training with sequence parallelism (ring attention).

Beyond the reference (SURVEY.md §5: it has no long-sequence story): the
time axis is sharded across the mesh, each device holds T/N positions,
and ring attention exchanges K/V blocks over the ring — the same
parameters and losses as dense single-device training (parity-tested in
tests/test_ring_attention.py), at O(T/N) memory per device.

Run:  python examples/lm_seq_parallel.py --devices 8
      python examples/lm_seq_parallel.py --devices 8 --seq-len 512
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report)


def main():
    parser = make_parser(__doc__, rows=512, epochs=4, batch_size=16,
                         learning_rate=3e-3)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--vocab-size", type=int, default=64)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--q-chunk", type=int, default=None,
                        help="within-device q block length for ring "
                             "attention (bounds transient memory to "
                             "[q_chunk, T_local] per hop)")
    parser.add_argument("--impl", choices=["xla", "flash"],
                        default="xla",
                        help="'flash': run each ring hop through the "
                             "Pallas hop kernels (ops.attention; "
                             "PERF.md §17 addendum 2)")
    add_data_option(parser)
    args = parse_args_and_setup(parser)
    from distkeras_tpu.profiling import profiler_trace

    with profiler_trace(args.profile_dir):
        _run(args)


def _run(args):
    import time

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.ops.losses import resolve_loss
    from distkeras_tpu.utils import shard_map

    n_dev = len(jax.devices())
    if args.seq_len % n_dev:
        raise SystemExit(f"--seq-len {args.seq_len} must divide by the "
                         f"{n_dev} devices")
    t_local = args.seq_len // n_dev
    if args.q_chunk and args.q_chunk < t_local \
            and t_local % args.q_chunk:
        raise SystemExit(
            f"--q-chunk {args.q_chunk} must divide the per-device "
            f"sequence length {t_local}")
    mesh = Mesh(np.asarray(jax.devices()), ("seq",))

    data = load_dataset(
        args, lambda: datasets.lm_synth(args.rows,
                                        seq_len=args.seq_len,
                                        vocab_size=args.vocab_size,
                                        seed=args.seed))
    rows = len(data)
    lm_cfg = dict(vocab_size=args.vocab_size, num_layers=args.layers,
                  d_model=args.d_model, num_heads=4,
                  max_len=args.seq_len, dtype="float32")
    seq_model = ModelSpec.from_config(model_config(
        "transformer_lm", (args.seq_len,), input_dtype="int32",
        seq_axis="seq", attn_q_chunk=args.q_chunk, **lm_cfg)).build()
    if args.impl == "flash":
        from distkeras_tpu.parallel.ring_attention import ring_attn_fn

        # --q-chunk maps to the kernel's q block size here (the XLA
        # impl's q_chunk arg does not apply to the flash path)
        seq_model = seq_model.clone(attn_fn=ring_attn_fn(
            "seq", impl="flash", block_q=args.q_chunk,
            block_k=args.q_chunk))
    dense_spec = ModelSpec.from_config(model_config(
        "transformer_lm", (args.seq_len,), input_dtype="int32",
        **lm_cfg))

    tokens = data["features"][:args.batch_size]
    variables = dense_spec.build().init(jax.random.key(args.seed),
                                        tokens)
    tx = optax.adam(args.learning_rate)
    opt_state = tx.init(variables["params"])
    loss_fn = resolve_loss("sparse_categorical_crossentropy")

    def shard_loss(vs, toks, tgt):
        return jax.lax.pmean(
            loss_fn(seq_model.apply(vs, toks), tgt), "seq")

    sharded = shard_map(
        shard_loss, mesh=mesh,
        in_specs=(P(), P(None, "seq"), P(None, "seq")), out_specs=P(),
        # the Pallas interpreter requires check_vma=False (JAX
        # limitation; see parallel.ring_attention docs)
        check_vma=args.impl != "flash")

    @jax.jit
    def step(vs, opt_state, toks, tgt):
        loss, g = jax.value_and_grad(
            lambda p: sharded({**vs, "params": p}, toks, tgt))(
                vs["params"])
        upd, opt_state = tx.update(g, opt_state)
        return ({**vs, "params": optax.apply_updates(vs["params"],
                                                     upd)},
                opt_state, loss)

    start = time.time()
    epoch_losses = []
    steps_per_epoch = rows // args.batch_size
    if not steps_per_epoch:
        raise SystemExit(f"--rows {rows} < --batch-size "
                         f"{args.batch_size}: no full batch to train on")
    for epoch in range(args.epochs):
        order = np.random.default_rng(args.seed + epoch).permutation(
            rows)
        losses = []
        for s in range(steps_per_epoch):
            idx = order[s * args.batch_size:(s + 1) * args.batch_size]
            variables, opt_state, loss = step(
                variables, opt_state, data["features"][idx],
                data["label"][idx])
            losses.append(float(loss))
        epoch_losses.append(float(np.mean(losses)))
        print(f"[lm_seq_parallel] epoch {epoch}: "
              f"loss {epoch_losses[-1]:.4f}")

    class _T:  # report() duck-type
        training_time = time.time() - start
        history = {"epoch_loss": epoch_losses}

    report("lm_seq_parallel", _T, {"final_loss": epoch_losses[-1]},
           seq_len=args.seq_len, devices=n_dev)


if __name__ == "__main__":
    main()

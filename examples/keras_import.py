"""Bring a Keras model: ingest, fine-tune distributed, evaluate.

The reference's entry artifact is a Keras model — users hand
``serialize_keras_model`` output to every trainer (SURVEY.md §3.5).
This pipeline does the same migration here: build (or load) a Keras
``Sequential``, ingest it with ``distkeras_tpu.compat.from_keras`` into
a flax model + mapped weights, continue training it with a distributed
trainer, and evaluate.  When keras is not installed the same
architecture JSON is ingested from a string — the shim needs no keras.

Run:  python examples/keras_import.py
      python examples/keras_import.py --trainer adag --devices 8
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report)

TRAINERS = ("single", "sync", "downpour", "adag")

# The MNIST-notebook MLP, as the reference's users would have written it
# (used when keras is not installed; identical to the keras path's arch).
_FALLBACK_ARCH = {
    "class_name": "Sequential",
    "config": {"layers": [
        {"class_name": "InputLayer",
         "config": {"batch_shape": [None, 28, 28, 1]}},
        {"class_name": "Flatten", "config": {}},
        {"class_name": "Dense",
         "config": {"units": 64, "activation": "relu"}},
        {"class_name": "Dense",
         "config": {"units": 10, "activation": "linear"}},
    ]},
}


def main():
    parser = make_parser(__doc__, rows=4096, epochs=3, batch_size=64,
                         learning_rate=3e-3)
    parser.add_argument("--trainer", choices=TRAINERS, default="sync")
    add_data_option(parser)
    args = parse_args_and_setup(parser)

    from distkeras_tpu import trainers
    from distkeras_tpu.compat import from_keras, from_keras_json
    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model

    try:
        import keras
    except ImportError:
        keras = None
    if keras is not None:
        model = keras.Sequential([
            keras.layers.Input((28, 28, 1)),
            keras.layers.Flatten(),
            keras.layers.Dense(64, activation="relu"),
            keras.layers.Dense(10),
        ])
        spec, variables = from_keras(model)
        source = f"keras {keras.__version__}"
    else:
        spec, variables = from_keras_json(json.dumps(_FALLBACK_ARCH))
        source = "architecture JSON (keras not installed)"
    print(f"[keras_import] ingested from {source}: "
          f"{[l['kind'] for l in spec.kwargs['layers']]}")

    data = load_dataset(
        args, lambda: datasets.mnist_synth(args.rows,
                                           seed=args.seed))
    holdout, train = data.shard(4, 0), data.shard(4, 1).concat(
        data.shard(4, 2)).concat(data.shard(4, 3))

    common = dict(loss="categorical_crossentropy",
                  worker_optimizer="adam",
                  learning_rate=args.learning_rate,
                  batch_size=args.batch_size, num_epoch=args.epochs,
                  seed=args.seed, profile_dir=args.profile_dir)
    if args.trainer == "single":
        t = trainers.SingleTrainer(spec.to_config(), **common)
    elif args.trainer == "sync":
        t = trainers.SyncTrainer(spec.to_config(),
                                 num_workers=args.workers, **common)
    else:
        cls = {"downpour": trainers.DOWNPOUR, "adag": trainers.ADAG}
        t = cls[args.trainer](spec.to_config(),
                              num_workers=args.workers,
                              communication_window=args.window,
                              **common)
    t.train(train, initial_variables=variables)
    metrics = evaluate_model(t.model, t.trained_variables, holdout)
    report(f"keras_import/{args.trainer}", t, metrics)


if __name__ == "__main__":
    main()

"""Shared plumbing for the example scripts.

The reference's user surface was example notebooks running the full
ETL -> train -> predict -> evaluate pipeline on a local Spark context
(SURVEY.md §1 L7, §4 "example notebooks as integration tests").  These
scripts are the rebuild's equivalent: each one is a runnable pipeline for
one BASELINE.md config, defaulting to small learnable synthetic data
(zero egress — see distkeras_tpu.data.datasets) and shapes that finish in
seconds on a laptop CPU or a single TPU chip.

``--devices N`` is the Spark ``local[N]`` analogue: it forces an
N-device virtual CPU mesh so the distributed trainers exercise real
mesh sharding + ICI-style collectives without N chips.  It must take
effect before jax initializes, hence ``parse_args_and_setup`` must be
called before importing anything that imports jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

# Make the examples runnable from a source checkout without installation.
_REPO_ROOT = str(Path(__file__).resolve().parent.parent)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def make_parser(description: str, **defaults) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--rows", type=int,
                   default=defaults.get("rows", 2048),
                   help="synthetic dataset rows")
    p.add_argument("--epochs", type=int,
                   default=defaults.get("epochs", 3))
    p.add_argument("--batch-size", type=int,
                   default=defaults.get("batch_size", 32),
                   help="per-worker batch size")
    p.add_argument("--workers", type=int,
                   default=defaults.get("workers", 4),
                   help="data-parallel workers (mesh axis size)")
    p.add_argument("--window", type=int,
                   default=defaults.get("window", 2),
                   help="communication window (local steps per commit)")
    p.add_argument("--learning-rate", type=float,
                   default=defaults.get("learning_rate", 0.01))
    p.add_argument("--devices", type=int, default=0, metavar="N",
                   help="force an N-device virtual CPU mesh (the "
                        "reference's local[N]; 0 = use real devices)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="write checkpoints here (enables --resume)")
    p.add_argument("--resume", default=None, metavar="DIR",
                   help="resume from a checkpoint directory")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="write a jax.profiler trace of the training "
                        "run there (view with TensorBoard)")
    p.add_argument("--seed", type=int, default=0)
    return p


def add_data_option(p: argparse.ArgumentParser,
                    required=("features", "label")):
    """Opt-in ``--data-npz`` for scripts that honor it via
    ``load_dataset`` (only those — a flag every script parses but most
    ignore would silently train on synthetic data).  ``required`` names
    the archive columns, single-sourced: it feeds both the help text
    and ``load_dataset``'s validation (via a parser default)."""
    p.set_defaults(_npz_required=tuple(required))
    p.add_argument("--data-npz", default=None, metavar="FILE",
                   help="train on real data from an .npz archive "
                        "instead of synthetic: each array becomes a "
                        f"Dataset column (needs {list(required)})")
    return p


def load_dataset(args, synth_fn, required=None, shuffle_seed=None):
    """The example's dataset: ``--data-npz FILE`` (real data, no egress
    needed — any locally produced archive works) or the config's
    synthetic fallback ``synth_fn()``.  Real archives are shuffled
    (seeded) so ordered rows — e.g. grouped by class — don't skew
    contiguous train/holdout splits.  ``required`` defaults to what
    ``add_data_option`` registered; pass it explicitly only when the
    real requirement depends on other args."""
    if args.data_npz is None:
        return synth_fn()
    if required is None:
        required = getattr(args, "_npz_required",
                           ("features", "label"))
    import numpy as np

    from distkeras_tpu.data.dataset import Dataset

    with np.load(args.data_npz) as archive:
        columns = {k: np.asarray(archive[k]) for k in archive.files}
    missing = [c for c in required if c not in columns]
    if missing:
        raise SystemExit(
            f"--data-npz {args.data_npz}: missing required "
            f"column(s) {missing}; found {sorted(columns)}")
    print(f"[data] loaded {args.data_npz}: "
          + ", ".join(f"{k}{tuple(v.shape)}"
                      for k, v in sorted(columns.items())))
    return Dataset(columns).shuffle(
        seed=args.seed if shuffle_seed is None else shuffle_seed)


def parse_args_and_setup(parser: argparse.ArgumentParser):
    """Parse args and, if requested, force a virtual CPU mesh.

    Must run before any jax *backend* is initialized (first device use),
    which holds as long as it is called before distkeras_tpu imports —
    XLA_FLAGS are read at backend init, and the platform pin is a
    jax.config update (same recipe as ``__graft_entry__._force_cpu_mesh``;
    env vars alone are ignored because the container's sitecustomize
    already imported jax).
    """
    args = parser.parse_args()
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        n = len(jax.devices())
        if n != args.devices:
            raise RuntimeError(
                f"--devices {args.devices} requested but the jax backend "
                f"was already initialized with {n} devices")
    return args


def report(config_name: str, trainer, metrics: dict, **extra) -> None:
    """Print the run summary: human-readable lines + one JSON line."""
    print(f"[{config_name}] trained in {trainer.training_time:.2f}s")
    losses = trainer.history.get("epoch_loss", [])
    if losses:
        print(f"[{config_name}] epoch loss: "
              + " -> ".join(f"{x:.4f}" for x in losses))
    for k, v in metrics.items():
        print(f"[{config_name}] {k}: {v:.4f}")
    summary = {
        "config": config_name,
        "training_time_s": round(trainer.training_time, 3),
        "epoch_loss": [round(float(x), 5) for x in losses],
        **{k: round(float(v), 5) for k, v in metrics.items()},
        **extra,
    }
    print(json.dumps(summary))


def timed(label: str):
    """Context manager printing wall time of a pipeline stage."""

    class _Timer:
        def __enter__(self):
            self.t0 = time.time()
            return self

        def __exit__(self, *exc):
            print(f"[{label}] {time.time() - self.t0:.2f}s")

    return _Timer()


def resolve_platform_defaults(args, **tiers):
    """Fill ``None``-defaulted size knobs per backend: each kwarg is
    ``attr=(cpu_value, other_value)``.  Conv demos need smaller CPU
    sizes — XLA:CPU lowers the PS round's batched-parameter convs
    through a very slow grouped-conv path, while the same program is
    faster than sequential stepping on TPU (PERF.md §10).  Call after
    ``parse_args_and_setup`` (the backend pin must land first)."""
    import jax

    on_cpu = jax.default_backend() == "cpu"
    for name, (cpu_value, other_value) in tiers.items():
        if getattr(args, name) is None:
            setattr(args, name, cpu_value if on_cpu else other_value)

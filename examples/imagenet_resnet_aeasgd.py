"""ImageNet ResNet + AEASGD — BASELINE.md row 3 (the flagship config).

Pipeline: synthetic ImageNet-shaped data -> AEASGD (elastic averaging)
over a worker mesh -> predict -> accuracy.  Defaults are scaled down
(ResNet-18 at 32px, 10 classes) so the example finishes in seconds on
CPU; ``--image-size 224 --num-classes 1000 --resnet 50`` is the real
flagship shape for a TPU chip.

Run:  python examples/imagenet_resnet_aeasgd.py --devices 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report,
                     resolve_platform_defaults)


def main():
    parser = make_parser(__doc__, rows=256, epochs=None, batch_size=4,
                         workers=8, window=2, learning_rate=0.02)
    parser.add_argument("--image-size", type=int, default=32)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--resnet", type=int, choices=(18, 50),
                        default=18)
    parser.add_argument("--rho", type=float, default=2.5,
                        help="elastic force (alpha = lr * rho)")
    parser.add_argument("--fidelity", choices=("faithful", "fast"),
                        default="faithful")
    add_data_option(parser)
    args = parse_args_and_setup(parser)
    resolve_platform_defaults(args, epochs=(1, 2))

    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import AEASGD

    data = load_dataset(
        args,
        lambda: datasets.imagenet_synth(
            args.rows, image_size=args.image_size,
            num_classes=args.num_classes, seed=args.seed + 2))
    stages = (2, 2, 2, 2) if args.resnet == 18 else (3, 4, 6, 3)
    cfg = model_config("resnet",
                       (args.image_size, args.image_size, 3),
                       num_classes=args.num_classes,
                       stage_sizes=stages,
                       bottleneck=args.resnet == 50, dtype="float32")
    trainer = AEASGD(cfg, num_workers=args.workers,
                     communication_window=args.window,
                     batch_size=args.batch_size, num_epoch=args.epochs,
                     rho=args.rho, learning_rate=args.learning_rate,
                     fidelity=args.fidelity, seed=args.seed,
                     checkpoint_dir=args.checkpoint_dir,
                     profile_dir=args.profile_dir)
    variables = trainer.train(data, resume_from=args.resume)
    metrics = evaluate_model(trainer.model, variables, data,
                             batch_size=64)
    report(f"imagenet_resnet{args.resnet}_aeasgd", trainer, metrics,
           image_size=args.image_size, fidelity=args.fidelity)


if __name__ == "__main__":
    main()

"""IMDB BiLSTM + DynSGD — BASELINE.md row 4.

Pipeline: synthetic token sequences -> BiLSTM classifier trained with
DynSGD (staleness-scaled commits) -> predict -> accuracy.

Run:  python examples/imdb_bilstm_dynsgd.py --devices 8
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from _common import (add_data_option, load_dataset,
                     make_parser, parse_args_and_setup, report)


def main():
    parser = make_parser(__doc__, rows=2048, epochs=3, batch_size=16,
                         workers=4, window=2, learning_rate=0.01)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--vocab-size", type=int, default=200)
    add_data_option(parser)
    args = parse_args_and_setup(parser)

    from distkeras_tpu.data import datasets
    from distkeras_tpu.evaluators import evaluate_model
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DynSGD

    data = load_dataset(
        args,
        lambda: datasets.imdb_synth(
            args.rows, seq_len=args.seq_len,
            vocab_size=args.vocab_size, seed=args.seed + 3))
    cfg = model_config("bilstm", (args.seq_len,), input_dtype="int32",
                       vocab_size=args.vocab_size, embed_dim=16,
                       hidden_dim=16, num_classes=2)
    trainer = DynSGD(cfg, num_workers=args.workers,
                     communication_window=args.window,
                     batch_size=args.batch_size, num_epoch=args.epochs,
                     learning_rate=args.learning_rate,
                     worker_optimizer="adam", seed=args.seed,
                     checkpoint_dir=args.checkpoint_dir,
                     profile_dir=args.profile_dir)
    variables = trainer.train(data, resume_from=args.resume)
    metrics = evaluate_model(trainer.model, variables, data,
                             batch_size=256)
    report("imdb_bilstm_dynsgd", trainer, metrics,
           seq_len=args.seq_len)


if __name__ == "__main__":
    main()

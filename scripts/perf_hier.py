"""Hierarchical-PS A/B: flat single-root socket PS vs GroupLeader
tree (ISSUE 20).

The single-root socket PS is the reference's known scalability
ceiling (PERF.md §12): every worker commit lands on one server, so
root load grows linearly with W.  ``parallel.hier_ps`` puts a
``GroupLeader`` in front of every g workers; the leader folds their
windows with the rule's closed-form combination and forwards ONE
upstream commit — the fold is the SAME SIZE as a single delta, so
root message count AND root bytes both drop exactly g×.

Part 1 — byte-exact parity + fan-in accounting: the same seeded
dyadic commit schedule through both topologies over real sockets;
asserts the final centers are byte-identical, the root applied every
logical commit, and the root saw exactly W/g upstream messages per
round carrying g× fewer bytes.

Part 2 — root-bound throughput A/B: on one box both arms share the
same cores, so the fan-in win is surfaced by modeling what the
hierarchy actually relieves — the root's fixed link capacity (the
§12 ceiling).  A shared serial token link charges every root-hop
message its actual packed in+out bytes at a fixed byte rate,
identically in both arms; the leader hop runs unthrottled.  Flat
pushes W×rounds messages through that link, hierarchical W/g — the
measured aggregate commit throughput ratio is the fan-in reduction
made visible.  The unthrottled wall-clock ratio is reported
alongside (informational: with leaders and root sharing one CPU the
extra fold tier costs, not saves; the hierarchy pays off where root
capacity, not worker CPU, binds).

``--smoke`` (tier-1 via test_examples.py SMOKE_SCRIPTS) runs the
W=16, g=4 cell, asserts parity / exact fan-in / ≥2× root-bound
throughput, and gates the numbers through ``perf_regress`` (pass +
forced breach in both directions), emitting trajectory-format
BENCH records:
    JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
        python scripts/perf_hier.py --smoke
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import numpy as np

import perf_regress


def _dyadic_center(leaves=6, dim=64, seed=0):
    """Dyadic-rational center: every leaf a multiple of 2^-6, well
    inside f32's 24-bit mantissa, so float addition is EXACT in any
    association order — the flat-vs-hier byte-identity assert tests
    the topology, not float reassociation."""
    rng = np.random.default_rng(seed)
    return {f"w{i}": (rng.integers(-512, 512, size=(dim, dim))
                      * 2.0 ** -6).astype(np.float32)
            for i in range(leaves)}


def _dyadic_delta(center, w, r):
    val = np.float32((((w * 7 + r) % 13) - 6) * 2.0 ** -6)
    return {k: np.full_like(v, val) for k, v in center.items()}


class _RootLink:
    """The root's modeled fixed-capacity serial link: a shared lock
    (one message at a time — a link, not a thread pool) charging
    actual bytes at ``bytes_per_s``.  Byte/message totals double as
    the fan-in accounting."""

    def __init__(self, bytes_per_s: float | None):
        self.bytes_per_s = bytes_per_s
        self._lock = threading.Lock()
        self.nbytes = 0
        self.msgs = 0

    def charge(self, nbytes: int) -> None:
        with self._lock:
            self.nbytes += nbytes
            self.msgs += 1
            if self.bytes_per_s:
                time.sleep(nbytes / self.bytes_per_s)


class _RootThrottled:
    """PS proxy metering the root hop: every commit-path message
    (payload/fold in + center reply out) crosses the shared link
    before the real server applies it.  Identical in both arms —
    only the MESSAGE COUNT differs by topology."""

    def __init__(self, ps, link: _RootLink, msg_bytes: int):
        self._ps = ps
        self._link = link
        self._msg_bytes = msg_bytes

    def __getattr__(self, name):
        return getattr(self._ps, name)

    def commit(self, worker_id, payload, local=None, seq=None):
        self._link.charge(self._msg_bytes)
        return self._ps.commit(worker_id, payload, local, seq=seq)

    def commit_packed(self, worker_id, payload, local=None, seq=None):
        # the socket handler prefers this path — meter it too, or the
        # flat arm would bypass the link entirely
        self._link.charge(self._msg_bytes)
        return self._ps.commit_packed(worker_id, payload, local,
                                      seq=seq)

    def commit_group(self, leader_id, fold, staleness, workers,
                     seq=None):
        # the leader's fold is the same packed size as one delta
        self._link.charge(self._msg_bytes)
        return self._ps.commit_group(leader_id, fold, staleness,
                                     workers, seq=seq)


def _hammer(center, addresses, rounds):
    """W socket workers (one per address entry), each pull + the
    seeded dyadic commit schedule; returns commits/sec."""
    from distkeras_tpu.parallel.host_ps import PSClient

    workers = len(addresses)
    barrier = threading.Barrier(workers + 1)
    errs = []

    def worker(w):
        try:
            client = PSClient(*addresses[w], w, center)
            client.pull()
            barrier.wait()
            for r in range(rounds):
                client.commit(_dyadic_delta(center, w, r), seq=r)
            client.close()
        except Exception as e:  # surfaced after join
            errs.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(workers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errs:
        raise errs[0]
    return workers * rounds / dt, dt


def _msg_bytes(center) -> int:
    """Packed in+out bytes of one root message (delta up, center
    reply down — identical trees, identical size)."""
    from distkeras_tpu.parallel.host_ps import pack_params

    return 2 * len(pack_params(center))


def run_flat(center, workers, rounds, link):
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    ps = HostParameterServer(DownpourRule(), center)
    server = PSServer(_RootThrottled(ps, link, _msg_bytes(center)),
                      center).start()
    cps, dt = _hammer(center, [server.address] * workers, rounds)
    final = {k: np.asarray(v).copy() for k, v in ps.center.items()}
    commits = ps.num_commits
    server.stop()
    return {"commits_per_sec": cps, "seconds": dt, "center": final,
            "root_commits": commits, "root_msgs": link.msgs,
            "root_bytes": link.nbytes}


def run_hier(center, workers, groups, rounds, link):
    from distkeras_tpu.parallel.hier_ps import (GroupLeader,
                                                HierPSServer)
    from distkeras_tpu.parallel.host_ps import HostParameterServer
    from distkeras_tpu.parallel.update_rules import DownpourRule

    g = workers // groups
    ps = HostParameterServer(DownpourRule(), center)
    root = HierPSServer(_RootThrottled(ps, link, _msg_bytes(center)),
                        center).start()
    leaders = [GroupLeader(DownpourRule(), center, root.address,
                           group_id=gi, aggregate_window=g).start()
               for gi in range(groups)]
    addrs = [leaders[w // g].address for w in range(workers)]
    cps, dt = _hammer(center, addrs, rounds)
    for lead in leaders:
        lead.drain()
        lead.stop()
    final = {k: np.asarray(v).copy() for k, v in ps.center.items()}
    out = {"commits_per_sec": cps, "seconds": dt, "center": final,
           "root_commits": ps.num_commits, "root_msgs": link.msgs,
           "root_bytes": link.nbytes,
           "upstream_commits": sum(l.num_upstream for l in leaders),
           "folded_commits": sum(l.num_commits for l in leaders)}
    root.stop()
    return out


def ab_cell(center, workers, groups, rounds, link_bytes_per_s):
    """One A/B cell through both topologies; parity + fan-in checks
    are structural, so every cell asserts them."""
    g = workers // groups
    flat_link = _RootLink(link_bytes_per_s)
    hier_link = _RootLink(link_bytes_per_s)
    flat = run_flat(center, workers, rounds, flat_link)
    hier = run_hier(center, workers, groups, rounds, hier_link)

    # byte-exact parity: same seeded schedule, dyadic values — any
    # difference is a topology bug, not float reassociation
    for k in center:
        assert (flat["center"][k].tobytes()
                == hier["center"][k].tobytes()), (
            f"flat/hier centers diverge at leaf {k!r}")
    total = workers * rounds
    assert flat["root_commits"] == hier["root_commits"] == total, (
        flat["root_commits"], hier["root_commits"], total)
    assert hier["upstream_commits"] == hier["root_msgs"] == total // g
    assert hier["folded_commits"] == total
    # the fold is one delta wide: bytes drop exactly g× with messages
    assert flat["root_bytes"] == g * hier["root_bytes"], (
        flat["root_bytes"], hier["root_bytes"], g)

    return {
        "bench": "hier_ab", "workers": workers, "groups": groups,
        "group_size": g, "rounds": rounds,
        "link_mb_per_s": (round(link_bytes_per_s / 1e6, 1)
                          if link_bytes_per_s else None),
        "flat_commits_per_sec": round(flat["commits_per_sec"], 1),
        "hier_commits_per_sec": round(hier["commits_per_sec"], 1),
        "speedup": round(hier["commits_per_sec"]
                         / flat["commits_per_sec"], 2),
        "root_msgs_flat": flat["root_msgs"],
        "root_msgs_hier": hier["root_msgs"],
        "fanin_reduction": flat["root_msgs"] / hier["root_msgs"],
        "root_mb_flat": round(flat["root_bytes"] / 1e6, 2),
        "root_mb_hier": round(hier["root_bytes"] / 1e6, 2),
        "hier_seconds": hier["seconds"],
    }


def full(rounds=8):
    center = _dyadic_center(leaves=8, dim=128)
    for link in (None, 50e6, 10e6):
        for groups in (2, 4, 8):
            row = ab_cell(center, workers=16, groups=groups,
                          rounds=rounds, link_bytes_per_s=link)
            print(json.dumps(row), flush=True)


def smoke(out_dir: str | None = None):
    """Seconds-scale W=16/g=4 cell with the full assertion set +
    perf_regress gate (tier-1)."""
    from distkeras_tpu import telemetry

    tmp = None
    if out_dir is None:
        tmp = tempfile.TemporaryDirectory()
        out_dir = tmp.name
    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    center = _dyadic_center(leaves=6, dim=64)

    # informational: same cell unthrottled (one shared CPU — the
    # extra fold tier costs; the fan-in win is a root-capacity story)
    raw = ab_cell(center, workers=16, groups=4, rounds=4,
                  link_bytes_per_s=None)
    print(json.dumps({**raw, "bench": "hier_ab_unthrottled"}),
          flush=True)

    tel = telemetry.enable()
    # the measured claim: root link at a fixed byte rate, W=16 g=4 —
    # hierarchical aggregate commit throughput ≥ 2× flat
    row = ab_cell(center, workers=16, groups=4, rounds=4,
                  link_bytes_per_s=8e6)
    print(json.dumps(row), flush=True)
    assert row["fanin_reduction"] == 4.0, row
    assert row["speedup"] >= 2.0, (
        f"root-bound hierarchical speedup {row['speedup']} < 2.0")

    snap_path = out / "registry.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    telemetry.disable()

    # ---- perf_regress hookup: upstream commit rate from the live
    # registry, the A/B speedup (higher is better), and root bytes
    # per logical commit (lower is better)
    cands = perf_regress.from_registry(
        str(snap_path), "hier_upstream_commits_per_sec",
        "ps_upstream_commits_total", row["hier_seconds"])
    assert cands[0]["value"] > 0, cands
    cands.append({"metric": "hier_speedup_vs_flat",
                  "value": row["speedup"], "unit": "x"})
    lower = [{"metric": "hier_root_bytes_per_commit",
              "value": row["root_mb_hier"] * 1e6 / (16 * 4),
              "unit": "bytes"}]
    for i, c in enumerate(cands + lower):
        for n in (1, 2, 3):  # synthetic trajectory from this run
            (out / f"BENCH_hier{i}_r{n:02d}.json").write_text(
                json.dumps({
                    "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                    "parsed": {"metric": c["metric"],
                               "value": c["value"] * (1 + 0.02 * n),
                               "unit": c.get("unit", "per_sec")}}))
    traj = perf_regress.load_trajectories(
        str(out / "BENCH_hier*.json"))
    rows = (perf_regress.evaluate(cands, traj, tolerance=0.5)
            + perf_regress.evaluate(lower, traj, tolerance=0.5,
                                    lower_is_better=True))
    print(perf_regress.render(rows))
    assert all(r["status"] == "pass" for r in rows), rows
    # forced breach, both directions: a collapsed rate and ballooned
    # root bytes must each trip the gate
    bad_hi = perf_regress.evaluate(
        [{"metric": cands[0]["metric"],
          "value": cands[0]["value"] / 10.0}], traj, tolerance=0.5)
    assert bad_hi[0]["status"] == "breach", bad_hi
    bad_lo = perf_regress.evaluate(
        [{"metric": lower[0]["metric"],
          "value": lower[0]["value"] * 10.0}], traj, tolerance=0.5,
        lower_is_better=True)
    assert bad_lo[0]["status"] == "breach", bad_lo
    print(json.dumps({"smoke": "ok"}), flush=True)
    if tmp is not None:
        tmp.cleanup()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="directory for the gate's BENCH records "
                         "(smoke; default: a temp dir)")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale W=16/g=4 parity + fan-in + "
                         "root-bound throughput gate (tier-1)")
    args = ap.parse_args()
    if args.smoke:
        smoke(args.out)
        return
    full(rounds=args.rounds)


if __name__ == "__main__":
    main()

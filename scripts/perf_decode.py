"""Serving throughput for the LM family: KV-cache autoregressive
decode (PERF.md §18).

Measures the two numbers that characterize the serving path on one
chip for a GPT-2-small-shaped ``TransformerLM``:

- **prefill**: one forward over the prompt that fills every layer's
  KV cache (compute-bound, ~the training forward);
- **decode**: per-token latency of the T=1 cached step inside
  ``lax.scan`` (bandwidth-bound: every weight is read per token), and
  the resulting tokens/s at the given batch.

Usage:  PYTHONPATH=/root/repo python scripts/perf_decode.py
        [--layers 12 --d-model 768 --prompt 512 --new 128 --batch 8]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.profiling import host_sync, peak_flops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--prompt", type=int, default=512)
    ap.add_argument("--new-lo", type=int, default=32)
    ap.add_argument("--new-hi", type=int, default=160)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="GQA: number of K/V heads (divides --heads); "
                         "shrinks the per-token KV-cache read by the "
                         "group factor (PERF.md §18 addendum)")
    ap.add_argument("--kv-dtype", default=None,
                    choices=["int8"],
                    help="int8: quantized KV cache (halves the bf16 "
                         "cache's per-token HBM traffic)")
    ap.add_argument("--attn", default="auto",
                    choices=["auto", "dense", "blockwise", "flash"],
                    help="prefill attention spelling (decode keeps "
                         "it for 128-aligned prompt chunks)")
    ap.add_argument("--prompt-lo", type=int, default=None,
                    help="with --prompt-hi: measure PREFILL marginal "
                         "cost by differencing two prompt lengths at "
                         "fixed new tokens (the §18 flash-prefill "
                         "row); skips the decode measurement")
    ap.add_argument("--prompt-hi", type=int, default=None)
    args = ap.parse_args()

    from distkeras_tpu.models import ModelSpec, generate, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype=args.dtype, attn=args.attn,
        num_kv_heads=args.kv_heads, kv_cache_dtype=args.kv_dtype)
    model = ModelSpec.from_config(spec).build()
    tokens = jnp.zeros((args.batch, args.max_len), jnp.int32)
    variables = model.init(jax.random.key(0), tokens[:, :8])
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(variables["params"]))
    prompt = jax.random.randint(jax.random.key(1),
                                (args.batch, args.prompt), 0,
                                args.vocab)

    if args.prompt_lo is not None or args.prompt_hi is not None:
        if not (args.prompt_lo and args.prompt_hi):
            raise SystemExit("--prompt-lo and --prompt-hi go together")
        # prefill marginal cost: t(prompt_hi) - t(prompt_lo) at fixed
        # new tokens — the tunnel round-trip and the decode tail
        # cancel, leaving the prefill cost of the extra tokens.  With
        # --attn flash/auto the 128-aligned prompt runs the Pallas
        # kernels; --attn dense is the round-4 O(T·max_len) cache read.
        def timed_prompt(t_len):
            p = jax.random.randint(jax.random.key(1),
                                   (args.batch, t_len), 0, args.vocab)
            f = jax.jit(lambda v, p: generate(model, v, p,
                                              max_new_tokens=8))
            host_sync(f(variables, p))
            t0 = time.perf_counter()
            for _ in range(args.reps):
                host_sync(f(variables, p))
            return (time.perf_counter() - t0) / args.reps

        t_lo = timed_prompt(args.prompt_lo)
        t_hi = timed_prompt(args.prompt_hi)
        extra = args.prompt_hi - args.prompt_lo
        print(json.dumps({
            "metric": "lm_prefill_marginal",
            "attn": args.attn,
            "model": f"lm L{args.layers} d{args.d_model} b{args.batch}",
            "prompt_lo": args.prompt_lo, "prompt_hi": args.prompt_hi,
            "prefill_ms_for_extra": round((t_hi - t_lo) * 1e3, 2),
            "prefill_us_per_token": round(
                (t_hi - t_lo) / extra / args.batch * 1e6, 2),
            "t_lo_ms": round(t_lo * 1e3, 2),
            "t_hi_ms": round(t_hi * 1e3, 2),
        }))
        return

    # Per-token decode cost by DIFFERENCING two generation lengths:
    # t(new_hi) - t(new_lo) cancels the prompt prefill AND the
    # tunnel's per-dispatch round-trip (~140 ms on this rig — it
    # swamps any absolute latency number, so no prefill/total latency
    # is reported; only the differenced per-token cost is meaningful
    # through the tunnel).  host_sync, not block_until_ready: the
    # tunneled platform can return from block_until_ready before
    # execution finishes (see profiling.host_sync).
    def timed(n_new):
        f = jax.jit(lambda v, p: generate(model, v, p,
                                          max_new_tokens=n_new))
        host_sync(f(variables, prompt))
        t0 = time.perf_counter()
        for _ in range(args.reps):
            host_sync(f(variables, prompt))
        return (time.perf_counter() - t0) / args.reps

    t_lo = timed(args.new_lo)
    t_hi = timed(args.new_hi)
    per_tok = (t_hi - t_lo) / (args.new_hi - args.new_lo)
    # decode is bandwidth-bound: each token reads every parameter once
    # (f32 param storage; compute casts to the model dtype)
    hbm_gbs = n_params * 4 / per_tok / 1e9
    peak, known = peak_flops(jax.devices()[0])
    print(json.dumps({
        "model": f"lm L{args.layers} d{args.d_model} "
                 f"prompt{args.prompt} new{args.new_lo}->"
                 f"{args.new_hi} b{args.batch}",
        "params_m": round(n_params / 1e6, 1),
        "per_token_ms": round(per_tok * 1e3, 3),
        "decode_tokens_per_sec": round(args.batch / per_tok, 1),
        "weight_read_gb_per_sec": round(hbm_gbs, 1),
        "mfu_decode": (round(2.0 * n_params * args.batch / per_tok
                             / peak, 4) if known else None),
        "t_lo_ms": round(t_lo * 1e3, 2),
        "t_hi_ms": round(t_hi * 1e3, 2),
    }))


if __name__ == "__main__":
    main()

"""Render PARITY.png from parity.json — the rebuild's version of the
reference README's convergence plots (SURVEY.md §6: the reference
published plots, not numbers; here both exist).

Run after scripts/parity.py:  python scripts/plot_parity.py
"""

from __future__ import annotations

import json
import pathlib

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent


def main():
    payload = json.loads((REPO / "parity.json").read_text())
    results = payload["results"]

    fig, (ax_loss, ax_acc) = plt.subplots(
        1, 2, figsize=(11, 4.2), gridspec_kw={"width_ratios": [3, 2]})

    for r in results:
        curve = r["loss_curve"]
        # per-round curves for async trainers, per-epoch for sync:
        # normalize the x axis to fraction of the training budget
        xs = [i / max(len(curve) - 1, 1) for i in range(len(curve))]
        style = "--" if "host" in r["trainer"] else "-"
        width = 2.4 if r["trainer"] == "SyncTrainer" else 1.4
        ax_loss.plot(xs, curve, style, linewidth=width,
                     label=r["trainer"])
    ax_loss.set_xlabel("fraction of training budget")
    ax_loss.set_ylabel("training loss")
    ax_loss.set_title("async PS family vs the synchronous control arm")
    ax_loss.legend(fontsize=7.5)
    ax_loss.grid(alpha=0.3)

    names = [r["trainer"] for r in results]
    accs = [r["accuracy"] for r in results]
    bars = ax_acc.barh(range(len(names)), accs, color=[
        "#444444" if n == "SyncTrainer" else
        "#2a6fb0" if "host" not in n else "#7fb02a" for n in names])
    ax_acc.set_yticks(range(len(names)), names, fontsize=7.5)
    ax_acc.invert_yaxis()
    ax_acc.set_xlim(0, 1)
    ax_acc.set_xlabel("eval accuracy (same budget)")
    ax_acc.grid(axis="x", alpha=0.3)
    for bar, acc in zip(bars, accs):
        ax_acc.text(acc + 0.01, bar.get_y() + bar.get_height() / 2,
                    f"{acc:.3f}", va="center", fontsize=7)

    fig.tight_layout()
    out = REPO / "PARITY.png"
    fig.savefig(out, dpi=130)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()

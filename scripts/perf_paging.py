"""Block-paged KV vs envelope pools A/B (ISSUE 13): two questions at
one fixed device-byte budget.

1. **Capacity A/B** — a heavy-tailed workload (mostly short requests,
   a few near-envelope ones) through two engine arms holding the SAME
   KV byte budget: ``envelope`` (budget // bytes-per-envelope-slot
   slots, every request billed for the full envelope) and ``paged``
   (budget // bytes-per-page pages, requests billed per page actually
   touched).  Reports the peak number of simultaneously live slots
   each arm sustains (sampled from the ``serving_slot_occupancy``
   gauge between steps), goodput, and asserts both arms' greedy
   tokens are byte-identical — the paged lowering gathers pages into
   the exact envelope layout and runs the unchanged legacy programs,
   so parity is structural.
2. **QoS drill** — a low-priority decode flood saturates each arm,
   then one high-priority interactive tenant submits.  On the
   envelope arm the request waits FIFO for a slot to drain; on the
   paged arm the QoS scheduler admits it next sweep (preempting a
   low-priority victim's pages if the pool is exhausted).  Reports
   the interactive TTFT p95 per arm over repeats.
3. **Gate** — ``serving_pages_allocated_per_sec`` is synthesized from
   the live registry (``from_registry``) and fed through
   ``scripts/perf_regress.py`` together with the paged arm's peak
   concurrency and goodput — against the repo's ``BENCH_*.json``
   trajectories normally, or a synthetic trajectory from this very
   run in ``--smoke`` (where the gate must pass and the ISSUE 13
   acceptance criteria are asserted: strictly more concurrent slots
   at the fixed budget, byte-identical tokens, and a lower
   interactive TTFT p95 than the flooded envelope arm).

Usage:  PYTHONPATH=/root/repo python scripts/perf_paging.py
        [--smoke] [--budget-slots 4] [--page-size 16]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress


def _build_model(args):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype=args.dtype)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))
    return model, variables


def _engine(model, variables, args, **kw):
    from distkeras_tpu.serving import DecodeEngine

    kw.setdefault("buckets", [args.env])
    kw.setdefault("prefill_align", args.page_size)
    return DecodeEngine(model, variables, **kw)


def kv_slot_bytes(model, variables, args):
    """Bytes one envelope slot's KV cache occupies, measured off a
    1-slot probe engine's actual device pool (not estimated)."""
    import jax

    with _engine(model, variables, args, slots=1) as probe:
        pool = probe._pools[0]
        return sum(x.nbytes for x in jax.tree_util.tree_leaves(
            pool.cache) if getattr(x, "ndim", 0) == 4)


def build_workload(args):
    """Heavy-tailed: ``--requests`` prompts, ``--long-frac`` of them
    near the envelope, the rest short — the traffic shape where
    per-page billing beats per-envelope billing."""
    rng = np.random.default_rng(args.seed)
    n_long = max(1, int(args.requests * args.long_frac))
    stride = max(1, args.requests // n_long)
    work = []
    for i in range(args.requests):
        if i % stride == 0:
            t = int(rng.integers(args.env * 5 // 8, args.env * 3 // 4))
            n_new = args.new_long
        else:
            t = int(rng.integers(args.short_lo, args.short_hi + 1))
            n_new = args.new_short
        prompt = rng.integers(0, args.vocab, (t,)).astype(np.int32)
        work.append({"prompt": prompt, "max_new_tokens": n_new,
                     "i": i})
    return work


def run_capacity_arm(model, variables, work, args, tel, *, paged,
                     slots, kv_pages=None):
    """Warm pass (compiles), then the timed pass with the peak
    slot-occupancy sampled between steps."""
    kw = {"slots": slots}
    if paged:
        kw["kv_pages"] = kv_pages
    with _engine(model, variables, args, **kw) as eng:
        list(eng.run(work))  # warm: every program in the set
        occ = tel.metrics.gauge("serving_slot_occupancy",
                                bucket=args.env)
        peak, results = 0, {}
        t0 = time.perf_counter()
        for w in work:
            eng.submit(w["prompt"],
                       max_new_tokens=w["max_new_tokens"],
                       meta={"i": w["i"]})
        while eng.has_work():
            for r in eng.step():
                assert r.get("error") is None, r
                results[r["i"]] = r
            peak = max(peak, int(occ.value))
        wall = time.perf_counter() - t0
    toks = sum(w["max_new_tokens"] for w in work)
    report = {"paged": paged, "slots": slots, "kv_pages": kv_pages,
              "peak_concurrent_slots": peak,
              "wall_s": round(wall, 4),
              "goodput_tok_s": round(toks / wall, 1)}
    return report, results


def run_qos_arm(model, variables, args, *, paged):
    """Interactive TTFT under a low-priority flood: best-of-repeats
    p95 (one warm drill first; the floor is the structural cost)."""
    rng = np.random.default_rng(args.seed + 1)
    flood = [rng.integers(0, args.vocab, (args.short_hi,))
             .astype(np.int32) for _ in range(args.flood)]
    hi = rng.integers(0, args.vocab, (args.short_lo,)).astype(np.int32)
    kw = ({"slots": args.flood, "kv_pages": args.kv_pages,
           "preemption": "swap"} if paged
          else {"slots": args.budget_slots})
    ttfts = []
    with _engine(model, variables, args, **kw) as eng:
        for rep in range(args.drill_repeats + 1):
            for j, p in enumerate(flood):
                eng.submit(p, max_new_tokens=args.new_long,
                           priority=0, meta={"i": f"lo{rep}.{j}"})
            list(eng.step())  # flood admitted and decoding
            eng.submit(hi, max_new_tokens=args.new_short, priority=2,
                       tenant="interactive", meta={"i": "hi"})
            got = None
            while eng.has_work():
                for r in eng.step():
                    assert r.get("error") is None, r
                    if r["i"] == "hi":
                        got = r
            if rep > 0:  # warm drill: compile time pollutes TTFT
                ttfts.append(got["ttft"])
    return {"paged": paged,
            "interactive_ttft_p95_s": round(
                float(np.percentile(ttfts, 95)), 5),
            "interactive_ttft_best_s": round(min(ttfts), 5)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + the ISSUE 13 acceptance "
                         "assertions (the tier-1 registration)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--env", type=int, default=256,
                    help="bucket envelope (tokens)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--budget-slots", type=int, default=4,
                    help="KV byte budget, expressed as this many "
                         "envelope slots; both arms get exactly it")
    ap.add_argument("--paged-slot-cap", type=int, default=16,
                    help="table rows on the paged arm (live decode "
                         "lanes; pages are the real constraint)")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--long-frac", type=float, default=0.125)
    ap.add_argument("--short-lo", type=int, default=8)
    ap.add_argument("--short-hi", type=int, default=24)
    ap.add_argument("--new-short", type=int, default=8)
    ap.add_argument("--new-long", type=int, default=24)
    ap.add_argument("--flood", type=int, default=8)
    ap.add_argument("--drill-repeats", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="perf_regress gate slack")
    args = ap.parse_args()

    if args.smoke:
        # small enough for CPU CI, shaped so the heavy tail leaves
        # most of the envelope budget idle (the paged arm's win)
        args.layers, args.d_model, args.heads = 2, 128, 4
        args.vocab, args.max_len, args.env = 64, 64, 64
        args.page_size, args.budget_slots = 8, 3
        args.paged_slot_cap = 12
        args.requests, args.long_frac = 16, 0.125
        args.short_lo, args.short_hi = 5, 9
        args.new_short, args.new_long = 4, 16
        args.flood, args.drill_repeats = 6, 3

    out_dir = pathlib.Path(args.out_dir
                           or tempfile.mkdtemp(prefix="dkt_page_"))
    out_dir.mkdir(parents=True, exist_ok=True)

    from distkeras_tpu import flight_recorder, telemetry

    tel = telemetry.enable()
    flight_recorder.start(out_dir / "fdr")
    model, variables = _build_model(args)
    work = build_workload(args)

    env_bytes = kv_slot_bytes(model, variables, args)
    page_bytes = env_bytes * args.page_size // args.env
    budget = args.budget_slots * env_bytes
    args.kv_pages = budget // page_bytes
    out = {"metric": "paged_kv_qos_ab",
           "model": f"lm L{args.layers} d{args.d_model}",
           "env": args.env, "page_size": args.page_size,
           "budget_bytes": int(budget),
           "env_slot_bytes": int(env_bytes),
           "page_bytes": int(page_bytes),
           "arms": {}}

    t_run0 = time.perf_counter()
    out["arms"]["envelope"], tok_env = run_capacity_arm(
        model, variables, work, args, tel, paged=False,
        slots=args.budget_slots)
    out["arms"]["paged"], tok_pag = run_capacity_arm(
        model, variables, work, args, tel, paged=True,
        slots=args.paged_slot_cap, kv_pages=args.kv_pages)
    run_seconds = time.perf_counter() - t_run0

    # the lowering must be INVISIBLE: byte-identical greedy tokens
    for i in sorted(tok_env):
        np.testing.assert_array_equal(
            tok_pag[i]["tokens"], tok_env[i]["tokens"],
            err_msg=f"request {i}")
    out["parity"] = "byte_identical"
    out["slot_gain"] = round(
        out["arms"]["paged"]["peak_concurrent_slots"]
        / max(out["arms"]["envelope"]["peak_concurrent_slots"], 1), 2)

    out["qos"] = {
        "envelope": run_qos_arm(model, variables, args, paged=False),
        "paged": run_qos_arm(model, variables, args, paged=True)}

    snap_path = out_dir / "registry.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    flight_recorder.stop()
    telemetry.disable()

    # ---- the perf_regress hookup: registry counter -> rate candidate
    cands = perf_regress.from_registry(
        str(snap_path), "serving_pages_allocated_per_sec",
        "serving_pages_allocated_total", run_seconds)
    cands.append({"metric": "paged_concurrent_slots",
                  "value": out["arms"]["paged"]
                  ["peak_concurrent_slots"]})
    cands.append({"metric": "paged_goodput_tok_s",
                  "value": out["arms"]["paged"]["goodput_tok_s"]})
    if args.smoke:
        # synthetic trajectory from this very run — the gate must pass
        for i, c in enumerate(cands):
            for n in (1, 2, 3):
                (out_dir / f"BENCH_c{i}_r{n:02d}.json").write_text(
                    json.dumps({
                        "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                        "parsed": {"metric": c["metric"],
                                   "value": c["value"] * (1 + 0.02 * n),
                                   "unit": "per_sec"}}))
        baselines = str(out_dir / "BENCH_*.json")
    else:
        baselines = perf_regress.DEFAULT_BASELINES
    rows = perf_regress.evaluate(
        cands, perf_regress.load_trajectories(baselines),
        tolerance=0.5 if args.smoke else args.tolerance)
    print(perf_regress.render(rows))
    out["gate"] = [{k: r[k] for k in ("metric", "value", "status")}
                   for r in rows]

    if args.smoke:
        # acceptance: strictly more live slots at the SAME byte budget
        assert (out["arms"]["paged"]["peak_concurrent_slots"]
                > out["arms"]["envelope"]["peak_concurrent_slots"]), \
            out["arms"]
        # the envelope arm is budget-bound at exactly its slot count
        assert (out["arms"]["envelope"]["peak_concurrent_slots"]
                == args.budget_slots), out["arms"]
        # QoS: the interactive tenant's TTFT under flood beats FIFO
        assert (out["qos"]["paged"]["interactive_ttft_p95_s"]
                < out["qos"]["envelope"]["interactive_ttft_p95_s"]), \
            out["qos"]
        assert all(r["status"] == "pass" for r in rows), rows
        out["smoke"] = "ok"
    print(json.dumps(out, default=repr))


if __name__ == "__main__":
    main()

"""Disaggregated prefill/decode A/B — flood-flat inter-token latency
(ISSUE 19 tentpole proof).

Four arms, one trace (steady decode traffic from the trace generator's
default tenant + a long-prompt burst from its ``prefill_heavy``
tenant, both out of ``simulator.generate_trace``):

1. ``disagg/idle``  — steady only, through a ``PrefillDecodeRouter``
   (1 chunked-prefill replica; 2 decode replicas — one PAGED, one
   envelope, so byte parity is pinned on both engine shapes);
2. ``disagg/flood`` — steady + flood through the same topology
   (prefix stores cleared between arms, so every handoff ships);
3. ``mono/idle``    — steady only, ``ServingGateway`` over the same
   engine count of monolithic replicas (whole-prompt prefill, no
   prefix store: the flood's prefill programs interleave with every
   live slot's decode steps);
4. ``mono/flood``   — steady + flood through the monolithic gateway.

Per arm, over the STEADY tenant only: inter-token latency proxied per
request as ``(t_finish - t_first) / (n_tokens - 1)`` (first token
excluded, so queueing never pollutes it) and TTFT as ``t_first -
t_submit``.  The headline metric is

    inter_token_p99_flood_over_idle = flood p99 / idle p99

per system.  The disaggregated ratio plus its flood TTFT p99 (both
lower-is-better) and a ``kv_pages_shipped_per_sec`` rate synthesized
from the live registry counter (``perf_regress.from_registry``) are
gated through ``scripts/perf_regress.py`` — in ``--smoke`` against a
synthetic trajectory written from this very run, where the gate must
pass AND breach when each metric is degraded 10x (both gate
directions exercised end to end).

Byte parity vs ``models.generate`` is asserted for EVERY result in
EVERY arm — paged and envelope decode replicas alike, smoke or not.
The timing-win assertions (disaggregated ratio <= 1.25 while the
monolithic ratio degrades past it) only run at full shapes; at
``--smoke`` shapes timing is noise and the claim would be dishonest
(the structural claims — parity, pages shipped, zero requeues, zero
errors — still hold and are asserted).

Usage:  PYTHONPATH=/root/repo python scripts/perf_prefill_decode.py
        [--smoke] [--steady 24] [--flood 12] [--block 16]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress


def build_workload(args):
    """Steady + flood request lists out of the trace generator: one
    ``TraceSpec`` with a default tenant and a ``prefill_heavy``
    tenant, duration grown until both target counts are met."""
    from distkeras_tpu.simulator import TraceSpec, generate_trace

    duration = 8.0
    for _ in range(12):
        spec = TraceSpec(
            duration_s=duration, mean_qps=6.0, seed=args.seed,
            prompt_median=args.prompt_median, prompt_sigma=0.4,
            prompt_min=3, prompt_max=args.prompt_max,
            output_alpha=2.0, output_min=args.out_min,
            output_max=args.out_max, vocab=args.vocab,
            sessions=8, prefix_groups=2, prefix_len=2,
            tenants=(("steady", 3.0, 1),
                     ("flood", 1.0, 1, "prefill_heavy")),
            heavy_prompt_median=args.heavy_median,
            heavy_prompt_sigma=0.25,
            heavy_output_max=args.heavy_out_max)
        arrivals = generate_trace(spec).arrivals
        steady = [a for a in arrivals if a.tenant == "steady"]
        flood = [a for a in arrivals if a.tenant == "flood"]
        if len(steady) >= args.steady and len(flood) >= args.flood:
            return steady[:args.steady], flood[:args.flood]
        duration *= 2.0
    raise RuntimeError("trace never produced enough arrivals")


def _build_model(args):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))
    return model, variables


def _warm(eng, work, args, passes=1):
    """Compile every program the timed run needs: one prompt per
    padded length (cold prefill + step), and with ``passes=2`` a
    second pass over the SAME prompts — by then the prefix store holds
    their blocks (donated on finish), so the prefix-hit tail-prefill
    programs the handoff path admits through get compiled too."""
    a = args.block
    lengths = sorted({-(-len(w.prompt) // a) * a for w in work})
    reqs = [{"prompt": np.zeros((t,), np.int32), "max_new_tokens": 2}
            for t in lengths]
    for _ in range(passes):
        list(eng.run(reqs))


def _mk_disagg(model, variables, work, args):
    """1 chunked-prefill replica + 2 decode replicas (one paged, one
    envelope), warmed then store-cleared (``swap_variables`` with the
    SAME weights: every engine lands on the same weights_ver with an
    empty store, so the timed arms actually ship their blocks)."""
    from distkeras_tpu.gateway import EngineReplica, PrefillDecodeRouter
    from distkeras_tpu.serving import DecodeEngine

    cache = 1 << 26
    npages = 2 * args.slots * (args.max_len // args.block)
    common = dict(slots=args.slots, prefill_align=args.block,
                  max_new_tokens=args.out_max,
                  prefix_cache_bytes=cache)
    pre = DecodeEngine(model, variables, prefill_chunk=args.block,
                       **common)
    d0 = DecodeEngine(model, variables, kv_pages=npages,
                      page_size=args.block, **common)
    d1 = DecodeEngine(model, variables, **common)
    for eng in (pre, d0, d1):
        _warm(eng, work, args, passes=2)
        eng.swap_variables(variables)
    return PrefillDecodeRouter(
        [EngineReplica(pre, name="p0")],
        [EngineReplica(d0, name="d0"), EngineReplica(d1, name="d1")],
        block_size=args.block, seed=args.seed)


def _mk_mono(model, variables, work, args):
    """The same engine count, monolithic: whole-prompt prefill, no
    prefix store — the flood prefills right next to the decode."""
    from distkeras_tpu.gateway import EngineReplica, ServingGateway
    from distkeras_tpu.serving import DecodeEngine

    def _eng():
        eng = DecodeEngine(model, variables, slots=args.slots,
                           prefill_align=args.block,
                           max_new_tokens=args.out_max)
        _warm(eng, work, args)
        return eng

    return ServingGateway([EngineReplica(_eng(), name=f"m{i}")
                           for i in range(3)], policy="least_loaded")


def run_arm(gw, work, want):
    """The backlog (trace order) through one gateway; asserts zero
    errors + byte parity for every result, returns steady-tenant
    latency stats."""
    t0 = time.perf_counter()
    rids = [(w, gw.submit(w.prompt, max_new_tokens=w.max_new,
                          tenant=w.tenant, priority=w.priority))
            for w in work]
    results = [(w, gw.result(rid, timeout=600)) for w, rid in rids]
    wall = time.perf_counter() - t0
    for w, r in results:
        assert r.get("error") is None, r
        np.testing.assert_array_equal(
            np.asarray(r["tokens"]), want(w),
            err_msg=f"token parity ({w.tenant}, len {len(w.prompt)})")
    steady = [r for w, r in results if w.tenant == "steady"]
    inter = [(r["t_finish"] - r["t_first"])
             / max(len(r["tokens"]) - 1, 1) for r in steady]
    ttft = [r["ttft"] for r in steady]
    return {"requests": len(results), "steady": len(steady),
            "wall_s": round(wall, 3),
            "inter_token_p99_s": float(np.percentile(inter, 99)),
            "inter_token_p50_s": float(np.percentile(inter, 50)),
            "ttft_p99_s": float(np.percentile(ttft, 99))}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + structural acceptance "
                         "assertions (the tier-1 registration); the "
                         "timing-win asserts need full shapes")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--steady", type=int, default=24,
                    help="steady-tenant requests (the measured set)")
    ap.add_argument("--flood", type=int, default=12,
                    help="prefill_heavy flood requests")
    ap.add_argument("--prompt-median", type=float, default=24.0)
    ap.add_argument("--prompt-max", type=int, default=224)
    ap.add_argument("--heavy-median", type=float, default=160.0)
    ap.add_argument("--heavy-out-max", type=int, default=8)
    ap.add_argument("--out-min", type=int, default=8)
    ap.add_argument("--out-max", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block", type=int, default=16,
                    help="prefill_align == page_size == router "
                         "block_size")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--tolerance", type=float, default=0.15)
    args = ap.parse_args()

    if args.smoke:
        args.layers, args.d_model, args.heads = 1, 32, 2
        args.vocab, args.max_len = 37, 64
        args.steady, args.flood = 16, 8
        args.prompt_median, args.prompt_max = 6.0, 40
        args.heavy_median, args.heavy_out_max = 26.0, 6
        args.out_min, args.out_max = 6, 8
        args.slots, args.block = 2, 4

    # every padded prompt + its output budget must fit the envelope
    assert (-(-args.prompt_max // args.block) * args.block
            + args.out_max <= args.max_len), "workload overflows env"

    out_dir = pathlib.Path(args.out_dir
                           or tempfile.mkdtemp(prefix="dkt_pd_"))
    out_dir.mkdir(parents=True, exist_ok=True)

    from distkeras_tpu import flight_recorder, telemetry
    from distkeras_tpu.models import generate

    tel = telemetry.enable()
    flight_recorder.start(out_dir / "fdr")
    model, variables = _build_model(args)
    steady, flood = build_workload(args)
    combined = sorted(steady + flood, key=lambda w: w.t)

    refs: dict = {}

    def want(w):
        key = (w.prompt.tobytes(), w.max_new)
        if key not in refs:
            refs[key] = np.asarray(generate(
                model, variables, w.prompt[None, :],
                max_new_tokens=w.max_new))[0, len(w.prompt):]
        return refs[key]

    out = {"metric": "prefill_decode_ab",
           "model": f"lm L{args.layers} d{args.d_model}",
           "steady": args.steady, "flood": args.flood,
           "block": args.block, "arms": {}}

    router = _mk_disagg(model, variables, combined, args)
    t_run0 = time.perf_counter()
    with router:
        out["arms"]["disagg_idle"] = run_arm(router, steady, want)
        # clear the prefix stores (same weights) between arms so the
        # flood arm ships every handoff instead of cluster-tier hits
        for rep in (*router.prefill, *router.decode):
            rep.swap(variables)
        out["arms"]["disagg_flood"] = run_arm(router, combined, want)
        hz = router.healthz()
        assert hz["state"] != "critical", hz
    disagg_seconds = time.perf_counter() - t_run0

    counters = tel.metrics.snapshot()["counters"]
    shipped = counters.get("serving_kv_pages_shipped_total", 0.0)
    requeued = counters.get("serving_handoff_requeue_total", 0.0)

    with _mk_mono(model, variables, combined, args) as gw:
        out["arms"]["mono_idle"] = run_arm(gw, steady, want)
        out["arms"]["mono_flood"] = run_arm(gw, combined, want)

    arms = out["arms"]
    ratio_disagg = (arms["disagg_flood"]["inter_token_p99_s"]
                    / max(arms["disagg_idle"]["inter_token_p99_s"],
                          1e-9))
    ratio_mono = (arms["mono_flood"]["inter_token_p99_s"]
                  / max(arms["mono_idle"]["inter_token_p99_s"], 1e-9))
    out["inter_token_p99_flood_over_idle"] = round(ratio_disagg, 4)
    out["mono_inter_token_p99_flood_over_idle"] = round(ratio_mono, 4)
    out["kv_pages_shipped"] = shipped
    out["handoff_requeues"] = requeued

    snap_path = out_dir / "registry.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    flight_recorder.stop()
    telemetry.disable()

    # structural acceptance, smoke or not: pages actually shipped,
    # nothing requeued (no faults were injected), mono never touched
    # the handoff path
    assert shipped > 0, counters
    assert requeued == 0, counters
    assert tel.metrics.snapshot()["counters"].get(
        "serving_kv_pages_shipped_total", 0.0) == shipped

    if not args.smoke:
        # the ISSUE 19 acceptance headline (full shapes only: at
        # --smoke shapes timing is noise and the claim is dishonest)
        assert ratio_disagg <= 1.25, out
        assert ratio_mono > ratio_disagg, out

    # ---- perf_regress gating, both directions ------------------------
    cands_lo = [
        {"metric": "inter_token_p99_flood_over_idle",
         "value": ratio_disagg, "lower_is_better": True},
        {"metric": "pd_ttft_p99_s",
         "value": arms["disagg_flood"]["ttft_p99_s"],
         "lower_is_better": True},
    ]
    cands_hi = perf_regress.from_registry(
        str(snap_path), "kv_pages_shipped_per_sec",
        "serving_kv_pages_shipped_total", disagg_seconds)
    assert cands_hi[0]["value"] > 0, cands_hi
    if args.smoke:
        # synthetic trajectory from this very run — the gate must pass
        for i, c in enumerate(cands_lo + cands_hi):
            for n in (1, 2, 3):
                (out_dir / f"BENCH_c{i}_r{n:02d}.json").write_text(
                    json.dumps({
                        "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                        "parsed": {"metric": c["metric"],
                                   "value": c["value"] * (1 + 0.02 * n),
                                   "unit": "ratio"}}))
        baselines = str(out_dir / "BENCH_*.json")
    else:
        baselines = perf_regress.DEFAULT_BASELINES
    traj = perf_regress.load_trajectories(baselines)
    tol = 0.5 if args.smoke else args.tolerance
    rows = perf_regress.evaluate(cands_lo, traj, tolerance=tol,
                                 lower_is_better=True)
    rows += perf_regress.evaluate(cands_hi, traj, tolerance=tol)
    print(perf_regress.render(rows))
    out["gate"] = [{k: r[k] for k in ("metric", "value", "status")}
                   for r in rows]

    if args.smoke:
        assert all(r["status"] == "pass" for r in rows), rows
        # forced breach, both gate directions: each lower-is-better
        # metric degraded 10x up, the rate degraded 10x down
        bad = perf_regress.evaluate(
            [{"metric": c["metric"], "value": c["value"] * 10.0}
             for c in cands_lo], traj, tolerance=0.5,
            lower_is_better=True)
        bad += perf_regress.evaluate(
            [{"metric": cands_hi[0]["metric"],
              "value": cands_hi[0]["value"] / 10.0}], traj,
            tolerance=0.5)
        assert all(r["status"] == "breach" for r in bad), bad
        print(json.dumps({"gate": "pass_and_breach", "ok": True}),
              flush=True)
        out["smoke"] = "ok"
    print(json.dumps(out, default=repr))


if __name__ == "__main__":
    main()

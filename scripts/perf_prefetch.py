"""IO/compute overlap A/B for the out-of-core path (VERDICT r3 #2).

Measures epoch wall-time of training over ``.npz`` shard files with the
one-deep segment prefetch disabled vs enabled
(``DKT_SEGMENT_PREFETCH=0|1``), plus the raw ingredients — pure segment
IO (load+shuffle) and pure device compute — so the table can say not
just "what changed" but "what bound the epoch".

Protocol: each arm trains ``1`` epoch and then ``1 + N`` epochs with a
fresh trainer; the difference is N steady-state epochs with the jit
compile and other fixed costs cancelled.  Results are appended to
stdout as one JSON line per arm; PERF.md carries the table.

Run on the TPU from the repo root:
    python scripts/perf_prefetch.py --trainer single
    python scripts/perf_prefetch.py --trainer adag
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trainer", choices=["single", "adag"],
                    default="single")
    ap.add_argument("--format", choices=["npz", "csv"], default="npz",
                    help="npz: ResNet-18 over image shards (host IO is "
                         "binary reads — cheap).  csv: Wide&Deep over "
                         "Criteo-shaped text shards with a per-shard "
                         "ETL map (parse + hash-bucket + assemble — "
                         "the host-heavy ingestion path)")
    ap.add_argument("--rows", type=int, default=None)
    ap.add_argument("--image", type=int, default=64)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=3,
                    help="steady-state epochs measured (on top of the "
                         "1-epoch warm arm)")
    ap.add_argument("--workers", type=int, default=4)
    args = ap.parse_args()

    from distkeras_tpu.data import (Dataset, ShardedDataset, datasets,
                                    transformers as tf)
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import ADAG, SingleTrainer

    tmp = tempfile.mkdtemp(prefix="dkt_prefetch_")
    if args.format == "npz":
        rows = args.rows or 4096
        full = datasets.synthetic_classification(
            rows, (args.image, args.image, 3), 100, seed=0)
        paths = full.to_npz_shards(os.path.join(tmp, "part"),
                                   rows_per_shard=rows // args.shards)
        sd = ShardedDataset(paths)
        # ResNet-18 (basic blocks 2-2-2-2) at the shard scale the
        # rig's host RAM supports; bf16 + group norm, the flagship's
        # settings.
        cfg = model_config("resnet", (args.image, args.image, 3),
                           num_classes=100, stage_sizes=(2, 2, 2, 2),
                           bottleneck=False, width=64)
    else:
        rows = args.rows or 65536
        num_dense, num_cat, buckets = 13, 26, 1000
        full = datasets.criteo_synth(rows, num_dense=num_dense,
                                     num_categorical=num_cat,
                                     vocab_size=5000, seed=0)
        dense = full["dense"]
        per = rows // args.shards
        paths = []
        header = (",".join(f"d{j}" for j in range(num_dense))
                  + "," + ",".join(f"c{j}" for j in range(num_cat))
                  + ",label")
        for s in range(args.shards):
            p = os.path.join(tmp, f"part-{s:05d}.csv")
            with open(p, "w") as fh:
                fh.write(header + "\n")
                for i in range(s * per, (s + 1) * per):
                    fh.write(",".join(
                        [f"{dense[i, j]:.6g}" for j in range(num_dense)]
                        + [str(full[f"c{j}"][i]) for j in range(num_cat)]
                        + [str(full["label"][i])]) + "\n")
            paths.append(p)
        etl = tf.Pipeline(
            [tf.HashBucketTransformer(f"c{j}", buckets)
             for j in range(num_cat)]
            + [tf.AssembleTransformer(
                [f"d{j}" for j in range(num_dense)]
                + [f"c{j}_bucket" for j in range(num_cat)])])
        base = Dataset.from_csv_shards(os.path.join(tmp, "part-*.csv"))
        etl.fit(base.load_shard(0))
        sd = base.map(etl.transform)
        cfg = model_config("wide_deep", (num_dense + num_cat,),
                           num_dense=num_dense,
                           num_categorical=num_cat,
                           vocab_size=buckets, num_classes=2)
    shard_mb = os.path.getsize(paths[0]) / 1e6

    def build():
        if args.trainer == "single":
            return SingleTrainer(cfg, batch_size=args.batch,
                                 learning_rate=0.1, seed=0)
        return ADAG(cfg, num_workers=args.workers,
                    communication_window=2,
                    batch_size=args.batch // args.workers,
                    learning_rate=0.1, seed=0)

    def timed_train(num_epoch: int):
        t = build()
        t.num_epoch = num_epoch
        start = time.monotonic()
        t.train(sd)
        wall = time.monotonic() - start
        # exact consumer-side blocked-on-segment seconds (recorded per
        # epoch by the trainers) — the noise-free counterpart of the
        # wall-clock A/B
        stalls = t.history.get("segment_stall_s", [])
        return wall, (sum(stalls[1:]) / max(len(stalls) - 1, 1)
                      if len(stalls) > 1 else (stalls or [0.0])[-1])

    # throwaway warmup: the very first train pays the device compile
    # (~20-110s through the tunnel); everything timed below reuses the
    # in-process XLA compile cache
    os.environ["DKT_SEGMENT_PREFETCH"] = "0"
    timed_train(1)

    # pure segment IO: what one epoch's loads+shuffles cost with no
    # training at all (the stall an overlapped epoch can hide)
    io_start = time.monotonic()
    for seg in sd.epoch_segments(seed=0):
        pass
    io_epoch = time.monotonic() - io_start

    out = {"trainer": args.trainer, "format": args.format, "rows": rows,
           "image": args.image, "shards": args.shards,
           "shard_mb": round(shard_mb, 1), "batch": args.batch,
           "steady_epochs": args.epochs,
           "io_epoch_s": round(io_epoch, 3)}
    for setting in ("0", "1"):
        os.environ["DKT_SEGMENT_PREFETCH"] = setting
        warm, _ = timed_train(1)
        long, stall = timed_train(1 + args.epochs)
        per_epoch = (long - warm) / args.epochs
        out[f"epoch_s_prefetch_{setting}"] = round(per_epoch, 3)
        out[f"total_1ep_s_prefetch_{setting}"] = round(warm, 3)
        out[f"stall_s_prefetch_{setting}"] = round(stall, 3)
    saved = out["epoch_s_prefetch_0"] - out["epoch_s_prefetch_1"]
    out["saved_s_per_epoch"] = round(saved, 3)
    out["saved_pct"] = round(100 * saved / out["epoch_s_prefetch_0"], 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Concurrency & protocol static-analysis driver (ISSUE 9).

Runs all three analysis passes over the package and exits non-zero on
unsuppressed findings:

    python scripts/lint_static.py            # full lint, exit 2 on dirt
    python scripts/lint_static.py --smoke    # lint + seeded self-check
    python scripts/lint_static.py --metrics-out lint.json

Suppression is in-source (``# lint: allow(<rule>)`` on or above the
flagged line) or via the committed baseline ``scripts/lint_baseline.txt``
(``Finding.baseline_key`` lines — rule|path|message, line-number-free).
Suppressions that no longer match any finding are themselves reported
(rule ``dead-suppression``; report-only unless ``--strict-baseline``).

Finding counts are emitted as ``lint_findings_total{rule=...}`` through
the telemetry registry; ``--metrics-out`` writes the registry snapshot
so ``perf_regress.py --from-registry`` can gate on finding-count
regressions exactly like any other counter.
"""

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from distkeras_tpu import telemetry  # noqa: E402
from distkeras_tpu.analysis import (  # noqa: E402
    dead_suppressions,
    filter_suppressed,
    load_baseline,
    lockcheck,
    package_files,
    read_sources,
    surfaces,
)

BASELINE = REPO / "scripts" / "lint_baseline.txt"


def run_lint(baseline_path: pathlib.Path = BASELINE):
    """All passes -> (unsuppressed findings, counts-by-rule, stats).
    ``stats["dead"]`` carries the dead-suppression findings, reported
    separately so the caller decides whether they gate."""
    paths = package_files(REPO)
    sources = read_sources(REPO, paths)
    findings = lockcheck.analyze_paths(REPO, paths)
    findings += surfaces.check_all(REPO, paths)
    kept, n_allowed = filter_suppressed(findings, sources)
    baseline = load_baseline(baseline_path)
    final = [f for f in kept if f.baseline_key() not in baseline]
    n_baselined = len(kept) - len(final)
    dead = dead_suppressions(findings, sources, baseline)
    counts: dict[str, int] = {}
    for f in final + dead:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    stats = {"files": len(paths), "raw": len(findings),
             "allowed": n_allowed, "baselined": n_baselined,
             "dead": dead}
    return final, counts, stats


def emit_metrics(counts, out_path=None):
    reg = telemetry.MetricsRegistry()
    total = reg.counter("lint_findings_total")
    total.inc(0)
    for rule, n in sorted(counts.items()):
        reg.counter("lint_findings_total", rule=rule).inc(n)
        total.inc(n)
    if out_path:
        pathlib.Path(out_path).write_text(
            json.dumps(reg.snapshot(), indent=2, sort_keys=True,
                       default=str))
    return reg


def self_check() -> list[str]:
    """Seeded-violation fixtures: every rule must fire on a source
    snippet that violates it — a broken analyzer fails loudly here
    rather than passing silently forever."""
    failures = []

    def expect(rules, got, label):
        got_rules = {f.rule for f in got}
        missing = set(rules) - got_rules
        if missing:
            failures.append(f"{label}: expected {sorted(missing)}, "
                            f"got {sorted(got_rules)}")

    expect([lockcheck.RULE_BLOCKING], lockcheck.analyze_source(
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._lock:\n"
        "            time.sleep(1)\n"), "blocking-under-lock")
    expect([lockcheck.RULE_ORDER], lockcheck.analyze_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b: pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a: pass\n"), "lock-order-inversion")
    expect([lockcheck.RULE_GUARDED], lockcheck.analyze_source(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0  # guarded-by: _lock\n"
        "    def bad(self):\n"
        "        self._n = 1\n"), "guarded-write")
    s = surfaces.extract_source(
        'm.counter("bogus_metric_zzz").inc()', "fixture.py")
    expect([surfaces.RULE_METRIC],
           surfaces.check_docs(s, docs="(empty)"), "undoc-metric")
    from distkeras_tpu.analysis import Finding, RULE_DEAD
    fixture_src = ("x = 1  # lint: allow(bogus-rule)\n"
                   "y = 2\n")
    dead = dead_suppressions(
        [Finding("other-rule", "fixture.py", 2, "m")],
        {"fixture.py": fixture_src.splitlines()},
        {"stale-rule|gone.py|old message"})
    expect([RULE_DEAD, RULE_DEAD], dead, "dead-suppression")
    if len(dead) != 2:
        failures.append(f"dead-suppression: expected a dead allow "
                        f"AND a dead baseline entry, got {dead}")
    s = surfaces.extract_source(
        'transport.send_msg(sock, b"Z")', "fixture.py",
        wire_scope="ps")
    expect([surfaces.RULE_OPCODE], surfaces.check_opcodes(s),
           "unregistered-opcode")
    s = surfaces.extract_source(
        'TIERS = {"bogus_tier": None}', "fixture.py")
    expect([surfaces.RULE_TIER],
           surfaces.check_docs(s, docs="(empty)"), "undoc-tier")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="full lint + seeded-violation self-check")
    ap.add_argument("--baseline", default=str(BASELINE))
    ap.add_argument("--strict-baseline", action="store_true",
                    help="exit 2 on dead suppressions (baseline "
                         "entries / allow comments matching nothing)")
    ap.add_argument("--metrics-out", default=None,
                    help="write the telemetry registry snapshot here")
    args = ap.parse_args(argv)

    findings, counts, stats = run_lint(pathlib.Path(args.baseline))
    emit_metrics(counts, args.metrics_out)

    for f in findings:
        print(f)
    dead = stats["dead"]
    for f in dead:
        print(f"{f}{'' if args.strict_baseline else '  (report-only)'}")
    print(f"lint_static: {stats['files']} files, "
          f"{len(findings)} unsuppressed finding(s) "
          f"({stats['allowed']} allowed in-source, "
          f"{stats['baselined']} baselined, "
          f"{len(dead)} dead suppression(s))")

    if args.smoke:
        failures = self_check()
        if failures:
            for msg in failures:
                print(f"SELF-CHECK FAILED: {msg}")
            return 1
        print("lint_static: self-check OK (all rules fire on seeded "
              "violations)")

    if findings:
        return 2
    return 2 if (args.strict_baseline and dead) else 0


if __name__ == "__main__":
    sys.exit(main())

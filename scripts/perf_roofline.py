"""Per-op roofline attribution of the ResNet-50 training step.

VERDICT r4 Weak #1: the flagship's MFU (0.31) sits 19 points under the
estimated ~0.5 bandwidth ceiling (PERF.md §3) and no per-op accounting
ever showed WHERE the step time goes.  This script produces that table:

- enumerates every op class in the b256/224px flagship step (each
  unique conv shape, each norm/elementwise shape, pool/dense/loss),
- measures each op's fwd and fwd+bwd time ON THE CHIP (scan-chained
  with a data-dependent gate, two chain lengths differenced — the
  tunnel's ~140 ms dispatch overhead cancels; see
  tpu-rig-quirks/PERF.md §5),
- computes each op's roofline bound: max(FLOPs / 197 TF/s,
  min-bytes / 820 GB/s) in bf16,
- reconciles: sum(measured per-op x count) vs the measured whole step.

Output: a markdown table (PERF.md §21) + a JSON line.

Run (real TPU): python scripts/perf_roofline.py
Smoke (CPU):    python scripts/perf_roofline.py --smoke
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
from jax import lax

PEAK = 197e12     # bf16 FLOP/s, TPU v5e (PERF.md header)
BW = 820e9        # HBM bytes/s


# ---------------------------------------------------------------------
# op inventory: ResNet-50 @ (batch, image), space_to_depth stem —
# exactly the bench.py flagship graph (models/resnet.py)
# ---------------------------------------------------------------------


def conv_inventory(image: int):
    """[(name, count, H_in, C_in, K, stride, C_out)] for the flagship.
    Spatial sizes assume image % 32 == 0 (224 or 64)."""
    s = image // 2   # after stem (stride-2-equivalent s2d conv)
    p = s // 2       # after 3x3/s2 maxpool
    ops = [("stem 4x4/s1 12->64 @%d" % s, 1, s, 12, 4, 1, 64)]
    spatial = p
    cin = 64
    for stage, (blocks, w) in enumerate(
            zip((3, 4, 6, 3), (64, 128, 256, 512))):
        cout = 4 * w
        stride = 1 if stage == 0 else 2
        out_sp = spatial // stride
        # first block (strided, with downsample projection)
        ops += [
            (f"1x1 {cin}->{w} @{spatial}", 1, spatial, cin, 1, 1, w),
            (f"3x3/s{stride} {w}->{w} @{spatial}", 1, spatial, w, 3,
             stride, w),
            (f"1x1 {w}->{cout} @{out_sp}", 1, out_sp, w, 1, 1, cout),
            (f"ds 1x1/s{stride} {cin}->{cout} @{spatial}", 1, spatial,
             cin, 1, stride, cout),
        ]
        # remaining blocks
        n = blocks - 1
        ops += [
            (f"1x1 {cout}->{w} @{out_sp}", n, out_sp, cout, 1, 1, w),
            (f"3x3 {w}->{w} @{out_sp}", n, out_sp, w, 3, 1, w),
            (f"1x1 {w}->{cout} @{out_sp} (x{n})", n, out_sp, w, 1, 1,
             cout),
        ]
        spatial, cin = out_sp, cout
    return ops


def norm_inventory(image: int):
    """[(name, count, H, C)] — every GN(+relu) site.  Residual
    add+relu sites are measured separately as 'add'."""
    p = image // 4
    ops = [("gn 64 @%d (stem)" % (image // 2), 1, image // 2, 64)]
    spatial = p
    for stage, (blocks, w) in enumerate(
            zip((3, 4, 6, 3), (64, 128, 256, 512))):
        cout = 4 * w
        out_sp = spatial // (1 if stage == 0 else 2)
        ops += [
            (f"gn {w} @{spatial}/{out_sp}", 2 * blocks,
             out_sp, w),                       # two mid-width norms
            (f"gn {cout} @{out_sp}", blocks + 1, out_sp, cout),
            (f"add+relu {cout} @{out_sp}", blocks, out_sp, cout),
        ]
        spatial = out_sp
    return ops


# ---------------------------------------------------------------------
# measurement: scan-chained, differenced
# ---------------------------------------------------------------------


def _time(go, carry0, rest, reps):
    """Best-of-reps wall time of the jitted chain (scalar-fetch sync)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = go(carry0, *rest)
        float(out)  # host fetch = the only reliable sync on this rig
        best = min(best, time.perf_counter() - t0)
    return best


def time_op(step, carry0, rest, est_ms, reps=3, target_ms=250.0,
            max_iters=4000):
    """Per-call seconds of ``step(carry, *rest) -> carry`` via two
    chain lengths: dispatch/sync overhead cancels in the difference.

    ``carry0`` is the loop-carried operand — a probe scalar for ops
    that are nonlinear in their input, or the WEIGHTS for convs (see
    ``conv_fwd_step``).  Only a scalar probe of the final carry is
    fetched (fetching a full carry through the 11 MB/s tunnel would
    dwarf the measurement).

    The tunnel's dispatch round-trip jitters by tens of ms, so the
    DIFFERENCED work must dominate it: the chain lengths are scaled
    from ``est_ms`` (the op's roofline bound — a lower bound on its
    real time, hence an upper bound on the iterations needed) so the
    difference carries ~``target_ms`` of real compute."""
    n_diff = int(min(max_iters,
                     max(24, target_ms / max(est_ms, 0.02))))
    n_lo = max(4, n_diff // 4)
    n_hi = n_lo + n_diff

    def build(n):
        @jax.jit
        def go(c0, *rest):
            def body(c, _):
                return step(c, *rest), None
            c, _ = lax.scan(body, c0, None, length=n)
            # probe element: every iteration's epsilon feeds the
            # carry multiplicatively, so one element of the final
            # carry transitively requires the whole chain
            probe = c if getattr(c, "ndim", 0) == 0 \
                else c.reshape(-1)[0]
            return probe.astype(jnp.float32)
        return go

    hi, lo = build(n_hi), build(n_lo)
    float(hi(carry0, *rest))  # compile + warm
    float(lo(carry0, *rest))
    t_hi = _time(hi, carry0, rest, reps)
    t_lo = _time(lo, carry0, rest, reps)
    return max(t_hi - t_lo, 1e-9) / (n_hi - n_lo)


def _gate(out):
    # The gate must (a) be genuinely value-dependent — `* 0 + 1` would
    # constant-fold and let XLA hoist the op out of the scan as
    # loop-invariant — and (b) depend on EVERY output element: a
    # single-element gate lets XLA's slice-sinking compute just one
    # conv window per iteration (the second broken run of this script:
    # convs "measuring" 100x under their FLOP bound while the
    # full-tensor GN stats measured true).  The full sum costs one
    # extra read-pass over the output (~bytes/BW), <10% on the
    # bandwidth-bound ops and noise on the compute-bound ones.
    return jnp.sum(out.astype(jnp.float32)) * 1e-24 + 1.0


# Convolution is BILINEAR, which defeats every scalar-gate scheme:
# with input x*s the dgrad cotangent path conv_t(r, w) references
# neither x nor s — structurally loop-invariant, hoisted (the third
# broken run measured exactly that).  So the conv chains carry the
# WEIGHTS: wc is perturbed each iteration by an output-derived epsilon
# (~1e-30, value-neutral but structurally load-bearing), making every
# conv in both passes depend on the carry.  The train loss is
# QUADRATIC in the output so the weight-grad's cotangent (2*out*r)
# also depends on wc.  Extra per-iteration cost: one fused output
# reduce + a weight-sized update — noise next to the conv itself.


def conv_fwd_step(stride):
    def step(wc, x):
        out = lax.conv_general_dilated(
            x, wc, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        eps = jnp.sum(out.astype(jnp.float32)) * 1e-30
        return wc * (1.0 + eps).astype(wc.dtype)
    return step


def conv_train_step(stride):
    # `r` is a RANDOM cotangent scaffold (an all-ones cotangent lets
    # XLA collapse the backward into reductions); it rides as an
    # ARGUMENT — a closure-captured array becomes an HLO literal,
    # which the 11 MB/s tunnel would ship per compile (the fourth
    # broken run: a 1.3 GB stem constant, never finished).
    def step(wc, x, r):
        def loss(x, w):
            # output stays bf16 so the dgrad/wgrad convs run bf16
            # like the model's
            out = lax.conv_general_dilated(
                x, w, (stride, stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return jnp.sum(out.astype(jnp.float32) ** 2
                           * r.astype(jnp.float32))
        gx, gw = jax.grad(loss, argnums=(0, 1))(x, wc)
        eps = (jnp.sum(gx.astype(jnp.float32))
               + jnp.sum(gw.astype(jnp.float32))) * 1e-30
        return wc * (1.0 + eps).astype(wc.dtype)
    return step


def gn_steps(c, x, scale, bias):
    import math

    groups = math.gcd(32, c)

    def apply(x):
        xf = x.astype(jnp.float32)
        b, h, w_, _ = x.shape
        g = xf.reshape(b, h, w_, groups, c // groups)
        mean = g.mean(axis=(1, 2, 4), keepdims=True)
        mean2 = (g * g).mean(axis=(1, 2, 4), keepdims=True)
        inv = lax.rsqrt(jnp.maximum(mean2 - mean * mean, 0.0) + 1e-5)
        y = ((g - mean) * inv).reshape(b, h, w_, c)
        return nn_relu(y * scale + bias).astype(x.dtype)

    def fwd(s, x, scale, bias, *_):
        return _gate(apply(x * s.astype(x.dtype)))

    def train(s, x, scale, bias, r):
        g = jax.grad(lambda x: jnp.sum(
            apply(x).astype(jnp.float32)
            * r.astype(jnp.float32)))(x * s.astype(x.dtype))
        return _gate(g)
    return fwd, train


def nn_relu(x):
    return jnp.maximum(x, 0)


def add_steps():
    def fwd(s, x, y, *_):
        return _gate(nn_relu(x * s.astype(x.dtype) + y))

    def train(s, x, y, r):
        g = jax.grad(lambda x: jnp.sum(
            nn_relu(x + y).astype(jnp.float32) * r))(
                x * s.astype(x.dtype))
        return _gate(g)
    return fwd, train


# ---------------------------------------------------------------------


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--image", type=int, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes on CPU (CI sanity, not a roofline)")
    args = ap.parse_args()
    if args.smoke:
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.devices()[0].platform != "cpu"
    batch = args.batch or (256 if on_tpu else 2)
    image = args.image or (224 if on_tpu else 64)
    reps = 3 if on_tpu else 1
    target = 250.0 if on_tpu else 5.0
    key = jax.random.key(0)

    rows = []

    probe = jnp.float32(1.0)  # scalar carry for the non-conv chains

    def measure(name, count, fwd_spec, train_spec, flops_fwd,
                bytes_fwd, bytes_train, train_overhead_ms=0.0):
        (step_fwd, c_fwd, rest_fwd) = fwd_spec
        (step_train, c_train, rest_train) = train_spec
        est_fwd = max(flops_fwd / PEAK, bytes_fwd / BW) * 1e3
        est_train = max(3 * flops_fwd / PEAK, bytes_train / BW) * 1e3
        t_fwd = time_op(step_fwd, c_fwd, rest_fwd, est_fwd, reps,
                        target)
        t_train = time_op(step_train, c_train, rest_train, est_train,
                          reps, target)
        t_corr = t_train - train_overhead_ms * 1e-3
        clamped = t_corr < t_fwd
        if clamped:
            # the analytic scaffold subtraction over-shot (XLA fused
            # the dout materialization away for this shape): flag it
            # rather than silently reporting a free backward
            print(f"    [clamp] {name}: corrected train "
                  f"{t_corr*1e3:.3f} < fwd — clamped to fwd",
                  flush=True)
        t_train = max(t_corr, t_fwd)
        rows.append({
            "name": name, "count": count,
            "fwd_ms": t_fwd * 1e3, "train_ms": t_train * 1e3,
            "flops_fwd": flops_fwd,
            "bound_fwd_ms": max(flops_fwd / PEAK,
                                bytes_fwd / BW) * 1e3,
            "bound_train_ms": max(3 * flops_fwd / PEAK,
                                  bytes_train / BW) * 1e3,
        })
        print(f"  {name:38s} x{count:2d}  fwd {t_fwd*1e3:7.3f} ms  "
              f"train {t_train*1e3:7.3f} ms", flush=True)

    print(f"[roofline] conv classes (b{batch}, {image}px, bf16)",
          flush=True)
    for name, count, h, cin, k, stride, cout in conv_inventory(image):
        ho = h // stride
        x = jax.random.normal(key, (batch, h, h, cin), jnp.bfloat16)
        w = jax.random.normal(key, (k, k, cin, cout),
                              jnp.bfloat16) * 0.05
        r = jax.random.normal(key, (batch, ho, ho, cout),
                              jnp.bfloat16)
        flops = 2.0 * batch * ho * ho * cout * k * k * cin
        b_in = x.size * 2
        b_w = w.size * 2
        b_out = batch * ho * ho * cout * 2
        bytes_fwd = b_in + b_w + b_out
        # dgrad: read dout+w, write dx; wgrad: read x+dout, write dw
        bytes_train = bytes_fwd + (b_out + b_w + b_in) \
            + (b_in + b_out + b_w)
        # the quadratic-loss scaffold re-reads out and writes dout —
        # traffic the model's own backward does NOT pay (its dout
        # arrives as the next op's cotangent, and the r read stands in
        # for exactly that) — subtract it analytically
        overhead_ms = 2 * b_out / BW * 1e3
        measure(name, count,
                (conv_fwd_step(stride), w, (x,)),
                (conv_train_step(stride), w, (x, r)), flops,
                bytes_fwd, bytes_train, train_overhead_ms=overhead_ms)

    print("[roofline] norm / elementwise classes", flush=True)
    for name, count, h, c in norm_inventory(image):
        x = jax.random.normal(key, (batch, h, h, c), jnp.bfloat16)
        nbytes = x.size * 2
        r = jax.random.normal(key, x.shape, jnp.bfloat16)
        if name.startswith("add"):
            y = jax.random.normal(key, x.shape, jnp.bfloat16)
            fwd, train = add_steps()
            op_args = (x, y, r)
            bytes_fwd, bytes_train = 3 * nbytes, 3 * nbytes + 2 * nbytes
            flops = x.size * 2.0
        else:
            scale = jnp.ones((c,), jnp.float32)
            bias = jnp.zeros((c,), jnp.float32)
            fwd, train = gn_steps(c, x, scale, bias)
            op_args = (x, scale, bias, r)
            # one stats read-pass + one normalize read+write pass
            bytes_fwd = 3 * nbytes
            bytes_train = bytes_fwd + 3 * nbytes
            flops = x.size * 8.0
        measure(name, count, (fwd, probe, op_args),
                (train, probe, op_args), flops, bytes_fwd,
                bytes_train)

    # tail: maxpool, global mean, dense+loss — measured as one class
    print("[roofline] tail (pool/dense/loss)", flush=True)
    s = image // 2
    xs = jax.random.normal(key, (batch, s, s, 64), jnp.bfloat16)
    rp = jax.random.normal(key, (batch, s // 2, s // 2, 64),
                           jnp.bfloat16)
    pool_fwd = lambda g, x, rp: _gate(lax.reduce_window(  # noqa: E731
        x * g.astype(x.dtype), -jnp.inf, lax.max,
        (1, 3, 3, 1), (1, 2, 2, 1), "SAME"))
    pool_train = lambda g, x, rp: _gate(  # noqa: E731
        jax.grad(lambda x: jnp.sum(
            lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
            .astype(jnp.float32) * rp))(x * g.astype(x.dtype)))
    measure("maxpool 3x3/s2 @stem", 1,
            (pool_fwd, probe, (xs, rp)),
            (pool_train, probe, (xs, rp)),
            xs.size * 9.0, xs.size * 2 * 1.25,
            xs.size * 2 * 2.5)
    xf = jax.random.normal(key, (batch, image // 32, image // 32, 2048),
                           jnp.bfloat16)
    wd = jax.random.normal(key, (2048, 1000), jnp.float32) * 0.02

    def head_fwd(g, x, w):
        pooled = jnp.mean(x * g.astype(x.dtype), axis=(1, 2))
        return _gate(pooled.astype(jnp.float32) @ w)

    def head_train(g, x, w):
        def loss(x, w):
            pooled = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
            return jnp.sum(jax.nn.log_softmax(pooled @ w))
        gx, gw = jax.grad(loss, (0, 1))(x * g.astype(x.dtype), w)
        return _gate(gx) * _gate(gw)

    measure("meanpool+dense+loss", 1,
            (head_fwd, probe, (xf, wd)),
            (head_train, probe, (xf, wd)),
            2.0 * batch * 2048 * 1000, xf.size * 2 + wd.size * 4,
            (xf.size * 2 + wd.size * 4) * 3)

    # ---- reconcile against the whole step --------------------------
    tot_fwd = sum(r["fwd_ms"] * r["count"] for r in rows)
    tot_train = sum(r["train_ms"] * r["count"] for r in rows)
    bound_train = sum(r["bound_train_ms"] * r["count"] for r in rows)
    def bucket(r):
        if "gn" in r["name"] or "add" in r["name"]:
            return "norm"
        if "pool" in r["name"] or "dense" in r["name"]:
            return "tail"
        return "conv"

    conv_train = sum(r["train_ms"] * r["count"] for r in rows
                     if bucket(r) == "conv")
    tail_train = sum(r["train_ms"] * r["count"] for r in rows
                     if bucket(r) == "tail")
    norm_train = tot_train - conv_train - tail_train

    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.profiling import (resnet50_model_flops,
                                         time_step_chain)
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    model = ResNet50(num_classes=1000 if on_tpu else 10,
                     stem="space_to_depth")
    tx = resolve_optimizer("momentum", 0.1)
    x = jnp.ones((batch, image, image, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x[:2])
    state = TrainState.create(variables, tx, jax.random.key(1))
    step = make_train_step(model, "categorical_crossentropy", tx)
    batch_dict = {"features": x,
                  "label": jnp.zeros((batch,), jnp.int32)}
    jit_step = jax.jit(step, donate_argnums=0)
    dt, _ = time_step_chain(jit_step, state, batch_dict,
                            n=20 if on_tpu else 2)
    step_ms = dt * 1e3
    mfu = (resnet50_model_flops(batch, image) / dt / PEAK
           if on_tpu else None)

    print("\n| op class | n | fwd ms | train ms | roofline train ms | "
          "roofline util |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        util = r["bound_train_ms"] / r["train_ms"]
        print(f"| {r['name']} | {r['count']} | {r['fwd_ms']:.3f} | "
              f"{r['train_ms']:.3f} | {r['bound_train_ms']:.3f} | "
              f"{util:.2f} |")
    print(f"\nsum fwd {tot_fwd:.1f} ms, sum train {tot_train:.1f} ms "
          f"(conv {conv_train:.1f} + norm/elt {norm_train:.1f} + "
          f"pool/head {tail_train:.1f}); "
          f"roofline-bound sum {bound_train:.1f} ms")
    print(f"measured full step {step_ms:.1f} ms"
          + (f", MFU {mfu:.4f}" if mfu else ""))
    print(json.dumps({
        "metric": "resnet50_roofline",
        "batch": batch, "image": image,
        "sum_op_train_ms": round(tot_train, 2),
        "sum_op_conv_ms": round(conv_train, 2),
        "sum_op_norm_elt_ms": round(norm_train, 2),
        "sum_op_tail_ms": round(tail_train, 2),
        "roofline_bound_ms": round(bound_train, 2),
        "full_step_ms": round(step_ms, 2),
        "mfu": round(mfu, 4) if mfu else None,
    }))


if __name__ == "__main__":
    main()

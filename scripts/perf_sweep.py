"""ResNet-50 step-time sweep on the real TPU chip (PERF.md experiments).

Runs a grid of configurations of the flagship training step and prints one
JSON line per config with step time, images/sec, XLA-counted FLOPs, and
both MFU flavors (honest analytic-model-FLOPs ``mfu`` and ``xla_mfu`` —
see PERF.md §1 for why they differ).  Serialized in one process so the
single-client TPU is never contended.

Usage:  PYTHONPATH=/root/repo:$PYTHONPATH python scripts/perf_sweep.py
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

from distkeras_tpu.profiling import (
    peak_flops,
    resnet50_model_flops,
    time_step_chain,
)


def run_config(batch, norm, input_dtype, image=224, n_steps=20):
    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    model = ResNet50(num_classes=1000, norm=norm)
    tx = resolve_optimizer("momentum", 0.1)
    x = jnp.ones((batch, image, image, 3), jnp.dtype(input_dtype))
    variables = model.init(jax.random.key(0), x[:2])
    state = TrainState.create(variables, tx, jax.random.key(1))
    step = make_train_step(model, "categorical_crossentropy", tx)
    bd = {"features": x, "label": jnp.zeros((batch,), jnp.int32)}

    jit_step = jax.jit(step, donate_argnums=0)
    compiled = jit_step.lower(state, bd).compile()
    cost = compiled.cost_analysis()
    flops = float(cost.get("flops", 0.0)) if cost else 0.0

    dt, _ = time_step_chain(jit_step, state, bd, n=n_steps)
    peak, known = peak_flops(jax.devices()[0])
    model_flops = resnet50_model_flops(batch, image)
    print(json.dumps({
        "batch": batch, "norm": norm, "input_dtype": input_dtype,
        "step_ms": round(dt * 1e3, 2),
        "images_per_sec": round(batch / dt, 1),
        "xla_gflops_per_image": round(flops / batch / 1e9, 2),
        "mfu": round(model_flops / dt / peak, 4) if known else None,
        "xla_mfu": round(flops / dt / peak, 4) if known else None,
    }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    dev = jax.devices()[0]
    print(json.dumps({"device": getattr(dev, "device_kind", str(dev)),
                      "platform": dev.platform}), flush=True)

    grid = [
        # (batch, norm, input_dtype)
        (128, "group", "float32"),
        (256, "group", "float32"),
        (512, "group", "float32"),
        (256, "group", "bfloat16"),
        (256, "batch", "float32"),
        (512, "batch", "bfloat16"),
        (1024, "batch", "bfloat16"),
    ]
    if args.quick:
        grid = grid[:2]
    for cfg in grid:
        try:
            run_config(*cfg)
        except Exception as e:  # OOM etc. — record and continue
            print(json.dumps({"batch": cfg[0], "norm": cfg[1],
                              "input_dtype": cfg[2],
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()

"""End-to-end A/B: ResNet-50 b256 train step, fusion='none' vs
'pallas_block', interleaved reps (PERF.md §11).

Usage:  PYTHONPATH=/root/repo python scripts/perf_fused_e2e.py
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

import time

from distkeras_tpu.profiling import (host_sync, peak_flops,
                                     resnet50_model_flops)


def timed_chain(step, state, batch, n):
    """Like profiling.time_step_chain but hands the threaded (donated)
    state back so rounds can be interleaved."""
    for _ in range(2):
        state, metrics = step(state, batch)
    host_sync(metrics)
    t0 = time.perf_counter()
    for _ in range(n):
        state, metrics = step(state, batch)
    val = host_sync(metrics)
    return (time.perf_counter() - t0) / n, val, state


def build(arm, batch, image, stem):
    """``arm``: 'none' | 'block[:stages]' | 'tail[:stages]', where
    stages is a comma-free digit string, e.g. 'block:01' = pallas_block
    fused at stages 0 and 1 only."""
    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    kind, _, stages = arm.partition(":")
    fusion = {"none": "none", "block": "pallas_block",
              "tail": "pallas_tail"}[kind]
    fusion_stages = tuple(int(c) for c in stages) if stages else None
    model = ResNet50(num_classes=1000, stem=stem, fusion=fusion,
                     fusion_stages=fusion_stages)
    tx = resolve_optimizer("momentum", 0.1)
    x = jnp.ones((batch, image, image, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x[:2])
    state = TrainState.create(variables, tx, jax.random.key(1))
    step = jax.jit(make_train_step(model, "categorical_crossentropy", tx),
                   donate_argnums=0)
    batch_dict = {"features": x,
                  "label": jnp.zeros((batch,), jnp.int32)}
    return step, state, batch_dict


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--stem", type=str, default="space_to_depth")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--n", type=int, default=15)
    ap.add_argument("--arms", type=str, default="none,block")
    args = ap.parse_args()

    peak, _ = peak_flops(jax.devices()[0])
    flops = resnet50_model_flops(args.batch, args.image)
    arms = {}
    for fusion in args.arms.split(","):
        arms[fusion] = build(fusion, args.batch, args.image, args.stem)
    for r in range(args.rounds):
        for fusion in list(arms):
            step, state, batch = arms[fusion]
            dt, val, state = timed_chain(step, state, batch, n=args.n)
            arms[fusion] = (step, state, batch)
            print(json.dumps({
                "arm": fusion, "round": r,
                "step_ms": round(dt * 1e3, 2),
                "img_per_sec": round(args.batch / dt, 1),
                "mfu": round(flops / dt / peak, 4),
                "loss_finite": bool(jnp.isfinite(val)),
            }), flush=True)


if __name__ == "__main__":
    main()

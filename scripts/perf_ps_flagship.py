"""PS-family flagship throughput: one compiled PS round, per tier.

BASELINE.json's north star is *AEASGD* on ResNet-50, but every prior
flagship number timed only the bare synchronous step.  This measures
the thing the PS family actually executes on-device: one commit round
— ``communication_window`` jitted train steps per worker followed by
the ``UpdateRule`` commits in permuted order — with the same
scalar-fetch sync and analytic-FLOPs MFU as the BENCH trajectory.

``--fidelity`` picks the lowering tier (``parallel.tiers``):

* ``faithful`` / ``fast`` — the emulated round (``ps_emulator``):
  workers stacked on one program, commits scanned / closed-form.
* ``mesh`` — the on-chip compiled data plane (``ps_dataplane``): one
  SPMD shard_map program per round, center sharded over the worker
  axis, deltas reduce-scattered, state buffers donated.  Delta family
  only (aeasgd is elastic — use the emulated tiers).

``--out FILE`` writes the parsed-format BENCH record (the ``parsed``
block of a ``BENCH_r*.json`` trajectory file), headline metric
``ps_round_images_per_sec_per_chip`` for the mesh tier, so
``perf_regress.py --candidate FILE`` gates it against the trajectory.

``--smoke`` is the CPU tier-1 proof at tiny shapes: mesh-vs-emulated
center/loss parity (plain and pipelined+flush), the one-compile-per-
round-shape guard via ``ps_round_compiles_total{fidelity="mesh"}``,
and the --out record gated through ``perf_regress.evaluate`` in both
directions (pass and forced breach).

Run on the TPU:  python scripts/perf_ps_flagship.py
                 [--fidelity faithful|fast|mesh]
                 [--trainer aeasgd|adag|downpour|dynsgd]
                 [--workers 4 --window 2 --batch 32 --image 224]
                 [--overlap] [--out BENCH_cand.json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
SCRIPTS = pathlib.Path(__file__).resolve().parent
if str(SCRIPTS) not in sys.path:
    sys.path.insert(0, str(SCRIPTS))


class _Arm:
    """One fidelity arm: device state + a drivable jitted round.

    ``mlp_dim`` swaps the ResNet for a tiny MLP over flat features —
    the smoke's strict-parity model (CPU convs are not batching-
    stable, see ``smoke()``)."""

    def __init__(self, args, fidelity: str, overlap: bool,
                 mlp_dim: int | None = None,
                 sync_metrics: bool = False):
        import jax
        import jax.numpy as jnp

        from distkeras_tpu import mesh as mesh_lib
        from distkeras_tpu.models import model_config
        from distkeras_tpu.parallel import ps_dataplane
        from distkeras_tpu.parallel.ps_emulator import (
            make_pipelined_round_fn, make_round_fn)
        from distkeras_tpu.trainers import (ADAG, AEASGD, DOWNPOUR,
                                            DynSGD)
        from distkeras_tpu.workers import TrainState, make_train_step

        cls = {"adag": ADAG, "aeasgd": AEASGD, "downpour": DOWNPOUR,
               "dynsgd": DynSGD}[args.trainer]
        if mlp_dim is not None:
            cfg = model_config("mlp", (mlp_dim,),
                               num_classes=args.classes, hidden=(32,))
        elif args.smoke:
            # one block per stage: the same code path at seconds scale
            cfg = model_config("resnet", (args.image, args.image, 3),
                               num_classes=args.classes,
                               stage_sizes=(1, 1, 1, 1),
                               bottleneck=False,
                               stem="space_to_depth")
        else:
            cfg = model_config("resnet", (args.image, args.image, 3),
                               num_classes=args.classes,
                               stage_sizes=(3, 4, 6, 3),
                               bottleneck=True,
                               stem="space_to_depth")
        t = cls(cfg, num_workers=args.workers,
                communication_window=args.window,
                batch_size=args.batch, learning_rate=args.lr,
                worker_optimizer="momentum", seed=0)

        self._rule = t.allocate_rule()
        self._W = args.workers
        self.overlap = overlap
        tx = t._tx()
        init_shape = ((2, mlp_dim) if mlp_dim is not None
                      else (2, args.image, args.image, 3))
        variables = t.model.init(jax.random.key(0),
                                 jnp.ones(init_shape, jnp.float32))
        center = variables["params"]
        model_state = {k: v for k, v in variables.items()
                       if k != "params"}

        def make_worker(rng):
            return TrainState.create(
                {"params": center, **model_state}, tx, rng)

        worker_keys = jax.random.split(jax.random.key(1), args.workers)
        ws = jax.vmap(make_worker)(worker_keys)
        ps = self._rule.init_state(center)
        step = make_train_step(t.model, t.loss, tx)

        self.dp = None
        self.n_chips = 1
        if fidelity == "mesh":
            placement = mesh_lib.place_workers(args.workers)
            if placement.mesh is None or placement.vmap_workers != 1:
                raise SystemExit(
                    f"--fidelity mesh maps one worker per device; "
                    f"num_workers={args.workers} does not fit "
                    f"{len(jax.devices())} devices (pass --devices N "
                    f"on CPU)")
            self._row = mesh_lib.batch_sharding(placement.mesh)
            self._rep = mesh_lib.replicated_sharding(placement.mesh)
            self.dp = ps_dataplane.MeshDataplane(
                self._rule, step, placement.mesh, center,
                pipelined=overlap,
                comm_dtype=getattr(args, "comm_dtype", "float32"),
                comm_codec=getattr(args, "comm_codec", None),
                metrics_every=getattr(args, "metrics_every", 1))
            mps, mws = self.dp.to_device(ps, ws)
            # async by default (the thing ISSUE 16 measures: round k+1
            # dispatched before round k's metrics land); sync_metrics
            # is the smoke's per-round parity mode
            self.driver = ps_dataplane.MeshRoundDriver(
                self.dp, mps, mws, sync=sync_metrics)
            self.n_chips = placement.mesh_workers
        else:
            self.ps, self.ws = ps, ws
            if overlap:
                self.round_jit = jax.jit(
                    make_pipelined_round_fn(self._rule, step),
                    donate_argnums=(0, 1, 4))
                self.pend = jax.tree_util.tree_map(jnp.zeros_like,
                                                   ws.params)
                self.pend_perm = jnp.arange(args.workers)
                self.valid = jnp.asarray(False)
            else:
                self.round_jit = jax.jit(
                    make_round_fn(self._rule, step, fidelity),
                    donate_argnums=(0, 1))

    def put(self, batch, perm):
        """Place one round's inputs (mesh tier: row-sharded batch,
        replicated permutation; emulated: as-is)."""
        import jax

        if self.dp is not None:
            return (jax.device_put(batch, self._row),
                    jax.device_put(perm, self._rep))
        return batch, perm

    def round(self, batch, perm):
        """One round.  Mesh tier: dispatch through the driver and
        return the latest fetched metrics (the just-run round's under
        ``sync_metrics``; possibly ``None`` early in an async run)."""
        if self.dp is not None:
            self.driver.dispatch(batch, perm)
            out = self.driver.poll()
            return out[-1] if out else None
        if self.overlap:
            (self.ps, self.ws, metrics, self.pend, self.pend_perm,
             self.valid) = self.round_jit(
                self.ps, self.ws, batch, perm, self.pend,
                self.pend_perm, self.valid)
        else:
            self.ps, self.ws, metrics = self.round_jit(
                self.ps, self.ws, batch, perm)
        return metrics

    def sync(self, metrics) -> float:
        """Block until every dispatched round has executed; return a
        loss scalar for the finite-ness health check."""
        import numpy as np

        from distkeras_tpu.profiling import host_sync

        if self.dp is not None:
            out = self.driver.drain()
            if out:
                metrics = out[-1]
            if metrics is None:
                return float("nan")
            return float(np.asarray(metrics["loss"]).reshape(-1)[0])
        return host_sync(metrics["loss"])

    def flush(self):
        """Drain the pipelined arm's carried pending commit."""
        if not self.overlap:
            return
        if self.dp is not None:
            self.driver.flush_pipeline()
        else:
            from distkeras_tpu.parallel.ps_emulator import \
                flush_pending

            self.ps = flush_pending(self._rule, self.ps, self.pend,
                                    self.pend_perm, self._W)

    def center_host(self):
        import jax

        c = (self.dp.center(self.driver.mps) if self.dp is not None
             else self.ps.center)
        return jax.device_get(c)


def measure(args, fidelity: str, overlap: bool) -> dict:
    """Warm, time ``--reps`` rounds, return the parsed BENCH record."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.profiling import peak_flops, resnet50_model_flops

    arm = _Arm(args, fidelity, overlap)
    x = jnp.ones((args.workers, args.window, args.batch,
                  args.image, args.image, 3), jnp.float32)
    y = jnp.zeros((args.workers, args.window, args.batch), jnp.int32)
    batch, perm = arm.put({"features": x, "label": y},
                          jnp.arange(args.workers))

    for _ in range(3):
        metrics = arm.round(batch, perm)
    arm.sync(metrics)
    t0 = time.perf_counter()
    for _ in range(args.reps):
        metrics = arm.round(batch, perm)
    val = arm.sync(metrics)
    dt = (time.perf_counter() - t0) / args.reps

    imgs = args.workers * args.window * args.batch
    peak, known = peak_flops(jax.devices()[0])
    # analytic MFU only where the model IS ResNet-50 (--smoke shrinks
    # the stages, so its FLOP formula would be fiction); peak_known
    # rides the record so a nominal CPU peak can't pass as measured
    mfu = None
    if peak == peak and not args.smoke:
        flops = resnet50_model_flops(imgs, args.image)
        mfu = round(flops / dt / (peak * arm.n_chips), 4)

    # mesh tier: one attribution round outside the timed window (the
    # sampled decomposition + the ledger's roofline pair, ISSUE 17)
    attrib, cost0 = {}, {}
    if fidelity == "mesh":
        arm.driver.attrib_every = 1
        arm.round(batch, perm)
        arm.sync(None)
        attrib = arm.driver.last_attrib or {}
        report = arm.dp.cost_report()
        cost0 = report[0] if report else {}

    if fidelity == "mesh":
        name = "ps_round_images_per_sec_per_chip"
        value = round(imgs / dt / arm.n_chips, 2)
        unit = "images/sec/chip"
    else:
        # legacy emulated metric: total throughput, faithful unsuffixed
        name = f"{args.trainer}_resnet50_emulated_round"
        if fidelity != "faithful":
            name += f"_{fidelity}"
        value = round(imgs / dt, 2)
        unit = "images/sec"
    if overlap:
        name += "_overlap"
    # self-describing like bench.py's records (ISSUE 16 satellite):
    # step_time_ms/mfu/comm_dtype/n_chips ride along so a BENCH file
    # holding this record needs no out-of-band context
    return {
        "metric": name, "value": value, "unit": unit,
        "fidelity": fidelity, "trainer": args.trainer,
        "mfu": mfu, "round_ms": round(dt * 1e3, 2),
        "step_time_ms": round(dt * 1e3 / args.window, 2),
        "per_step_ms": round(dt * 1e3 / args.window, 2),
        "workers": args.workers, "window": args.window,
        "batch_per_worker": args.batch,
        "global_images_per_round": imgs, "image": args.image,
        "n_chips": arm.n_chips,
        "chips": arm.n_chips,
        "comm_dtype": getattr(args, "comm_dtype", "float32"),
        "comm_codec": getattr(args, "comm_codec", None),
        "mfu_roofline": (round(attrib["mfu_roofline"], 4)
                         if "mfu_roofline" in attrib else None),
        "mfu_observed": (round(attrib["mfu_observed"], 4)
                         if "mfu_observed" in attrib else None),
        "attrib": {seg: round(attrib[seg] * 1e3, 3)
                   for seg in ("host_gap", "dispatch",
                               "device_compute", "ring_fetch")
                   if seg in attrib},
        "compile_s": (round(cost0["compile_s"], 3)
                      if "compile_s" in cost0 else None),
        "peak_known": bool(cost0.get("peak_known", known)),
        "loss_finite": bool(np.isfinite(val)),
    }


def smoke(args) -> dict:
    """Tier-1 proof: parity, compile guard, and the perf gate wired
    end to end — all at tiny CPU shapes."""
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    import perf_regress
    from distkeras_tpu import telemetry
    from distkeras_tpu.parallel.ps_emulator import commit_permutation

    tel = telemetry.enable()
    rounds = 3
    # Parity runs on a tiny MLP, NOT the ResNet: XLA CPU convolutions
    # are not batching-stable (the same window computed solo-shaped,
    # as the mesh tier's per-device program does, vs vmapped over
    # workers, as the emulated tier does, differs by ~1e-2 on logits
    # — measured, backend property), so conv centers can only agree
    # to the noise floor.  Matmuls ARE stable, so the MLP proves the
    # data plane's round semantics to 2e-5.
    dim = 24
    rng = np.random.RandomState(0)
    batches = [
        {"features": jnp.asarray(
            rng.randn(args.workers, args.window, args.batch, dim),
            jnp.float32),
         "label": jnp.asarray(
            rng.randint(0, args.classes,
                        (args.workers, args.window, args.batch)),
            jnp.int32)}
        for _ in range(rounds)]
    import jax

    pkey = jax.random.key(2)
    perms = []
    for _ in range(rounds):
        pkey, sub = jax.random.split(pkey)
        perms.append(commit_permutation(sub, args.workers))

    def assert_close(a, b, what):
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=2e-5, atol=1e-6,
                                       err_msg=what)

    for trainer in ("downpour", "dynsgd"):
        args.trainer = trainer
        ref = _Arm(args, "fast", False, mlp_dim=dim)
        got = _Arm(args, "mesh", False, mlp_dim=dim,
                   sync_metrics=True)
        for b, p in zip(batches, perms):
            mr = ref.round(*ref.put(b, p))
            mg = got.round(*got.put(b, p))
            assert_close(mr["loss"], mg["loss"], f"{trainer} loss")
        assert_close(ref.center_host(), got.center_host(),
                     f"{trainer} center")

        refp = _Arm(args, "faithful", True, mlp_dim=dim)
        gotp = _Arm(args, "mesh", True, mlp_dim=dim,
                    sync_metrics=True)
        for b, p in zip(batches, perms):
            refp.round(*refp.put(b, p))
            gotp.round(*gotp.put(b, p))
        refp.flush()
        gotp.flush()
        assert_close(refp.center_host(), gotp.center_host(),
                     f"{trainer} pipelined center")
        print(json.dumps({"parity": trainer, "ok": True}), flush=True)

    # compile guard: 3 rounds per arm, exactly ONE trace per round
    # shape (2 trainers x 1 program per fidelity label)
    comp = {k: v for k, v in tel.metrics.snapshot()["counters"].items()
            if k.startswith("ps_round_compiles_total")}
    assert comp.get('ps_round_compiles_total{fidelity="mesh"}') == 2, \
        comp
    assert comp.get(
        'ps_round_compiles_total{fidelity="mesh_pipelined"}') == 2, \
        comp

    # the measured record, gated through perf_regress both ways
    args.trainer = "downpour"
    rec = measure(args, "mesh", overlap=False)
    assert rec["loss_finite"], rec
    out_dir = pathlib.Path(tempfile.mkdtemp(prefix="dkt_flagship_"))
    cand = pathlib.Path(args.out) if args.out \
        else out_dir / "candidate.json"
    cand.write_text(json.dumps(rec))
    (out_dir / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "cmd": "smoke", "rc": 0, "tail": "", "parsed": rec}))
    traj = perf_regress.load_trajectories(str(out_dir / "BENCH_*.json"))
    rows = perf_regress.evaluate([json.loads(cand.read_text())], traj,
                                 tolerance=0.5)
    assert [r["status"] for r in rows] == ["pass"], rows
    bad = perf_regress.evaluate(
        [{"metric": rec["metric"], "value": rec["value"] / 10.0}],
        traj, tolerance=0.5)
    assert bad[0]["status"] == "breach", bad
    print(json.dumps({"gate": rec["metric"], "pass_and_breach": True}),
          flush=True)
    telemetry.disable()
    print(json.dumps({"smoke": "ok"}))
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trainer", default="aeasgd",
                    choices=["adag", "aeasgd", "downpour", "dynsgd"])
    ap.add_argument("--fidelity", default="faithful",
                    choices=["faithful", "fast", "mesh"],
                    help="lowering tier for the round program "
                         "(mesh = the SPMD compiled data plane; "
                         "delta family only)")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--window", type=int, default=2)
    ap.add_argument("--batch", type=int, default=32,
                    help="per-worker batch")
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--comm-dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="mesh tier: delta reduce-scatter wire dtype")
    ap.add_argument("--comm-codec", default=None,
                    choices=[None, "int8"],
                    help="mesh tier: center re-broadcast codec")
    ap.add_argument("--metrics-every", type=int, default=1,
                    help="mesh tier: rounds per metrics-ring fetch")
    ap.add_argument("--overlap", action="store_true",
                    help="commit-pipelined round (delta family): the "
                         "commit of round k-1 rides in the same "
                         "program as window k — VERDICT r4 #2")
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual host devices (CPU runs; set "
                         "before jax imports)")
    ap.add_argument("--out", default=None,
                    help="write the parsed-format BENCH record here "
                         "(perf_regress.py --candidate input)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny-shape CPU proof: parity + compile "
                         "guard + the perf gate, tier-1 mode")
    args = ap.parse_args()

    if args.smoke:
        args.devices = args.devices or 4
        args.workers, args.window, args.batch = 4, 2, 2
        args.image, args.classes, args.reps = 32, 8, 2
        # stable regime: at the default lr the tiny config is chaotic
        # and conv-batching float noise (solo-shaped device programs
        # vs the emulated tier's vmap — different accumulation order)
        # would compound to O(1) center differences
        args.lr = 1e-3
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    if args.smoke:
        smoke(args)
        return

    rec = measure(args, args.fidelity, args.overlap)
    print(json.dumps(rec))
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(rec))


if __name__ == "__main__":
    main()

"""PS-family flagship throughput: the emulated-fidelity async round on
the TPU (VERDICT r3 #6).

BASELINE.json's north star is *AEASGD* on ResNet-50, but every prior
flagship number timed only the bare synchronous step.  This measures
the thing the PS family actually executes on-device: one emulated
commit round — ``communication_window`` jitted train steps per worker
(workers vmapped over the chip / sharded over a mesh) followed by the
``UpdateRule`` commits in permuted order (design 5b: the PS as XLA
collective state, no tunnel/host round-trip) — with the same
scalar-fetch sync and analytic-FLOPs MFU as ``bench.py``.

Run on the TPU:  python scripts/perf_ps_flagship.py
                 [--trainer aeasgd|adag|downpour|dynsgd]
                 [--workers 4 --window 2 --batch 32 --image 224]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trainer", default="aeasgd",
                    choices=["adag", "aeasgd", "downpour", "dynsgd"])
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--window", type=int, default=2)
    ap.add_argument("--batch", type=int, default=32,
                    help="per-worker batch")
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--overlap", action="store_true",
                    help="commit-pipelined round (delta family): the "
                         "commit scan of round k-1 rides in the same "
                         "program as window k — VERDICT r4 #2")
    args = ap.parse_args()

    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.models import model_config
    from distkeras_tpu.parallel.ps_emulator import make_round_fn
    from distkeras_tpu.profiling import (host_sync, peak_flops,
                                         resnet50_model_flops)
    from distkeras_tpu.trainers import ADAG, AEASGD, DOWNPOUR, DynSGD
    from distkeras_tpu.workers import TrainState, make_train_step

    cls = {"adag": ADAG, "aeasgd": AEASGD, "downpour": DOWNPOUR,
           "dynsgd": DynSGD}[args.trainer]
    cfg = model_config("resnet", (args.image, args.image, 3),
                       num_classes=args.classes,
                       stage_sizes=(3, 4, 6, 3), bottleneck=True,
                       stem="space_to_depth")
    t = cls(cfg, num_workers=args.workers,
            communication_window=args.window, batch_size=args.batch,
            learning_rate=0.1, worker_optimizer="momentum", seed=0)

    rule = t.allocate_rule()
    tx = t._tx()
    variables = t.model.init(
        jax.random.key(0),
        jnp.ones((2, args.image, args.image, 3), jnp.float32))
    center = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    def make_worker(rng):
        return TrainState.create({"params": center, **model_state},
                                 tx, rng)

    worker_keys = jax.random.split(jax.random.key(1), args.workers)
    worker_states = jax.vmap(make_worker)(worker_keys)
    step = make_train_step(t.model, t.loss, tx)
    ps_state = rule.init_state(center)

    # [W, window, B, H, W, C] device batch — what the emulated arm
    # feeds each round
    x = jnp.ones((args.workers, args.window, args.batch,
                  args.image, args.image, 3), jnp.float32)
    y = jnp.zeros((args.workers, args.window, args.batch), jnp.int32)
    batch = {"features": x, "label": y}
    perm = jnp.arange(args.workers)

    if args.overlap:
        from distkeras_tpu.parallel.ps_emulator import \
            make_pipelined_round_fn

        round_fn = make_pipelined_round_fn(rule, step)
        round_jit = jax.jit(round_fn, donate_argnums=(0, 1, 4))
        pend = jax.tree_util.tree_map(jnp.zeros_like,
                                      worker_states.params)
        valid = jnp.asarray(False)

        def run():
            nonlocal ps_state, worker_states, pend, valid
            (ps_state, worker_states, metrics, pend, _,
             valid) = round_jit(ps_state, worker_states, batch, perm,
                                pend, perm, valid)
            return metrics
    else:
        round_fn = make_round_fn(rule, step, "faithful")
        round_jit = jax.jit(round_fn, donate_argnums=(0, 1))

        def run():
            nonlocal ps_state, worker_states
            ps_state, worker_states, metrics = round_jit(
                ps_state, worker_states, batch, perm)
            return metrics

    for _ in range(3):
        metrics = run()
    host_sync(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(args.reps):
        metrics = run()
    val = host_sync(metrics["loss"])
    dt = (time.perf_counter() - t0) / args.reps

    imgs = args.workers * args.window * args.batch
    flops = resnet50_model_flops(imgs, args.image)
    peak, known = peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": (f"{args.trainer}_resnet50_emulated_round"
                   + ("_overlap" if args.overlap else "")),
        "images_per_sec": round(imgs / dt, 2),
        "mfu": round(flops / dt / peak, 4) if known else None,
        "round_ms": round(dt * 1e3, 2),
        "per_step_ms": round(dt * 1e3 / args.window, 2),
        "workers": args.workers, "window": args.window,
        "batch_per_worker": args.batch,
        "global_images_per_round": imgs,
        "image": args.image,
        "loss_finite": bool(np.isfinite(val)),
    }))


if __name__ == "__main__":
    main()

"""Where does the ResNet-50 step time go?  Ablation timing on the TPU.

Isolates: host-dispatch overhead (scan-K vs single step), forward vs
backward vs optimizer, norm cost, and input-resolution scaling.  Prints one
JSON line per experiment; results land in PERF.md.

Usage:  PYTHONPATH=/root/repo:$PYTHONPATH python scripts/perf_ablate.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from distkeras_tpu.profiling import (
    host_sync,
    peak_flops,
    resnet50_model_flops,
    time_step_chain,
)


def timed(fn, *args, n=20):
    """Time a stateless (non-donating) function."""
    out = fn(*args)
    out = fn(*args)
    host_sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    host_sync(out)
    return (time.perf_counter() - t0) / n


def report(name, dt, batch, train=True, image=224):
    peak, known = peak_flops(jax.devices()[0])
    model_flops = resnet50_model_flops(batch, image, train=train)
    print(json.dumps({
        "exp": name, "step_ms": round(dt * 1e3, 2),
        "images_per_sec": round(batch / dt, 1),
        "honest_mfu": round(model_flops / dt / peak, 4) if known else None,
    }), flush=True)


def main():
    from distkeras_tpu.models import ResNet50
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       make_window_runner,
                                       resolve_optimizer)

    batch = 256

    def build(norm="group", image=224):
        model = ResNet50(num_classes=1000, norm=norm)
        tx = resolve_optimizer("momentum", 0.1)
        x = jnp.ones((batch, image, image, 3), jnp.float32)
        variables = model.init(jax.random.key(0), x[:2])
        state = TrainState.create(variables, tx, jax.random.key(1))
        bd = {"features": x, "label": jnp.zeros((batch,), jnp.int32)}
        return model, tx, state, bd

    # 1. baseline full step
    model, tx, state, bd = build()
    step = make_train_step(model, "categorical_crossentropy", tx)
    jit_step = jax.jit(step, donate_argnums=0)
    dt, _ = time_step_chain(jit_step, state, bd)
    report("full_step_b256", dt, batch)

    # 2. scan-4 window in one dispatch (amortizes host overhead)
    model, tx, state, bd = build()
    window = make_window_runner(step)
    bd4 = {k: jnp.broadcast_to(v[None], (4, *v.shape)) for k, v in bd.items()}
    jit_win = jax.jit(window, donate_argnums=0)
    dt, _ = time_step_chain(jit_win, state, bd4)
    report("scan4_per_step_b256", dt / 4, batch)

    # 3. forward only (inference mode)
    model, tx, state, bd = build()
    fwd = jax.jit(lambda v, x: model.apply(v, x, train=False))
    dt = timed(fwd, state.variables(), bd["features"])
    report("forward_only_b256", dt, batch, train=False)

    # 4. forward + backward, no optimizer update
    model, tx, state, bd = build()
    from distkeras_tpu.ops.losses import resolve_loss
    loss_fn = resolve_loss("categorical_crossentropy")

    def grads_only(params, x, y):
        return jax.grad(
            lambda p: loss_fn(model.apply({"params": p}, x, train=True),
                              y))(params)
    jit_g = jax.jit(grads_only)
    dt = timed(jit_g, state.params, bd["features"], bd["label"])
    report("fwd_bwd_b256", dt, batch)

    # 5. norm ablation: no norm at all
    model, tx, state, bd = build(norm="none")
    step = make_train_step(model, "categorical_crossentropy", tx)
    jit_step = jax.jit(step, donate_argnums=0)
    dt, _ = time_step_chain(jit_step, state, bd)
    report("full_step_nonorm_b256", dt, batch)

    # 6. resolution scaling: 112 px
    model, tx, state, bd = build(image=112)
    step = make_train_step(model, "categorical_crossentropy", tx)
    jit_step = jax.jit(step, donate_argnums=0)
    dt, _ = time_step_chain(jit_step, state, bd)
    report("full_step_112px_b256", dt, batch, image=112)


if __name__ == "__main__":
    main()

"""Host-PS ceiling quantification at ResNet-18 scale (PERF.md §12).

The reference's known scalability ceiling is the parameter server
(SURVEY.md §2.4: GIL threads, full-weight pickle per window).  The
rebuild's socket PS re-creates that architecture deliberately; this
script measures where it saturates:

Part 1 — raw PS throughput: N hammering threads, each loop = pull +
commit of a ResNet-18-sized delta (~11.2M params, ~45 MB msgpack raw)
against the real ``PSServer`` over loopback TCP.  Reports commits/sec
and payload GB/s vs thread count, raw vs int8 wire.

Part 2 — end-to-end stall fraction: DOWNPOUR(fidelity='host',
transport='socket') training ResNet-18 @32px, ``PSClient.pull/commit``
wall-time instrumented, for window in {1, 4, 16} x {raw, int8}.
Reports rows/sec and the fraction of worker wall-time spent inside the
PS exchange (the "worker-stall fraction").

Run on CPU (the host arm's per-thread device programs are plain convs —
no vmapped-conv slow path), so the wire path is measured without the
TPU tunnel's 11 MB/s transfer distortion:
    JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python scripts/perf_host_ps.py
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import numpy as np


def resnet18_center():
    import jax.numpy as jnp

    from distkeras_tpu.models.resnet import ResNet18

    model = ResNet18(num_classes=10, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 32, 32, 3)))
    params = jax.tree_util.tree_map(np.asarray, variables["params"])
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    return params, n


def part1_raw_throughput(center, n_params, commits=8, workers_list=(1, 2, 4, 8)):
    from distkeras_tpu.parallel.compression import resolve_codec
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSClient, PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule
    from distkeras_tpu.utils import tree_zeros_like

    delta = jax.tree_util.tree_map(
        lambda x: (0.001 * np.ones_like(x)), center)
    for codec_name in (None, "int8"):
        codec = resolve_codec(codec_name)
        payload = codec.encode(delta) if codec else delta
        for workers in workers_list:
            ps = HostParameterServer(DownpourRule(), center)
            server = PSServer(ps, center).start()
            host, port = server.address
            barrier = threading.Barrier(workers + 1)
            done = []

            def worker(w):
                client = PSClient(host, port, w, center,
                                  codec=codec_name)
                client.pull()
                barrier.wait()  # start together
                for s in range(commits):
                    client.commit(payload, seq=s)
                done.append(w)
                client.close()

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(workers)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            total = commits * workers
            raw_bytes = sum(x.nbytes for x in
                            jax.tree_util.tree_leaves(delta))
            wire = (len(payload) if codec
                    else raw_bytes)  # msgpack adds only framing
            print(json.dumps({
                "bench": "ps_raw", "wire": codec_name or "raw",
                "workers": workers,
                "commits_per_sec": round(total / dt, 2),
                "payload_mb": round(wire / 1e6, 1),
                "wire_gb_per_sec": round(total * wire / dt / 1e9, 3),
            }), flush=True)
            server.stop()
            assert len(done) == workers


class _PSCallClock:
    """Context manager instrumenting ``PSClient.pull/commit`` wall time
    (worker threads race on the accumulators; lock-protected)."""

    def __init__(self):
        self.t = 0.0
        self.n = 0
        self._lock = threading.Lock()

    def __enter__(self):
        from distkeras_tpu.parallel import host_ps

        self._mod = host_ps
        self._orig = (host_ps.PSClient.pull, host_ps.PSClient.commit)

        def timed(fn):
            def inner(s, *a, **k):
                t0 = time.perf_counter()
                out = fn(s, *a, **k)
                dt = time.perf_counter() - t0
                with self._lock:
                    self.t += dt
                    self.n += 1
                return out
            return inner

        host_ps.PSClient.pull = timed(self._orig[0])
        host_ps.PSClient.commit = timed(self._orig[1])
        return self

    def __exit__(self, *exc):
        self._mod.PSClient.pull = self._orig[0]
        self._mod.PSClient.commit = self._orig[1]
        return False


def part2_e2e_stall(rows=256, workers=4):
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    cfg = model_config("resnet", (32, 32, 3), num_classes=10,
                       stage_sizes=(2, 2, 2, 2), bottleneck=False,
                       dtype="float32")

    for codec in (None, "int8"):
        for window in (1, 4, 16):
            # at least 2 rounds per worker at every window
            rows_w = max(rows, 2 * workers * 8 * window)
            data = datasets.synthetic_classification(
                rows_w, (32, 32, 3), 10, seed=0)
            with _PSCallClock() as acc:
                t = DOWNPOUR(cfg, num_workers=workers,
                             communication_window=window,
                             batch_size=8, num_epoch=1,
                             learning_rate=0.01, seed=0,
                             fidelity="host", transport="socket",
                             compression=codec)
                t0 = time.perf_counter()
                t.train(data)
                wall = time.perf_counter() - t0
            wire = sum(t.history.get("commit_wire_bytes", []))
            out = {
                "bench": "e2e", "wire": codec or "raw",
                "window": window,
                "rows": rows_w,
                "rows_per_sec": round(rows_w / wall, 1),
                "ps_calls": acc.n,
                "stall_fraction": round(acc.t / (workers * wall), 3),
                "epoch_loss": round(t.history["epoch_loss"][-1], 3),
            }
            if wire:  # only the compressed arm tracks wire bytes
                out["commit_wire_mb"] = round(wire / 1e6, 1)
            print(json.dumps(out), flush=True)


def part3_cross_host(window=16, workers=4, rows=None):
    """Part 3 — the §12 recipe validated across REAL processes: a
    2-process jax.distributed cluster (PS on process 0, the DCN arm over
    real TCP), DOWNPOUR host/socket at ResNet-18@32px, window 16,
    raw vs int8 wire.  Reports global commits/s and per-process stall
    fraction."""
    from distkeras_tpu.deploy import run_multiprocess

    for codec in ("raw", "int8"):
        results = run_multiprocess(
            __file__, 2,
            args=["--part", "child", "--codec", codec,
                  "--window", str(window), "--workers", str(workers),
                  *(("--rows", str(rows)) if rows else ())],
            env={"JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
            timeout_s=1800.0)
        per_proc = [json.loads(r.stdout.strip().splitlines()[-1])
                    for r in results]
        wall = max(p["wall_s"] for p in per_proc)
        commits = per_proc[0]["commits"]  # telemetry is broadcast
        out = {
            "bench": "cross_host", "wire": codec, "window": window,
            "workers": workers, "processes": 2,
            "rows": per_proc[0]["rows"],
            "commits": commits,
            "commits_per_sec": round(commits / wall, 2),
            "rows_per_sec": round(per_proc[0]["rows"] / wall, 1),
            "stall_fraction_per_proc": [p["stall_fraction"]
                                        for p in per_proc],
            "epoch_loss": per_proc[0]["epoch_loss"],
        }
        wire_mb = per_proc[0].get("commit_wire_mb")
        if wire_mb:
            out["commit_wire_mb"] = wire_mb
        print(json.dumps(out), flush=True)


def part3_child(args):
    """One process of the cross-host arm (invoked by part3 via
    run_multiprocess)."""
    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    mesh_lib.initialize_cluster()
    workers = args.workers
    window = args.window
    rows = args.rows or max(512, 2 * workers * 8 * window)
    data = datasets.synthetic_classification(rows, (32, 32, 3), 10,
                                             seed=0)
    cfg = model_config("resnet", (32, 32, 3), num_classes=10,
                       stage_sizes=(2, 2, 2, 2), bottleneck=False,
                       dtype="float32")
    codec = None if args.codec == "raw" else args.codec
    local_workers = workers // jax.process_count()
    with _PSCallClock() as acc:
        t = DOWNPOUR(cfg, num_workers=workers,
                     communication_window=window, batch_size=8,
                     num_epoch=1, learning_rate=0.01, seed=0,
                     fidelity="host", transport="socket",
                     compression=codec)
        t0 = time.perf_counter()
        t.train(data)
        wall = time.perf_counter() - t0
    wire = sum(t.history.get("commit_wire_bytes", []))
    out = {
        "process": jax.process_index(),
        "rows": rows,
        "wall_s": round(wall, 3),
        "commits": len(t.history["staleness"][-1]),
        "stall_fraction": round(acc.t / (local_workers * wall), 3),
        "epoch_loss": round(t.history["epoch_loss"][-1], 3),
    }
    if wire:
        out["commit_wire_mb"] = round(wire / 1e6, 1)
    print(json.dumps(out), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--commits", type=int, default=8)
    ap.add_argument("--rows", type=int, default=None)
    ap.add_argument("--part", choices=["1", "2", "3", "both", "child"],
                    default="both")
    ap.add_argument("--window", type=int, default=16)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--codec", default="raw")
    args = ap.parse_args()
    if args.part == "child":
        part3_child(args)
        return
    center, n = resnet18_center()
    print(json.dumps({"model": "resnet18", "params": n,
                      "raw_mb": round(4 * n / 1e6, 1)}), flush=True)
    if args.part in ("1", "both"):
        part1_raw_throughput(center, n, commits=args.commits)
    if args.part in ("2", "both"):
        part2_e2e_stall(rows=args.rows or 256)
    if args.part == "3":
        part3_cross_host(window=args.window, workers=args.workers,
                         rows=args.rows)


if __name__ == "__main__":
    main()

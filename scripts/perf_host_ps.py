"""Host-PS ceiling quantification at ResNet-18 scale (PERF.md §12).

The reference's known scalability ceiling is the parameter server
(SURVEY.md §2.4: GIL threads, full-weight pickle per window).  The
rebuild's socket PS re-creates that architecture deliberately; this
script measures where it saturates:

Part 1 — raw PS throughput: N hammering threads, each loop = pull +
commit of a ResNet-18-sized delta (~11.2M params, ~45 MB msgpack raw)
against the real ``PSServer`` over loopback TCP.  Reports commits/sec
and payload GB/s vs thread count, raw vs int8 wire.

Part 2 — end-to-end stall fraction: DOWNPOUR(fidelity='host',
transport='socket') training ResNet-18 @32px, ``PSClient.pull/commit``
wall-time instrumented, for window in {1, 4, 16} x {raw, int8}.
Reports rows/sec and the fraction of worker wall-time spent inside the
PS exchange (the "worker-stall fraction").

Part 4 — sharded-PS A/B (PERF.md §25): ``ShardedParameterServer`` over
the shard-addressed zero-copy wire vs the single-mutex ``PSServer``
baseline, K ∈ {1, 2, 4, 8} x workers ∈ {2, 4, 8} hammering full-tree
commits at ResNet-18 scale, plus a stale-polling reader measuring the
version-delta pull's wire-byte savings.  ``--smoke`` runs a seconds-
scale arm at MLP scale with parity/savings assertions (tier-1 via
test_examples.py SMOKE_SCRIPTS).

Run on CPU (the host arm's per-thread device programs are plain convs —
no vmapped-conv slow path), so the wire path is measured without the
TPU tunnel's 11 MB/s transfer distortion:
    JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python scripts/perf_host_ps.py
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import numpy as np


def resnet18_center():
    import jax.numpy as jnp

    from distkeras_tpu.models.resnet import ResNet18

    model = ResNet18(num_classes=10, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 32, 32, 3)))
    params = jax.tree_util.tree_map(np.asarray, variables["params"])
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    return params, n


def part1_raw_throughput(center, n_params, commits=8, workers_list=(1, 2, 4, 8)):
    from distkeras_tpu.parallel.compression import resolve_codec
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSClient, PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule
    from distkeras_tpu.utils import tree_zeros_like

    delta = jax.tree_util.tree_map(
        lambda x: (0.001 * np.ones_like(x)), center)
    for codec_name in (None, "int8"):
        codec = resolve_codec(codec_name)
        payload = codec.encode(delta) if codec else delta
        for workers in workers_list:
            ps = HostParameterServer(DownpourRule(), center)
            server = PSServer(ps, center).start()
            host, port = server.address
            barrier = threading.Barrier(workers + 1)
            done = []

            def worker(w):
                client = PSClient(host, port, w, center,
                                  codec=codec_name)
                client.pull()
                barrier.wait()  # start together
                for s in range(commits):
                    client.commit(payload, seq=s)
                done.append(w)
                client.close()

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(workers)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            total = commits * workers
            raw_bytes = sum(x.nbytes for x in
                            jax.tree_util.tree_leaves(delta))
            wire = (len(payload) if codec
                    else raw_bytes)  # msgpack adds only framing
            print(json.dumps({
                "bench": "ps_raw", "wire": codec_name or "raw",
                "workers": workers,
                "commits_per_sec": round(total / dt, 2),
                "payload_mb": round(wire / 1e6, 1),
                "wire_gb_per_sec": round(total * wire / dt / 1e9, 3),
            }), flush=True)
            server.stop()
            assert len(done) == workers


class _PSCallClock:
    """Context manager instrumenting ``PSClient.pull/commit`` wall time
    (worker threads race on the accumulators; lock-protected)."""

    def __init__(self):
        self.t = 0.0
        self.n = 0
        self._lock = threading.Lock()

    def __enter__(self):
        from distkeras_tpu.parallel import host_ps

        self._mod = host_ps
        self._orig = (host_ps.PSClient.pull, host_ps.PSClient.commit)

        def timed(fn):
            def inner(s, *a, **k):
                t0 = time.perf_counter()
                out = fn(s, *a, **k)
                dt = time.perf_counter() - t0
                with self._lock:
                    self.t += dt
                    self.n += 1
                return out
            return inner

        host_ps.PSClient.pull = timed(self._orig[0])
        host_ps.PSClient.commit = timed(self._orig[1])
        return self

    def __exit__(self, *exc):
        self._mod.PSClient.pull = self._orig[0]
        self._mod.PSClient.commit = self._orig[1]
        return False


def part2_e2e_stall(rows=256, workers=4):
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    cfg = model_config("resnet", (32, 32, 3), num_classes=10,
                       stage_sizes=(2, 2, 2, 2), bottleneck=False,
                       dtype="float32")

    for codec in (None, "int8"):
        for window in (1, 4, 16):
            # at least 2 rounds per worker at every window
            rows_w = max(rows, 2 * workers * 8 * window)
            data = datasets.synthetic_classification(
                rows_w, (32, 32, 3), 10, seed=0)
            with _PSCallClock() as acc:
                t = DOWNPOUR(cfg, num_workers=workers,
                             communication_window=window,
                             batch_size=8, num_epoch=1,
                             learning_rate=0.01, seed=0,
                             fidelity="host", transport="socket",
                             compression=codec)
                t0 = time.perf_counter()
                t.train(data)
                wall = time.perf_counter() - t0
            wire = sum(t.history.get("commit_wire_bytes", []))
            out = {
                "bench": "e2e", "wire": codec or "raw",
                "window": window,
                "rows": rows_w,
                "rows_per_sec": round(rows_w / wall, 1),
                "ps_calls": acc.n,
                "stall_fraction": round(acc.t / (workers * wall), 3),
                "epoch_loss": round(t.history["epoch_loss"][-1], 3),
            }
            if wire:  # only the compressed arm tracks wire bytes
                out["commit_wire_mb"] = round(wire / 1e6, 1)
            print(json.dumps(out), flush=True)


def part3_cross_host(window=16, workers=4, rows=None):
    """Part 3 — the §12 recipe validated across REAL processes: a
    2-process jax.distributed cluster (PS on process 0, the DCN arm over
    real TCP), DOWNPOUR host/socket at ResNet-18@32px, window 16,
    raw vs int8 wire.  Reports global commits/s and per-process stall
    fraction."""
    from distkeras_tpu.deploy import run_multiprocess

    for codec in ("raw", "int8"):
        results = run_multiprocess(
            __file__, 2,
            args=["--part", "child", "--codec", codec,
                  "--window", str(window), "--workers", str(workers),
                  *(("--rows", str(rows)) if rows else ())],
            env={"JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
            timeout_s=1800.0)
        per_proc = [json.loads(r.stdout.strip().splitlines()[-1])
                    for r in results]
        wall = max(p["wall_s"] for p in per_proc)
        commits = per_proc[0]["commits"]  # telemetry is broadcast
        out = {
            "bench": "cross_host", "wire": codec, "window": window,
            "workers": workers, "processes": 2,
            "rows": per_proc[0]["rows"],
            "commits": commits,
            "commits_per_sec": round(commits / wall, 2),
            "rows_per_sec": round(per_proc[0]["rows"] / wall, 1),
            "stall_fraction_per_proc": [p["stall_fraction"]
                                        for p in per_proc],
            "epoch_loss": per_proc[0]["epoch_loss"],
        }
        wire_mb = per_proc[0].get("commit_wire_mb")
        if wire_mb:
            out["commit_wire_mb"] = wire_mb
        print(json.dumps(out), flush=True)


def _hammer_commits(center, num_shards, workers, commits,
                    use_seq=True):
    """One A/B cell: ``workers`` threads, each loop = one full-tree
    delta commit against a freshly-built server; K=1 is the single-
    mutex ``HostParameterServer`` + ``pack_params`` wire (the
    baseline), K>1 the ``ShardedParameterServer`` over the
    shard-addressed scatter-gather wire.  Returns commits/sec."""
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSClient, PSServer)
    from distkeras_tpu.parallel.sharded_ps import (
        ShardedParameterServer, ShardedPSClient)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    delta = jax.tree_util.tree_map(
        lambda x: (0.001 * np.ones_like(x)), center)
    if num_shards > 1:
        ps = ShardedParameterServer(DownpourRule(), center, num_shards)
    else:
        ps = HostParameterServer(DownpourRule(), center)
    server = PSServer(ps, center).start()
    host, port = server.address
    barrier = threading.Barrier(workers + 1)
    errs = []

    def worker(w):
        try:
            if num_shards > 1:
                client = ShardedPSClient(host, port, w, center,
                                         num_shards=num_shards)
            else:
                client = PSClient(host, port, w, center)
            client.pull()
            barrier.wait()
            for s in range(commits):
                client.commit(delta, seq=s if use_seq else None)
            client.close()
        except Exception as e:  # surfaced after join
            errs.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(workers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    server.stop()
    if errs:
        raise errs[0]
    return commits * workers / dt


def part4_sharded_ab(center, commits=6, shards_list=(1, 2, 4, 8),
                     workers_list=(2, 4, 8)):
    """The §25 grid: sharded commit throughput vs the single-mutex
    baseline, per (K, workers); the baseline row is K=1."""
    results = {}
    for workers in workers_list:
        for k in shards_list:
            cps = _hammer_commits(center, k, workers, commits)
            results[(k, workers)] = cps
            base = results.get((1, workers))
            print(json.dumps({
                "bench": "ps_sharded", "shards": k, "workers": workers,
                "commits_per_sec": round(cps, 2),
                "speedup_vs_mutex": (round(cps / base, 2)
                                     if base else 1.0),
            }), flush=True)
    return results


def part4_version_delta(center, num_shards=4, commit_rounds=6,
                        polls_per_round=4):
    """Stale-polling reader: a writer commits full-tree deltas while a
    reader pulls ``polls_per_round`` times per commit — the version-
    delta wire ships only shards whose clock advanced, so most polls
    cost a 2-byte header instead of the full parameter set."""
    from distkeras_tpu.parallel.host_ps import PSServer
    from distkeras_tpu.parallel.sharded_ps import (
        ShardedParameterServer, ShardedPSClient, leaf_nbytes)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    delta = jax.tree_util.tree_map(
        lambda x: (0.001 * np.ones_like(x)), center)
    full_bytes = leaf_nbytes(jax.tree_util.tree_leaves(center))
    ps = ShardedParameterServer(DownpourRule(), center, num_shards)
    server = PSServer(ps, center).start()
    host, port = server.address
    writer = ShardedPSClient(host, port, 0, center,
                             num_shards=num_shards)
    stats = {}
    reader = ShardedPSClient(host, port, 1, center,
                             num_shards=num_shards, stats=stats)
    writer.pull()
    reader.pull()  # first pull is always full (empty cache)
    for s in range(commit_rounds):
        writer.commit(delta, seq=s)
        for _ in range(polls_per_round):
            reader.pull()
    polls = commit_rounds * polls_per_round
    naive = polls * full_bytes
    shipped = naive - stats["pull_bytes_saved"]
    out = {
        "bench": "ps_version_delta", "shards": num_shards,
        "polls": polls, "full_pull_mb": round(full_bytes / 1e6, 2),
        "naive_mb": round(naive / 1e6, 1),
        "shipped_mb": round(shipped / 1e6, 1),
        "bytes_saved_frac": round(stats["pull_bytes_saved"] / naive,
                                  3),
        "shards_skipped": stats["pull_shards_skipped"],
    }
    print(json.dumps(out), flush=True)
    writer.close()
    reader.close()
    server.stop()
    return out


def _smoke_center(leaves=12, rows=64):
    rng = np.random.default_rng(0)
    return {f"w{i}": rng.normal(size=(rows, 8 + i)).astype(np.float32)
            for i in range(leaves)}


def smoke():
    """Seconds-scale correctness + direction check of the sharded PS
    (tier-1; the measured §25 numbers come from the full parts)."""
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSServer)
    from distkeras_tpu.parallel.sharded_ps import (
        ShardedParameterServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    center = _smoke_center()
    # parity: identical serial schedule through both servers
    deltas = [jax.tree_util.tree_map(
        lambda x: ((i + 1) * 1e-3 * np.ones_like(x)), center)
        for i in range(4)]
    ref = HostParameterServer(DownpourRule(), center)
    sha = ShardedParameterServer(DownpourRule(), center, 2)
    for ps in (ref, sha):
        for w in range(2):
            ps.pull(w)
        for i, d in enumerate(deltas):
            ps.commit(i % 2, d, seq=i // 2)
    for k in center:
        np.testing.assert_array_equal(np.asarray(ref.center[k]),
                                      np.asarray(sha.center[k]))
    assert ref.staleness_log == sha.staleness_log
    print(json.dumps({"bench": "smoke_parity", "ok": True}),
          flush=True)
    # wire throughput runs (no assertion on the ratio at smoke scale)
    for k in (1, 2):
        cps = _hammer_commits(center, k, workers=2, commits=3)
        print(json.dumps({"bench": "smoke_sharded", "shards": k,
                          "commits_per_sec": round(cps, 1)}),
              flush=True)
    # version-delta pulls must actually save bytes
    out = part4_version_delta(center, num_shards=2, commit_rounds=2,
                              polls_per_round=3)
    assert out["bytes_saved_frac"] > 0.5, out
    # sharded kill/warm-restart keeps the center byte-identical
    sha2 = ShardedParameterServer.from_snapshot(DownpourRule(),
                                               sha.snapshot())
    for k in center:
        np.testing.assert_array_equal(np.asarray(sha.center[k]),
                                      np.asarray(sha2.center[k]))
    print(json.dumps({"bench": "smoke_restart", "ok": True}),
          flush=True)
    # a PSServer restarted from that snapshot serves it
    srv = PSServer.restart_from(sha.snapshot(), DownpourRule(), center)
    assert srv.ps.num_shards == 2
    srv.stop()
    print(json.dumps({"smoke": "ok"}), flush=True)


def part3_child(args):
    """One process of the cross-host arm (invoked by part3 via
    run_multiprocess)."""
    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    mesh_lib.initialize_cluster()
    workers = args.workers
    window = args.window
    rows = args.rows or max(512, 2 * workers * 8 * window)
    data = datasets.synthetic_classification(rows, (32, 32, 3), 10,
                                             seed=0)
    cfg = model_config("resnet", (32, 32, 3), num_classes=10,
                       stage_sizes=(2, 2, 2, 2), bottleneck=False,
                       dtype="float32")
    codec = None if args.codec == "raw" else args.codec
    local_workers = workers // jax.process_count()
    with _PSCallClock() as acc:
        t = DOWNPOUR(cfg, num_workers=workers,
                     communication_window=window, batch_size=8,
                     num_epoch=1, learning_rate=0.01, seed=0,
                     fidelity="host", transport="socket",
                     compression=codec)
        t0 = time.perf_counter()
        t.train(data)
        wall = time.perf_counter() - t0
    wire = sum(t.history.get("commit_wire_bytes", []))
    out = {
        "process": jax.process_index(),
        "rows": rows,
        "wall_s": round(wall, 3),
        "commits": len(t.history["staleness"][-1]),
        "stall_fraction": round(acc.t / (local_workers * wall), 3),
        "epoch_loss": round(t.history["epoch_loss"][-1], 3),
    }
    if wire:
        out["commit_wire_mb"] = round(wire / 1e6, 1)
    print(json.dumps(out), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--commits", type=int, default=8)
    ap.add_argument("--rows", type=int, default=None)
    ap.add_argument("--part",
                    choices=["1", "2", "3", "4", "both", "child"],
                    default="both")
    ap.add_argument("--window", type=int, default=16)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--codec", default="raw")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale sharded-PS correctness arm "
                         "(tier-1)")
    args = ap.parse_args()
    if args.smoke:
        smoke()
        return
    if args.part == "child":
        part3_child(args)
        return
    center, n = resnet18_center()
    print(json.dumps({"model": "resnet18", "params": n,
                      "raw_mb": round(4 * n / 1e6, 1)}), flush=True)
    if args.part in ("1", "both"):
        part1_raw_throughput(center, n, commits=args.commits)
    if args.part in ("2", "both"):
        part2_e2e_stall(rows=args.rows or 256)
    if args.part == "3":
        part3_cross_host(window=args.window, workers=args.workers,
                         rows=args.rows)
    if args.part == "4":
        part4_sharded_ab(center, commits=args.commits)
        part4_version_delta(center)


if __name__ == "__main__":
    main()

"""Perf regression sentinel — compare candidate metrics against the
recorded BENCH_*.json trajectory baselines and exit nonzero on breach
(ISSUE 6 tentpole 4: the CI perf gate).

Baselines are the repo's benchmark trajectory files (``BENCH_r01.json``
..., each ``{"n", "cmd", "rc", "tail", "parsed": {"metric", "value",
"unit", ...}}``).  The gate takes the MEDIAN of each metric's
trajectory as its reference (one noisy run neither tightens nor
loosens the gate) and flags a candidate below ``(1 - tolerance) *
reference`` (``--lower-is-better`` flips the direction for latency-
style metrics).  A baseline run that itself failed (``rc != 0``) is
excluded from the trajectory.

Candidates come from either:

* ``--candidate FILE`` — a JSON file holding one parsed-format record
  (``{"metric": ..., "value": ...}``) or a list of them, e.g. the
  ``parsed`` block a fresh ``bench.py`` run printed;
* ``--from-registry SNAP --metric NAME --counter C --seconds S`` — a
  ``MetricsRegistry.snapshot()`` JSON from a smoke run, synthesizing
  ``NAME = sum(counter C) / S`` (a rate), so a CPU smoke can gate on
  its own throughput without a device benchmark.

``--smoke`` is the self-contained tier-1 proof: it runs a tiny
socket-transport training, derives a commits/sec candidate from the
live registry, gates it against a synthetic trajectory written from
the same run (pass), then gates a 10x-degraded candidate (must
breach) — both directions of the sentinel exercised end to end.
"""

from __future__ import annotations

import argparse
import glob
import json
import pathlib
import statistics
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

DEFAULT_BASELINES = str(REPO / "BENCH_*.json")


# ---- the gate ----------------------------------------------------------

def load_trajectories(pattern: str) -> dict[str, list[float]]:
    """metric name -> trajectory of values, oldest first, failed runs
    (rc != 0) excluded.

    Keying by metric name is what keeps MIXED-metric BENCH files from
    cross-comparing: a file whose run emitted the mesh-tier
    ``ps_round_images_per_sec_per_chip`` record never lands in the
    single-chip ``resnet50_train_*`` trajectory (ISSUE 16).  ``parsed``
    may be one record or a LIST of records (a run that printed several
    JSON lines, e.g. the flagship sweep) — each list entry joins its
    own metric's trajectory at the same ``n``.
    """
    out: dict[str, list[float]] = {}
    records = []
    for path in glob.glob(pattern):
        try:
            rec = json.load(open(path))
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed")
        if not parsed or rec.get("rc", 0) != 0:
            continue
        entries = parsed if isinstance(parsed, list) else [parsed]
        for p in entries:
            if isinstance(p, dict) and "metric" in p and "value" in p:
                records.append((rec.get("n", 0), p))
    for _, parsed in sorted(records, key=lambda r: r[0]):
        out.setdefault(parsed["metric"], []).append(
            float(parsed["value"]))
    return out


def evaluate(candidates: list[dict],
             trajectories: dict[str, list[float]],
             tolerance: float = 0.15,
             lower_is_better: bool = False) -> list[dict]:
    """One verdict row per candidate metric: reference (trajectory
    median), bound, pass/breach/no-baseline."""
    rows = []
    for cand in candidates:
        name, value = cand["metric"], float(cand["value"])
        traj = trajectories.get(name)
        if not traj:
            rows.append({"metric": name, "value": value,
                         "status": "no-baseline"})
            continue
        ref = statistics.median(traj)
        if lower_is_better:
            bound = ref * (1.0 + tolerance)
            ok = value <= bound
        else:
            bound = ref * (1.0 - tolerance)
            ok = value >= bound
        rows.append({"metric": name, "value": value, "ref": ref,
                     "bound": bound, "trajectory": traj,
                     "status": "pass" if ok else "breach"})
    return rows


def render(rows: list[dict]) -> str:
    lines = ["perf regression gate"]
    for r in rows:
        if r["status"] == "no-baseline":
            lines.append(f"  {r['metric']:<44} value={r['value']:g} "
                         "— no baseline trajectory, skipped")
            continue
        lines.append(
            f"  {r['metric']:<44} value={r['value']:g} "
            f"ref(median of {len(r['trajectory'])})={r['ref']:g} "
            f"bound={r['bound']:g} -> {r['status'].upper()}")
    return "\n".join(lines)


def from_registry(snapshot_path: str, metric: str, counter: str,
                  seconds: float) -> list[dict]:
    """Synthesize a rate candidate from a registry-snapshot JSON: the
    sum of every labeled series of ``counter``, divided by the run's
    wall seconds."""
    snap = json.load(open(snapshot_path))
    total = sum(v for key, v in snap.get("counters", {}).items()
                if key == counter or key.startswith(counter + "{"))
    return [{"metric": metric, "value": total / seconds,
             "unit": "per_sec"}]


# ---- the smoke run -----------------------------------------------------

def smoke(out_dir: str) -> None:
    import time

    from distkeras_tpu import telemetry
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    tel = telemetry.enable()
    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(512, (8,), 4, seed=0)
    t0 = time.perf_counter()
    DOWNPOUR(mlp, fidelity="host", transport="socket", num_workers=2,
             communication_window=2, batch_size=16, num_epoch=1,
             learning_rate=0.01, worker_optimizer="adam").train(data)
    seconds = time.perf_counter() - t0
    snap_path = out / "registry.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    telemetry.disable()

    cands = from_registry(str(snap_path), "smoke_ps_commits_per_sec",
                          "ps_commits_total", seconds)
    assert cands[0]["value"] > 0, cands

    # synthetic trajectory from this very run: the gate's reference
    for n in (1, 2, 3):
        (out / f"BENCH_r{n:02d}.json").write_text(json.dumps({
            "n": n, "cmd": "smoke", "rc": 0, "tail": "",
            "parsed": {"metric": "smoke_ps_commits_per_sec",
                       "value": cands[0]["value"] * (1 + 0.02 * n),
                       "unit": "per_sec"}}))
    traj = load_trajectories(str(out / "BENCH_*.json"))

    rows = evaluate(cands, traj, tolerance=0.5)
    print(render(rows))
    assert all(r["status"] == "pass" for r in rows), rows

    degraded = [{"metric": cands[0]["metric"],
                 "value": cands[0]["value"] / 10.0}]
    bad = evaluate(degraded, traj, tolerance=0.5)
    print(render(bad))
    assert bad[0]["status"] == "breach", bad

    unknown = evaluate([{"metric": "no_such_metric", "value": 1.0}],
                       traj)
    assert unknown[0]["status"] == "no-baseline", unknown
    print("smoke: ok")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--baselines", default=DEFAULT_BASELINES,
                    help="baseline trajectory glob "
                         "(default: repo BENCH_*.json)")
    ap.add_argument("--candidate", default=None,
                    help="candidate JSON: one parsed-format record or "
                         "a list of them")
    ap.add_argument("--from-registry", default=None, metavar="SNAP",
                    help="MetricsRegistry.snapshot() JSON to derive a "
                         "rate candidate from")
    ap.add_argument("--metric", default=None,
                    help="--from-registry: candidate metric name")
    ap.add_argument("--counter", default=None,
                    help="--from-registry: counter to rate")
    ap.add_argument("--seconds", type=float, default=None,
                    help="--from-registry: run wall seconds")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="allowed fractional slack vs the trajectory "
                         "median")
    ap.add_argument("--lower-is-better", action="store_true",
                    help="breach when the candidate EXCEEDS the bound "
                         "(latency-style metrics)")
    ap.add_argument("--smoke", action="store_true",
                    help="self-contained gate proof (tier-1 mode)")
    ap.add_argument("--out-dir", default=None,
                    help="--smoke artifact directory (temp default)")
    args = ap.parse_args()

    if args.smoke:
        smoke(args.out_dir or tempfile.mkdtemp(prefix="dkt_gate_"))
        return

    if args.candidate:
        loaded = json.load(open(args.candidate))
        candidates = loaded if isinstance(loaded, list) else [loaded]
        if all("parsed" in c for c in candidates):
            candidates = [c["parsed"] for c in candidates]
    elif args.from_registry:
        if not (args.metric and args.counter and args.seconds):
            ap.error("--from-registry needs --metric, --counter and "
                     "--seconds")
        candidates = from_registry(args.from_registry, args.metric,
                                   args.counter, args.seconds)
    else:
        ap.error("pass --candidate or --from-registry (or --smoke)")

    rows = evaluate(candidates, load_trajectories(args.baselines),
                    tolerance=args.tolerance,
                    lower_is_better=args.lower_is_better)
    print(render(rows))
    if any(r["status"] == "breach" for r in rows):
        sys.exit(2)


if __name__ == "__main__":
    main()

"""Microbenchmark: fused Pallas bottleneck kernels vs the XLA chains
they replace, at each ResNet-50 b256 stage geometry (PERF.md §11).

Compares, per stage:
  A: relu(gn(conv1x1(x)))            — fused_conv1x1_gn vs XLA chain
  B: relu(gn(conv1x1(relu(gn(y2)))) + res)
                                     — fused_bottleneck_tail vs XLA chain
each as forward-only and as a full VJP (sum-loss gradient).

Methodology: per-dispatch timing is useless here — the tunnel costs
~4 ms of host time per executable launch (PERF.md §3), an order of
magnitude above the ops themselves.  Each measurement therefore runs a
K-step ``lax.scan`` chain inside ONE jit, with a scalar carry
perturbing the weights (op A) or the input (op B) so XLA cannot hoist
or CSE the repeated computation, and reports wall/K.  For op B the
input perturbation adds one full R+W of y2 per iteration to BOTH arms
(equal absolute cost, so it dilutes — never inflates — the reported
speedup).

Usage:  PYTHONPATH=/root/repo python scripts/perf_fused.py

CAVEAT (measured, unresolved): on the tunneled chip the K-step scan
chains wrapping the Pallas custom-VJP calls compile for >10 minutes
without completing (plain per-dispatch jits of the same ops compile in
seconds).  Per-dispatch timing is the fallback here but is
overhead-dominated (~13 ms floor).  The measurement that decided the
fusion question is the END-TO-END A/B in ``perf_fused_e2e.py`` (full
train step, 100+ ms, dispatch amortized) — PERF.md §11.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.ops.fused_block import (fused_bottleneck_tail,
                                           fused_conv1x1_gn)
from distkeras_tpu.ops.pallas_kernels import group_norm_reference
from distkeras_tpu.profiling import host_sync


def chain(f, perturb_idx, args, k):
    """jit(scan): run ``f(*args)`` k times, carry a scalar from each
    output into a tiny perturbation of ``args[perturb_idx]`` so every
    iteration depends on the previous one."""

    def body(c, _):
        a = list(args)
        a[perturb_idx] = a[perturb_idx] + c.astype(a[perturb_idx].dtype)
        out = f(*a)
        leaf = out[0] if isinstance(out, tuple) else out
        return (leaf.ravel()[0].astype(jnp.float32) * 1e-20), None

    def run():
        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
        return c

    return jax.jit(run)


def timed_chain(f, perturb_idx, args, k=8, reps=3):
    fn = chain(f, perturb_idx, args, k)
    host_sync(fn())
    host_sync(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    host_sync(out)
    return (time.perf_counter() - t0) / (reps * k)


def xla_gn(y, gamma, beta, groups, relu):
    """The flax-equivalent GN lowering (E[x^2]-E[x]^2 one-pass stats,
    f32 math, bf16 out) — what the unfused model runs."""
    return group_norm_reference(y, gamma, beta, groups=groups,
                                relu=relu)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--only", type=str, default="",
                    help="comma-separated geometry-name filter "
                         "(substring match), e.g. 's1,s2'")
    args = ap.parse_args()
    n = args.batch
    rng = np.random.default_rng(0)

    stages = {
        "s1.op1": (3136, 256, 64),
        "s2.op1": (784, 512, 128),
        "s3.op1": (196, 1024, 256),
        "s4.op1": (49, 2048, 512),
        "s1.tail": (3136, 64, 256),
        "s2.tail": (784, 128, 512),
        "s3.tail": (196, 256, 1024),
        "s4.tail": (49, 512, 2048),
    }

    wanted = [s for s in args.only.split(",") if s]
    for name, (hw, cin, cout) in stages.items():
        if wanted and not any(s in name for s in wanted):
            continue
        g = 32
        x = jnp.asarray(rng.normal(size=(n, hw, cin)), jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(cin, cout)) * 0.05,
                        jnp.bfloat16)
        gamma = jnp.ones((cout,), jnp.float32)
        beta = jnp.zeros((cout,), jnp.float32)
        if name.endswith("op1"):
            def fused(x, w, gamma, beta):
                return fused_conv1x1_gn(x, w, gamma, beta, groups=g)

            def xla(x, w, gamma, beta):
                y = jnp.dot(x, w, preferred_element_type=jnp.float32)
                return xla_gn(y.astype(jnp.bfloat16), gamma, beta, g,
                              True)

            fa = (x, w, gamma, beta)
            pidx = 1  # perturb w: nothing is loop-invariant in either arm
        else:
            g2 = jnp.ones((cin,), jnp.float32)
            b2 = jnp.zeros((cin,), jnp.float32)
            res = jnp.asarray(rng.normal(size=(n, hw, cout)),
                              jnp.bfloat16)

            def fused(x, w, g2, b2, gamma, beta, res):
                return fused_bottleneck_tail(x, w, g2, b2, gamma, beta,
                                             res, groups2=g, groups3=g)

            def xla(x, w, g2, b2, gamma, beta, res):
                h = xla_gn(x, g2, b2, g, True)
                y = jnp.dot(h, w, preferred_element_type=jnp.float32)
                z = xla_gn(y.astype(jnp.bfloat16), gamma, beta, g,
                           False)
                return jnp.maximum(z + res.astype(z.dtype), 0)

            fa = (x, w, g2, b2, gamma, beta, res)
            pidx = 0  # perturb y2: equal extra R+W in both arms

        res_row = {"geom": name,
                   "shape": f"[{n},{hw},{cin}]x[{cin},{cout}]"}
        for tag, f in (("fused", fused), ("xla", xla)):
            grad = jax.grad(
                lambda *a: jnp.sum(f(*a).astype(jnp.float32)),
                argnums=tuple(range(len(fa))))
            res_row[f"{tag}_fwd_ms"] = round(timed_chain(
                f, pidx, fa, k=args.k, reps=args.reps) * 1e3, 3)
            res_row[f"{tag}_vjp_ms"] = round(timed_chain(
                grad, pidx, fa, k=args.k, reps=args.reps) * 1e3, 3)
        res_row["fwd_speedup"] = round(
            res_row["xla_fwd_ms"] / res_row["fused_fwd_ms"], 2)
        res_row["vjp_speedup"] = round(
            res_row["xla_vjp_ms"] / res_row["fused_vjp_ms"], 2)
        print(json.dumps(res_row), flush=True)


if __name__ == "__main__":
    main()

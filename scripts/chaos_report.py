"""Chaos / recovery report — exercise the fault-tolerance layer end to
end and summarize the recovery evidence from the telemetry registry.

Two scenarios (both run by ``--smoke``, the tier-1 registration via
test_examples.py's scripts-coverage check; tune them with the flags):

1. **Chaos-scheduled SOCKET training round** — an async host-PS
   training run over the real TCP transport inside a seed-pinned
   ``ChaosTransport`` (connection resets + mid-frame truncations +
   delays).  The run must finish inside the workers' retry budget and
   stay exactly-once (applied commits == completed rounds).
2. **Engine overload + drain** — a ``DecodeEngine`` with a bounded
   admission queue under 2x queue-bound overload: excess submits shed
   (``serving_shed_total``), a poisoned request is isolated as an
   ``error`` result, and ``drain()`` returns every accepted request.

The report prints, per layer: injected fault counts, client retries and
backoff spent, commit/dedupe/snapshot counters, shed/error counts —
the "what fired, what recovered, what it cost" summary an operator
would want after a chaos day.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def chaos_training_round(seed: int, rows: int) -> dict:
    """Scenario 1: seed-pinned chaos over the socket PS arm."""
    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.parallel.faults import ChaosTransport
    from distkeras_tpu.trainers import DOWNPOUR

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(rows, (8,), 4, seed=0)
    with ChaosTransport(seed=seed, reset_rate=0.15, truncate_rate=0.1,
                        delay_rate=0.1, delay_s=0.01, skip_ops=4,
                        max_injections=5) as chaos:
        t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                     num_workers=2, communication_window=2,
                     batch_size=16, num_epoch=1, learning_rate=0.01,
                     worker_optimizer="adam", worker_retries=10)
        t.train(data)
    rounds = len(t.history["round_loss"])
    commits = t.parameter_server_state.num_commits
    assert commits == rounds, (
        f"exactly-once violated under chaos: {commits} commits for "
        f"{rounds} rounds")
    assert "worker_failures" not in t.history, t.history[
        "worker_failures"]
    loss = t.history["epoch_loss"]
    assert np.isfinite(loss).all(), loss
    return {"injected": dict(chaos.counts), "rounds": rounds,
            "commits": commits,
            "retried_rounds": sum(map(len, t.history.get(
                "worker_round_retries", []))),
            "final_loss": float(loss[-1])}


def engine_overload_and_drain(seed: int) -> dict:
    """Scenario 2: bounded-queue shedding + poisoned-request isolation
    + graceful drain on a tiny LM."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.serving import DecodeEngine, ShedError

    spec = model_config("transformer_lm", (32,), input_dtype="int32",
                        vocab_size=61, num_layers=1, d_model=32,
                        num_heads=2, max_len=32, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 32), jnp.int32))
    slots, bound = 2, 2
    eng = DecodeEngine(model, variables, slots=slots, prefill_align=4,
                       max_new_tokens=5, queue_bound=bound)
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 61, (t,)).astype(np.int32)
               for t in [5, 7, 4, 6, 5, 8, 4, 5]]  # 2x (slots + bound)
    accepted, shed = [], 0
    for i, p in enumerate(prompts):
        try:
            accepted.append(eng.submit(p, request_id=i))
        except ShedError:
            shed += 1
    assert shed > 0, "2x queue-bound overload failed to shed"

    # poison one accepted request's prefill: it must error out alone
    pool = eng._pools[0]
    real_prefill = pool.prefill_fn
    poison_len = len(prompts[accepted[-1]])

    def poisoned(variables, cache, state, prompt, slot, last_idx,
                 n_left0, eos_id, rng):
        if int(last_idx) == poison_len - 1:
            raise RuntimeError("chaos: poisoned request")
        return real_prefill(variables, cache, state, prompt, slot,
                            last_idx, n_left0, eos_id, rng)

    pool.prefill_fn = poisoned
    results = {r["request_id"]: r for r in eng.drain()}
    pool.prefill_fn = real_prefill
    assert sorted(results) == sorted(accepted), (
        "drain lost in-flight requests")
    errors = [r for r in results.values() if "error" in r]
    ok = [r for r in results.values() if "error" not in r]
    assert errors and ok, (len(errors), len(ok))
    leftovers = eng.close()
    assert leftovers == [] and not eng.has_work()
    return {"submitted": len(prompts), "accepted": len(accepted),
            "shed": shed, "errors": len(errors),
            "completed": len(ok)}


def registry_lines(tel) -> list[str]:
    """The recovery-relevant counters/histograms, straight from the
    telemetry registry."""
    lines = ["== telemetry recovery summary =="]
    snap = tel.metrics.snapshot()
    wanted = ("chaos_injected_total", "ps_client_retries_total",
              "ps_commits_total", "ps_commit_dedup_total",
              "ps_snapshots_total", "ps_restarts_total",
              "serving_shed_total", "serving_request_errors_total",
              "serving_finished_total")
    for key, value in sorted(snap["counters"].items()):
        if key.split("{")[0] in wanted:
            lines.append(f"  counter    {key:<52} {value:g}")
    for key, h in sorted(snap["histograms"].items()):
        if key.split("{")[0] == "ps_client_backoff_seconds":
            mean = h["sum"] / h["count"] if h["count"] else float("nan")
            lines.append(f"  histogram  {key:<38} n={h['count']} "
                         f"total_sleep={h['sum']:.3f}s "
                         f"mean={mean * 1e3:.1f}ms")
    return lines


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes (the tier-1 mode)")
    ap.add_argument("--seed", type=int, default=7,
                    help="chaos schedule seed (pins every injection)")
    ap.add_argument("--rows", type=int, default=1024,
                    help="training rows for the chaos round")
    ap.add_argument("--out", default=None,
                    help="also write the report to this file")
    args = ap.parse_args()
    if args.smoke:
        args.rows = min(args.rows, 1024)

    from distkeras_tpu import telemetry

    tel = telemetry.enable()
    train = chaos_training_round(args.seed, args.rows)
    serve = engine_overload_and_drain(args.seed)

    lines = ["distkeras_tpu chaos / recovery report",
             f"(chaos seed {args.seed} — the same seed replays the "
             "same injection schedule)",
             "== scenario 1: chaos-scheduled SOCKET training =="]
    lines += [f"  injected {k:<10} {n}"
              for k, n in sorted(train["injected"].items())]
    lines += [
        f"  rounds completed       {train['rounds']}",
        f"  commits applied        {train['commits']} "
        "(== rounds: exactly-once held)",
        f"  rounds retried         {train['retried_rounds']}",
        f"  final epoch loss       {train['final_loss']:.4f}",
        "== scenario 2: engine overload + poisoned request + drain ==",
        f"  submitted              {serve['submitted']}",
        f"  accepted               {serve['accepted']}",
        f"  shed at the door       {serve['shed']}",
        f"  isolated as error      {serve['errors']}",
        f"  completed clean        {serve['completed']} "
        "(drain returned every accepted request)",
    ]
    lines += registry_lines(tel)
    report = "\n".join(lines)

    if args.smoke:
        for needle in ("chaos_injected_total", "serving_shed_total",
                       "ps_client_retries_total",
                       "serving_request_errors_total",
                       "exactly-once held"):
            assert needle in report, f"report lacks {needle}:\n{report}"
        report += "\nsmoke: ok"
    telemetry.disable()

    print(report)
    if args.out:
        pathlib.Path(args.out).write_text(report + "\n")


if __name__ == "__main__":
    main()

"""Chaos / recovery report — exercise the fault-tolerance layer end to
end and summarize the recovery evidence from the telemetry registry.

Four scenarios (all run by ``--smoke``, the tier-1 registration via
test_examples.py's scripts-coverage check; tune them with the flags):

1. **Chaos-scheduled SOCKET training round** — an async host-PS
   training run over the real TCP transport inside a seed-pinned
   ``ChaosTransport`` (connection resets + mid-frame truncations +
   delays).  The run must finish inside the workers' retry budget and
   stay exactly-once (applied commits == completed rounds).
2. **Engine overload + drain** — a ``DecodeEngine`` with a bounded
   admission queue under 2x queue-bound overload: excess submits shed
   (``serving_shed_total``), a poisoned request is isolated as an
   ``error`` result, and ``drain()`` returns every accepted request.
3. **Replicated-PS primary kill** (ISSUE 10) — a 2-node replica group
   loses its primary mid-training: the standby self-promotes (epoch
   2), the workers fail over, commits lost must be ZERO, and the
   kill -> promote latency plus the run's commit throughput are gated
   through ``perf_regress`` (the latency lower-is-better).
4. **Elastic reshard + receiver kill mid-move** (ISSUE 14) — an
   elastic PS group splits and live-migrates shards under a
   ``ps_elastic`` training run, then the RECEIVING server of a second
   migration is killed mid-stream: the cutover aborts cleanly, the
   old owner un-fences, commits lost must be ZERO, and the successful
   migration's latency is ``perf_regress``-gated.

The report prints, per layer: injected fault counts, client retries and
backoff spent, commit/dedupe/snapshot counters, shed/error counts,
promotion latency and epoch — the "what fired, what recovered, what it
cost" summary an operator would want after a chaos day.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import perf_regress  # noqa: E402  (sibling script, path set above)


def chaos_training_round(seed: int, rows: int) -> dict:
    """Scenario 1: seed-pinned chaos over the socket PS arm."""
    import numpy as np

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.parallel.faults import ChaosTransport
    from distkeras_tpu.trainers import DOWNPOUR

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(rows, (8,), 4, seed=0)
    with ChaosTransport(seed=seed, reset_rate=0.15, truncate_rate=0.1,
                        delay_rate=0.1, delay_s=0.01, skip_ops=4,
                        max_injections=5) as chaos:
        t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                     num_workers=2, communication_window=2,
                     batch_size=16, num_epoch=1, learning_rate=0.01,
                     worker_optimizer="adam", worker_retries=10)
        t.train(data)
    rounds = len(t.history["round_loss"])
    commits = t.parameter_server_state.num_commits
    assert commits == rounds, (
        f"exactly-once violated under chaos: {commits} commits for "
        f"{rounds} rounds")
    assert "worker_failures" not in t.history, t.history[
        "worker_failures"]
    loss = t.history["epoch_loss"]
    assert np.isfinite(loss).all(), loss
    return {"injected": dict(chaos.counts), "rounds": rounds,
            "commits": commits,
            "retried_rounds": sum(map(len, t.history.get(
                "worker_round_retries", []))),
            "final_loss": float(loss[-1])}


def failover_round(rows: int, out_dir: str) -> dict:
    """Scenario 3 (ISSUE 10): kill the PRIMARY of a 2-node replicated
    PS group mid-training.  The standby must promote itself (epoch
    bump), every worker's ``ResilientPSClient`` must walk its replica
    list onto the new primary, and the run must finish with ZERO lost
    commits (the promoted node's commit count == completed rounds —
    the replicated dedupe table keeps retried commits exactly-once
    across the failover).  Promotion latency is measured from the
    fsynced ``ps_kill`` flight event to the successor's ``ps_promote``
    and gated through ``perf_regress``."""
    import json
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu import flight_recorder, telemetry
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.parallel.replicated_ps import make_replica_group
    from distkeras_tpu.parallel.update_rules import DownpourRule
    from distkeras_tpu.trainers import DOWNPOUR

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    flight_dir = out / "flight"

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(rows, (8,), 4, seed=0)
    model = ModelSpec.from_config(mlp).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    center = jax.tree_util.tree_map(np.asarray, variables["params"])

    flight_recorder.start(flight_dir)
    nodes = make_replica_group(DownpourRule(), center, replicas=2,
                               failover_timeout=0.5)
    try:
        def killer():
            while nodes[0].ps.num_commits < 3:
                time.sleep(0.002)
            nodes[0].kill()

        k = threading.Thread(target=killer)
        k.start()
        t0 = time.perf_counter()
        t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                     num_workers=2, communication_window=2,
                     batch_size=16, num_epoch=1, learning_rate=0.01,
                     worker_optimizer="adam", worker_retries=14,
                     ps_replicas=[n.worker_address for n in nodes])
        t.train(data)
        seconds = time.perf_counter() - t0
        k.join()
        rounds = len(t.history["round_loss"])
        commits = nodes[1].ps.num_commits
        epoch = nodes[1].ps.epoch
    finally:
        for n in nodes:
            n.stop()
    events = flight_recorder.active().read_events()
    flight_recorder.stop()

    kills = [e for e in events if e["kind"] == "ps_kill"]
    promotes = [e for e in events if e["kind"] == "ps_promote"
                and e["reason"] == "failover"]
    assert kills and promotes, (
        f"failover story incomplete: {len(kills)} kills, "
        f"{len(promotes)} failover promotions")
    latency = promotes[0]["wall_s"] - kills[-1]["wall_s"]
    assert commits == rounds, (
        f"commits lost across failover: {commits} commits for "
        f"{rounds} rounds")
    assert t.history["ps_epoch"][-1] == epoch == 3, (
        t.history.get("ps_epoch"), epoch)
    assert t.history["ps_failovers"][-1] >= 1, t.history

    # ---- the perf_regress hookup: gate the recovery cost both ways —
    # commit throughput (from the live registry) must not collapse,
    # kill -> promote latency must not balloon (lower is better)
    snap_path = out / "registry.json"
    snap_path.write_text(json.dumps(telemetry.metrics().snapshot(),
                                    default=repr))
    cands = perf_regress.from_registry(
        str(snap_path), "failover_commits_per_sec",
        "ps_commits_total", seconds)
    latency_cand = [{"metric": "failover_promotion_latency_s",
                     "value": latency, "unit": "s"}]
    for i, c in enumerate(cands + latency_cand):
        for n in (1, 2, 3):  # synthetic trajectory from this very run
            (out / f"BENCH_fo{i}_r{n:02d}.json").write_text(
                json.dumps({
                    "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                    "parsed": {"metric": c["metric"],
                               "value": c["value"] * (1 + 0.02 * n),
                               "unit": c.get("unit", "per_sec")}}))
    traj = perf_regress.load_trajectories(str(out / "BENCH_fo*.json"))
    gate = (perf_regress.evaluate(cands, traj, tolerance=0.5)
            + perf_regress.evaluate(latency_cand, traj, tolerance=0.5,
                                    lower_is_better=True))
    assert all(r["status"] == "pass" for r in gate), gate
    return {"rounds": rounds, "commits": commits, "epoch": epoch,
            "failovers": int(t.history["ps_failovers"][-1]),
            "worker_retries": sum(map(len, t.history.get(
                "worker_round_retries", []))),
            "promotion_latency_s": latency, "gate": gate}


def elastic_migration_round(rows: int, out_dir: str) -> dict:
    """Scenario 4 (ISSUE 14): live resharding under fire.  A 2-server
    elastic PS group serves a ``ps_elastic`` training run while an ops
    thread (a) splits a shard, (b) migrates a shard to a freshly added
    server (zero downtime — the cutover latency comes from the
    ``shard_migrate_cutover`` flight event), then (c) starts a second
    migration and KILLS the receiving server mid-stream: the cutover
    must abort cleanly (``MigrationAborted``), the old owner must
    un-fence, and the run must finish with ZERO lost commits.  Commit
    throughput is gated via ``perf_regress.from_registry`` and the
    successful migration's latency as a lower-is-better candidate."""
    import json
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu import flight_recorder, telemetry
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.parallel.elastic_ps import (ElasticPSGroup,
                                                   MigrationAborted)
    from distkeras_tpu.parallel.update_rules import DownpourRule
    from distkeras_tpu.trainers import DOWNPOUR

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(rows, (8,), 4, seed=0)
    model = ModelSpec.from_config(mlp).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    center = jax.tree_util.tree_map(np.asarray, variables["params"])

    flight_recorder.start(out / "flight")
    grp = ElasticPSGroup(DownpourRule(), center, num_shards=2,
                         num_servers=2)
    ops: dict = {"aborted": None, "error": None}
    try:
        def _wait_commits(n):
            while grp.num_commits < n:
                time.sleep(0.002)

        def driver():
            try:
                _wait_commits(2)
                plan = grp.nodes[0].map.plan
                wide = max(range(len(plan)),
                           key=lambda s: len(plan[s]))
                grp.split(wide)
                _wait_commits(4)
                dst = grp.add_server("127.0.0.1")
                grp.migrate(0, dst)
                _wait_commits(6)
                # the receiver-kill: a fresh empty server dies while
                # the courier is streaming shard 1 into it
                doomed = grp.add_server("127.0.0.1")
                grp.start_migration(1, doomed)
                grp.servers[doomed].kill()
                try:
                    grp.cutover(1, timeout=10.0)
                    ops["aborted"] = False
                except MigrationAborted:
                    ops["aborted"] = True
            except Exception as e:  # surface, don't hang the report
                ops["error"] = e

        th = threading.Thread(target=driver)
        th.start()
        t0 = time.perf_counter()
        t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                     num_workers=2, communication_window=2,
                     batch_size=16, num_epoch=1, learning_rate=0.01,
                     worker_optimizer="adam", worker_retries=14,
                     ps_elastic=True, ps_address=grp.addresses[0])
        t.train(data)
        seconds = time.perf_counter() - t0
        th.join()
        rounds = len(t.history["round_loss"])
        commits = grp.num_commits
        shards = grp.num_shards
    finally:
        grp.stop()
    events = flight_recorder.active().read_events()
    flight_recorder.stop()

    if ops["error"] is not None:
        raise ops["error"]
    assert ops["aborted"], "receiver kill did not abort the cutover"
    assert commits == rounds, (
        f"commits lost across resharding: {commits} commits for "
        f"{rounds} rounds")
    assert np.isfinite(t.history["epoch_loss"]).all()
    cutovers = [e for e in events
                if e["kind"] == "shard_migrate_cutover"]
    aborts = [e for e in events if e["kind"] == "shard_migrate_abort"]
    splits = [e for e in events if e["kind"] == "shard_split"]
    assert splits and cutovers and aborts, (
        f"resharding story incomplete: {len(splits)} splits, "
        f"{len(cutovers)} cutovers, {len(aborts)} aborts")
    latency = float(cutovers[0]["latency_s"])

    # ---- perf_regress hookup: shard-commit throughput from the live
    # registry, migration latency lower-is-better
    snap_path = out / "registry.json"
    snap_path.write_text(json.dumps(telemetry.metrics().snapshot(),
                                    default=repr))
    cands = perf_regress.from_registry(
        str(snap_path), "elastic_commits_per_sec",
        "ps_shard_commits_total", seconds)
    latency_cand = [{"metric": "elastic_migration_latency_s",
                     "value": latency, "unit": "s"}]
    for i, c in enumerate(cands + latency_cand):
        for n in (1, 2, 3):  # synthetic trajectory from this very run
            (out / f"BENCH_el{i}_r{n:02d}.json").write_text(
                json.dumps({
                    "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                    "parsed": {"metric": c["metric"],
                               "value": c["value"] * (1 + 0.02 * n),
                               "unit": c.get("unit", "per_sec")}}))
    traj = perf_regress.load_trajectories(str(out / "BENCH_el*.json"))
    gate = (perf_regress.evaluate(cands, traj, tolerance=0.5)
            + perf_regress.evaluate(latency_cand, traj, tolerance=0.5,
                                    lower_is_better=True))
    assert all(r["status"] == "pass" for r in gate), gate
    return {"rounds": rounds, "commits": commits, "shards": shards,
            "migration_latency_s": latency,
            "aborts": len(aborts), "gate": gate}


def engine_overload_and_drain(seed: int) -> dict:
    """Scenario 2: bounded-queue shedding + poisoned-request isolation
    + graceful drain on a tiny LM."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.serving import DecodeEngine, ShedError

    spec = model_config("transformer_lm", (32,), input_dtype="int32",
                        vocab_size=61, num_layers=1, d_model=32,
                        num_heads=2, max_len=32, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 32), jnp.int32))
    slots, bound = 2, 2
    eng = DecodeEngine(model, variables, slots=slots, prefill_align=4,
                       max_new_tokens=5, queue_bound=bound)
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 61, (t,)).astype(np.int32)
               for t in [5, 7, 4, 6, 5, 8, 4, 5]]  # 2x (slots + bound)
    accepted, shed = [], 0
    for i, p in enumerate(prompts):
        try:
            accepted.append(eng.submit(p, request_id=i))
        except ShedError:
            shed += 1
    assert shed > 0, "2x queue-bound overload failed to shed"

    # poison one accepted request's prefill: it must error out alone
    pool = eng._pools[0]
    real_prefill = pool.prefill_fn
    poison_len = len(prompts[accepted[-1]])

    def poisoned(variables, cache, state, prompt, slot, last_idx,
                 n_left0, eos_id, rng):
        if int(last_idx) == poison_len - 1:
            raise RuntimeError("chaos: poisoned request")
        return real_prefill(variables, cache, state, prompt, slot,
                            last_idx, n_left0, eos_id, rng)

    pool.prefill_fn = poisoned
    results = {r["request_id"]: r for r in eng.drain()}
    pool.prefill_fn = real_prefill
    assert sorted(results) == sorted(accepted), (
        "drain lost in-flight requests")
    errors = [r for r in results.values() if "error" in r]
    ok = [r for r in results.values() if "error" not in r]
    assert errors and ok, (len(errors), len(ok))
    leftovers = eng.close()
    assert leftovers == [] and not eng.has_work()
    return {"submitted": len(prompts), "accepted": len(accepted),
            "shed": shed, "errors": len(errors),
            "completed": len(ok)}


def registry_lines(tel) -> list[str]:
    """The recovery-relevant counters/histograms, straight from the
    telemetry registry."""
    lines = ["== telemetry recovery summary =="]
    snap = tel.metrics.snapshot()
    wanted = ("chaos_injected_total", "chaos_window_injected_total",
              "sim_kills_total", "slo_violation_seconds_total",
              "autoscale_deferred_total",
              "sim_drill_convergence_seconds_total",
              "ps_client_retries_total",
              "ps_commits_total", "ps_commit_dedup_total",
              "ps_snapshots_total", "ps_restarts_total",
              "ps_promotions_total", "ps_client_failovers_total",
              "ps_fenced_total", "ps_replicated_entries_total",
              "ps_shard_fence_refresh_total", "ps_map_refresh_total",
              "elastic_reshards_total",
              "elastic_migrations_aborted_total",
              "serving_shed_total", "serving_request_errors_total",
              "serving_finished_total")
    for key, value in sorted(snap["counters"].items()):
        if key.split("{")[0] in wanted:
            lines.append(f"  counter    {key:<52} {value:g}")
    for key, h in sorted(snap["histograms"].items()):
        if key.split("{")[0] == "ps_client_backoff_seconds":
            mean = h["sum"] / h["count"] if h["count"] else float("nan")
            lines.append(f"  histogram  {key:<38} n={h['count']} "
                         f"total_sleep={h['sum']:.3f}s "
                         f"mean={mean * 1e3:.1f}ms")
    return lines


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes (the tier-1 mode)")
    ap.add_argument("--seed", type=int, default=7,
                    help="chaos schedule seed (pins every injection)")
    ap.add_argument("--rows", type=int, default=1024,
                    help="training rows for the chaos round")
    ap.add_argument("--out", default=None,
                    help="also write the report to this file")
    ap.add_argument("--out-dir", default=None,
                    help="failover-round artifact directory "
                         "(temp default)")
    args = ap.parse_args()
    if args.smoke:
        args.rows = min(args.rows, 1024)

    import tempfile

    from distkeras_tpu import telemetry

    tel = telemetry.enable()
    # failover first: its perf_regress rate candidate reads the
    # registry while only scenario 3's commits are in it (scenario 4's
    # gate counts ps_shard_commits_total, which nothing else touches)
    fail = failover_round(args.rows, args.out_dir or tempfile.mkdtemp(
        prefix="dkt_chaos_fo_"))
    elastic = elastic_migration_round(
        args.rows, args.out_dir or tempfile.mkdtemp(
            prefix="dkt_chaos_el_"))
    train = chaos_training_round(args.seed, args.rows)
    serve = engine_overload_and_drain(args.seed)

    lines = ["distkeras_tpu chaos / recovery report",
             f"(chaos seed {args.seed} — the same seed replays the "
             "same injection schedule)",
             "== scenario 1: chaos-scheduled SOCKET training =="]
    lines += [f"  injected {k:<10} {n}"
              for k, n in sorted(train["injected"].items())]
    lines += [
        f"  rounds completed       {train['rounds']}",
        f"  commits applied        {train['commits']} "
        "(== rounds: exactly-once held)",
        f"  rounds retried         {train['retried_rounds']}",
        f"  final epoch loss       {train['final_loss']:.4f}",
        "== scenario 2: engine overload + poisoned request + drain ==",
        f"  submitted              {serve['submitted']}",
        f"  accepted               {serve['accepted']}",
        f"  shed at the door       {serve['shed']}",
        f"  isolated as error      {serve['errors']}",
        f"  completed clean        {serve['completed']} "
        "(drain returned every accepted request)",
        "== scenario 3: replicated-PS primary kill + failover ==",
        f"  rounds completed       {fail['rounds']}",
        f"  commits on successor   {fail['commits']} "
        "(== rounds: commits lost = 0)",
        f"  fencing epoch          {fail['epoch']}",
        f"  client failovers       {fail['failovers']}",
        f"  rounds retried         {fail['worker_retries']}",
        f"  promotion latency      "
        f"{fail['promotion_latency_s'] * 1e3:.1f}ms "
        "(kill -> ps_promote, perf_regress gated)",
        "== scenario 4: elastic reshard + receiver kill mid-move ==",
        f"  rounds completed       {elastic['rounds']}",
        f"  commits on group       {elastic['commits']} "
        "(== rounds: commits lost = 0 across split/migrate/abort)",
        f"  final shard count      {elastic['shards']}",
        f"  migration latency      "
        f"{elastic['migration_latency_s'] * 1e3:.1f}ms "
        "(fence -> cutover, perf_regress gated)",
        f"  aborted moves          {elastic['aborts']} "
        "(receiver killed mid-stream; old owner un-fenced)",
    ]
    lines += registry_lines(tel)
    report = "\n".join(lines)

    if args.smoke:
        for needle in ("chaos_injected_total", "serving_shed_total",
                       "ps_client_retries_total",
                       "serving_request_errors_total",
                       "exactly-once held", "ps_promotions_total",
                       "commits lost = 0", "migration latency",
                       "old owner un-fenced"):
            assert needle in report, f"report lacks {needle}:\n{report}"
        report += "\nsmoke: ok"
    telemetry.disable()

    print(report)
    if args.out:
        pathlib.Path(args.out).write_text(report + "\n")


if __name__ == "__main__":
    main()

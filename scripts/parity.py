"""Async-vs-sync convergence parity — BASELINE.md's primary metric.

Trains the same model on the same dataset with the same per-worker batch
size and epoch budget through the synchronous control arm (SyncTrainer)
and each async PS trainer (ADAG / AEASGD / DynSGD / DOWNPOUR), then
writes the loss curves + final-accuracy table to ``parity.json`` and
``PARITY.md``.  This is the evidence that the on-mesh emulated-staleness
design (ps_emulator, SURVEY.md §7 design 5b) matches the sync arm's
convergence — the research core of the rebuild.

Runs on a forced 8-virtual-device CPU mesh so results are reproducible
anywhere:  python scripts/parity.py [--workers 8] [--epochs 4]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

# The MLP/LSTM runs force the virtual CPU mesh before jax initializes
# (the reference's local[N] analogue; see tests/conftest.py for why
# config-after-import).  The conv run stays on the real device: XLA:CPU
# lowers the emulator's batched-parameter convs ~25-100x slow
# (PERF.md §10).  A real pre-parse (not an argv-token scan) so both
# `--model conv` and `--model=conv` spellings are honored.
_pre = argparse.ArgumentParser(add_help=False)
_pre.add_argument("--model", choices=["mlp", "conv", "lstm"],
                  default="mlp")
_ON_CPU_MESH = _pre.parse_known_args()[0].model != "conv"
if _ON_CPU_MESH:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

if _ON_CPU_MESH:
    jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def run(trainer_name: str, cls, cfg, data, kwargs, eval_data):
    from distkeras_tpu.evaluators import evaluate_model

    t = cls(cfg, **kwargs)
    t.train(data)
    metrics = evaluate_model(t.model, t.trained_variables, eval_data,
                             batch_size=512)
    curve = t.history.get("round_loss") or t.history.get("epoch_loss")
    return {
        "trainer": trainer_name,
        "final_loss": float(curve[-1]),
        "accuracy": metrics["accuracy"],
        "training_time_s": round(t.training_time, 2),
        "epoch_loss": [round(x, 4) for x in t.history["epoch_loss"]],
        "loss_curve": [round(x, 4) for x in curve],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--window", type=int, default=None,
                    help="communication window (default: 4 mlp/conv, "
                         "2 lstm — the IMDB/DynSGD baseline shape)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--model", choices=["mlp", "conv", "lstm"],
                    default="mlp",
                    help="'conv' reruns the harness on the CIFAR-shaped "
                         "ConvNet (different gradient geometry — "
                         "SURVEY.md §7 hard part #1).  Run it on the "
                         "TPU: XLA:CPU lowers the emulator's "
                         "batched-parameter convs ~25-100x slow "
                         "(PERF.md §10).  'lstm' runs the third "
                         "geometry: a BiLSTM over token sequences (the "
                         "IMDB/DynSGD baseline row) with adam workers.")
    ap.add_argument("--learning-rate", type=float, default=None,
                    help="shared lr for every arm (default: 0.05 mlp, "
                         "0.02 conv, 0.005 lstm)")
    ap.add_argument("--margin", type=float, default=None,
                    help="class-center margin of the synthetic task "
                         "(default 1.0 mlp, 0.55 conv — sized so the "
                         "conv sync arm lands ~0.8, leaving headroom "
                         "to RESOLVE degradations; the round-3 table's "
                         "margin-1.0 task saturated at 1.0000)")
    ap.add_argument("--skip-host", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="emulated arms only.  Default True for "
                         "--model conv: 8 free-running conv workers "
                         "serialized through the single tunneled chip "
                         "starve the PS socket past its 30s timeout; "
                         "the host-vs-emulator staleness equivalence "
                         "is established at MLP scale where threads "
                         "aren't device-serialized.  Pass "
                         "--no-skip-host to force them.")
    ap.add_argument("--render-only", action="store_true",
                    help="regenerate PARITY.md from the saved parity "
                         "JSONs without training anything")
    args = ap.parse_args()
    if args.render_only:
        render_markdown()
        return
    # conv: the FULL-SCALE (8-worker) host arms stay off by default
    # (they starve the PS through the single tunneled chip), but the
    # 2-worker scoped host-vs-emulated twins run unless the user
    # explicitly passed --skip-host
    host_scoped_twins = (args.model == "conv"
                         and args.skip_host is not True)
    if args.skip_host is None:
        args.skip_host = args.model == "conv"
    if args.window is None:
        args.window = 2 if args.model == "lstm" else 4

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import (ADAG, AEASGD, DOWNPOUR, DynSGD,
                                        EAMSGD, SyncTrainer)

    import numpy as np

    n_eval = 2048
    worker_optimizer = "sgd"
    if args.model == "lstm" and args.margin is not None:
        raise SystemExit("--margin applies to the mlp/conv synthetic "
                         "tasks; the lstm task is token-count-based")
    if args.model == "conv":
        cfg = model_config("convnet", (32, 32, 3), num_classes=10,
                           widths=(16, 32), dense=64)
        args.margin = args.margin or 0.55  # recorded = used
        full = datasets.synthetic_classification(
            args.rows + n_eval, (32, 32, 3), 10, seed=0,
            margin=args.margin)
        # calibrated pair: margin 0.55 x lr 0.02 parks the sync arm
        # at ~0.91 on the 4-epoch default (~0.835 at 3; lr 0.01
        # under-converges to 0.45, which inverts the table: async arms
        # make more optimizer progress per epoch and lap an
        # unconverged control)
        args.learning_rate = args.learning_rate or 0.02  # recorded=used
        lr = args.learning_rate
    elif args.model == "lstm":
        # The IMDB/DynSGD baseline shape (BASELINE.md row 4): token
        # sequences through a BiLSTM, adam workers (plain SGD does not
        # learn this task inside any smoke budget — measured 0.56-0.58
        # at lr in {0.1, 0.3, 1.0} vs 0.97 for adam at 0.005).
        cfg = model_config("bilstm", (32,), input_dtype="int32",
                           vocab_size=200, embed_dim=16, hidden_dim=16,
                           num_classes=2)
        full = datasets.imdb_synth(args.rows + n_eval, seq_len=32,
                                   vocab_size=200, seed=3)
        args.learning_rate = args.learning_rate or 0.005
        lr = args.learning_rate
        worker_optimizer = "adam"
    else:
        cfg = model_config("mlp", (16,), num_classes=8, hidden=(64,))
        args.margin = args.margin or 1.0  # recorded = used
        full = datasets.synthetic_classification(
            args.rows + n_eval, (16,), 8, seed=0, margin=args.margin)
        args.learning_rate = args.learning_rate or 0.05
        lr = args.learning_rate
    # train/eval are a split of ONE mixture (same class centers —
    # a different seed would draw different centers, i.e. a different
    # task, and eval accuracy would sit at chance).
    idx = np.arange(len(full))
    data = full.filter(idx < args.rows)
    eval_data = full.filter(idx >= args.rows)

    common = dict(batch_size=args.batch, num_epoch=args.epochs,
                  learning_rate=lr, seed=0)
    if worker_optimizer != "sgd":
        # only the lstm arm overrides: EAMSGD's nesterov-worker default
        # must survive on the sgd-family tables
        common["worker_optimizer"] = worker_optimizer
    async_kwargs = dict(num_workers=args.workers,
                        communication_window=args.window, **common)

    results = [run("SyncTrainer", SyncTrainer, cfg, data,
                   dict(num_workers=args.workers, **common), eval_data)]
    print(json.dumps({"arm": "SyncTrainer",
                      "accuracy": results[0]["accuracy"]}), flush=True)
    # DOWNPOUR's unnormalized window-sum deltas make its stable lr
    # scale ~1/(workers x window) (the per-family laws recorded in
    # PARITY.md).  The MLP geometry happens to tolerate the shared lr;
    # conv gradients do not (measured: shared-lr DOWNPOUR on the conv
    # task sits at chance while every normalized-rule arm is fine), so
    # the conv table runs DOWNPOUR at its law-scaled lr and says so.
    if args.model == "conv":
        # best of its own lr sweep {lr, lr/window, lr/W, lr/(W*window),
        # lr/(2W*window)}: shared lr diverges (chance), everything
        # smaller under-converges non-monotonically.  The residual gap
        # this row shows is the point: DOWNPOUR is the rule WITHOUT
        # staleness compensation — the weakness ADAG/DynSGD exist to
        # fix, and conv geometry exposes it where the MLP did not.
        downpour_name = "DOWNPOUR (lr/W, best of sweep)"
        downpour_extra = {"learning_rate": lr / args.workers}
    else:
        downpour_name, downpour_extra = "DOWNPOUR", {}
    if args.model == "lstm":
        # Elastic rows: with adam workers the worker steps are large
        # relative to the elastic pull (alpha = lr x rho), so the
        # EMA-center transient needs a stronger rho to close inside the
        # budget — both points shown so the transient is visible.
        # EAMSGD is omitted: its only difference from AEASGD is the
        # nesterov worker optimizer, which the shared adam override
        # replaces — the run would be bit-identical to AEASGD's.
        elastic_rows = [("AEASGD (rho 2.5)", AEASGD, {"rho": 2.5}),
                        ("AEASGD (rho 10)", AEASGD, {"rho": 10.0})]
        dynsgd_row = ("DynSGD", DynSGD, {})
    elif args.model == "conv":
        # The de-saturated task exposes the per-family lr laws the MLP
        # masked (PARITY.md "scaling laws" table): DynSGD's stable lr
        # is ~1/window of the sgd-stable lr (measured here: shared
        # lr 0.02 -> 0.57, law lr -> parity-with-budget), and EAMSGD's
        # nesterov workers amplify lr ~10x (shared lr overshoots to
        # 0.82; half of it restores parity).  Law-scaled rows say so
        # in the name; AEASGD stays at the shared lr.
        dynsgd_row = ("DynSGD (lr/window, law)", DynSGD,
                      {"learning_rate": lr / args.window})
        elastic_rows = [("AEASGD", AEASGD, {"rho": 2.5}),
                        ("EAMSGD (lr/2, momentum law)", EAMSGD,
                         {"rho": 2.5, "learning_rate": lr / 2})]
    else:
        # The mlp elastic family runs at the SHARED lr: round 2
        # down-tuned AEASGD to lr=0.02 and recorded a -6.3-point gap
        # that a rho x lr sweep showed was lr under-convergence, not an
        # elastic-rule defect (gap at lr=0.05 is <0.005 for any rho in
        # [1, 10]; at lr=0.1 AEASGD *beats* sync).  rho=2.5 is the
        # paper-ish middle of the flat region.
        elastic_rows = [("AEASGD", AEASGD, {"rho": 2.5}),
                        ("EAMSGD", EAMSGD, {"rho": 2.5})]
        dynsgd_row = ("DynSGD", DynSGD, {})
    for name, cls, extra in [
        ("ADAG", ADAG, {}),
        dynsgd_row,
        (downpour_name, DOWNPOUR, downpour_extra),
        *elastic_rows,
        # the faithful concurrent arm (design 5a): real racing threads
        # against a host PS — validates the emulator's staleness
        # semantics (same UpdateRule math, emergent instead of
        # deterministic staleness)
        ("ADAG (host threads)", ADAG, {"fidelity": "host"}),
        ("DOWNPOUR (host, socket)", DOWNPOUR,
         {"fidelity": "host", "transport": "socket"}),
        # lossy wire + error feedback must not cost convergence
        ("DOWNPOUR (host, socket, int8 wire)", DOWNPOUR,
         {"fidelity": "host", "transport": "socket",
          "compression": "int8"}),
    ]:
        if args.skip_host and extra.get("fidelity") == "host":
            continue
        kw = {**async_kwargs, **extra}
        results.append(run(name, cls, cfg, data, kw, eval_data))
        print(json.dumps({"arm": name,
                          "accuracy": results[-1]["accuracy"]}),
              flush=True)

    if host_scoped_twins:
        # Scoped host twins (VERDICT r3 weak #3): 8 free-running conv
        # workers serialized through the single tunneled chip starve
        # the PS socket, so the emulator≡thread-race agreement is
        # established at a 2-worker scope — each host row next to its
        # EMULATED twin at the identical config, which is the claim
        # under test (same rule, same scale, deterministic vs emergent
        # staleness).
        scoped = dict(num_workers=2,
                      communication_window=args.window, **common)
        scoped_lr = {"learning_rate": lr / 2}  # DOWNPOUR law at W=2
        for name, cls, extra in [
            ("ADAG (emulated twin, 2w)", ADAG, {}),
            ("ADAG (host threads, 2w)", ADAG,
             {"fidelity": "host", "worker_timeout": 300.0}),
            ("DOWNPOUR (emulated twin, 2w, lr/W)", DOWNPOUR,
             dict(scoped_lr)),
            ("DOWNPOUR (host socket, 2w, lr/W)", DOWNPOUR,
             {"fidelity": "host", "transport": "socket",
              "worker_timeout": 300.0, **scoped_lr}),
        ]:
            kw = {**scoped, **extra}
            results.append(run(name, cls, cfg, data, kw, eval_data))
            print(json.dumps({"arm": name,
                              "accuracy": results[-1]["accuracy"]}),
                  flush=True)

    downpour_sweep = []
    if args.model == "conv":
        # Window sweep for DOWNPOUR (VERDICT r3 weak #4): if the
        # collapse is staleness/window-sum-driven it should ease as the
        # window shrinks toward 1; if it does not, the story is wrong.
        from distkeras_tpu.evaluators import evaluate_model

        table_row = next(r for r in results
                         if r["trainer"] == downpour_name)
        for w in (1, 2, 4):
            if w == args.window:
                # identical config to the table's DOWNPOUR row
                # (same law lr, same seed) — reuse, don't retrain
                acc = table_row["accuracy"]
            else:
                t = DOWNPOUR(cfg, num_workers=args.workers,
                             communication_window=w,
                             **{**common,
                                "learning_rate": lr / args.workers})
                t.train(data)
                acc = evaluate_model(
                    t.model, t.trained_variables, eval_data,
                    batch_size=512)["accuracy"]
            downpour_sweep.append(
                {"window": w, "learning_rate": lr / args.workers,
                 "accuracy": round(float(acc), 4)})
            print(json.dumps({"arm": f"DOWNPOUR window={w}",
                              "accuracy": acc}), flush=True)

    sync_acc = results[0]["accuracy"]
    for r in results[1:]:
        r["accuracy_gap_vs_sync"] = round(r["accuracy"] - sync_acc, 4)

    payload = {
        "config": vars(args),
        "model": cfg,
        "note": ("identical dataset/epochs/per-worker batch; staleness "
                 "emulated on-mesh with per-round permuted commit order "
                 "(ps_emulator 'faithful' default); '(host ...)' rows "
                 "run the concurrent host-side PS (design 5a) with "
                 "emergent staleness from real thread races"),
        "results": results,
    }
    if downpour_sweep:
        payload["downpour_window_sweep"] = downpour_sweep
    out_json = {"mlp": "parity.json", "conv": "parity_conv.json",
                "lstm": "parity_lstm.json"}[args.model]
    (REPO / out_json).write_text(json.dumps(payload, indent=2))
    render_markdown()
    print(json.dumps({r["trainer"]: r["accuracy"] for r in results},
                     indent=2))


def render_markdown():
    """(Re)generate PARITY.md from whichever of parity.json /
    parity_conv.json / parity_lstm.json exist — callable standalone
    (``--render-only``) so prose edits do not require retraining."""

    def table(payload) -> list[str]:
        c = payload["config"]
        fam = payload["model"]["family"]
        shape = {"mlp": "MLP (16,)->8",
                 "convnet": "ConvNet (32,32,3)->10, widths (16,32)",
                 "bilstm": "BiLSTM T=32 vocab 200, embed/hidden 16, "
                           "adam workers"}[fam]
        lines = [
            f"Setup: {shape}, {c['rows']} rows, {c['workers']} workers, "
            f"batch {c['batch']}/worker, window {c['window']}, "
            f"{c['epochs']} epochs.",
            "",
            "| Trainer | final loss | eval accuracy | gap vs sync "
            "| time (s) |",
            "|---|---|---|---|---|",
        ]
        for r in payload["results"]:
            gap = r.get("accuracy_gap_vs_sync", "—")
            lines.append(
                f"| {r['trainer']} | {r['final_loss']:.4f} | "
                f"{r['accuracy']:.4f} | {gap} | {r['training_time_s']} |")
        return lines

    lines = [
        "# PARITY — async PS trainers vs the synchronous control arm",
        "",
        "BASELINE.md primary metric: \"async-vs-sync convergence curves\".",
        "Full curves in `parity.json` / `parity_conv.json`; the MLP run "
        "is rendered in `PARITY.png` (scripts/plot_parity.py).  The MLP "
        "table runs on the 8-virtual-device CPU mesh; the ConvNet table "
        "(different gradient geometry — SURVEY.md §7 hard part #1) runs "
        "on the TPU chip, where the emulator's vmapped-window convs are "
        "fast (PERF.md §10).",
        "",
        "![convergence curves + accuracy table](PARITY.png)",
    ]
    def _load(fname):
        p = REPO / fname
        return json.loads(p.read_text()) if p.exists() else None

    mlp_payload = _load("parity.json")
    conv_payload = _load("parity_conv.json")
    lstm_payload = _load("parity_lstm.json")
    if mlp_payload:
        lines += ["", "## MLP scale", ""]
        lines += table(mlp_payload)
    if conv_payload:
        margin = conv_payload["config"].get("margin") or 0.55
        conv_lr = conv_payload["config"].get("learning_rate") or 0.02

        def row_acc(prefix):
            for r in conv_payload["results"]:
                if r["trainer"].startswith(prefix):
                    return r["accuracy"]
            return None

        sync_acc = conv_payload["results"][0]["accuracy"]
        adag_gap = (row_acc("ADAG") or 0) - sync_acc
        twin_deltas = [
            abs((row_acc(f"{fam} (host") or 0)
                - (row_acc(f"{fam} (emulated twin") or 0))
            for fam in ("ADAG", "DOWNPOUR")
            if row_acc(f"{fam} (host") is not None]
        twin_pts = (max(twin_deltas) * 100) if twin_deltas else None
        lines += [
            "", "## ConvNet scale (second gradient geometry)", "",
            f"Emulated arms on the TPU chip, margin-{margin} task, "
            f"lr {conv_lr} (round 3's margin-1.0 table saturated — "
            "four async arms at accuracy 1.0000 cannot RESOLVE "
            "sub-point degradation; this calibration parks sync at "
            f"{sync_acc:.2f} so every gap carries signal).  "
            "Findings:", "",
            f"- **ADAG lands ABOVE sync ({adag_gap:+.3f})**: on an "
            "unconverged budget the async family applies more "
            "optimizer progress per epoch (W commits per round vs "
            "one averaged step); with headroom in the task that "
            "shows as a lead, not a staleness deficit.",
            "- **The de-saturated task exposes the per-family lr "
            "laws** the forgiving tasks masked: at the shared lr "
            "DynSGD landed 0.57 and EAMSGD 0.82 (measured during "
            "calibration) — not staleness damage but lr-law "
            "violations (DynSGD's stable lr is ~1/window of "
            "sgd-stable; nesterov amplifies lr ~10x).  Their "
            "law-scaled rows (named in the table) restore "
            f"{row_acc('DynSGD') or 0:.2f} / "
            f"{row_acc('EAMSGD') or 0:.2f}.  DynSGD's residual gap "
            "at its law lr is a BUDGET transient of the most "
            "conservative rule: the same config at 8/12 epochs "
            "reaches 0.975 / 0.993 (one-off probe).",
        ] + ([
            f"- **Host≡emulated twins agree to {twin_pts:.1f} "
            "point(s)** ('(... 2w)' rows — scoped to 2 workers "
            "because 8 free-running conv workers starve the PS "
            "through the one tunneled chip): the emulator's "
            "deterministic staleness matches real thread races on "
            "conv geometry, closing the round-3 gap where this held "
            "only for MLPs.",
        ] if twin_pts is not None else []) + [
            "- **DOWNPOUR's collapse is mechanism-confirmed** by the "
            "window sweep below: monotone in the window, near-parity "
            "at window 1.", ""]
        lines += table(conv_payload)
        sweep = conv_payload.get("downpour_window_sweep")
        if sweep:
            lines += [
                "", "### DOWNPOUR window sweep (collapse mechanism)",
                "",
                "If DOWNPOUR's conv degradation is staleness/window-"
                "sum-driven it must ease as the window shrinks toward "
                "1 (fresher commits, smaller sums); if it were flat "
                "across windows, the story would be wrong "
                "(round 2's AEASGD lesson).  Measured at lr/W — "
                "monotone, near-parity at window 1: the collapse is "
                "the window-sum mechanism, confirmed:",
                "",
                "| window | eval accuracy |", "|---|---|",
            ] + [f"| {s['window']} | {s['accuracy']:.4f} |"
                 for s in sweep]
    if lstm_payload:
        lines += [
            "", "## BiLSTM scale (recurrent gradient geometry)", "",
            "The third gradient geometry (SURVEY.md §7 hard part #1): "
            "recurrence, gate saturation, shared weights through time, "
            "sparse embedding rows — the IMDB/DynSGD baseline shape "
            "(BASELINE.md row 4), run with adam workers because plain "
            "SGD does not learn the token-count task inside any smoke "
            "budget (measured: 0.56-0.58 at lr in {0.1, 0.3, 1.0} vs "
            "0.97 for adam).  Findings, all window-driven transients, "
            "none staleness-rule defects: (1) at window 1 ADAG matches "
            "sync to 0.2 points, and an MLP-with-adam control at "
            "window 4 shows NO gap — the window-4 degradation seen at "
            "lstm geometry is a recurrence x window x adam "
            "interaction, so the table runs the baseline window 2; "
            "(2) the elastic EMA-center lags inside the budget at "
            "rho 2.5 but closes to <0.5 points at rho 10 with 6 "
            "epochs (adam's large worker steps need a stronger pull — "
            "alpha = lr x rho); (3) the host-thread twins are the one "
            "place recurrent geometry shows RUN-TO-RUN VARIANCE: "
            "across five repeated runs at this exact setting "
            "ADAG-host landed 0.81/0.82/0.86/0.95/0.97 (sync "
            "0.96-0.97; emulated ADAG 0.95, deterministic) and "
            "DOWNPOUR-host 0.92/0.94/0.94/0.96/0.98 (emulated 0.97); "
            "int8 0.87 and 0.91 over two runs — emergent staleness "
            "schedules (mean staleness ~7 commits vs the emulator's "
            "~3.5 at 8 workers) differ per run, and the adam transient "
            "amplifies them where the MLP/conv geometries (sgd, "
            "flatter window response) did not.  The emulated rows are "
            "deterministic and sit inside the host twins' observed "
            "range, which is the staleness-equivalence claim stated "
            "at the honest precision this geometry supports.", ""]
        lines += table(lstm_payload)
    lines += [
        "",
        "Interpretation: the async family must land within a few points "
        "of the sync arm's accuracy on the same budget; DynSGD's "
        "staleness scaling and ADAG's window normalization should show "
        "no degradation at this staleness level (max staleness = "
        "workers-1 commits/round).  The '(host ...)' rows are "
        "the faithful concurrent arm (free-running threads, mutex PS, "
        "emergent staleness — design 5a): their agreement with the "
        "emulated rows is the evidence that the on-mesh deterministic "
        "staleness semantics (design 5b) match real asynchrony.  The "
        "'int8 wire' row adds commit compression with error feedback "
        "(parallel/compression.py): its agreement shows the lossy wire "
        "does not cost convergence either.",
        "",
        "## Elastic-family tuning (round-3 sweep)",
        "",
        "Round 2 recorded AEASGD 6.3 points BELOW sync — the one arm "
        "outside the acceptance bar.  A rho x lr sweep at this exact "
        "scale (rho in {1, 2.5, 5, 10} x lr in {0.02, 0.05, 0.1}) "
        "localized it: at the shared lr=0.05 the gap is < 0.005 for "
        "EVERY rho, and at lr=0.1 AEASGD beats sync by +0.01; only the "
        "lr=0.02 column (what round 2 ran) degrades, uniformly across "
        "rho.  The regression was learning-rate under-convergence of "
        "the local SGD, not elastic-pull damage; the elastic law is "
        "lr-neutral in this regime.  EAMSGD (Nesterov workers) lands "
        "ABOVE sync at every sweep point (+0.02..+0.026).  Both arms "
        "now run at the shared lr and are CI-enforced "
        "(tests/test_parity.py).",
        "",
        "## Per-family learning-rate scaling laws",
        "",
        "At THIS artifact's staleness level (8 workers, window 4) every "
        "family tolerates the shared lr.  When scaling workers/window "
        "up, the stable lr scales per family (measured in "
        "examples/compare_trainers.py, whose defaults encode them):",
        "",
        "| Family | stable lr vs plain-SGD lr | why |",
        "|---|---|---|",
        "| Sync / ADAG | ~1/workers | ADAG normalizes the window sum; "
        "commits average like a bigger batch |",
        "| DOWNPOUR | ~1/(workers x window) | unnormalized window-sum "
        "deltas accumulate workers x window gradients per round |",
        "| DynSGD | ~1/window | staleness scaling 1/(tau+1) already "
        "divides by the commit depth, leaving the window sum |",
        "| AEASGD / EAMSGD | shared lr (alpha = lr x rho couples the "
        "pull strength) | elastic exchange is symmetric; rho in "
        "[1, 10] is flat at this scale |",
    ]
    (REPO / "PARITY.md").write_text("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()

"""Async-vs-sync convergence parity — BASELINE.md's primary metric.

Trains the same model on the same dataset with the same per-worker batch
size and epoch budget through the synchronous control arm (SyncTrainer)
and each async PS trainer (ADAG / AEASGD / DynSGD / DOWNPOUR), then
writes the loss curves + final-accuracy table to ``parity.json`` and
``PARITY.md``.  This is the evidence that the on-mesh emulated-staleness
design (ps_emulator, SURVEY.md §7 design 5b) matches the sync arm's
convergence — the research core of the rebuild.

Runs on a forced 8-virtual-device CPU mesh so results are reproducible
anywhere:  python scripts/parity.py [--workers 8] [--epochs 4]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

# The MLP run forces the virtual CPU mesh before jax initializes (the
# reference's local[N] analogue; see tests/conftest.py for why
# config-after-import).  The conv run stays on the real device: XLA:CPU
# lowers the emulator's batched-parameter convs ~25-100x slow
# (PERF.md §10).  A real pre-parse (not an argv-token scan) so both
# `--model conv` and `--model=conv` spellings are honored.
_pre = argparse.ArgumentParser(add_help=False)
_pre.add_argument("--model", choices=["mlp", "conv"], default="mlp")
_ON_CPU_MESH = _pre.parse_known_args()[0].model != "conv"
if _ON_CPU_MESH:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

if _ON_CPU_MESH:
    jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def run(trainer_name: str, cls, cfg, data, kwargs, eval_data):
    from distkeras_tpu.evaluators import evaluate_model

    t = cls(cfg, **kwargs)
    t.train(data)
    metrics = evaluate_model(t.model, t.trained_variables, eval_data,
                             batch_size=512)
    curve = t.history.get("round_loss") or t.history.get("epoch_loss")
    return {
        "trainer": trainer_name,
        "final_loss": float(curve[-1]),
        "accuracy": metrics["accuracy"],
        "training_time_s": round(t.training_time, 2),
        "epoch_loss": [round(x, 4) for x in t.history["epoch_loss"]],
        "loss_curve": [round(x, 4) for x in curve],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--window", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--model", choices=["mlp", "conv"], default="mlp",
                    help="'conv' reruns the harness on the CIFAR-shaped "
                         "ConvNet (different gradient geometry — "
                         "SURVEY.md §7 hard part #1).  Run it on the "
                         "TPU: XLA:CPU lowers the emulator's "
                         "batched-parameter convs ~25-100x slow "
                         "(PERF.md §10).")
    ap.add_argument("--learning-rate", type=float, default=None,
                    help="shared lr for every arm (default: 0.05 mlp, "
                         "0.01 conv)")
    ap.add_argument("--skip-host", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="emulated arms only.  Default True for "
                         "--model conv: 8 free-running conv workers "
                         "serialized through the single tunneled chip "
                         "starve the PS socket past its 30s timeout; "
                         "the host-vs-emulator staleness equivalence "
                         "is established at MLP scale where threads "
                         "aren't device-serialized.  Pass "
                         "--no-skip-host to force them.")
    args = ap.parse_args()
    if args.skip_host is None:
        args.skip_host = args.model == "conv"

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import (ADAG, AEASGD, DOWNPOUR, DynSGD,
                                        EAMSGD, SyncTrainer)

    import numpy as np

    if args.model == "conv":
        cfg = model_config("convnet", (32, 32, 3), num_classes=10,
                           widths=(16, 32), dense=64)
        n_eval = 2048
        full = datasets.cifar10_synth(args.rows + n_eval, seed=0)
        lr = args.learning_rate or 0.01
    else:
        cfg = model_config("mlp", (16,), num_classes=8, hidden=(64,))
        n_eval = 2048
        full = datasets.synthetic_classification(
            args.rows + n_eval, (16,), 8, seed=0)
        lr = args.learning_rate or 0.05
    # train/eval are a split of ONE mixture (same class centers —
    # a different seed would draw different centers, i.e. a different
    # task, and eval accuracy would sit at chance).
    idx = np.arange(len(full))
    data = full.filter(idx < args.rows)
    eval_data = full.filter(idx >= args.rows)

    common = dict(batch_size=args.batch, num_epoch=args.epochs,
                  learning_rate=lr, seed=0)
    async_kwargs = dict(num_workers=args.workers,
                        communication_window=args.window, **common)

    results = [run("SyncTrainer", SyncTrainer, cfg, data,
                   dict(num_workers=args.workers, **common), eval_data)]
    print(json.dumps({"arm": "SyncTrainer",
                      "accuracy": results[0]["accuracy"]}), flush=True)
    # DOWNPOUR's unnormalized window-sum deltas make its stable lr
    # scale ~1/(workers x window) (the per-family laws recorded in
    # PARITY.md).  The MLP geometry happens to tolerate the shared lr;
    # conv gradients do not (measured: shared-lr DOWNPOUR on the conv
    # task sits at chance while every normalized-rule arm is fine), so
    # the conv table runs DOWNPOUR at its law-scaled lr and says so.
    if args.model == "conv":
        # best of its own lr sweep {lr, lr/window, lr/W, lr/(W*window),
        # lr/(2W*window)}: shared lr diverges (chance), everything
        # smaller under-converges non-monotonically.  The residual gap
        # this row shows is the point: DOWNPOUR is the rule WITHOUT
        # staleness compensation — the weakness ADAG/DynSGD exist to
        # fix, and conv geometry exposes it where the MLP did not.
        downpour_name = "DOWNPOUR (lr/W, best of sweep)"
        downpour_extra = {"learning_rate": lr / args.workers}
    else:
        downpour_name, downpour_extra = "DOWNPOUR", {}
    for name, cls, extra in [
        ("ADAG", ADAG, {}),
        ("DynSGD", DynSGD, {}),
        (downpour_name, DOWNPOUR, downpour_extra),
        # The elastic family runs at the SHARED lr: round 2 down-tuned
        # AEASGD to lr=0.02 and recorded a -6.3-point gap that a
        # rho x lr sweep showed was lr under-convergence, not an
        # elastic-rule defect (gap at lr=0.05 is <0.005 for any rho in
        # [1, 10]; at lr=0.1 AEASGD *beats* sync).  rho=2.5 is the
        # paper-ish middle of the flat region.
        ("AEASGD", AEASGD, {"rho": 2.5}),
        ("EAMSGD", EAMSGD, {"rho": 2.5}),
        # the faithful concurrent arm (design 5a): real racing threads
        # against a host PS — validates the emulator's staleness
        # semantics (same UpdateRule math, emergent instead of
        # deterministic staleness)
        ("ADAG (host threads)", ADAG, {"fidelity": "host"}),
        ("DOWNPOUR (host, socket)", DOWNPOUR,
         {"fidelity": "host", "transport": "socket"}),
        # lossy wire + error feedback must not cost convergence
        ("DOWNPOUR (host, socket, int8 wire)", DOWNPOUR,
         {"fidelity": "host", "transport": "socket",
          "compression": "int8"}),
    ]:
        if args.skip_host and extra.get("fidelity") == "host":
            continue
        kw = {**async_kwargs, **extra}
        results.append(run(name, cls, cfg, data, kw, eval_data))
        print(json.dumps({"arm": name,
                          "accuracy": results[-1]["accuracy"]}),
              flush=True)

    sync_acc = results[0]["accuracy"]
    for r in results[1:]:
        r["accuracy_gap_vs_sync"] = round(r["accuracy"] - sync_acc, 4)

    payload = {
        "config": vars(args),
        "model": cfg,
        "note": ("identical dataset/epochs/per-worker batch; staleness "
                 "emulated on-mesh with per-round permuted commit order "
                 "(ps_emulator 'faithful' default); '(host ...)' rows "
                 "run the concurrent host-side PS (design 5a) with "
                 "emergent staleness from real thread races"),
        "results": results,
    }
    out_json = ("parity.json" if args.model == "mlp"
                else "parity_conv.json")
    (REPO / out_json).write_text(json.dumps(payload, indent=2))

    def table(payload) -> list[str]:
        c = payload["config"]
        fam = payload["model"]["family"]
        shape = ("MLP (16,)->8" if fam == "mlp"
                 else "ConvNet (32,32,3)->10, widths (16,32)")
        lines = [
            f"Setup: {shape}, {c['rows']} rows, {c['workers']} workers, "
            f"batch {c['batch']}/worker, window {c['window']}, "
            f"{c['epochs']} epochs.",
            "",
            "| Trainer | final loss | eval accuracy | gap vs sync "
            "| time (s) |",
            "|---|---|---|---|---|",
        ]
        for r in payload["results"]:
            gap = r.get("accuracy_gap_vs_sync", "—")
            lines.append(
                f"| {r['trainer']} | {r['final_loss']:.4f} | "
                f"{r['accuracy']:.4f} | {gap} | {r['training_time_s']} |")
        return lines

    lines = [
        "# PARITY — async PS trainers vs the synchronous control arm",
        "",
        "BASELINE.md primary metric: \"async-vs-sync convergence curves\".",
        "Full curves in `parity.json` / `parity_conv.json`; the MLP run "
        "is rendered in `PARITY.png` (scripts/plot_parity.py).  The MLP "
        "table runs on the 8-virtual-device CPU mesh; the ConvNet table "
        "(different gradient geometry — SURVEY.md §7 hard part #1) runs "
        "on the TPU chip, where the emulator's vmapped-window convs are "
        "fast (PERF.md §10).",
        "",
        "![convergence curves + accuracy table](PARITY.png)",
    ]
    mlp_payload = (payload if args.model == "mlp" else
                   (json.loads((REPO / "parity.json").read_text())
                    if (REPO / "parity.json").exists() else None))
    conv_payload = (payload if args.model == "conv" else
                    (json.loads((REPO / "parity_conv.json").read_text())
                     if (REPO / "parity_conv.json").exists() else None))
    if mlp_payload:
        lines += ["", "## MLP scale", ""]
        lines += table(mlp_payload)
    if conv_payload:
        lines += [
            "", "## ConvNet scale (second gradient geometry)", "",
            "Emulated arms on the TPU chip (host arms: see "
            "--skip-host help).  The staleness-compensated rules "
            "(ADAG, DynSGD) and the elastic family match or beat sync "
            "on conv geometry exactly as on the MLP.  DOWNPOUR — the "
            "one rule with NO staleness compensation — degrades here "
            "at every lr in its sweep (shared lr: chance; smaller: "
            "non-monotonic under-convergence).  That asymmetry is the "
            "reference's own research premise made measurable: "
            "conv gradient geometry exposes the uncompensated-rule "
            "weakness that ADAG was invented to fix, which the "
            "too-forgiving MLP task masked.", ""]
        lines += table(conv_payload)
    lines += [
        "",
        "Interpretation: the async family must land within a few points "
        "of the sync arm's accuracy on the same budget; DynSGD's "
        "staleness scaling and ADAG's window normalization should show "
        "no degradation at this staleness level (max staleness = "
        f"{args.workers - 1} commits/round).  The '(host ...)' rows are "
        "the faithful concurrent arm (free-running threads, mutex PS, "
        "emergent staleness — design 5a): their agreement with the "
        "emulated rows is the evidence that the on-mesh deterministic "
        "staleness semantics (design 5b) match real asynchrony.  The "
        "'int8 wire' row adds commit compression with error feedback "
        "(parallel/compression.py): its agreement shows the lossy wire "
        "does not cost convergence either.",
        "",
        "## Elastic-family tuning (round-3 sweep)",
        "",
        "Round 2 recorded AEASGD 6.3 points BELOW sync — the one arm "
        "outside the acceptance bar.  A rho x lr sweep at this exact "
        "scale (rho in {1, 2.5, 5, 10} x lr in {0.02, 0.05, 0.1}) "
        "localized it: at the shared lr=0.05 the gap is < 0.005 for "
        "EVERY rho, and at lr=0.1 AEASGD beats sync by +0.01; only the "
        "lr=0.02 column (what round 2 ran) degrades, uniformly across "
        "rho.  The regression was learning-rate under-convergence of "
        "the local SGD, not elastic-pull damage; the elastic law is "
        "lr-neutral in this regime.  EAMSGD (Nesterov workers) lands "
        "ABOVE sync at every sweep point (+0.02..+0.026).  Both arms "
        "now run at the shared lr and are CI-enforced "
        "(tests/test_parity.py).",
        "",
        "## Per-family learning-rate scaling laws",
        "",
        "At THIS artifact's staleness level (8 workers, window 4) every "
        "family tolerates the shared lr.  When scaling workers/window "
        "up, the stable lr scales per family (measured in "
        "examples/compare_trainers.py, whose defaults encode them):",
        "",
        "| Family | stable lr vs plain-SGD lr | why |",
        "|---|---|---|",
        "| Sync / ADAG | ~1/workers | ADAG normalizes the window sum; "
        "commits average like a bigger batch |",
        "| DOWNPOUR | ~1/(workers x window) | unnormalized window-sum "
        "deltas accumulate workers x window gradients per round |",
        "| DynSGD | ~1/window | staleness scaling 1/(tau+1) already "
        "divides by the commit depth, leaving the window sum |",
        "| AEASGD / EAMSGD | shared lr (alpha = lr x rho couples the "
        "pull strength) | elastic exchange is symmetric; rho in "
        "[1, 10] is flat at this scale |",
    ]
    (REPO / "PARITY.md").write_text("\n".join(lines) + "\n")
    print(json.dumps({r["trainer"]: r["accuracy"] for r in results},
                     indent=2))


if __name__ == "__main__":
    main()

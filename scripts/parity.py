"""Async-vs-sync convergence parity — BASELINE.md's primary metric.

Trains the same model on the same dataset with the same per-worker batch
size and epoch budget through the synchronous control arm (SyncTrainer)
and each async PS trainer (ADAG / AEASGD / DynSGD / DOWNPOUR), then
writes the loss curves + final-accuracy table to ``parity.json`` and
``PARITY.md``.  This is the evidence that the on-mesh emulated-staleness
design (ps_emulator, SURVEY.md §7 design 5b) matches the sync arm's
convergence — the research core of the rebuild.

Runs on a forced 8-virtual-device CPU mesh so results are reproducible
anywhere:  python scripts/parity.py [--workers 8] [--epochs 4]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib

# Force the virtual CPU mesh before jax initializes (the reference's
# local[N] analogue; see tests/conftest.py for why config-after-import).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def run(trainer_name: str, cls, cfg, data, kwargs, eval_data):
    from distkeras_tpu.evaluators import evaluate_model

    t = cls(cfg, **kwargs)
    t.train(data)
    metrics = evaluate_model(t.model, t.trained_variables, eval_data,
                             batch_size=512)
    curve = t.history.get("round_loss") or t.history.get("epoch_loss")
    return {
        "trainer": trainer_name,
        "final_loss": float(curve[-1]),
        "accuracy": metrics["accuracy"],
        "training_time_s": round(t.training_time, 2),
        "epoch_loss": [round(x, 4) for x in t.history["epoch_loss"]],
        "loss_curve": [round(x, 4) for x in curve],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--window", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import (ADAG, AEASGD, DOWNPOUR, DynSGD,
                                        EAMSGD, SyncTrainer)

    import numpy as np

    cfg = model_config("mlp", (16,), num_classes=8, hidden=(64,))
    # train/eval are a split of ONE mixture (same class centers —
    # a different seed would draw different centers, i.e. a different
    # task, and eval accuracy would sit at chance).
    n_eval = 2048
    full = datasets.synthetic_classification(args.rows + n_eval, (16,),
                                             8, seed=0)
    idx = np.arange(len(full))
    data = full.filter(idx < args.rows)
    eval_data = full.filter(idx >= args.rows)

    common = dict(batch_size=args.batch, num_epoch=args.epochs,
                  learning_rate=0.05, seed=0)
    async_kwargs = dict(num_workers=args.workers,
                        communication_window=args.window, **common)

    results = [run("SyncTrainer", SyncTrainer, cfg, data,
                   dict(num_workers=args.workers, **common), eval_data)]
    for name, cls, extra in [
        ("ADAG", ADAG, {}),
        ("DynSGD", DynSGD, {}),
        ("DOWNPOUR", DOWNPOUR, {}),
        # The elastic family runs at the SHARED lr: round 2 down-tuned
        # AEASGD to lr=0.02 and recorded a -6.3-point gap that a
        # rho x lr sweep showed was lr under-convergence, not an
        # elastic-rule defect (gap at lr=0.05 is <0.005 for any rho in
        # [1, 10]; at lr=0.1 AEASGD *beats* sync).  rho=2.5 is the
        # paper-ish middle of the flat region.
        ("AEASGD", AEASGD, {"rho": 2.5}),
        ("EAMSGD", EAMSGD, {"rho": 2.5}),
        # the faithful concurrent arm (design 5a): real racing threads
        # against a host PS — validates the emulator's staleness
        # semantics (same UpdateRule math, emergent instead of
        # deterministic staleness)
        ("ADAG (host threads)", ADAG, {"fidelity": "host"}),
        ("DOWNPOUR (host, socket)", DOWNPOUR,
         {"fidelity": "host", "transport": "socket"}),
        # lossy wire + error feedback must not cost convergence
        ("DOWNPOUR (host, socket, int8 wire)", DOWNPOUR,
         {"fidelity": "host", "transport": "socket",
          "compression": "int8"}),
    ]:
        kw = {**async_kwargs, **extra}
        results.append(run(name, cls, cfg, data, kw, eval_data))

    sync_acc = results[0]["accuracy"]
    for r in results[1:]:
        r["accuracy_gap_vs_sync"] = round(r["accuracy"] - sync_acc, 4)

    payload = {
        "config": vars(args),
        "model": cfg,
        "note": ("identical dataset/epochs/per-worker batch; staleness "
                 "emulated on-mesh with per-round permuted commit order "
                 "(ps_emulator 'faithful' default); '(host ...)' rows "
                 "run the concurrent host-side PS (design 5a) with "
                 "emergent staleness from real thread races"),
        "results": results,
    }
    (REPO / "parity.json").write_text(json.dumps(payload, indent=2))

    lines = [
        "# PARITY — async PS trainers vs the synchronous control arm",
        "",
        "BASELINE.md primary metric: \"async-vs-sync convergence curves\".",
        f"Setup: MLP (16,)->8, {args.rows} rows, {args.workers} workers, "
        f"batch {args.batch}/worker, window {args.window}, "
        f"{args.epochs} epochs, 8-virtual-device CPU mesh.  Full curves "
        "in `parity.json`; rendered in `PARITY.png` "
        "(scripts/plot_parity.py).",
        "",
        "![convergence curves + accuracy table](PARITY.png)",
        "",
        "| Trainer | final loss | eval accuracy | gap vs sync | time (s) |",
        "|---|---|---|---|---|",
    ]
    for r in results:
        gap = r.get("accuracy_gap_vs_sync", "—")
        lines.append(
            f"| {r['trainer']} | {r['final_loss']:.4f} | "
            f"{r['accuracy']:.4f} | {gap} | {r['training_time_s']} |")
    lines += [
        "",
        "Interpretation: the async family must land within a few points "
        "of the sync arm's accuracy on the same budget; DynSGD's "
        "staleness scaling and ADAG's window normalization should show "
        "no degradation at this staleness level (max staleness = "
        f"{args.workers - 1} commits/round).  The '(host ...)' rows are "
        "the faithful concurrent arm (free-running threads, mutex PS, "
        "emergent staleness — design 5a): their agreement with the "
        "emulated rows is the evidence that the on-mesh deterministic "
        "staleness semantics (design 5b) match real asynchrony.  The "
        "'int8 wire' row adds commit compression with error feedback "
        "(parallel/compression.py): its agreement shows the lossy wire "
        "does not cost convergence either.",
        "",
        "## Elastic-family tuning (round-3 sweep)",
        "",
        "Round 2 recorded AEASGD 6.3 points BELOW sync — the one arm "
        "outside the acceptance bar.  A rho x lr sweep at this exact "
        "scale (rho in {1, 2.5, 5, 10} x lr in {0.02, 0.05, 0.1}) "
        "localized it: at the shared lr=0.05 the gap is < 0.005 for "
        "EVERY rho, and at lr=0.1 AEASGD beats sync by +0.01; only the "
        "lr=0.02 column (what round 2 ran) degrades, uniformly across "
        "rho.  The regression was learning-rate under-convergence of "
        "the local SGD, not elastic-pull damage; the elastic law is "
        "lr-neutral in this regime.  EAMSGD (Nesterov workers) lands "
        "ABOVE sync at every sweep point (+0.02..+0.026).  Both arms "
        "now run at the shared lr and are CI-enforced "
        "(tests/test_parity.py).",
    ]
    (REPO / "PARITY.md").write_text("\n".join(lines) + "\n")
    print(json.dumps({r["trainer"]: r["accuracy"] for r in results},
                     indent=2))


if __name__ == "__main__":
    main()

"""Trace merge — stitch per-process Perfetto/Chrome trace dumps into
one timeline with cross-process flow arrows (ISSUE 6 tentpole 1).

Each process dumps its own ring (``Tracer.write_chrome_trace``): the
PS server process holds the ``ps_rpc`` handler spans, every trainer
process holds its workers' ``ps_client_*`` spans.  The 17-byte wire
trace header (``parallel.transport.trace_header``) links them: the
client stamps its span id on the request and emits a flow-start
("s"), the server handler emits the matching flow-end ("f") — so
after ``telemetry.merge_traces`` aligns the wall clocks, Perfetto
draws an arrow from each surviving commit/pull to the handler that
served it, and a retry storm under ``ChaosTransport`` reads as one
causal chain (shared ``trace_id`` from the ``ps_op`` retry-loop
span).

Two modes:

* ``--out merged.json a.json b.json ...`` — merge trace files an
  earlier multi-process run wrote.
* ``--smoke`` — self-contained two-process proof (the tier-1
  registration): spawns a REAL second Python process hosting a
  ``PSServer``, trains against it over the socket wire with mild
  client-side chaos, dumps one trace per process, merges them, and
  asserts every server-side flow-end pairs with exactly one
  client-side flow-start across the process boundary.

(``--serve`` is the internal child-process mode of the smoke.)
"""

from __future__ import annotations

import argparse
import collections
import json
import pathlib
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

def _mlp_config():
    from distkeras_tpu.models import model_config

    return model_config("mlp", (8,), num_classes=4, hidden=(16,))


def _center():
    """Deterministic center: both processes derive the identical
    template, so the child's server serves the parent's model."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.models import ModelSpec

    model = ModelSpec.from_config(_mlp_config()).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    return (jax.tree_util.tree_map(np.asarray, variables["params"]),
            variables)


# ---- merge -------------------------------------------------------------

def merge_files(paths: list[str], out: str) -> dict:
    from distkeras_tpu import telemetry

    traces = [json.load(open(p)) for p in paths]
    merged = telemetry.merge_traces(*traces)
    pathlib.Path(out).write_text(json.dumps(merged))
    return merged


def summarize(merged: dict) -> str:
    events = merged["traceEvents"]
    pids = sorted({e["pid"] for e in events if "pid" in e})
    spans = collections.Counter(e["name"] for e in events
                                if e.get("ph") == "X")
    starts = [e for e in events if e.get("ph") == "s"]
    ends = [e for e in events if e.get("ph") == "f"]
    lines = [f"merged {len(events)} events across "
             f"{len(pids)} process tracks {pids}",
             f"flow arrows: {len(starts)} starts, {len(ends)} ends"]
    for name, n in spans.most_common():
        lines.append(f"  span {name:<24} n={n}")
    return "\n".join(lines)


def check_flow_pairing(merged: dict) -> int:
    """Every flow-end must match exactly ONE flow-start by (cat, id);
    orphan starts are legal (a chaos-eaten message has a sender but no
    handler).  Returns the number of paired arrows."""
    events = merged["traceEvents"]
    starts = collections.Counter(
        (e["cat"], e["id"]) for e in events if e.get("ph") == "s")
    ends = [(e["cat"], e["id"]) for e in events if e.get("ph") == "f"]
    for key in ends:
        assert starts.get(key, 0) == 1, (
            f"flow-end {key} has {starts.get(key, 0)} matching "
            f"starts (want exactly 1)")
    return len(ends)


# ---- smoke: the child (PS server) process ------------------------------

def serve(trace_out: str) -> None:
    """Child-process body: host a traced ``PSServer`` until the parent
    closes our stdin, then dump this process's trace and exit."""
    from distkeras_tpu import telemetry
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    telemetry.enable()
    center, _ = _center()
    ps = HostParameterServer(DownpourRule(), center)
    srv = PSServer(ps, center).start()
    print(f"PORT {srv.address[1]}", flush=True)
    sys.stdin.readline()  # parent closes stdin / sends a line: done
    srv.stop()
    telemetry.tracer().write_chrome_trace(trace_out)
    print(f"COMMITS {ps.num_commits}", flush=True)


# ---- smoke: device-trace alignment (ISSUE 17) --------------------------

def device_alignment_case(out_dir: str) -> None:
    """Unified host+device timeline: capture a ``jax.profiler`` device
    trace around a host tracer span, load it via
    ``telemetry.load_device_trace`` (wall anchor from
    ``profiling.profiler_trace``), merge with the host dump, and assert
    the device events land inside the host capture span's wall window.
    Skips cleanly when the profiler can't capture on this backend."""
    from distkeras_tpu import profiling, telemetry

    log_dir = pathlib.Path(out_dir) / "device_profile"
    host_path = pathlib.Path(out_dir) / "trace-host.json"
    telemetry.enable()
    try:
        import jax
        import jax.numpy as jnp

        with profiling.profiler_trace(str(log_dir)):
            with telemetry.span("device_capture"):
                f = jax.jit(lambda x: (x @ x.T).sum())
                f(jnp.ones((256, 256), jnp.float32)).block_until_ready()
    except Exception as e:  # profiler backend unavailable here
        telemetry.disable()
        print("device-trace alignment: skipped "
              f"({type(e).__name__}: {e})")
        return
    telemetry.tracer().write_chrome_trace(host_path)
    telemetry.disable()

    device_paths = profiling.find_device_traces(str(log_dir))
    if not device_paths:
        print("device-trace alignment: skipped "
              "(profiler produced no device trace)")
        return
    device = telemetry.load_device_trace(device_paths[0])
    assert "wallAnchor" in device, \
        "profiler_trace wall anchor not found next to the capture"
    # tag device events so they stay identifiable post-merge
    for e in device["traceEvents"]:
        if isinstance(e, dict):
            e["cat"] = "device:" + str(e.get("cat", ""))
    host = json.load(open(host_path))
    merged = telemetry.merge_traces(host, device)  # host anchor = base
    pathlib.Path(out_dir, "merged-device.json").write_text(
        json.dumps(merged))

    events = merged["traceEvents"]
    caps = [e for e in events if e.get("ph") == "X"
            and e["name"] == "device_capture"]
    assert caps, "host capture span missing from merged timeline"
    dev_ts = [e["ts"] for e in events
              if str(e.get("cat", "")).startswith("device:")
              and "ts" in e]
    assert dev_ts, "no device events survived the merge"
    # device events happened INSIDE the host capture span; allow
    # generous slack for profiler start/stop bookkeeping outside it
    lo = caps[0]["ts"] - 5e6
    hi = caps[0]["ts"] + caps[0].get("dur", 0.0) + 5e6
    mid = (min(dev_ts) + max(dev_ts)) / 2.0
    assert lo <= mid <= hi, (
        f"device events not aligned with the host capture window: "
        f"device mid ts {mid} outside [{lo}, {hi}]")
    print(f"device-trace alignment: {len(dev_ts)} device events "
          f"aligned into the host capture window "
          f"({device_paths[0].rsplit('/', 1)[-1]})")


# ---- smoke: the parent (trainer) process -------------------------------

def smoke(out_dir: str) -> None:
    from distkeras_tpu import telemetry
    from distkeras_tpu.data import datasets
    from distkeras_tpu.parallel.faults import ChaosTransport
    from distkeras_tpu.trainers import DOWNPOUR

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    server_trace = out / "trace-server.json"
    client_trace = out / "trace-client.json"

    child = subprocess.Popen(
        [sys.executable, __file__, "--serve", str(server_trace)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        cwd=str(REPO))
    try:
        port_line = child.stdout.readline().split()
        assert port_line and port_line[0] == "PORT", port_line
        port = int(port_line[1])

        telemetry.enable()
        _, variables = _center()
        data = datasets.synthetic_classification(512, (8,), 4, seed=0)
        # mild client-side chaos: a couple of scheduled resets force
        # the resilient client's retry path, so the merged trace shows
        # a retry chain under one ps_op trace id
        with ChaosTransport(seed=3, reset_rate=0.08,
                            max_injections=2, skip_ops=6):
            t = DOWNPOUR(_mlp_config(), fidelity="host",
                         transport="socket",
                         ps_address=("127.0.0.1", port),
                         num_workers=2, communication_window=2,
                         batch_size=16, num_epoch=1,
                         learning_rate=0.01,
                         worker_optimizer="adam", worker_retries=8)
            t.train(data, initial_variables=variables)
        telemetry.tracer().write_chrome_trace(client_trace)
        telemetry.disable()
    finally:
        child.stdin.close()
        child.wait(timeout=60)

    merged = merge_files([str(client_trace), str(server_trace)],
                         str(out / "merged.json"))
    print(summarize(merged))

    events = merged["traceEvents"]
    pids = {e["pid"] for e in events if "pid" in e}
    assert len(pids) == 2, f"expected 2 process tracks, got {pids}"
    paired = check_flow_pairing(merged)
    assert paired > 0, "no cross-process flow arrows paired"
    # the server handler spans carry the client link by hex span id
    client_spans = {e["args"]["span_id"] for e in events
                    if e.get("ph") == "X"
                    and e["name"].startswith("ps_client_")}
    rpc = [e for e in events if e.get("ph") == "X"
           and e["name"] == "ps_rpc"]
    assert rpc, "no ps_rpc handler spans in the server trace"
    for e in rpc:
        assert e["args"]["link_span"] in client_spans, e
    print(f"paired flow arrows: {paired}; "
          f"linked ps_rpc handler spans: {len(rpc)}")
    device_alignment_case(out_dir)
    print("smoke: ok")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("traces", nargs="*",
                    help="per-process Chrome trace JSON files")
    ap.add_argument("--out", default=None,
                    help="write the merged trace here")
    ap.add_argument("--smoke", action="store_true",
                    help="two-process merge proof (tier-1 mode)")
    ap.add_argument("--out-dir", default=None,
                    help="--smoke artifact directory (temp default)")
    ap.add_argument("--serve", default=None, metavar="TRACE_OUT",
                    help=argparse.SUPPRESS)  # internal child mode
    args = ap.parse_args()

    if args.serve:
        serve(args.serve)
        return
    if args.smoke:
        smoke(args.out_dir or tempfile.mkdtemp(prefix="dkt_trace_"))
        return
    if not args.traces or not args.out:
        ap.error("merge mode needs trace files and --out "
                 "(or pass --smoke)")
    merged = merge_files(args.traces, args.out)
    print(summarize(merged))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()

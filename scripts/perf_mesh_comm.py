"""Mesh-tier comm compression A/B: f32 vs bf16 vs int8 (ISSUE 16).

Three arms of the SAME compiled PS round (``ps_dataplane``), differing
only in the wire:

* ``f32``   — baseline: f32 center all_gather + f32 delta psum_scatter
* ``bf16``  — ``comm_dtype="bfloat16"``: the delta reduce-scatter
  narrowed to bf16 (wire AND reduction)
* ``int8``  — ``comm_codec="int8"``: the center re-broadcast quantized
  on-device with per-leaf symmetric scales

Per arm it reports round/step time, the static wire bytes
(``comm_bytes_per_round``), and bytes saved vs f32; the run asserts

* codec-law parity: the on-chip quantizer is bitwise the host
  ``Int8Codec`` (``q`` exact, scale to f32-vs-f64 rtol), and
* trajectory parity: each compressed arm's center stays within the
  quantization-step bound of the f32 arm's center (both lossy wires
  perturb the PULLED center, never the stored shards).

The model is deliberately comm-heavy (one wide MLP layer, window=1,
small batch), so the collective — not the matmul — dominates the
round; that is the regime the knobs exist for.  On CPU the collectives
are emulated memcpy loops: the int8 arm's honest 1-byte gather wins,
while bf16 arithmetic is software-emulated and typically LOSES — both
are recorded as-is (PERF.md §31); on a real TPU ICI both shrink.

Headline gating (``perf_regress``): the bytes-saved counter becomes a
rate candidate via ``from_registry`` and the step time a
lower-is-better candidate via ``evaluate`` — both checked in both
directions (pass + forced breach) in ``--smoke``, which runs the whole
A/B at tiny shapes and is registered in SMOKE_SCRIPTS.

Run:  python scripts/perf_mesh_comm.py [--devices 4] [--dim 2048]
          [--reps 5] [--out CAND.json] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
SCRIPTS = pathlib.Path(__file__).resolve().parent
if str(SCRIPTS) not in sys.path:
    sys.path.insert(0, str(SCRIPTS))

ARMS = (("f32", "float32", None),
        ("bf16", "bfloat16", None),
        ("int8", "float32", "int8"))


def _measure_arm(args, comm_dtype, comm_codec):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.models import build_model, model_config
    from distkeras_tpu.parallel import ps_dataplane
    from distkeras_tpu.parallel.ps_emulator import commit_permutation
    from distkeras_tpu.parallel.update_rules import RULES
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    W = args.workers
    model = build_model(model_config(
        "mlp", (args.dim,), num_classes=args.classes,
        hidden=(args.dim,)))
    tx = resolve_optimizer("momentum", args.lr)
    center = model.init(jax.random.key(0),
                        jnp.ones((2, args.dim), jnp.float32))["params"]
    rule = RULES["downpour"]()
    step = make_train_step(model, "sparse_categorical_crossentropy",
                           tx)

    placement = mesh_lib.place_workers(W)
    if placement.mesh is None or placement.vmap_workers != 1:
        raise SystemExit(
            f"needs one device per worker; {W} workers vs "
            f"{len(jax.devices())} devices (pass --devices N on CPU)")
    dp = ps_dataplane.MeshDataplane(
        rule, step, placement.mesh, center, comm_dtype=comm_dtype,
        comm_codec=comm_codec)

    def make_worker(rng):
        return TrainState.create({"params": center}, tx, rng)

    mps, mws = dp.to_device(
        rule.init_state(center),
        jax.vmap(make_worker)(jax.random.split(jax.random.key(1), W)))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    rng = np.random.RandomState(0)
    batch = jax.device_put(
        {"features": jnp.asarray(
            rng.randn(W, args.window, args.batch, args.dim),
            jnp.float32),
         "label": jnp.asarray(
            rng.randint(0, args.classes,
                        (W, args.window, args.batch)), jnp.int32)},
        row)
    perm = jax.device_put(commit_permutation(jax.random.key(2), W),
                          rep)

    driver = ps_dataplane.MeshRoundDriver(dp, mps, mws)
    driver.dispatch(batch, perm)
    driver.drain()  # warm: compile + first execution
    t0 = time.perf_counter()
    for _ in range(args.reps):
        driver.dispatch(batch, perm)
    metrics = driver.drain()
    dt = (time.perf_counter() - t0) / args.reps

    losses = np.concatenate([m["loss"] for m in metrics])
    center_host = jax.device_get(dp.center(driver.mps))
    return {
        "comm_dtype": comm_dtype, "comm_codec": comm_codec,
        "round_ms": round(dt * 1e3, 2),
        "step_time_ms": round(dt * 1e3 / args.window, 2),
        "comm_bytes_per_round": dp.comm_bytes_per_round,
        "comm_bytes_saved_per_round": dp.comm_bytes_saved_per_round,
        "loss_finite": bool(np.isfinite(losses).all()),
        "workers": W,
    }, center_host, dp


def _assert_codec_law():
    """The on-chip quantizer IS the host ``Int8Codec`` law (the parity
    oracle the wire format is defined by): ``q`` bitwise, scale to
    f32-vs-f64 rounding."""
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu.parallel import ps_dataplane
    from distkeras_tpu.parallel.compression import Int8Codec

    rng = np.random.RandomState(7)
    x = (rng.randn(4097) * 0.21).astype(np.float32)
    q, s = ps_dataplane.quantize_int8(jnp.asarray(x))
    enc = Int8Codec().encode_leaf(x)
    assert np.array_equal(np.asarray(q),
                          np.frombuffer(enc["q"], np.int8))
    np.testing.assert_allclose(float(s), enc["s"], rtol=1e-6)


def run(args) -> list[dict]:
    import jax
    import numpy as np

    from distkeras_tpu import telemetry
    from distkeras_tpu.parallel import ps_dataplane

    _assert_codec_law()
    tel = telemetry.enable()
    t_wall = time.perf_counter()
    results, centers = {}, {}
    for name, dt, codec in ARMS:
        rec, center, dp = _measure_arm(args, dt, codec)
        results[name], centers[name] = rec, center
        print(json.dumps({"arm": name, **rec}), flush=True)
    seconds = time.perf_counter() - t_wall
    snap = tel.metrics.snapshot()
    telemetry.disable()

    # trajectory parity: lossy wires perturb only the PULLED center;
    # after `reps+1` rounds every leaf must sit within the accumulated
    # quantization step of the f32 trajectory.  Bound: per-round pull
    # error <= scale/2 per element, amplified through the window run —
    # 8x slack covers the optimizer's gain at lr<=0.1.
    import jax.numpy as jnp
    qstep = max(
        float(jnp.max(jnp.abs(leaf)) / 127.0)
        for leaf in jax.tree_util.tree_leaves(centers["f32"]))
    atol = 8.0 * qstep * (args.reps + 1)
    for name in ("bf16", "int8"):
        for la, lb in zip(jax.tree_util.tree_leaves(centers["f32"]),
                          jax.tree_util.tree_leaves(centers[name])):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       atol=atol, rtol=0,
                                       err_msg=f"{name} center parity")
        assert results[name]["loss_finite"], results[name]
    print(json.dumps({"parity": "ok", "atol": round(atol, 6)}),
          flush=True)

    # wire accounting sanity: the knobs actually shrink their
    # collective (static bytes, no timing noise)
    f32b = results["f32"]["comm_bytes_per_round"]
    assert results["int8"]["comm_bytes_per_round"]["gather"] < \
        f32b["gather"]
    assert results["bf16"]["comm_bytes_per_round"]["scatter"] < \
        f32b["scatter"]

    best = min(("bf16", "int8"),
               key=lambda n: results[n]["step_time_ms"])
    summary = {
        "metric": "mesh_comm_best_step_time_ms",
        "value": results[best]["step_time_ms"],
        "unit": "ms", "lower_is_better": True,
        "best_arm": best,
        "f32_step_time_ms": results["f32"]["step_time_ms"],
        "speedup_vs_f32": round(
            results["f32"]["step_time_ms"]
            / results[best]["step_time_ms"], 3),
        "bytes_saved_per_round":
            results[best]["comm_bytes_saved_per_round"],
        "workers": args.workers, "dim": args.dim,
        "device": getattr(jax.devices()[0], "device_kind",
                          str(jax.devices()[0])),
    }
    print(json.dumps(summary), flush=True)
    if not args.smoke:
        # the acceptance headline: a compressed arm beats f32 on step
        # time (CPU-honest; at tiny --smoke shapes timing is noise and
        # the claim would be dishonest, so only the full run asserts)
        assert summary["speedup_vs_f32"] > 1.0, summary

    # ---- perf_regress gating, both directions ------------------------
    import perf_regress

    out_dir = pathlib.Path(tempfile.mkdtemp(prefix="dkt_meshcomm_"))
    snap_path = out_dir / "registry.json"
    snap_path.write_text(json.dumps(snap, default=repr))
    saved_rate = perf_regress.from_registry(
        str(snap_path), "mesh_comm_bytes_saved_per_sec",
        "ps_round_comm_bytes_saved_total", seconds)
    assert saved_rate[0]["value"] > 0, saved_rate
    cands = [summary] + saved_rate
    for n in (1, 2):
        (out_dir / f"BENCH_r{n:02d}.json").write_text(json.dumps({
            "n": n, "cmd": "perf_mesh_comm", "rc": 0, "tail": "",
            "parsed": cands}))  # parsed-as-LIST: mixed-metric file
    traj = perf_regress.load_trajectories(str(out_dir / "BENCH_*.json"))
    rows = perf_regress.evaluate(saved_rate, traj, tolerance=0.5)
    rows += perf_regress.evaluate([summary], traj, tolerance=0.5,
                                  lower_is_better=True)
    print(perf_regress.render(rows), flush=True)
    assert all(r["status"] == "pass" for r in rows), rows
    bad = perf_regress.evaluate(
        [{"metric": "mesh_comm_best_step_time_ms",
          "value": summary["value"] * 10.0}], traj, tolerance=0.5,
        lower_is_better=True)
    bad += perf_regress.evaluate(
        [{"metric": "mesh_comm_bytes_saved_per_sec",
          "value": saved_rate[0]["value"] / 10.0}], traj,
        tolerance=0.5)
    assert all(r["status"] == "breach" for r in bad), bad
    print(json.dumps({"gate": "pass_and_breach", "ok": True}),
          flush=True)

    records = [summary] + [
        {"metric": f"mesh_comm_{name}_step_time_ms",
         "value": rec["step_time_ms"], "unit": "ms",
         "lower_is_better": True, **rec}
        for name, rec in results.items()]
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(records))
    if args.smoke:
        print(json.dumps({"smoke": "ok"}))
    return records


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--window", type=int, default=1)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--dim", type=int, default=4096,
                    help="MLP width; params ~= dim^2 + dim*classes "
                         "(comm-heavy by design; below ~4096 the "
                         "round is compute-bound on CPU and the "
                         "compressed arms stop winning)")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual host devices (CPU runs)")
    ap.add_argument("--out", default=None,
                    help="write the parsed-format records (a LIST) "
                         "for perf_regress.py --candidate")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, no timing-win assert; tier-1 "
                         "mode")
    args = ap.parse_args()

    if args.smoke:
        args.devices = args.devices or 4
        args.workers, args.window, args.batch = 4, 1, 4
        args.dim, args.classes, args.reps = 64, 8, 2
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
    run(args)


if __name__ == "__main__":
    main()

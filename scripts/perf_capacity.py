"""Capacity model + closed-loop autoscaler drill over production
traffic (ISSUE 18 tentpole): the test bed the autoscaler and failover
machinery had never faced — a MOVING load curve with faults firing
mid-flight.

Two phases, one report:

1. **Capacity fit** — for each replica count, a stepped-rate search
   (``simulator.stepped_rate_search``) replays flat-rate segments of
   the production request mix (lognormal prompts, Pareto outputs,
   session-sticky prefixes, tenant/priority classes) at a geometric
   rate ladder until error-free SLO attainment (TTFT at the fixed
   ``--slo-ttft``) breaks.  The passing rungs fit a ``CapacityModel``
   — sustainable QPS vs replicas — published as
   ``sim_capacity_qps{replicas=N}`` gauges.  A second axis reruns the
   search at one replica with the CONCURRENT socket-PS training
   tenant flat vs hierarchical (``ps_groups``; ISSUE 20), pricing the
   aggregation tier's co-tenant tax as
   ``sim_capacity_qps{replicas=1,ps_groups=g}`` points.
2. **Closed-loop drill** — a diurnal trace with a flash crowd runs
   against a 1-replica gateway plus a pre-warmed ``ReplicaPool``; the
   ``telemetry.Autoscaler`` (queue-depth SLO breaches only, busy-guard
   wired to ``gateway.busy``) must track the fitted model's
   ``required(rate_at(t))`` while a ``ChaosSchedule`` opens a
   reset+delay transport-fault window inside the crowd (hitting a
   CONCURRENT socket-PS training tenant — train+serve tenancy) and
   kills the original serving replica mid-crowd.  Convergence seconds
   (``sim_drill_convergence_seconds_total``) and the watchdog's
   ``slo_violation_seconds_total`` are gated through
   ``perf_regress.from_registry`` as lower-is-better per-second rates;
   the fitted capacity gates higher-is-better.

``--smoke`` (the tier-1 registration via test_examples.py) runs tiny
CPU shapes and asserts the ISSUE 18 acceptance criteria: a fitted
capacity point exists, every drill deficit episode converged, SLO
violation minutes were accrued (and bounded), the kill+window faults
actually fired, exactly-once held for BOTH tenants (no duplicate or
lost serving results; training commits == rounds under the fault
window), decoded tokens are byte-identical to the single-model
reference, and the perf_regress gate passes on this run's own
trajectory AND breaches when the metrics are degraded 10x — both
directions.

Usage:  PYTHONPATH=/root/repo python scripts/perf_capacity.py
        [--smoke] [--replica-configs 1,2] [--slo-ttft 0.3]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress
import postmortem


def _build_model(args):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))
    return model, variables


def _warmed_engine(model, variables, args):
    """A DecodeEngine with every padded prompt length the trace can
    produce pre-compiled (compiles are shared via the process jit
    cache, so warming N engines costs ~one compile set)."""
    from distkeras_tpu.serving import DecodeEngine

    eng = DecodeEngine(model, variables, slots=args.slots,
                       prefill_align=args.prefill_align,
                       max_new_tokens=args.output_max)
    a = args.prefill_align
    lo = -(-args.prompt_min // a) * a
    hi = -(-args.prompt_max // a) * a
    lengths = list(range(lo, hi + 1, a))
    list(eng.run([{"prompt": np.zeros((t,), np.int32),
                   "max_new_tokens": 2} for t in lengths]))
    return eng


def _base_spec(args, **over):
    from distkeras_tpu.simulator import TraceSpec

    kw = dict(duration_s=1.0, mean_qps=1.0, seed=args.seed,
              prompt_median=args.prompt_median, prompt_sigma=0.4,
              prompt_min=args.prompt_min, prompt_max=args.prompt_max,
              output_alpha=1.6, output_min=args.output_min,
              output_max=args.output_max, vocab=args.vocab,
              sessions=12, session_zipf=1.8, prefix_groups=3,
              prefix_len=4,
              tenants=(("free", 0.7, 0), ("paid", 0.3, 2)))
    kw.update(over)
    return TraceSpec(**kw)


def _wait_idle(reps, timeout_s: float = 15.0) -> None:
    """Let a failed rung's backlog finish before the next config is
    measured (bounded — leftover load would pollute the next rung)."""
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if all(r.load() == 0 for r in reps if r.alive):
            return
        time.sleep(0.05)


def run_capacity_phase(model, variables, args):
    """Phase 1: stepped-rate search per replica config, one gateway
    grown replica by replica, then the fitted model."""
    from distkeras_tpu.gateway import EngineReplica, ServingGateway
    from distkeras_tpu.simulator import (CapacityModel,
                                         stepped_rate_search)

    configs = sorted(args.replica_configs)
    reps = [EngineReplica(_warmed_engine(model, variables, args),
                          name=f"cap-r{i}")
            for i in range(max(configs))]
    ladder = tuple(float(q) for q in args.ladder)
    points, searches = [], []
    with ServingGateway(reps[:1], policy="least_loaded", retries=8,
                        backoff_base=0.01) as gw:
        for k in configs:
            while gw.alive_replicas() < k:
                gw.add_replica(reps[gw.alive_replicas()])
            # unscored warm pass: flush per-replica first-use costs
            # (jit reuse, slot-pool setup) out of the scored rungs —
            # least_loaded spreads these across every idle replica
            warm_ids = [gw.submit(
                np.arange(args.prompt_min, dtype=np.int32)
                % args.vocab, max_new_tokens=args.output_min)
                for _ in range(2 * k)]
            for rid in warm_ids:
                gw.result(rid, timeout=30.0)
            search = stepped_rate_search(
                gw, _base_spec(args), slo_ttft_s=args.slo_ttft,
                attainment=args.attainment, ladder=ladder,
                min_arrivals=args.min_arrivals,
                max_segment_s=args.max_segment,
                drain_timeout_s=args.drain_timeout,
                config={"replicas": k})
            searches.append(search)
            if search["point"] is not None:
                points.append(search["point"])
            _wait_idle(reps)
    if not points:
        raise SystemExit("no configuration sustained the bottom rung "
                         "— the ladder starts above this machine")
    return CapacityModel(points), searches


def run_hier_axis_phase(model, variables, args, cap_model):
    """Second capacity axis (ISSUE 20 satellite; the ROADMAP item 3
    leftover): sustainable serving QPS at ONE replica while the
    concurrent socket-PS training tenant runs flat (``ps_groups=0``)
    vs hierarchical (GroupLeader topology) at the same worker count —
    the sweep prices the aggregation tier's co-tenant CPU tax, and
    each point lands on ``sim_capacity_qps{replicas=1,ps_groups=g}``
    (the extra config key flows into the gauge labels)."""
    from distkeras_tpu.gateway import EngineReplica, ServingGateway
    from distkeras_tpu.simulator import stepped_rate_search

    c1 = cap_model.capacity(1)
    ladder = tuple(sorted({max(1.0, c1 / 4), max(1.0, c1 / 2),
                           max(1.0, c1)}))
    axis = []
    for groups in sorted(args.hier_configs):
        workers = 4
        g = workers // groups if groups else 0
        ps_groups = ([(None, list(range(i * g, (i + 1) * g)))
                      for i in range(groups)] if groups else None)
        rep = EngineReplica(_warmed_engine(model, variables, args),
                            name=f"hier-g{groups}")
        stop = threading.Event()
        stats = {"runs": 0, "rounds": 0, "commits": 0, "errors": []}
        trainer = threading.Thread(
            target=_training_tenant, args=(stop, stats, args.rows),
            kwargs={"ps_groups": ps_groups, "num_workers": workers},
            daemon=True)
        with ServingGateway([rep], policy="least_loaded", retries=8,
                            backoff_base=0.01) as gw:
            warm_ids = [gw.submit(
                np.arange(args.prompt_min, dtype=np.int32)
                % args.vocab, max_new_tokens=args.output_min)
                for _ in range(2)]
            for rid in warm_ids:
                gw.result(rid, timeout=30.0)
            trainer.start()
            search = stepped_rate_search(
                gw, _base_spec(args), slo_ttft_s=args.slo_ttft,
                attainment=args.attainment, ladder=ladder,
                min_arrivals=args.min_arrivals,
                max_segment_s=args.max_segment,
                drain_timeout_s=args.drain_timeout,
                config={"replicas": 1, "ps_groups": groups})
            stop.set()
            trainer.join(60)
            _wait_idle([rep])
        axis.append({"ps_groups": groups,
                     "sustainable_qps": search["sustainable_qps"],
                     "capped": search["capped"],
                     "train": dict(stats)})
    return axis


def _drill_watchdog(registry):
    """Queue-depth-only SLO: every other signal is disabled so the
    drill's violation accounting is purely load-driven (and recovers
    when the queue drains — cumulative-histogram signals would latch
    a crowd breach forever)."""
    from distkeras_tpu.telemetry import (DEFAULT_SLO_THRESHOLDS,
                                         LOWER_IS_WORSE_SLO_SIGNALS,
                                         SLOWatchdog)

    thresholds = {k: ((-1.0, -2.0) if k in LOWER_IS_WORSE_SLO_SIGNALS
                      else (1e9, 2e9))
                  for k in DEFAULT_SLO_THRESHOLDS}
    thresholds["queue_depth"] = (3.0, 10.0)
    return SLOWatchdog(registry, thresholds=thresholds,
                       sustain_secs=0.2)


def _training_tenant(stop, stats, rows, ps_groups=None,
                     num_workers=2):
    """The concurrent train tenancy: socket-PS DOWNPOUR rounds looping
    until the drill ends, each run asserted exactly-once (commits ==
    rounds) even while the chaos window resets/delays its wire.
    ``ps_groups`` runs the same tenancy through the hierarchical
    GroupLeader topology (the second capacity axis)."""
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(rows, (8,), 4, seed=0)
    while not stop.is_set():
        try:
            t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                         num_workers=num_workers,
                         communication_window=2,
                         batch_size=16, num_epoch=1,
                         learning_rate=0.01, ps_groups=ps_groups,
                         worker_optimizer="adam", worker_retries=14)
            t.train(data)
            rounds = len(t.history["round_loss"])
            commits = t.parameter_server_state.num_commits
            stats["runs"] += 1
            stats["rounds"] += rounds
            stats["commits"] += commits
            if commits != rounds:
                stats["errors"].append(
                    f"run {stats['runs']}: {commits} commits for "
                    f"{rounds} rounds")
            if "worker_failures" in t.history:
                stats["errors"].append(
                    f"run {stats['runs']}: worker_failures "
                    f"{t.history['worker_failures']}")
        except Exception as e:  # noqa: BLE001 — surfaced in asserts
            stats["errors"].append(f"run {stats['runs'] + 1}: {e!r}")
            return
        # breathe between runs: the trainer is a tenant, not a DoS —
        # unthrottled it starves the serve path's CPU share
        stop.wait(0.5)


def run_drill_phase(model, variables, args, cap_model):
    """Phase 2: the closed-loop drill, self-calibrated from the fitted
    single-replica capacity C1 — base load 0.35*C1, flash crowd 3x
    (beyond one replica once the training tenant taxes the cores),
    transport-fault window and a replica kill INSIDE the crowd."""
    from distkeras_tpu import telemetry
    from distkeras_tpu.gateway import EngineReplica, ServingGateway
    from distkeras_tpu.simulator import (ChaosSchedule, ReplicaPool,
                                         generate_trace, run_drill)
    from distkeras_tpu.telemetry import Autoscaler

    c1 = cap_model.capacity(1)
    # 0.35 (not 0.5): the concurrent training tenant taxes the same
    # cores, so drill-time capacity runs below the phase-1 fit
    base_qps = max(0.35 * c1, 1.0)
    crowd = (2.0, 8.0)
    spec = _base_spec(
        args, duration_s=args.drill_duration, mean_qps=base_qps,
        diurnal_amplitude=0.12, seed=args.seed + 7,
        flash_crowds=((crowd[0], crowd[1], 3.0),))
    schedule = ChaosSchedule(
        windows=((crowd[0] + 0.5, crowd[0] + 3.5,
                  ("reset", "delay")),),
        kills=(((crowd[0] + crowd[1]) / 2, "drill-r0"),))

    rep0 = EngineReplica(_warmed_engine(model, variables, args),
                         name="drill-r0")
    spares = [EngineReplica(_warmed_engine(model, variables, args),
                            name=f"drill-s{i}") for i in (1, 2)]
    schedule.register_kill("drill-r0", rep0.kill)

    tel = telemetry.metrics()
    watchdog = _drill_watchdog(tel)
    stop = threading.Event()
    train_stats = {"runs": 0, "rounds": 0, "commits": 0, "errors": []}
    trainer = threading.Thread(
        target=_training_tenant, args=(stop, train_stats, args.rows),
        daemon=True)
    with ServingGateway([rep0], policy="least_loaded", retries=8,
                        backoff_base=0.01) as gw:
        pool = ReplicaPool(gw, spares)
        scaler = Autoscaler(
            watchdog, spawn_replica=pool.spawn_replica,
            drain_replica=pool.drain_replica,
            replica_count=pool.replica_count,
            min_replicas=1, max_replicas=2, cooldown_s=0.6,
            idle_sustain_s=3600.0,
            gateway_scale_signals=("queue_depth",), busy=gw.busy)
        with schedule.chaos_transport(
                seed=args.chaos_seed, delay_s=0.005,
                window_rate=0.35, max_injections=10) as ct:
            trainer.start()
            t0 = time.perf_counter()
            drill = run_drill(
                generate_trace(spec), gw, scaler, cap_model,
                schedule=schedule, slo_ttft_s=args.slo_ttft,
                tick_interval_s=0.2, max_replicas=2,
                drain_timeout_s=args.drain_timeout)
            stop.set()
            trainer.join(60)
            wall = time.perf_counter() - t0
        # close the violation accrual; give the sustain window a beat
        # to commit the drained-queue ok state
        final = watchdog.evaluate()
        for _ in range(8):
            if final["state"] == "ok":
                break
            time.sleep(0.1)
            final = watchdog.evaluate()
        end_replicas = gw.alive_replicas()
    return {"drill": drill, "wall_s": wall, "chaos": dict(ct.counts),
            "train": train_stats, "final_state": final["state"],
            "end_replicas": end_replicas, "base_qps": base_qps,
            "spec": spec}


def _verify_parity(model, variables, results, limit=3):
    """Byte parity: simulator results vs the single-model reference,
    on the smallest completed requests (bounded compile cost)."""
    from distkeras_tpu.models import generate

    done = [r for r in results if r.get("error") is None]
    done.sort(key=lambda r: (len(r["prompt"]), len(r["tokens"])))
    for r in done[:limit]:
        prompt = np.asarray(r["prompt"], np.int32)
        want = np.asarray(generate(
            model, variables, prompt[None, :],
            max_new_tokens=len(r["tokens"])))[0, len(prompt):]
        np.testing.assert_array_equal(np.asarray(r["tokens"]), want)
    return min(limit, len(done))


def _gate(cands, out_dir, tag, *, lower_is_better, tolerance):
    """Smoke gate: a synthetic 3-run trajectory from this very run —
    the candidates must PASS against it, and a 10x-degraded copy must
    BREACH (both directions of the wiring proven)."""
    for i, c in enumerate(cands):
        for n in (1, 2, 3):
            (out_dir / f"BENCH_{tag}{i}_r{n:02d}.json").write_text(
                json.dumps({
                    "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                    "parsed": {"metric": c["metric"],
                               "value": c["value"] * (1 + 0.02 * n),
                               "unit": c.get("unit", "x")}}))
    trajs = perf_regress.load_trajectories(
        str(out_dir / f"BENCH_{tag}*.json"))
    rows = perf_regress.evaluate(cands, trajs, tolerance=tolerance,
                                 lower_is_better=lower_is_better)
    factor = 10.0 if lower_is_better else 0.1
    degraded = [dict(c, value=c["value"] * factor) for c in cands]
    breach_rows = perf_regress.evaluate(
        degraded, trajs, tolerance=tolerance,
        lower_is_better=lower_is_better)
    return rows, breach_rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + the ISSUE 18 acceptance "
                         "assertions (the tier-1 registration)")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--prefill-align", type=int, default=16)
    ap.add_argument("--prompt-median", type=float, default=48.0)
    ap.add_argument("--prompt-min", type=int, default=16)
    ap.add_argument("--prompt-max", type=int, default=128)
    ap.add_argument("--output-min", type=int, default=8)
    ap.add_argument("--output-max", type=int, default=64)
    ap.add_argument("--replica-configs", default="1,2",
                    help="comma-separated replica counts to probe")
    ap.add_argument("--hier-configs", default="0,2",
                    help="comma-separated training-tenant ps_groups "
                         "counts for the second capacity axis "
                         "(0 = flat topology)")
    ap.add_argument("--ladder", default="6,12,24,48,96,192",
                    help="comma-separated QPS rungs")
    ap.add_argument("--slo-ttft", type=float, default=0.3,
                    help="the fixed TTFT SLO (seconds)")
    ap.add_argument("--attainment", type=float, default=0.9)
    ap.add_argument("--min-arrivals", type=int, default=10)
    ap.add_argument("--max-segment", type=float, default=1.6)
    ap.add_argument("--drain-timeout", type=float, default=12.0)
    ap.add_argument("--drill-duration", type=float, default=12.0)
    ap.add_argument("--rows", type=int, default=160,
                    help="training-tenant rows per DOWNPOUR run")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chaos-seed", type=int, default=13)
    ap.add_argument("--tolerance", type=float, default=0.15)
    ap.add_argument("--out-dir", default=None)
    args = ap.parse_args()

    if args.smoke:
        # shapes chosen so one replica sustains ~50 QPS on a laptop
        # CPU: the 40-rung passes and the 80-rung fails decisively,
        # keeping the fitted capacity stable run to run
        args.layers, args.d_model, args.heads = 2, 64, 2
        args.vocab, args.max_len = 61, 128
        args.slots, args.prefill_align = 1, 8
        args.prompt_median, args.prompt_min, args.prompt_max = \
            20.0, 8, 48
        args.output_min, args.output_max = 16, 48
        args.ladder = "5,10,20,40,80,160"
        # long enough segments that an over-capacity rung's queue
        # actually blows through the TTFT SLO (decisive fail)
        args.min_arrivals = 80
        args.rows = 160
    args.replica_configs = [int(x) for x
                            in args.replica_configs.split(",")]
    args.hier_configs = [int(x) for x in args.hier_configs.split(",")]
    args.ladder = [float(x) for x in args.ladder.split(",")]

    out_dir = pathlib.Path(args.out_dir
                           or tempfile.mkdtemp(prefix="dkt_cap_"))
    out_dir.mkdir(parents=True, exist_ok=True)

    from distkeras_tpu import flight_recorder, telemetry

    flight_recorder.start(out_dir / "fdr")
    model, variables = _build_model(args)

    # ---- phase 1: capacity --------------------------------------------
    telemetry.enable()
    cap_model, searches = run_capacity_phase(model, variables, args)
    # second axis: the training tenant's PS topology (flat vs
    # hierarchical ps_groups) at fixed replicas=1
    hier_axis = run_hier_axis_phase(model, variables, args, cap_model)
    telemetry.metrics().snapshot()  # phase A registry, then reset
    telemetry.disable()

    # ---- phase 2: drill (fresh registry so the gated counters are
    # the drill's alone) ------------------------------------------------
    tel = telemetry.enable()
    drill_out = run_drill_phase(model, variables, args, cap_model)
    snap_path = out_dir / "registry_drill.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    flight_recorder.stop()
    telemetry.disable()

    drill = drill_out["drill"]
    rep = drill["replay"]
    wall = drill_out["wall_s"]

    out = {"metric": "traffic_capacity_drill",
           "capacity": cap_model.describe(),
           "hier_axis": hier_axis,
           "searches": [{k: s[k] for k in ("sustainable_qps",
                                           "capped", "rungs")}
                        for s in searches],
           "drill": {"base_qps": drill_out["base_qps"],
                     "episodes": drill["episodes"],
                     "converged": drill["converged"],
                     "final_state": drill_out["final_state"],
                     "end_replicas": drill_out["end_replicas"],
                     "chaos": drill_out["chaos"],
                     "train": dict(drill_out["train"]),
                     "arrivals": rep["arrivals"],
                     "completed": rep["completed"],
                     "errors": rep["errors"],
                     "duplicates": rep["duplicates"],
                     "slo_attainment": rep["slo_attainment"],
                     "ttft_p95_s": rep["ttft_p95_s"],
                     "wall_s": round(wall, 3)}}

    # ---- perf_regress wiring ------------------------------------------
    lower = perf_regress.from_registry(
        str(snap_path), "drill_convergence_frac",
        "sim_drill_convergence_seconds_total", wall)
    lower += perf_regress.from_registry(
        str(snap_path), "drill_slo_violation_frac",
        "slo_violation_seconds_total", wall)
    higher = [{"metric": "sim_capacity_qps_r1",
               "value": cap_model.capacity(1), "unit": "qps"}]
    if args.smoke:
        rows_lo, breach_lo = _gate(lower, out_dir, "lo",
                                   lower_is_better=True,
                                   tolerance=0.5)
        rows_hi, breach_hi = _gate(higher, out_dir, "hi",
                                   lower_is_better=False,
                                   tolerance=0.5)
    else:
        trajs = perf_regress.load_trajectories(
            perf_regress.DEFAULT_BASELINES)
        rows_lo = perf_regress.evaluate(lower, trajs,
                                        tolerance=args.tolerance,
                                        lower_is_better=True)
        rows_hi = perf_regress.evaluate(higher, trajs,
                                        tolerance=args.tolerance)
        breach_lo = breach_hi = []
    print(perf_regress.render(rows_lo + rows_hi))
    out["gate"] = [{k: r[k] for k in ("metric", "value", "status")}
                   for r in rows_lo + rows_hi]

    # ---- the drill story from the flight ring -------------------------
    from distkeras_tpu.flight_recorder import FlightRecorder

    events = FlightRecorder(out_dir / "fdr").read_events()
    story = postmortem.drill_story(events)
    for s in story[:80]:
        print(f"  +{s['wall_s'] - story[0]['wall_s']:7.3f}s "
              f"{s['what']}")

    if args.smoke:
        snap = json.loads(snap_path.read_text())
        counters = snap["counters"]

        def csum(name):
            return sum(v for k, v in counters.items()
                       if k == name or k.startswith(name + "{"))

        # a fitted capacity point per probed config, none ladder-capped
        assert len(cap_model.points) == len(args.replica_configs)
        assert cap_model.capacity(1) > 0
        # the second axis measured every ps_groups config with its
        # training tenant exactly-once (flat AND hierarchical)
        assert len(hier_axis) == len(args.hier_configs)
        for pt in hier_axis:
            assert pt["sustainable_qps"] > 0, pt
            assert pt["train"]["runs"] >= 1, pt
            assert not pt["train"]["errors"], pt["train"]["errors"]
        assert not any(s["capped"] for s in searches), (
            "the rate ladder never saturated — raise the top rung")
        # the closed-loop drill converged: every deficit episode
        # (crowd onset, mid-crowd kill) closed before the trace ended
        assert drill["episodes"], "no deficit episode ever opened"
        assert drill["converged"], drill["episodes"]
        assert drill_out["end_replicas"] == 2
        assert drill_out["final_state"] == "ok", drill_out
        # violation minutes accrued, and bounded by the drill wall
        viol = csum("slo_violation_seconds_total")
        assert 0.0 < viol < wall, (viol, wall)
        conv = csum("sim_drill_convergence_seconds_total")
        assert 0.0 < conv < wall, (conv, wall)
        # the faults actually fired: the scheduled kill, and window
        # faults on the training tenant's wire inside the crowd
        assert csum("sim_kills_total") == 1
        assert csum("chaos_window_injected_total") > 0, (
            drill_out["chaos"])
        # exactly-once, both tenants: every serving arrival got
        # exactly one result (no losses, duplicates, or errors
        # across the kill + fault window) ...
        assert rep["errors"] == 0, rep["errors"]
        assert rep["duplicates"] == 0
        assert rep["undrained"] == 0
        assert rep["completed"] == rep["arrivals"]
        rids = [r["request_id"] for r in rep["results"]]
        assert len(set(rids)) == len(rids) == rep["arrivals"]
        # ... and the training tenant stayed exactly-once through the
        # reset/delay window (commits == rounds every run)
        assert drill_out["train"]["runs"] >= 1
        assert not drill_out["train"]["errors"], (
            drill_out["train"]["errors"])
        # byte parity vs the single-model reference
        assert _verify_parity(model, variables, rep["results"]) > 0
        # the gate wiring works in BOTH directions
        assert len(lower) == 2 and len(higher) == 1
        assert all(r["status"] == "pass"
                   for r in rows_lo + rows_hi), (rows_lo, rows_hi)
        assert all(r["status"] == "breach"
                   for r in breach_lo + breach_hi), (breach_lo,
                                                     breach_hi)
        # the postmortem can replay the drill
        kinds = {s["kind"] for s in story}
        assert {"sim_phase", "sim_kill", "slo_state"} <= kinds, kinds
        out["smoke"] = "ok"
    print(json.dumps(out, default=repr))


if __name__ == "__main__":
    main()

"""Mesh-round attribution: measured vs roofline, per comm arm (ISSUE 17).

Runs the three comm arms of the compiled PS round (f32 / bf16 / int8 —
the same arms as ``perf_mesh_comm``) on a deliberately comm-heavy MLP
and prints the ATTRIBUTION TABLE: the measured round decomposed into
host_gap / dispatch / device_compute / ring_fetch segments
(``MeshRoundDriver`` sampled timing) next to the XLA cost ledger's
roofline prediction (compute vs comm bound, from
``MeshDataplane.cost_report()`` against ``profiling.peak_flops`` /
``peak_bandwidth``), plus compile time and how many rounds amortize it.

The run asserts the LEDGER INVARIANTS (static wire accounting, no
timing noise):

* int8 center gather = 1/4 of the f32 gather plus the per-leaf scale
  side channel (the MLP center is all-f32, so the law is exact);
* bf16 delta scatter = 1/2 of the f32 scatter;
* both cross-checked against the live
  ``ps_round_comm_bytes_saved_total`` counter: after R dispatched
  rounds the counter equals R x (f32 bytes - compressed bytes);
* attrib-on training is BYTE-IDENTICAL to attrib-off (sampling only
  reads); and the disabled-path guard stays within the PERF.md no-op
  budget (``attrib.attrib_overhead``).

Headline gating (``perf_regress``, both directions — pass + forced
breach): ``mesh_round_mfu_observed`` and the
``mesh_round_mfu_of_roofline`` ratio (observed/roofline — the
BENCH-trajectory form of the ``mfu_gap`` SLO signal), so a regressed
round loop breaches the gate even when absolute throughput noise would
hide it.

Run:  python scripts/perf_attrib.py [--devices 4] [--dim 2048]
          [--reps 3] [--out CAND.json] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
SCRIPTS = pathlib.Path(__file__).resolve().parent
if str(SCRIPTS) not in sys.path:
    sys.path.insert(0, str(SCRIPTS))

ARMS = (("f32", "float32", None),
        ("bf16", "bfloat16", None),
        ("int8", "float32", "int8"))

SEGMENTS = ("host_gap", "dispatch", "device_compute", "ring_fetch")


def _build(args, comm_dtype, comm_codec, attrib_every=0):
    """One comm arm's dataplane + driver + seeded inputs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.models import build_model, model_config
    from distkeras_tpu.parallel import ps_dataplane
    from distkeras_tpu.parallel.ps_emulator import commit_permutation
    from distkeras_tpu.parallel.update_rules import RULES
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    W = args.workers
    model = build_model(model_config(
        "mlp", (args.dim,), num_classes=args.classes,
        hidden=(args.dim,)))
    tx = resolve_optimizer("momentum", args.lr)
    center = model.init(jax.random.key(0),
                        jnp.ones((2, args.dim), jnp.float32))["params"]
    rule = RULES["downpour"]()
    step = make_train_step(model, "sparse_categorical_crossentropy",
                           tx)

    placement = mesh_lib.place_workers(W)
    if placement.mesh is None or placement.vmap_workers != 1:
        raise SystemExit(
            f"needs one device per worker; {W} workers vs "
            f"{len(jax.devices())} devices (pass --devices N on CPU)")
    dp = ps_dataplane.MeshDataplane(
        rule, step, placement.mesh, center, comm_dtype=comm_dtype,
        comm_codec=comm_codec)

    def make_worker(rng):
        return TrainState.create({"params": center}, tx, rng)

    mps, mws = dp.to_device(
        rule.init_state(center),
        jax.vmap(make_worker)(jax.random.split(jax.random.key(1), W)))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    rng = np.random.RandomState(0)
    batches = [jax.device_put(
        {"features": jnp.asarray(
            rng.randn(W, args.window, args.batch, args.dim),
            jnp.float32),
         "label": jnp.asarray(
            rng.randint(0, args.classes,
                        (W, args.window, args.batch)), jnp.int32)},
        row) for _ in range(3)]
    perm = jax.device_put(commit_permutation(jax.random.key(2), W),
                          rep)
    driver = ps_dataplane.MeshRoundDriver(dp, mps, mws,
                                          attrib_every=attrib_every)
    return dp, driver, batches, perm


def _measure_arm(args, comm_dtype, comm_codec):
    """Warm, time ``--reps`` rounds attrib-OFF, then decompose one
    sampled round; return (record, dp)."""
    import numpy as np

    dp, driver, batches, perm = _build(args, comm_dtype, comm_codec)
    batch = batches[0]
    driver.dispatch(batch, perm)
    driver.drain()  # warm: AOT compile (into the ledger) + first run
    t0 = time.perf_counter()
    for _ in range(args.reps):
        driver.dispatch(batch, perm)
    metrics = driver.drain()
    dt = (time.perf_counter() - t0) / args.reps

    # attribution pass OUTSIDE the timed window: a sampled round
    # serializes host on device by design
    driver.attrib_every = 1
    driver.dispatch(batch, perm)
    metrics += driver.drain()
    attrib = driver.last_attrib or {}

    report = dp.cost_report()
    cost = report[0] if report else {}
    roof = cost.get("roofline", {})
    losses = np.concatenate([m["loss"] for m in metrics])
    rec = {
        "comm_dtype": comm_dtype, "comm_codec": comm_codec,
        "round_ms": round(dt * 1e3, 3),
        "attrib": {seg: round(attrib.get(seg, 0.0) * 1e3, 3)
                   for seg in SEGMENTS},
        "mfu_observed": attrib.get("mfu_observed"),
        "mfu_roofline": attrib.get("mfu_roofline"),
        "peak_known": bool(cost.get("peak_known", False)),
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes_accessed"),
        "peak_temp_bytes": cost.get("peak_temp_bytes"),
        "roofline_compute_ms": round(
            roof.get("t_compute_s", 0.0) * 1e3, 3),
        "roofline_comm_ms": round(roof.get("t_comm_s", 0.0) * 1e3, 3),
        "roofline_ms": round(roof.get("t_roofline_s", 0.0) * 1e3, 3),
        "bound": roof.get("bound"),
        "compile_s": round(cost.get("compile_s", 0.0), 3),
        "amortize_rounds": (round(cost.get("compile_s", 0.0) / dt, 1)
                            if dt > 0 else None),
        "comm_bytes_per_round": dp.comm_bytes_per_round,
        "comm_bytes_saved_per_round": dp.comm_bytes_saved_per_round,
        "rounds_dispatched": args.reps + 2,
        "loss_finite": bool(np.isfinite(losses).all()),
        "workers": args.workers,
    }
    return rec, dp


def _train_center(args, attrib_every, rounds=3):
    """Short f32 training run; returns the final center (host)."""
    import jax

    dp, driver, batches, perm = _build(args, "float32", None,
                                       attrib_every=attrib_every)
    for r in range(rounds):
        driver.dispatch(batches[r % len(batches)], perm)
    driver.drain()
    return jax.device_get(dp.center(driver.mps))


def _assert_byte_identity(args):
    """Acceptance: attrib-on training is bitwise attrib-off (sampling
    only READS device state)."""
    import jax
    import numpy as np

    off = _train_center(args, attrib_every=0)
    on = _train_center(args, attrib_every=2)
    for la, lb in zip(jax.tree_util.tree_leaves(off),
                      jax.tree_util.tree_leaves(on)):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), \
            "attrib sampling perturbed the trained center"
    print(json.dumps({"byte_identity": "ok", "rounds": 3,
                      "attrib_every": 2}), flush=True)


def _assert_ledger_invariants(results, snap, args):
    """Static wire laws + the live saved-bytes counter cross-check."""
    W = args.workers
    f32 = results["f32"]["comm_bytes_per_round"]
    bf16 = results["bf16"]["comm_bytes_per_round"]
    int8 = results["int8"]["comm_bytes_per_round"]

    # the MLP center is all-f32, so the compression laws are exact:
    # int8 gather = f32/4 + the (n_leaves+1) x 4B x W scale side
    # channel; bf16 scatter = f32/2
    n_leaves = results["f32"]["f32_leaves"]
    side = (n_leaves + 1) * 4 * W
    assert int8["gather"] - side == f32["gather"] // 4, \
        (int8, f32, side)
    assert bf16["scatter"] == f32["scatter"] // 2, (bf16, f32)
    # saved-vs-f32 is exactly the collective-byte delta
    saved_int8 = results["int8"]["comm_bytes_saved_per_round"]
    saved_bf16 = results["bf16"]["comm_bytes_saved_per_round"]
    assert saved_int8 == f32["gather"] - int8["gather"], \
        (saved_int8, f32, int8)
    assert saved_bf16 == f32["scatter"] - bf16["scatter"], \
        (saved_bf16, f32, bf16)
    assert results["f32"]["comm_bytes_saved_per_round"] == 0

    # live counter: every dispatched compressed round accounted its
    # static savings — R rounds x (bf16 + int8 savings)
    counter = snap["counters"].get(
        'ps_round_comm_bytes_saved_total{fidelity="mesh"}', 0)
    rounds = results["bf16"]["rounds_dispatched"]
    want = rounds * (saved_bf16 + saved_int8)
    assert counter == want, (counter, want)
    print(json.dumps({
        "ledger_invariants": "ok",
        "int8_gather_quarter": True, "bf16_scatter_half": True,
        "saved_counter": counter,
        "saved_per_round": {"bf16": saved_bf16, "int8": saved_int8},
    }), flush=True)


def _print_table(results):
    cols = ("arm", "round_ms", "gap", "disp", "comp", "fetch",
            "roof_comp", "roof_comm", "roof_ms", "bound",
            "mfu_obs", "mfu_roof", "compile_s", "amort")
    rows = [cols]
    for name, r in results.items():
        a = r["attrib"]
        fmt = lambda v: ("-" if v is None else
                         f"{v:.4g}" if isinstance(v, float) else str(v))
        rows.append(tuple(fmt(v) for v in (
            name, r["round_ms"], a["host_gap"], a["dispatch"],
            a["device_compute"], a["ring_fetch"],
            r["roofline_compute_ms"], r["roofline_comm_ms"],
            r["roofline_ms"], r["bound"], r["mfu_observed"],
            r["mfu_roofline"], r["compile_s"], r["amortize_rounds"])))
    widths = [max(len(row[i]) for row in rows)
              for i in range(len(cols))]
    print("measured vs roofline (ms per round; mfu vs "
          "peak{, peak_known=%s}):"
          % results["f32"]["peak_known"], flush=True)
    for row in rows:
        print("  " + "  ".join(c.rjust(w)
                               for c, w in zip(row, widths)),
              flush=True)


def run(args) -> list[dict]:
    import jax

    from distkeras_tpu import attrib as attrib_lib
    from distkeras_tpu import telemetry

    tel = telemetry.enable()
    results = {}
    for name, dtype, codec in ARMS:
        rec, dp = _measure_arm(args, dtype, codec)
        if name == "f32":
            rec["f32_leaves"] = len(
                dp.spec.groups["float32"].indices)
        results[name] = rec
        print(json.dumps({"arm": name, **rec}), flush=True)
    snap = tel.metrics.snapshot()
    telemetry.disable()

    _print_table(results)
    _assert_ledger_invariants(results, snap, args)
    _assert_byte_identity(args)

    # disabled-path guard stays inside the PERF.md no-op budget (the
    # bound is generous vs the measured ~10-60ns so CI load can't
    # flake it; the PERF row quotes the measured figure)
    guard = attrib_lib.attrib_overhead(
        n=20_000 if args.smoke else 200_000)
    assert guard["disabled_ns"] < 1_000, guard
    print(json.dumps({"attrib_overhead": guard}), flush=True)

    # ---- perf_regress gating, both directions ------------------------
    import perf_regress

    obs = results["f32"]["mfu_observed"]
    roof = results["f32"]["mfu_roofline"]
    assert obs is not None and roof is not None and roof > 0, results
    cands = [
        {"metric": "mesh_round_mfu_observed", "value": round(obs, 6),
         "unit": "mfu", "peak_known": results["f32"]["peak_known"]},
        {"metric": "mesh_round_mfu_of_roofline",
         "value": round(obs / roof, 6), "unit": "frac",
         "peak_known": results["f32"]["peak_known"]},
    ]
    out_dir = pathlib.Path(tempfile.mkdtemp(prefix="dkt_attrib_"))
    for n in (1, 2):
        (out_dir / f"BENCH_r{n:02d}.json").write_text(json.dumps({
            "n": n, "cmd": "perf_attrib", "rc": 0, "tail": "",
            "parsed": cands}))
    traj = perf_regress.load_trajectories(str(out_dir / "BENCH_*.json"))
    rows = perf_regress.evaluate(cands, traj, tolerance=0.5)
    print(perf_regress.render(rows), flush=True)
    assert all(r["status"] == "pass" for r in rows), rows
    bad = perf_regress.evaluate(
        [{"metric": c["metric"], "value": c["value"] / 10.0}
         for c in cands], traj, tolerance=0.5)
    assert all(r["status"] == "breach" for r in bad), bad
    print(json.dumps({"gate": "pass_and_breach", "ok": True}),
          flush=True)

    records = cands + [
        {"metric": f"mesh_attrib_{name}_round_ms",
         "value": rec["round_ms"], "unit": "ms",
         "lower_is_better": True, **rec}
        for name, rec in results.items()]
    if args.out:
        pathlib.Path(args.out).write_text(json.dumps(records))
    if args.smoke:
        print(json.dumps({"smoke": "ok"}))
    return records


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--window", type=int, default=1)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--dim", type=int, default=2048,
                    help="MLP width (comm-heavy regime, as in "
                         "perf_mesh_comm)")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual host devices (CPU runs)")
    ap.add_argument("--out", default=None,
                    help="write the parsed-format records (a LIST) "
                         "for perf_regress.py --candidate")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes; tier-1 mode")
    args = ap.parse_args()

    if args.smoke:
        args.devices = args.devices or 4
        args.workers, args.window, args.batch = 4, 1, 4
        args.dim, args.classes, args.reps = 64, 8, 2
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
    run(args)


if __name__ == "__main__":
    main()

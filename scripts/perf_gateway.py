"""Serving-gateway A/B + failover drill + rolling-update drill
(ISSUE 7 satellite e): one workload, three questions.

1. **A/B 1 vs K** — the SAME saturated backlog through a
   ``ServingGateway`` over one in-process ``EngineReplica`` and then
   over ``--replicas`` of them: aggregate goodput tokens/s and
   queue-to-first-token p50/p95 per arm, plus the K-vs-1 speedup.
   Engines are warmed (every padded prompt length + the step program)
   before the timed run, so compile time never pollutes TTFT.
2. **Failover drill** — K socket replicas (``ReplicaServer`` /
   ``RemoteReplica``) under seeded ``ChaosTransport`` on the
   gateway→replica hop; one replica is killed with the backlog in
   flight.  Reports failover latency (kill → ``t_finish`` of each
   request that failed over off the victim, p50/p95/max) and the
   flight-recorder story (``replica_down`` → ``failover`` counts).
3. **Rolling-update drill** — a live ``HostParameterServer`` holds
   scaled weights; ``rolling_update(ps)`` swaps them into every
   replica one at a time while a pump thread keeps traffic flowing.
   Reports rollout wall time and the failed-request count (must be 0).

Metrics are fed through ``scripts/perf_regress.py``: a
``gateway_requests_per_sec`` candidate is synthesized from the live
telemetry registry (``from_registry``) and gated — against the repo's
``BENCH_*.json`` trajectories normally, or against a synthetic
trajectory written from this very run in ``--smoke`` (where the gate
must pass and both ISSUE 7 acceptance criteria are asserted: the
chaos-kill backlog completes exactly once with solo-reference tokens,
and the rolling update lands in every replica with zero failed
requests).

Usage:  PYTHONPATH=/root/repo python scripts/perf_gateway.py
        [--smoke] [--replicas 3] [--policy least_loaded]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress


def build_workload(args):
    """Saturated backlog: prompt lengths and output budgets drawn so
    every padded prompt + budget fits the single max_len envelope."""
    rng = np.random.default_rng(args.seed)
    a = args.prefill_align
    work = []
    while len(work) < args.requests:
        t = int(rng.integers(args.prompt_lo, args.prompt_hi + 1))
        n = int(rng.integers(args.new_lo, args.new_hi + 1))
        if -(-t // a) * a + n <= args.max_len:
            work.append({"prompt": rng.integers(
                0, args.vocab, (t,)).astype(np.int32), "n_new": n})
    return work


def _percentiles(xs):
    return (round(float(np.percentile(xs, 50)), 4),
            round(float(np.percentile(xs, 95)), 4))


def _build_model(args):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype=args.dtype)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))
    return model, variables


def _warmed_engine(model, variables, work, args):
    """A DecodeEngine with every padded prompt length's prefill AND
    the step program compiled (each engine owns its programs, so each
    replica warms separately — excluded from all timed runs)."""
    from distkeras_tpu.serving import DecodeEngine

    eng = DecodeEngine(model, variables, slots=args.slots,
                       prefill_align=args.prefill_align,
                       max_new_tokens=args.new_hi)
    a = args.prefill_align
    lengths = sorted({-(-len(w["prompt"]) // a) * a for w in work})
    list(eng.run([{"prompt": np.zeros((t,), np.int32),
                   "max_new_tokens": 2} for t in lengths]))
    return eng


def run_ab_arm(model, variables, work, args, k):
    """The backlog through a gateway over ``k`` warmed in-process
    replicas; TTFT is queue-to-first (everything arrives at t0)."""
    from distkeras_tpu.gateway import EngineReplica, ServingGateway

    reps = [EngineReplica(_warmed_engine(model, variables, work, args),
                          name=f"r{i}") for i in range(k)]
    with ServingGateway(reps, policy=args.policy) as gw:
        t0 = time.perf_counter()
        rids = [gw.submit(w["prompt"], max_new_tokens=w["n_new"])
                for w in work]
        results = [gw.result(r, timeout=600) for r in rids]
        wall = time.perf_counter() - t0
    assert all(r.get("error") is None for r in results), results
    goodput = sum(w["n_new"] for w in work)
    p50, p95 = _percentiles([r["t_first"] - t0 for r in results])
    return {"replicas": k, "wall_s": round(wall, 3),
            "goodput_tok_s": round(goodput / wall, 1),
            "queue_to_first_p50_s": p50,
            "queue_to_first_p95_s": p95}, results


def run_failover(model, variables, work, args):
    """K socket replicas under targeted chaos; kill one mid-backlog.
    Failover latency = kill → ``t_finish`` of each request that the
    flight recorder shows failing over off the victim."""
    from distkeras_tpu import flight_recorder
    from distkeras_tpu.gateway import (EngineReplica, RemoteReplica,
                                       ReplicaServer, ServingGateway)
    from distkeras_tpu.parallel.faults import ChaosTransport

    servers = [ReplicaServer(EngineReplica(
        _warmed_engine(model, variables, work, args),
        name=f"s{i}")).start() for i in range(args.replicas)]
    ports = {s.address[1] for s in servers}
    remotes = [RemoteReplica("127.0.0.1", s.address[1], name=f"s{i}")
               for i, s in enumerate(servers)]
    victim = 1 % len(servers)
    try:
        with ChaosTransport(seed=args.chaos_seed,
                            reset_rate=args.reset_rate,
                            max_injections=args.max_injections,
                            skip_ops=2, target_ports=ports) as ct:
            with ServingGateway(remotes, policy="round_robin",
                                retries=8, backoff_base=0.01,
                                seed=args.seed) as gw:
                t0 = time.perf_counter()
                rids = [gw.submit(w["prompt"],
                                  max_new_tokens=w["n_new"])
                        for w in work]
                t_kill = time.perf_counter()
                servers[victim].kill()
                results = [gw.result(r, timeout=600) for r in rids]
                wall = time.perf_counter() - t0
        injected = ct.total_injected
    finally:
        for s in servers:
            s.stop()
    events = (flight_recorder.active().read_events()
              if flight_recorder.active() else [])
    failed_over = {e["request_id"] for e in events
                   if e["kind"] == "failover"
                   and e.get("replica") == f"s{victim}"}
    by_rid = {r["request_id"]: r for r in results}
    lat = [by_rid[rid]["t_finish"] - t_kill
           for rid in failed_over if rid in by_rid
           and by_rid[rid].get("t_finish", 0) > t_kill]
    out = {"replicas": args.replicas, "victim": f"s{victim}",
           "wall_s": round(wall, 3),
           "chaos_injected": injected,
           "requests_failed_over": len(failed_over),
           "flight_replica_down": sum(
               1 for e in events if e["kind"] == "replica_down"),
           "flight_failover": sum(
               1 for e in events if e["kind"] == "failover")}
    if lat:
        p50, p95 = _percentiles(lat)
        out.update({"failover_p50_s": p50, "failover_p95_s": p95,
                    "failover_max_s": round(max(lat), 4)})
    return out, results, injected


def run_rolling_update(model, variables, work, args):
    """Live-PS rollout under traffic: a pump thread keeps requests
    flowing while every replica is drained, swapped, and readmitted
    one at a time.  Failed traffic must be zero."""
    import jax

    from distkeras_tpu.gateway import EngineReplica, ServingGateway
    from distkeras_tpu.parallel.host_ps import HostParameterServer
    from distkeras_tpu.parallel.update_rules import DownpourRule

    new_params = jax.tree_util.tree_map(lambda x: x * 0.7,
                                        variables["params"])
    ps = HostParameterServer(DownpourRule(), new_params)
    reps = [EngineReplica(_warmed_engine(model, variables, work, args),
                          name=f"r{i}") for i in range(args.replicas)]
    stop = threading.Event()
    traffic: list = []

    def pump(gw):
        k = 0
        while not stop.is_set():
            w = work[k % len(work)]
            rid = gw.submit(w["prompt"], max_new_tokens=w["n_new"])
            traffic.append(gw.result(rid, timeout=600))
            k += 1

    with ServingGateway(reps, policy="least_loaded", retries=6,
                        backoff_base=0.005) as gw:
        t = threading.Thread(target=pump, args=(gw,), daemon=True)
        t.start()
        try:
            t0 = time.perf_counter()
            report = gw.rolling_update(ps, quiesce_timeout=120)
            wall = time.perf_counter() - t0
        finally:
            stop.set()
            t.join(60)
        post = [gw.result(gw.submit(w["prompt"],
                                    max_new_tokens=w["n_new"]),
                          timeout=600) for w in work[:2]]
    failed = [r for r in traffic if r.get("error")]
    return {"replicas": args.replicas, "rollout_wall_s": round(wall, 3),
            "updated": report["updated"], "skipped": report["skipped"],
            "rolled_back": report["rolled_back"],
            "traffic_requests": len(traffic),
            "traffic_failed": len(failed)}, new_params, post, reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + the ISSUE 7 acceptance "
                         "assertions (the tier-1 registration)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--prompt-lo", type=int, default=16)
    ap.add_argument("--prompt-hi", type=int, default=96)
    ap.add_argument("--new-lo", type=int, default=8)
    ap.add_argument("--new-hi", type=int, default=32)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--prefill-align", type=int, default=16)
    ap.add_argument("--replicas", type=int, default=3,
                    help="K for the K-replica arm / drills")
    ap.add_argument("--policy", default="least_loaded",
                    choices=["round_robin", "least_loaded", "session"])
    ap.add_argument("--chaos-seed", type=int, default=11)
    ap.add_argument("--reset-rate", type=float, default=0.15)
    ap.add_argument("--max-injections", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None,
                    help="artifact directory (flight recorder, "
                         "registry snapshot, smoke gate trajectory)")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="perf_regress gate slack")
    args = ap.parse_args()

    if args.smoke:
        args.layers, args.d_model, args.heads = 1, 32, 2
        args.vocab, args.max_len, args.dtype = 37, 32, "float32"
        args.requests, args.prompt_lo, args.prompt_hi = 10, 3, 9
        args.new_lo, args.new_hi = 3, 6
        args.slots, args.prefill_align, args.replicas = 2, 4, 3

    out_dir = pathlib.Path(args.out_dir
                           or tempfile.mkdtemp(prefix="dkt_gw_"))
    out_dir.mkdir(parents=True, exist_ok=True)

    import jax

    from distkeras_tpu import flight_recorder, telemetry
    from distkeras_tpu.models import generate

    tel = telemetry.enable()
    flight_recorder.start(out_dir / "fdr")
    model, variables = _build_model(args)
    work = build_workload(args)
    goodput = sum(w["n_new"] for w in work)

    out = {"metric": "gateway_ab_failover_rollout",
           "model": f"lm L{args.layers} d{args.d_model}",
           "requests": args.requests, "policy": args.policy,
           "goodput_tokens": int(goodput), "arms": {}}

    t_run0 = time.perf_counter()
    out["arms"]["solo"], _ = run_ab_arm(model, variables, work,
                                        args, 1)
    out["arms"]["gateway"], gw_results = run_ab_arm(
        model, variables, work, args, args.replicas)
    out["speedup_k_vs_1"] = round(
        out["arms"]["gateway"]["goodput_tok_s"]
        / out["arms"]["solo"]["goodput_tok_s"], 3)

    out["failover"], fo_results, injected = run_failover(
        model, variables, work, args)
    out["rolling_update"], new_params, post, reps = \
        run_rolling_update(model, variables, work, args)
    run_seconds = time.perf_counter() - t_run0

    snap_path = out_dir / "registry.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    flight_recorder.stop()
    telemetry.disable()

    # ---- the perf_regress hookup: registry counter -> rate candidate
    cands = perf_regress.from_registry(
        str(snap_path), "gateway_requests_per_sec",
        "gateway_requests_total", run_seconds)
    cands.append({"metric": "gateway_goodput_tok_s",
                  "value": out["arms"]["gateway"]["goodput_tok_s"]})
    if args.smoke:
        # synthetic trajectory from this very run — the gate must pass
        for i, c in enumerate(cands):
            for n in (1, 2, 3):
                (out_dir / f"BENCH_c{i}_r{n:02d}.json").write_text(
                    json.dumps({
                        "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                        "parsed": {"metric": c["metric"],
                                   "value": c["value"] * (1 + 0.02 * n),
                                   "unit": "per_sec"}}))
        baselines = str(out_dir / "BENCH_*.json")
    else:
        baselines = perf_regress.DEFAULT_BASELINES
    rows = perf_regress.evaluate(
        cands, perf_regress.load_trajectories(baselines),
        tolerance=0.5 if args.smoke else args.tolerance)
    print(perf_regress.render(rows))
    out["gate"] = [{k: r[k] for k in ("metric", "value", "status")}
                   for r in rows]

    if args.smoke:
        # acceptance 1: chaos kill — exactly once, solo-parity tokens
        assert injected > 0
        assert [r.get("error") for r in fo_results] == \
            [None] * len(work)
        assert len({r["request_id"] for r in fo_results}) == len(work)
        for res_set in (gw_results, fo_results):
            for w, r in zip(work, res_set):
                want = np.asarray(generate(
                    model, variables, w["prompt"][None, :],
                    max_new_tokens=w["n_new"]))[0, len(w["prompt"]):]
                np.testing.assert_array_equal(np.asarray(r["tokens"]),
                                              want)
        assert out["failover"]["flight_replica_down"] > 0
        assert out["failover"]["flight_failover"] > 0
        # acceptance 2: rolling update landed everywhere, zero failed
        ru = out["rolling_update"]
        assert ru["updated"] == [f"r{i}" for i in range(args.replicas)]
        assert not ru["rolled_back"] and not ru["skipped"]
        assert ru["traffic_failed"] == 0
        new_vars = dict(variables)
        new_vars["params"] = new_params
        for rep in reps:
            got = jax.tree_util.tree_leaves(rep.variables()["params"])
            for g, ww in zip(got,
                             jax.tree_util.tree_leaves(new_params)):
                np.testing.assert_allclose(np.asarray(g),
                                           np.asarray(ww))
        for w, r in zip(work[:2], post):
            want = np.asarray(generate(
                model, new_vars, w["prompt"][None, :],
                max_new_tokens=w["n_new"]))[0, len(w["prompt"]):]
            np.testing.assert_array_equal(np.asarray(r["tokens"]),
                                          want)
        # the gate passed on this run's own trajectory
        assert all(r["status"] == "pass" for r in rows), rows
        out["smoke"] = "ok"
    print(json.dumps(out, default=repr))


if __name__ == "__main__":
    main()

"""Speculative decoding A/B (ISSUE 15): baseline vs n-gram vs
draft-model arms on a repetitive-suffix and a non-repetitive workload.

1. **Parity** — every arm's greedy tokens are asserted byte-identical
   to the baseline arm on BOTH engines (envelope and paged): the
   greedy acceptance rule makes speculation a pure scheduling
   optimization, so parity is structural, not statistical.
2. **A/B** — per arm and workload: engine steps consumed (the decode
   quanta — each one is a full weight/KV read, the unit speculation
   actually amortizes), wall-clock tokens/s, and the proposer's
   acceptance rate.  The repetitive workload (tiled motifs, long
   continuations that re-tread the context) is where prompt-lookup
   drafting earns acceptance; the non-repetitive workload is the
   honest control where it collapses toward zero.
3. **Gate** — ``serving_spec_tokens_per_sec`` is synthesized from the
   live registry (``from_registry``) and gated through
   ``scripts/perf_regress.py`` together with the acceptance rate —
   against the repo's ``BENCH_*.json`` trajectories normally, or a
   synthetic trajectory from this very run in ``--smoke`` (where the
   gate must pass and the ISSUE 15 acceptance criteria are asserted:
   byte-identical tokens on both engine arms, fewer engine steps than
   baseline on the repetitive workload, and the acceptance-rate
   telemetry visible in the registry snapshot).

Usage:  PYTHONPATH=/root/repo python scripts/perf_spec.py
        [--smoke] [--k 4] [--ngram 2]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress


def _build_model(args, *, layers=None, d_model=None):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=layers or args.layers,
        d_model=d_model or args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype=args.dtype)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))
    return model, variables


def build_workloads(args):
    """Two workloads, same request count and token budget.  The
    repetitive one tiles a short motif through the prompt — the
    context shape where a tiny model's continuation re-treads its own
    history and prompt-lookup drafting earns acceptance."""
    rng = np.random.default_rng(args.seed)
    rep, nonrep = [], []
    for i in range(args.requests):
        motif = rng.integers(0, args.vocab,
                             (args.motif,)).astype(np.int32)
        rep.append({"prompt": np.tile(
            motif, args.prompt // args.motif + 1
            )[:args.prompt].astype(np.int32),
            "max_new_tokens": args.new, "i": i})
        nonrep.append({"prompt": rng.integers(
            0, args.vocab, (args.prompt,)).astype(np.int32),
            "max_new_tokens": args.new, "i": i})
    return {"repetitive": rep, "nonrepetitive": nonrep}


def run_arm(model, variables, workloads, args, *, speculative=None,
            kv_pages=None, warm=True):
    """ONE engine per (arm, engine-kind) — both workloads share the
    same bucket/chunk shapes, so one warm pass compiles the whole
    program set and every later drive runs warm (compile dominates
    the CPU smoke otherwise).  Parity-only passes skip the warm drive
    (``warm=False``): their timing is never reported.  Returns
    ``{workload: (report, results)}``; acceptance counters are
    differenced around each timed drive so the rate is per-workload
    even though the engine's counters are cumulative."""
    from distkeras_tpu.serving import DecodeEngine

    kw = {"slots": args.slots, "buckets": [args.env],
          "prefill_align": args.prefill_align}
    if kv_pages is not None:
        kw["kv_pages"] = kv_pages
    if speculative is not None:
        kw["speculative"] = speculative

    def drive(eng, work):
        for w in work:
            eng.submit(w["prompt"],
                       max_new_tokens=w["max_new_tokens"],
                       meta={"i": w["i"]})
        steps, res = 0, {}
        t0 = time.perf_counter()
        while eng.has_work():
            for r in eng.step():
                assert r.get("error") is None, r
                res[r["i"]] = r
            steps += 1
        return steps, time.perf_counter() - t0, res

    out = {}
    with DecodeEngine(model, variables, **kw) as eng:
        if warm:
            drive(eng, next(iter(workloads.values())))
        for wname, work in workloads.items():
            s0 = eng.spec_stats()
            steps, wall, res = drive(eng, work)
            s1 = eng.spec_stats()
            prop = s1.get("proposed", 0) - s0.get("proposed", 0)
            acc = s1.get("accepted", 0) - s0.get("accepted", 0)
            toks = sum(len(r["tokens"]) for r in res.values())
            out[wname] = ({"steps": steps, "wall_s": round(wall, 4),
                           "tokens": toks,
                           "tokens_per_sec": round(toks / wall, 1),
                           "accept_rate": (round(acc / prop, 4)
                                           if prop else None)}, res)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + the ISSUE 15 acceptance "
                         "assertions (the tier-1 registration)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--env", type=int, default=256)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--prefill-align", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--prompt", type=int, default=48,
                    help="prompt length (tokens)")
    ap.add_argument("--motif", type=int, default=5,
                    help="repetitive-workload motif length")
    ap.add_argument("--new", type=int, default=128,
                    help="new tokens per request")
    ap.add_argument("--k", type=int, default=4,
                    help="proposal window")
    ap.add_argument("--ngram", type=int, default=2,
                    help="n-gram match length")
    ap.add_argument("--draft-layers", type=int, default=1)
    ap.add_argument("--draft-d-model", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--tolerance", type=float, default=0.15)
    args = ap.parse_args()

    if args.smoke:
        args.layers, args.d_model, args.heads = 2, 128, 4
        args.vocab, args.max_len, args.env = 64, 64, 64
        args.prefill_align, args.slots = 8, 4
        args.requests, args.prompt, args.motif = 6, 16, 5
        args.new = 36
        args.draft_layers, args.draft_d_model = 1, 64

    out_dir = pathlib.Path(args.out_dir
                           or tempfile.mkdtemp(prefix="dkt_spec_"))
    out_dir.mkdir(parents=True, exist_ok=True)

    from distkeras_tpu import telemetry

    tel = telemetry.enable()
    model, variables = _build_model(args)
    draft_model, draft_variables = _build_model(
        args, layers=args.draft_layers, d_model=args.draft_d_model)
    workloads = build_workloads(args)

    arms = {
        "baseline": None,
        "ngram": {"proposer": "ngram", "k": args.k,
                  "ngram": args.ngram},
        "draft": {"proposer": "draft", "k": args.k,
                  "draft_model": draft_model,
                  "draft_variables": draft_variables},
    }
    # page budget: the submit-time worst case for every slot at once
    kv_pages = args.slots * (args.env // args.prefill_align)

    out = {"metric": "speculative_decode_ab",
           "model": f"lm L{args.layers} d{args.d_model}",
           "draft": f"lm L{args.draft_layers} d{args.draft_d_model}",
           "k": args.k, "ngram": args.ngram, "workloads": {}}
    t_run0 = time.perf_counter()
    env_runs = {aname: run_arm(model, variables, workloads, args,
                               speculative=sp)
                for aname, sp in arms.items()}
    # the paged lowering of each arm must match it token-for-token
    # (one paged pass per arm and workload; parity is the point, not
    # timing, so these engines skip the warm drive)
    paged_runs = {aname: run_arm(model, variables, workloads, args,
                                 speculative=sp, kv_pages=kv_pages,
                                 warm=False)
                  for aname, sp in arms.items()}
    for wname in workloads:
        base = env_runs["baseline"][wname][1]
        for aname in arms:
            for i in sorted(base):
                np.testing.assert_array_equal(
                    env_runs[aname][wname][1][i]["tokens"],
                    base[i]["tokens"],
                    err_msg=f"{aname}/{wname} request {i}")
                np.testing.assert_array_equal(
                    paged_runs[aname][wname][1][i]["tokens"],
                    base[i]["tokens"],
                    err_msg=f"paged {aname}/{wname} request {i}")
        out["workloads"][wname] = {
            aname: env_runs[aname][wname][0] for aname in arms}
    run_seconds = time.perf_counter() - t_run0
    out["parity"] = "byte_identical_both_engines"

    snap = tel.metrics.snapshot()
    snap_path = out_dir / "registry.json"
    snap_path.write_text(json.dumps(snap, default=repr))
    telemetry.disable()

    # ---- the perf_regress hookup ------------------------------------
    rep = out["workloads"]["repetitive"]
    cands = perf_regress.from_registry(
        str(snap_path), "serving_spec_tokens_per_sec",
        "serving_tokens_total", run_seconds)
    # the headline tokens/s is the ngram arm on its winning workload
    cands.append({"metric": "spec_ngram_tokens_per_sec",
                  "value": rep["ngram"]["tokens_per_sec"]})
    cands.append({"metric": "spec_accept_rate",
                  "value": rep["ngram"]["accept_rate"] or 0.0})
    if args.smoke:
        for i, c in enumerate(cands):
            for n in (1, 2, 3):
                (out_dir / f"BENCH_c{i}_r{n:02d}.json").write_text(
                    json.dumps({
                        "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                        "parsed": {"metric": c["metric"],
                                   "value": c["value"] * (1 + 0.02 * n),
                                   "unit": "per_sec"}}))
        baselines = str(out_dir / "BENCH_*.json")
    else:
        baselines = perf_regress.DEFAULT_BASELINES
    rows = perf_regress.evaluate(
        cands, perf_regress.load_trajectories(baselines),
        tolerance=0.5 if args.smoke else args.tolerance)
    print(perf_regress.render(rows))
    out["gate"] = [{k: r[k] for k in ("metric", "value", "status")}
                   for r in rows]

    if args.smoke:
        # speculation must EARN acceptance where the context repeats…
        assert rep["ngram"]["accept_rate"] > 0.02, rep
        # …and convert it into fewer decode quanta than the baseline
        # (each step is a full weight read — the bandwidth unit a
        # real accelerator amortizes; CPU wall-clock is reported
        # honestly but not gated, the verify is compute-bound there)
        assert rep["ngram"]["steps"] < rep["baseline"]["steps"], rep
        assert rep["draft"]["steps"] < rep["baseline"]["steps"], rep
        # acceptance-rate telemetry is IN the registry snapshot
        assert any(k.startswith("serving_spec_proposed_total")
                   for k in snap["counters"]), list(snap["counters"])
        assert any(k.startswith("serving_spec_accept_rate")
                   for k in snap["gauges"]), list(snap["gauges"])
        assert all(r["status"] == "pass" for r in rows), rows
        out["smoke"] = "ok"
    print(json.dumps(out, default=repr))


if __name__ == "__main__":
    main()

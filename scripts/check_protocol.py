"""Protocol model-checker CI gate (ISSUE 11).

Exhaustively explores the replicated-PS election/fencing/replication
protocol (``analysis.protomodel`` over ``analysis.modelcheck``) and
exits 2 on any invariant violation — or on a mutation-harness miss,
because a checker that can't catch known-unsafe mutants proves
nothing:

    python scripts/check_protocol.py             # all scenarios, full
    python scripts/check_protocol.py --scenario rewind
    python scripts/check_protocol.py --mutate    # every mutant must
                                                 # yield a replayable
                                                 # counterexample
    python scripts/check_protocol.py --smoke     # tier-1: small clean
                                                 # sweep + 2 mutants
    python scripts/check_protocol.py --replay "<schedule tokens>" \
        --scenario rewind --with-mutant skip-rewind

``modelcheck_states_explored_total`` / ``modelcheck_violations_total
{invariant=...}`` are emitted through the telemetry registry;
``--metrics-out`` writes the snapshot so ``perf_regress.py
--from-registry`` can gate on exploration throughput like any other
counter.
"""

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from distkeras_tpu import telemetry  # noqa: E402
from distkeras_tpu.analysis import modelcheck, protomodel  # noqa: E402

#: --smoke trims every scenario's bounds to keep tier-1 fast; the
#: rewind scenario still reaches its seeded divergence window.
SMOKE_BOUNDS = {"max_depth": 10, "max_states": 3_000}
SMOKE_MUTANTS = ("no-quorum", "no-dedupe-repl")


def run_clean(names, bounds_override=None) -> int:
    """Explore scenarios expecting ZERO violations; returns rc."""
    rc = 0
    for name in names:
        model, bounds = protomodel.build(name)
        if bounds_override:
            bounds = {**bounds, **bounds_override}
        t0 = time.perf_counter()
        rep = modelcheck.Explorer(model, **bounds).run()
        dt = time.perf_counter() - t0
        status = "ok" if rep.violation is None else "VIOLATION"
        print(f"scenario {name}: {status} — {rep.states} states, "
              f"{rep.executions} executions, {rep.truncated} at "
              f"bound, depth<={bounds['max_depth']}, {dt:.2f}s")
        if rep.violation is not None:
            print(f"  {rep.violation}")
            rc = 2
    return rc


def run_mutants(muts, bounds_override=None) -> int:
    """Every known-unsafe mutant must produce a minimized,
    schedule-replayable counterexample breaking the EXPECTED
    invariant; anything less is a checker failure."""
    rc = 0
    for mut in muts:
        desc, scen, want = protomodel.MUTANTS[mut]
        model, bounds = protomodel.build(scen, mutants=[mut])
        if bounds_override:
            bounds = {**bounds, **bounds_override}
        explorer = modelcheck.Explorer(model, **bounds)
        t0 = time.perf_counter()
        rep = explorer.run()
        dt = time.perf_counter() - t0
        v = rep.violation
        if v is None:
            print(f"mutant {mut} ({scen}): MISSED — no "
                  f"counterexample in {rep.states} states ({dt:.2f}s)")
            rc = 2
            continue
        # the explorer replay-verifies during minimization; verify
        # once more from the printed string — the artifact a human
        # would paste into --replay
        rv = explorer.replay(v.schedule)
        replayed = (rv is not None and rv.invariant == v.invariant
                    and rv.schedule == v.schedule)
        ok = v.invariant == want and replayed
        print(f"mutant {mut} ({scen}): "
              f"{'caught' if ok else 'WRONG'} — {v.invariant} at "
              f"depth {v.depth} (want {want}, replay "
              f"{'ok' if replayed else 'FAILED'}), {rep.states} "
              f"states, {dt:.2f}s")
        print(f"  guard flipped: {desc}")
        print(f"  schedule: {v.schedule}")
        if not ok:
            rc = 2
    return rc


def run_replay(scenario: str, mutants, schedule: str) -> int:
    model, _ = protomodel.build(scenario, mutants=mutants)
    v = modelcheck.Explorer(model).replay(schedule)
    if v is None:
        print("replay: schedule runs clean (no violation)")
        return 0
    print(f"replay: {v}")
    return 2


def emit_metrics(out_path) -> None:
    if out_path:
        pathlib.Path(out_path).write_text(
            json.dumps(telemetry.metrics().snapshot(), indent=2,
                       sort_keys=True, default=str))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default=None,
                    choices=sorted(protomodel.SCENARIOS),
                    help="explore one scenario (default: all)")
    ap.add_argument("--mutate", action="store_true",
                    help="mutation harness: every known-unsafe "
                         "mutant must be caught")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 subset: trimmed clean sweep + "
                         f"mutants {', '.join(SMOKE_MUTANTS)}")
    ap.add_argument("--replay", default=None, metavar="SCHEDULE",
                    help="re-execute a schedule string against "
                         "--scenario (+ --with-mutant)")
    ap.add_argument("--with-mutant", action="append", default=[],
                    choices=sorted(protomodel.MUTANTS),
                    help="apply a mutant during --replay")
    ap.add_argument("--max-depth", type=int, default=None)
    ap.add_argument("--max-states", type=int, default=None)
    ap.add_argument("--metrics-out", default=None,
                    help="write the telemetry registry snapshot here")
    args = ap.parse_args(argv)

    telemetry.enable()  # the explorer's counters need a live registry
    override = {}
    if args.max_depth is not None:
        override["max_depth"] = args.max_depth
    if args.max_states is not None:
        override["max_states"] = args.max_states

    if args.replay:
        if not args.scenario:
            ap.error("--replay needs --scenario")
        rc = run_replay(args.scenario, args.with_mutant, args.replay)
    elif args.smoke:
        rc = run_clean(sorted(protomodel.SCENARIOS),
                       {**SMOKE_BOUNDS, **override})
        rc = max(rc, run_mutants(SMOKE_MUTANTS, override))
        if rc == 0:
            print("check_protocol: smoke OK (clean sweep at smoke "
                  "bounds; every smoke mutant caught + replayed)")
    elif args.mutate:
        rc = run_mutants(sorted(protomodel.MUTANTS), override)
        if rc == 0:
            print(f"check_protocol: all {len(protomodel.MUTANTS)} "
                  "mutants caught with replayable counterexamples")
    else:
        names = [args.scenario] if args.scenario else sorted(
            protomodel.SCENARIOS)
        rc = run_clean(names, override)
        if rc == 0:
            print(f"check_protocol: {len(names)} scenario(s) "
                  "explored to their bounds, zero violations")

    emit_metrics(args.metrics_out)
    return rc


if __name__ == "__main__":
    sys.exit(main())

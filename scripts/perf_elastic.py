"""Elastic-PS live-migration + autoscaler drill (ISSUE 14): does
resharding under load actually cost nothing, and does the autoscaler
actually close the loop?

1. **Live-migration A/B** — the SAME commit hammer (W workers, R
   commits each, over ``ResilientPSClient.for_elastic``) against an
   elastic PS group twice: once steady (fixed topology) and once with
   a shard live-migrated to a freshly added server mid-run.  Per arm:
   commit throughput and staleness p99; the report shows the
   throughput dip and staleness delta the move cost, and the
   fence->cutover latency from the ``shard_migrate_cutover`` flight
   event.  Exactly-once must hold across both arms (group commits ==
   commits issued).
2. **Autoscaler, PS domain** — a 1-shard group is hammered until
   ``ps_lock_wait`` (lock-wait seconds per shard commit) breaches a
   threshold calibrated from the single-shard baseline; the
   ``telemetry.Autoscaler`` must decide ``split``, execute it via
   ``ElasticPSGroup.split`` live, and the breach must CLEAR within
   the bounds (``max_shards``) — the closed loop, not just the
   decision.
3. **Autoscaler, gateway domain** — a 1-replica ``ServingGateway``
   under a decode backlog until ``queue_depth`` breaches; the
   autoscaler must spawn a second ``EngineReplica`` through
   ``gateway.add_replica`` (the rolling_update drain-swap-readmit
   plumbing: registered excluded, warmed from the live peer, then
   admitted), the new replica must actually serve traffic, and the
   signal must clear once the backlog drains.

Every decision (executed and suppressed) lands as an
``autoscale_decision`` flight event; the report ends with
``postmortem.scaling_story``'s replay of the whole drill.  Throughput
and migration latency are gated through ``scripts/perf_regress.py``
(``from_registry`` for the rate, lower-is-better for the latency).

Usage:  PYTHONPATH=/root/repo python scripts/perf_elastic.py
        [--smoke] [--workers 4] [--commits 30]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress
import postmortem


def _center(hidden=(192, 192)):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    mlp = model_config("mlp", (64,), num_classes=4, hidden=hidden)
    model = ModelSpec.from_config(mlp).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 64), jnp.float32))
    import jax.tree_util as jtu
    return jtu.tree_map(np.asarray, variables["params"])


_WORKER_IDS = iter(range(1, 1 << 20))


def hammer(grp, template, workers: int, commits: int,
           during=None, at: int | None = None) -> float:
    """W worker threads, each pulling once then pushing ``commits``
    constant deltas through the resilient elastic client; returns the
    wall seconds.  ``during`` (optional) fires on a side thread once
    the group has absorbed ``at`` commits — the mid-run topology
    change.  Worker ids are globally unique across calls: a reused
    (worker, seq) pair would be DEDUPED by the group's exactly-once
    table and the burst would measure cached replies, not commits."""
    import jax.tree_util as jtu

    from distkeras_tpu.parallel.host_ps import ResilientPSClient

    base = grp.num_commits
    ids = [next(_WORKER_IDS) for _ in range(workers)]
    errors: list[Exception] = []

    def work(w):
        cl = ResilientPSClient.for_elastic(
            [grp.addresses[0]], worker_id=ids[w], template=template,
            retries=8, seed=w)
        try:
            center = cl.pull()
            delta = jtu.tree_map(
                lambda x: np.full_like(x, 1e-4), center)
            for _ in range(commits):
                cl.commit(delta)
            cl.done()
        except Exception as e:
            errors.append(e)
        finally:
            cl.close()

    ops, finished = None, threading.Event()
    if during is not None:
        def trigger():
            while (grp.num_commits < base + at
                   and not finished.is_set()):
                time.sleep(0.001)
            if not finished.is_set():
                during()
        ops = threading.Thread(target=trigger)
        ops.start()
    threads = [threading.Thread(target=work, args=(w,))
               for w in range(workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    finished.set()
    if ops is not None:
        ops.join()
        assert grp.num_commits >= base + (at or 0), (
            "the mid-run trigger never fired")
    if errors:
        raise errors[0]
    return wall


def _signals():
    from distkeras_tpu import telemetry

    return telemetry.SLOWatchdog(telemetry.metrics()).signals()


def migration_ab(args, out: pathlib.Path) -> dict:
    """Arm A: fixed topology.  Arm B: same load, one shard
    live-migrated mid-run.  Fresh telemetry registry per arm so the
    staleness histogram and throughput counters are per-arm."""
    from distkeras_tpu import flight_recorder, telemetry
    from distkeras_tpu.parallel.elastic_ps import ElasticPSGroup
    from distkeras_tpu.parallel.update_rules import DownpourRule

    center = _center()
    grp = ElasticPSGroup(DownpourRule(), center, num_shards=2,
                         num_servers=2)
    issued = 0
    try:
        arms = {}
        telemetry.enable()
        wall = hammer(grp, center, args.workers, args.commits)
        issued += args.workers * args.commits
        sig = _signals()
        snap = out / "steady_registry.json"
        snap.write_text(json.dumps(telemetry.metrics().snapshot(),
                                   default=repr))
        arms["steady"] = {
            "wall_s": wall,
            "commits_per_sec": args.workers * args.commits / wall,
            "staleness_p99": sig.get("staleness_p99")}

        telemetry.enable()  # fresh registry for the moving arm

        def move():
            dst = grp.add_server("127.0.0.1")
            grp.migrate(0, dst)

        wall = hammer(grp, center, args.workers, args.commits,
                      during=move,
                      at=args.workers * args.commits // 3)
        issued += args.workers * args.commits
        sig = _signals()
        arms["move"] = {
            "wall_s": wall,
            "commits_per_sec": args.workers * args.commits / wall,
            "staleness_p99": sig.get("staleness_p99")}
        applied = grp.num_commits
    finally:
        grp.stop()
    assert applied == issued, (
        f"exactly-once violated across the move: {applied} applied "
        f"for {issued} issued")
    events = flight_recorder.active().read_events()
    cutovers = [e for e in events
                if e["kind"] == "shard_migrate_cutover"]
    assert cutovers, "the moving arm never cut over"
    arms["migration_latency_s"] = float(cutovers[-1]["latency_s"])
    arms["dip"] = (arms["move"]["commits_per_sec"]
                   / arms["steady"]["commits_per_sec"])
    arms["steady_snapshot"] = str(snap)
    arms["commits_applied"] = applied
    return arms


def autoscaler_ps_loop(args) -> dict:
    """Breach -> split -> clear, end to end: calibrate the
    ``ps_lock_wait`` threshold from the single-shard baseline, then
    let the autoscaler split the live group until the signal drops
    below it (bounded by ``max_shards``)."""
    from distkeras_tpu import telemetry
    from distkeras_tpu.parallel.elastic_ps import ElasticPSGroup
    from distkeras_tpu.parallel.update_rules import DownpourRule

    # the wide center makes the lock-held apply real WORK (~ms of
    # GIL-releasing numpy per commit): on a starved single-CPU box
    # the scheduler serializes threads so µs-scale holds rarely
    # collide and the measured "contention" collapses into scheduler
    # noise that no split can clear — ms-scale holds queue for real,
    # and the signal divides by K no matter how noisy the machine is
    center = _center(hidden=(768, 768))
    grp = ElasticPSGroup(DownpourRule(), center, num_shards=1,
                         num_servers=1)
    workers = max(args.workers, 6)  # contention IS the signal here
    try:
        # warmup burst (unmeasured): first-connect and first-touch
        # costs would otherwise inflate the baseline 10x
        telemetry.enable()
        hammer(grp, center, workers, args.commits)
        # baseline burst: the single-shard lock-wait level IS the
        # problem the drill wants solved — the operator's threshold
        # sits at 0.35x of it (the "this is unacceptable" line), so
        # the baseline registry itself is the breaching evidence.
        # Splitting divides the per-shard hold time by K, multiplies
        # the shard-commit denominator by K, and collapses the queue
        # on top, so the signal drops well below 1/K per split —
        # clearing the threshold with margin by the K=4 bound.
        telemetry.enable()
        hammer(grp, center, workers, args.commits)
        base = _signals().get("ps_lock_wait", 0.0)
        assert base > 0, "no lock contention measured at K=1"
        thresholds = {"ps_lock_wait": (0.35 * base, 60.0 * base)}

        def do_split():
            plan = grp.nodes[0].map.plan
            wide = max(range(len(plan)), key=lambda s: len(plan[s]))
            grp.split(wide)

        scaler = telemetry.Autoscaler(
            telemetry.SLOWatchdog(telemetry.metrics(),
                                  thresholds=thresholds),
            split_shard=do_split, merge_shards=None,
            shard_count=lambda: grp.num_shards,
            min_shards=1, max_shards=4, cooldown_s=0.0,
            idle_sustain_s=1e9,
            ps_scale_signals=("ps_lock_wait",))
        trail = []
        for it in range(5):
            if it:
                # per-burst registry: the signal is THIS burst's
                # contention, not the run's cumulative mean (the
                # baseline burst above is iteration 0's evidence)
                telemetry.enable()
                hammer(grp, center, workers, args.commits)
            wd = telemetry.SLOWatchdog(telemetry.metrics(),
                                       thresholds=thresholds)
            scaler.watchdog = wd
            verdict = wd.evaluate()
            decisions = scaler.step(verdict)
            trail.append({
                "shards_before": (grp.num_shards
                                  - sum(1 for d in decisions
                                        if d["executed"])),
                "ps_lock_wait": verdict["signals"].get("ps_lock_wait"),
                "breached": "ps_lock_wait" in verdict["breaches"],
                "decisions": decisions})
            if not trail[-1]["breached"]:
                break
        shards = grp.num_shards
    finally:
        grp.stop()
    assert not trail[-1]["breached"], (
        f"autoscaler failed to clear ps_lock_wait within bounds: "
        f"{trail}")
    executed = [d for t in trail for d in t["decisions"]
                if d["executed"] and d["action"] == "split"]
    assert executed and shards > 1, (trail, shards)
    return {"baseline_lock_wait_s": base,
            "threshold_s": thresholds["ps_lock_wait"][0],
            "final_shards": shards, "splits": len(executed),
            "trail": trail}


def autoscaler_gateway_loop(args) -> dict:
    """Breach -> spawn -> serve -> clear on the serving side: a
    saturated 1-replica gateway trips ``queue_depth``; the autoscaler
    admits a second replica via ``gateway.add_replica`` (warmed from
    the live peer), which must then take real traffic."""
    import jax
    import jax.numpy as jnp

    from distkeras_tpu import telemetry
    from distkeras_tpu.gateway import EngineReplica, ServingGateway
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.serving import DecodeEngine

    spec = model_config("transformer_lm", (32,), input_dtype="int32",
                        vocab_size=61, num_layers=1, d_model=32,
                        num_heads=2, max_len=32, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))

    def engine():
        # 24-token budgets keep the backlog IN the queue long enough
        # for the watchdog to see it (a 4-token budget drains in ~10ms
        # on CPU — faster than any sane polling interval)
        eng = DecodeEngine(model, variables, slots=2,
                           prefill_align=8, max_new_tokens=24)
        # warm the padded prefill + step programs out of the timed path
        list(eng.run([{"prompt": np.zeros((8,), np.int32),
                       "max_new_tokens": 2}]))
        return eng

    telemetry.enable()
    rng = np.random.default_rng(args.seed)
    prompts = [rng.integers(0, 61, (6,)).astype(np.int32)
               for _ in range(24)]
    gw = ServingGateway([EngineReplica(engine(), name="g0")],
                        policy="least_loaded")
    names = iter(f"auto{i}" for i in range(8))
    scaler = telemetry.Autoscaler(
        telemetry.SLOWatchdog(telemetry.metrics(),
                              thresholds={"queue_depth": (3.0, 1e9)}),
        spawn_replica=lambda: gw.add_replica(
            EngineReplica(engine(), name=next(names))),
        replica_count=lambda: len(gw.healthz()["replicas"]),
        min_replicas=1, max_replicas=3, cooldown_s=0.0,
        idle_sustain_s=1e9, gateway_scale_signals=("queue_depth",))
    with gw:
        rids = [gw.submit(p) for p in prompts[:12]]
        # the replica driver moves submissions into the engine queue
        # asynchronously; poll until the backlog is visible (the
        # production autoscaler loop ticks every interval_s anyway)
        deadline = time.perf_counter() + 10.0
        while True:
            verdict = scaler.watchdog.evaluate()
            if ("queue_depth" in verdict["breaches"]
                    or time.perf_counter() > deadline):
                break
            time.sleep(0.01)
        decisions = scaler.step(verdict)
        assert "queue_depth" in verdict["breaches"], verdict
        spawned = [d for d in decisions
                   if d["action"] == "spawn" and d["executed"]]
        assert spawned, decisions
        rids += [gw.submit(p) for p in prompts[12:]]
        results = [gw.result(r, timeout=300) for r in rids]
        assert all(r.get("error") is None for r in results), results
        cleared = scaler.watchdog.evaluate()
    assert "queue_depth" not in cleared["breaches"], cleared
    snap = telemetry.metrics().snapshot()
    auto_served = sum(
        v for k, v in snap["counters"].items()
        if k.startswith("gateway_requests_total")
        and 'replica="auto' in k)
    assert auto_served > 0, (
        "the spawned replica never served a request")
    return {"breach": {k: v["value"]
                       for k, v in verdict["breaches"].items()},
            "spawned": [d["action"] for d in spawned],
            "replicas": len(gw.healthz()["replicas"]),
            "served_by_spawned": int(auto_served),
            "completed": len(results)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes (the tier-1 mode)")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--commits", type=int, default=30,
                    help="commits per worker per burst/arm")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None,
                    help="artifact directory (temp default)")
    args = ap.parse_args()
    if args.smoke:
        args.workers = min(args.workers, 4)
        args.commits = min(args.commits, 30)
    out = pathlib.Path(args.out_dir or tempfile.mkdtemp(
        prefix="dkt_perf_elastic_"))
    out.mkdir(parents=True, exist_ok=True)

    from distkeras_tpu import flight_recorder, telemetry

    flight_recorder.start(out / "flight")
    ab = migration_ab(args, out)
    ps_loop = autoscaler_ps_loop(args)
    gw_loop = autoscaler_gateway_loop(args)
    events = flight_recorder.active().read_events()
    story = postmortem.scaling_story(events)
    telemetry.disable()
    flight_recorder.stop()

    # ---- perf_regress: steady throughput from the registry snapshot,
    # moving-arm throughput directly, migration latency lower-is-better
    cands = perf_regress.from_registry(
        ab["steady_snapshot"], "elastic_steady_commits_per_sec",
        "ps_commits_total", ab["steady"]["wall_s"])
    cands.append({"metric": "elastic_move_commits_per_sec",
                  "value": ab["move"]["commits_per_sec"],
                  "unit": "per_sec"})
    latency_cand = [{"metric": "elastic_migration_latency_s",
                     "value": ab["migration_latency_s"], "unit": "s"}]
    for i, c in enumerate(cands + latency_cand):
        for n in (1, 2, 3):  # synthetic trajectory from this very run
            (out / f"BENCH_pe{i}_r{n:02d}.json").write_text(
                json.dumps({
                    "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                    "parsed": {"metric": c["metric"],
                               "value": c["value"] * (1 + 0.02 * n),
                               "unit": c.get("unit", "per_sec")}}))
    traj = perf_regress.load_trajectories(str(out / "BENCH_pe*.json"))
    gate = (perf_regress.evaluate(cands, traj, tolerance=0.5)
            + perf_regress.evaluate(latency_cand, traj, tolerance=0.5,
                                    lower_is_better=True))
    assert all(r["status"] == "pass" for r in gate), gate

    stal = {a: (f"{ab[a]['staleness_p99']:.1f}"
                if ab[a]["staleness_p99"] is not None else "n/a")
            for a in ("steady", "move")}
    lines = [
        "distkeras_tpu elastic PS / autoscaler report",
        "== live-migration A/B (same load, fixed vs moving) ==",
        f"  steady  {ab['steady']['commits_per_sec']:8.1f} commits/s"
        f"  staleness p99 {stal['steady']}",
        f"  moving  {ab['move']['commits_per_sec']:8.1f} commits/s"
        f"  staleness p99 {stal['move']}",
        f"  throughput during move   {ab['dip'] * 100:.0f}% of steady",
        f"  migration latency        "
        f"{ab['migration_latency_s'] * 1e3:.1f}ms (fence -> cutover)",
        f"  commits applied          {ab['commits_applied']} "
        "(== issued: exactly-once across the move)",
        "== autoscaler closed loop: PS domain ==",
        f"  baseline ps_lock_wait    "
        f"{ps_loop['baseline_lock_wait_s'] * 1e3:.2f}ms/commit at K=1",
        f"  threshold (calibrated)   "
        f"{ps_loop['threshold_s'] * 1e3:.2f}ms/commit",
    ]
    for t in ps_loop["trail"]:
        acts = [f"{d['action']}{'' if d['executed'] else '(supp)'}"
                for d in t["decisions"]] or ["-"]
        lines.append(
            f"  K={t['shards_before']}: ps_lock_wait "
            f"{t['ps_lock_wait'] * 1e3:.2f}ms "
            f"{'BREACH' if t['breached'] else 'clear'} "
            f"-> {', '.join(acts)}")
    lines += [
        f"  splits executed          {ps_loop['splits']} "
        f"(final K={ps_loop['final_shards']}; breach cleared)",
        "== autoscaler closed loop: gateway domain ==",
        f"  queue_depth breach       "
        f"{gw_loop['breach'].get('queue_depth'):g}",
        f"  spawned                  {gw_loop['spawned']} "
        f"(fleet now {gw_loop['replicas']}, via gateway.add_replica)",
        f"  served by spawned        {gw_loop['served_by_spawned']}",
        f"  completed clean          {gw_loop['completed']} "
        "(queue_depth cleared after drain)",
        f"== scaling story (postmortem replay, {len(story)} "
        "events) ==",
    ]
    t0 = story[0]["wall_s"] if story else 0.0
    lines += [f"  +{s['wall_s'] - t0:7.3f}s {s['what']}"
              for s in story]
    lines += ["== perf_regress gate =="]
    lines += [f"  {r['metric']:<32} {r['value']:.4g} {r['status']}"
              for r in gate]
    report = "\n".join(lines)
    if args.smoke:
        for needle in ("exactly-once across the move",
                       "breach cleared", "gateway.add_replica",
                       "autoscale", "migration latency"):
            assert needle in report, f"report lacks {needle}:\n{report}"
        report += "\nsmoke: ok"
    print(report)


if __name__ == "__main__":
    main()

"""TransformerLM training throughput on one chip (PERF.md §13).

The ResNet-50 number is the BASELINE.md flagship; this records the
transformer side — tokens/sec and analytic MFU for a GPT-2-small-shaped
``TransformerLM`` — so the long-context family has a measured baseline
too.  MFU uses the standard 6 * params * tokens training-FLOPs
estimate (PaLM appendix convention; attention FLOPs reported
separately), against the chip's bf16 peak.

Usage:  PYTHONPATH=/root/repo python scripts/perf_lm.py
        [--layers 12 --d-model 768 --seq-len 1024 --batch 8]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.profiling import host_sync, peak_flops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--attn",
                    choices=["auto", "dense", "blockwise", "flash"],
                    default="dense",
                    help="'blockwise': device-local flash-style "
                         "attention (online-softmax q-chunks, no "
                         "[T,T] materialization) — the long-T lever "
                         "PERF.md §13 measures.  'flash': the same "
                         "algorithm as hand-written Pallas kernels "
                         "(ops.attention, PERF.md §17)")
    ap.add_argument("--q-chunk", type=int, default=128,
                    help="q block length for --attn blockwise; for "
                         "--attn flash the kernel's measured default "
                         "blocks (512/1024) are used")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize each block in the backward "
                         "(jax.checkpoint): ~1 extra forward of FLOPs "
                         "for O(layers) less activation memory")
    ap.add_argument("--experts", type=int, default=0,
                    help=">0 swaps every block's FFN for a top-1 "
                         "Switch MoE with this many experts (dense "
                         "einsum form; runs replicated on one chip).  "
                         "MFU is computed on ACTIVE params (one "
                         "expert per token), the number that tracks "
                         "useful work")
    args = ap.parse_args()

    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    spec = model_config(
        "transformer_lm", (args.seq_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.seq_len, dtype="bfloat16",
        num_experts=args.experts,
        remat_blocks=args.remat,
        attn=args.attn if args.attn in ("auto", "dense") else "auto",
        blockwise_attn=args.attn == "blockwise",
        flash_attn=args.attn == "flash",
        attn_q_chunk=(args.q_chunk if args.attn == "blockwise"
                      else None))
    model = ModelSpec.from_config(spec).build()
    tx = resolve_optimizer("adam", 3e-4)
    tokens = jnp.zeros((args.batch, args.seq_len), jnp.int32)
    variables = model.init(jax.random.key(0), tokens[:2])
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(variables["params"]))
    state = TrainState.create(variables, tx, jax.random.key(1))
    step = jax.jit(make_train_step(
        model, "sparse_categorical_crossentropy", tx),
        donate_argnums=0)
    batch = {"features": tokens, "label": tokens}

    for _ in range(3):
        state, metrics = step(state, batch)
    host_sync(metrics)
    t0 = time.perf_counter()
    for _ in range(args.reps):
        state, metrics = step(state, batch)
    val = host_sync(metrics)
    dt = (time.perf_counter() - t0) / args.reps

    toks = args.batch * args.seq_len
    # 6ND (fwd 2ND + bwd 4ND) + attention term 12*L*d*T^2 (fwd+bwd).
    # MoE: count ACTIVE params — top-1 routing touches one expert's
    # FFN per token, so (E-1) experts' FFN weights are excluded.
    n_active = n_params
    if args.experts > 1:
        per_expert_ffn = 2 * args.d_model * (args.d_model
                                             * 4) + args.d_model * 5
        n_active -= (args.experts - 1) * args.layers * per_expert_ffn
    flops_param = 6.0 * n_active * toks
    flops_attn = (12.0 * args.layers * args.d_model
                  * args.seq_len * args.seq_len * args.batch)
    peak, known = peak_flops(jax.devices()[0])
    print(json.dumps({
        "model": f"lm L{args.layers} d{args.d_model} T{args.seq_len}",
        "attn": args.attn,
        "experts": args.experts,
        "params_active_m": round(n_active / 1e6, 1),
        "params_m": round(n_params / 1e6, 1),
        "step_ms": round(dt * 1e3, 2),
        "tokens_per_sec": round(toks / dt, 1),
        "mfu_6nd": (round(flops_param / dt / peak, 4)
                    if known else None),
        "mfu_with_attn": (round((flops_param + flops_attn) / dt / peak,
                                4) if known else None),
        "loss_finite": bool(np.isfinite(val)),
    }))


if __name__ == "__main__":
    main()

"""Shared-prefix KV cache + chunked prefill A/B (ISSUE 8 satellite):
one shared-system-prompt workload, three questions.

1. **Prefix A/B** — the SAME workload (a few shared prompt heads,
   unique tails) through four engine arms: ``baseline`` (both knobs
   off), ``prefix`` (``prefix_cache_bytes``), ``chunk``
   (``prefill_chunk``), and ``both``.  Every arm is warmed with one
   full pass (compiles every program AND brings the prefix store to
   steady state), then timed.  Reports TTFT p50/p95 per arm, prefill
   tokens saved as a fraction of all prompt tokens, and asserts all
   four arms' greedy tokens are byte-identical — the optimization
   must be invisible.
2. **Interleave drill** — one live slot decodes while a max-length
   prompt prefills next to it.  Each engine step yields the live slot
   at most one token, so per-step wall time IS its inter-token gap;
   with chunking off the admission step swallows the whole prefill
   (one giant gap), with chunking on every gap is bounded by the
   chunk quantum.  Reports the gap max/p95 and step count for both
   arms (median over repeats).
3. **Gate** — a ``serving_prefill_tokens_saved_per_sec`` candidate is
   synthesized from the live telemetry registry (``from_registry``)
   and fed through ``scripts/perf_regress.py`` — against the repo's
   ``BENCH_*.json`` trajectories normally, or against a synthetic
   trajectory from this very run in ``--smoke`` (where the gate must
   pass and the ISSUE 8 acceptance criteria are asserted: >= 50%
   prefill tokens eliminated at steady state, TTFT p50 improved vs
   cache-off, and the chunked arm's worst inter-token gap strictly
   under the unchunked arm's).

Usage:  PYTHONPATH=/root/repo python scripts/perf_prefix.py
        [--smoke] [--prefill-chunk 32] [--prefix-cache-mb 64]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
if str(REPO / "scripts") not in sys.path:
    sys.path.insert(0, str(REPO / "scripts"))

import numpy as np

import perf_regress


def build_workload(args):
    """``--requests`` prompts over ``--shared-heads`` distinct
    ``--head-len``-token heads with unique tails — the system-prompt
    traffic shape the prefix store exists for."""
    rng = np.random.default_rng(args.seed)
    heads = [rng.integers(0, args.vocab, (args.head_len,))
             .astype(np.int32) for _ in range(args.shared_heads)]
    work = []
    for i in range(args.requests):
        tail = rng.integers(
            0, args.vocab,
            (int(rng.integers(args.tail_lo, args.tail_hi + 1)),)
        ).astype(np.int32)
        work.append({"prompt": np.concatenate(
            [heads[i % len(heads)], tail]), "n_new": args.new})
    return work


def _percentiles(xs):
    return (round(float(np.percentile(xs, 50)), 5),
            round(float(np.percentile(xs, 95)), 5))


def _build_model(args):
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype=args.dtype)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))
    return model, variables


def _engine(model, variables, args, **kw):
    from distkeras_tpu.serving import DecodeEngine

    kw.setdefault("slots", args.slots)
    return DecodeEngine(model, variables,
                        prefill_align=args.prefill_align,
                        max_new_tokens=args.new, **kw)


def run_arm(model, variables, work, args, *, prefix=False,
            chunk=False):
    """One engine arm: warm pass (compiles + store steady state),
    then the timed pass.  Token savings are measured on the timed
    pass only — the steady-state fraction, not the cold-start one."""
    kw = {}
    if prefix:
        kw["prefix_cache_bytes"] = args.prefix_cache_mb << 20
    if chunk:
        kw["prefill_chunk"] = args.prefill_chunk
    reqs = [{"prompt": w["prompt"], "max_new_tokens": w["n_new"]}
            for w in work]
    with _engine(model, variables, args, **kw) as eng:
        list(eng.run(reqs))  # warm: programs + prefix store
        saved0 = eng.prefix_stats().get("tokens_saved", 0)
        p50s, p95s, wall = [], [], 0.0
        for _ in range(args.passes):  # best-of-N vs host jitter
            t0 = time.perf_counter()
            results = list(eng.run(reqs))
            wall += time.perf_counter() - t0
            assert all(r.get("error") is None for r in results), \
                results
            p50, p95 = _percentiles([r["ttft"] for r in results])
            p50s.append(p50)
            p95s.append(p95)
        saved = (eng.prefix_stats().get("tokens_saved", 0)
                 - saved0) / args.passes
    ttft_p50, ttft_p95 = min(p50s), min(p95s)
    prompt_tok = sum(len(w["prompt"]) for w in work)
    report = {"prefix": prefix, "chunk": chunk,
              "wall_s": round(wall, 4),
              "goodput_tok_s": round(
                  args.passes * sum(w["n_new"] for w in work)
                  / wall, 1),
              "ttft_p50_s": ttft_p50, "ttft_p95_s": ttft_p95,
              "prefill_tokens_saved": int(saved),
              "prompt_tokens": int(prompt_tok),
              "saved_frac": round(saved / prompt_tok, 3)}
    tokens = [np.asarray(r["tokens"]) for r in results]
    return report, tokens


def run_interleave(model, variables, args, chunk):
    """Live slot's per-step inter-token gaps while a max-length
    prompt prefills beside it (see module docstring); one warm drill
    first, then the median-of-repeats max/p95."""
    rng = np.random.default_rng(args.seed + 1)
    live = rng.integers(0, args.vocab, (8,)).astype(np.int32)
    a = args.prefill_align
    t_long = (args.max_len - args.new) // a * a
    long = rng.integers(0, args.vocab, (t_long,)).astype(np.int32)
    live_new = args.max_len - 8 - 4
    kw = {"prefill_chunk": args.prefill_chunk} if chunk else {}
    maxes, p95s, counts = [], [], []
    with _engine(model, variables, args, slots=2, **kw) as eng:
        for rep in range(args.drill_repeats + 1):
            eng.submit(live, max_new_tokens=live_new,
                       request_id=f"live{rep}")
            eng.step()  # live prefill; it decodes from here on
            eng.submit(long, max_new_tokens=args.new,
                       request_id=f"long{rep}")
            stamps, results = [], {}
            while eng.has_work():
                t0 = time.perf_counter()
                for r in eng.step():
                    assert r.get("error") is None, r
                    results[r["request_id"]] = r
                stamps.append((t0, time.perf_counter() - t0))
            # the window: steps from the long submit until its first
            # token materialized (telemetry.now IS perf_counter)
            t_first = results[f"long{rep}"]["t_first"]
            gaps = [dt for t0, dt in stamps if t0 < t_first]
            if rep == 0:
                continue  # warm drill: compile time pollutes gaps
            maxes.append(max(gaps))
            p95s.append(float(np.percentile(gaps, 95)))
            counts.append(len(gaps))
    # best-of-repeats: the floor is the structural cost, noise only
    # ever inflates a repeat above it
    return {"chunk": bool(chunk),
            "prefill_window_steps": int(np.median(counts)),
            "intertoken_max_s": round(float(np.min(maxes)), 5),
            "intertoken_p95_s": round(float(np.min(p95s)), 5)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + the ISSUE 8 acceptance "
                         "assertions (the tier-1 registration)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--shared-heads", type=int, default=4)
    ap.add_argument("--head-len", type=int, default=192)
    ap.add_argument("--tail-lo", type=int, default=8)
    ap.add_argument("--tail-hi", type=int, default=24)
    ap.add_argument("--new", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--prefill-align", type=int, default=16)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--prefix-cache-mb", type=int, default=64)
    ap.add_argument("--drill-repeats", type=int, default=3)
    ap.add_argument("--passes", type=int, default=2,
                    help="timed passes per arm; TTFT percentiles are "
                         "best-of (floor = structural cost)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None,
                    help="artifact directory (flight recorder, "
                         "registry snapshot, smoke gate trajectory)")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="perf_regress gate slack")
    args = ap.parse_args()

    if args.smoke:
        # big enough that a 64-token prefill costs visibly more than
        # a handful of block-copy dispatches, small enough for CPU CI
        args.layers, args.d_model, args.heads = 2, 256, 4
        args.vocab, args.max_len, args.dtype = 64, 64, "float32"
        args.requests, args.shared_heads = 8, 2
        args.head_len, args.tail_lo, args.tail_hi = 48, 4, 6
        args.new, args.slots = 4, 4
        args.prefill_align, args.prefill_chunk = 16, 16
        args.drill_repeats, args.passes = 5, 3

    out_dir = pathlib.Path(args.out_dir
                           or tempfile.mkdtemp(prefix="dkt_pfx_"))
    out_dir.mkdir(parents=True, exist_ok=True)

    from distkeras_tpu import flight_recorder, telemetry

    tel = telemetry.enable()
    flight_recorder.start(out_dir / "fdr")
    model, variables = _build_model(args)
    work = build_workload(args)

    out = {"metric": "prefix_cache_chunked_prefill_ab",
           "model": f"lm L{args.layers} d{args.d_model}",
           "requests": args.requests,
           "shared_heads": args.shared_heads,
           "head_len": args.head_len, "arms": {}}

    t_run0 = time.perf_counter()
    arms = {"baseline": {}, "prefix": {"prefix": True},
            "chunk": {"chunk": True},
            "both": {"prefix": True, "chunk": True}}
    tokens = {}
    for name, sel in arms.items():
        out["arms"][name], tokens[name] = run_arm(
            model, variables, work, args, **sel)
    run_seconds = time.perf_counter() - t_run0

    # the optimization must be INVISIBLE: byte-identical greedy tokens
    for name in ("prefix", "chunk", "both"):
        for i, (got, want) in enumerate(zip(tokens[name],
                                            tokens["baseline"])):
            np.testing.assert_array_equal(
                got, want, err_msg=f"arm {name} request {i}")
    out["parity"] = "byte_identical"
    out["ttft_p50_speedup"] = round(
        out["arms"]["baseline"]["ttft_p50_s"]
        / max(out["arms"]["prefix"]["ttft_p50_s"], 1e-9), 3)

    out["interleave"] = {
        "unchunked": run_interleave(model, variables, args, False),
        "chunked": run_interleave(model, variables, args, True)}

    snap_path = out_dir / "registry.json"
    snap_path.write_text(json.dumps(tel.metrics.snapshot(),
                                    default=repr))
    flight_recorder.stop()
    telemetry.disable()

    # ---- the perf_regress hookup: registry counter -> rate candidate
    cands = perf_regress.from_registry(
        str(snap_path), "serving_prefill_tokens_saved_per_sec",
        "serving_prefill_tokens_saved_total", run_seconds)
    cands.append({"metric": "prefix_goodput_tok_s",
                  "value": out["arms"]["both"]["goodput_tok_s"]})
    if args.smoke:
        # synthetic trajectory from this very run — the gate must pass
        for i, c in enumerate(cands):
            for n in (1, 2, 3):
                (out_dir / f"BENCH_c{i}_r{n:02d}.json").write_text(
                    json.dumps({
                        "n": n, "cmd": "smoke", "rc": 0, "tail": "",
                        "parsed": {"metric": c["metric"],
                                   "value": c["value"] * (1 + 0.02 * n),
                                   "unit": "per_sec"}}))
        baselines = str(out_dir / "BENCH_*.json")
    else:
        baselines = perf_regress.DEFAULT_BASELINES
    rows = perf_regress.evaluate(
        cands, perf_regress.load_trajectories(baselines),
        tolerance=0.5 if args.smoke else args.tolerance)
    print(perf_regress.render(rows))
    out["gate"] = [{k: r[k] for k in ("metric", "value", "status")}
                   for r in rows]

    if args.smoke:
        # acceptance: >= 50% of steady-state prefill eliminated...
        assert out["arms"]["prefix"]["saved_frac"] >= 0.5, out["arms"]
        assert out["arms"]["both"]["saved_frac"] >= 0.5, out["arms"]
        # ...TTFT improved vs cache-off...
        assert (out["arms"]["prefix"]["ttft_p50_s"]
                < out["arms"]["baseline"]["ttft_p50_s"]), out["arms"]
        # ...and the chunked arm's worst inter-token gap is bounded
        # by the chunk quantum, not the full prompt: strictly under
        # the unchunked arm's monolithic-prefill gap, over a window
        # of several steps (the prefill really was interleaved)
        il = out["interleave"]
        assert (il["chunked"]["intertoken_max_s"]
                < il["unchunked"]["intertoken_max_s"]), il
        assert (il["chunked"]["prefill_window_steps"]
                > il["unchunked"]["prefill_window_steps"]), il
        assert all(r["status"] == "pass" for r in rows), rows
        out["smoke"] = "ok"
    print(json.dumps(out, default=repr))


if __name__ == "__main__":
    main()

"""Observability report — summarize a telemetry run (metrics snapshot
+ trace-event timeline) into one text report.

Two modes:

* ``--metrics m.jsonl --trace t.json`` — summarize artifacts an
  earlier run wrote (``MetricsRegistry.write_jsonl`` /
  ``Tracer.write_chrome_trace``, e.g. from
  ``scripts/perf_serving.py --metrics ... --trace ...``).  Either flag
  alone works.
* ``--smoke`` — self-contained end-to-end proof at tiny CPU shapes
  (the tier-1 registration, via test_examples.py's scripts-coverage
  check): enables telemetry, runs (1) a mixed-length ``DecodeEngine``
  workload and (2) an async host-PS training run over the REAL socket
  transport, writes both artifacts to ``--out-dir`` (a temp dir by
  default), asserts the report shows PS commit spans, per-worker round
  spans on distinct thread tracks, queue/occupancy gauges, a TTFT
  histogram and per-bucket compile counters — then prints the report.

The report sections: counters (sorted by value), gauges, histograms
(count / mean / p50 / p95 at bucket resolution), series (count + last),
and trace tracks (per-thread span rollup: which spans, how many, how
much wall time).
"""

from __future__ import annotations

import argparse
import collections
import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


# ---- summarizers -------------------------------------------------------

def _hist_percentile(buckets: dict, count: int, hi, q: float):
    need = q * count
    for edge, cum in buckets.items():
        if cum >= need:
            return float(edge)
    return hi


def summarize_metrics(path: str) -> list[str]:
    recs = [json.loads(line) for line in open(path) if line.strip()]
    by_kind: dict[str, list] = collections.defaultdict(list)
    for r in recs:
        by_kind[r["kind"]].append(r)
    lines = [f"== metrics ({len(recs)} series from {path}) =="]
    for r in sorted(by_kind.get("counter", ()),
                    key=lambda r: -r["value"]):
        lines.append(f"  counter    {r['key']:<58} {r['value']:g}")
    for r in sorted(by_kind.get("gauge", ()), key=lambda r: r["key"]):
        lines.append(f"  gauge      {r['key']:<58} {r['value']:g}")
    for r in sorted(by_kind.get("histogram", ()),
                    key=lambda r: r["key"]):
        n = r["count"]
        mean = r["sum"] / n if n else float("nan")
        p50 = _hist_percentile(r["buckets"], n, r["max"], 0.5)
        p95 = _hist_percentile(r["buckets"], n, r["max"], 0.95)
        lines.append(
            f"  histogram  {r['key']:<38} n={n} mean={mean:.4g} "
            f"p50<={p50:.4g} p95<={p95:.4g}")
    for r in sorted(by_kind.get("series", ()), key=lambda r: r["key"]):
        vals = r["values"]
        last = vals[-1] if vals else None
        lines.append(f"  series     {r['key']:<38} n={len(vals)} "
                     f"last={last!r}")
    return lines


def summarize_trace(path: str) -> list[str]:
    trace = json.load(open(path))
    events = trace["traceEvents"]
    names = {e["tid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    lines = [f"== trace ({len(spans)} spans, {len(instants)} instant "
             f"events, {len(names)} thread tracks from {path}) =="]
    by_tid: dict[int, list] = collections.defaultdict(list)
    for e in spans:
        by_tid[e["tid"]].append(e)
    for tid in sorted(by_tid):
        evs = by_tid[tid]
        per_name: dict[str, list] = collections.defaultdict(list)
        for e in evs:
            per_name[e["name"]].append(e["dur"])
        track = names.get(tid, str(tid))
        lines.append(f"  track {track} (tid {tid}):")
        for name, durs in sorted(per_name.items(),
                                 key=lambda kv: -sum(kv[1])):
            lines.append(
                f"    {name:<24} n={len(durs):<5} "
                f"total={sum(durs) / 1e6:.3f}s "
                f"mean={sum(durs) / len(durs) / 1e3:.2f}ms")
    per_instant = collections.Counter(e["name"] for e in instants)
    for name, n in per_instant.most_common():
        lines.append(f"  instant {name:<22} n={n}")
    return lines


def build_report(metrics_path: str | None,
                 trace_path: str | None) -> str:
    lines: list[str] = ["distkeras_tpu observability report"]
    if metrics_path:
        lines += summarize_metrics(metrics_path)
    if trace_path:
        lines += summarize_trace(trace_path)
    return "\n".join(lines)


# ---- the smoke run -----------------------------------------------------

def smoke_run(out_dir: str) -> tuple[str, str]:
    """Tiny engine + host-PS(socket) runs with telemetry on; returns
    (metrics_path, trace_path)."""
    import numpy as np

    from distkeras_tpu import telemetry
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.trainers import DOWNPOUR

    tel = telemetry.enable()

    # (1) mixed-length continuous-batching serving
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.serving import DecodeEngine

    spec = model_config("transformer_lm", (32,), input_dtype="int32",
                        vocab_size=61, num_layers=1, d_model=32,
                        num_heads=2, max_len=32, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 32), jnp.int32))
    eng = DecodeEngine(model, variables, slots=3, buckets=[16, 32],
                       prefill_align=4, max_new_tokens=6)
    rng = np.random.default_rng(0)
    reqs = [{"prompt": rng.integers(0, 61, (t,)).astype(np.int32),
             "max_new_tokens": int(n)}
            for t, n in zip([5, 9, 3, 14, 7, 4], [6, 3, 5, 4, 2, 6])]
    list(eng.run(reqs))

    # (1b) one disaggregated prefill->decode handoff, so the report
    # surfaces the handoff counters (serving_kv_pages_shipped_total,
    # serving_handoff_requeue_total — the latter pre-touched at 0)
    from distkeras_tpu.gateway import EngineReplica, PrefillDecodeRouter

    def _pd_engine():
        return DecodeEngine(model, variables, slots=2, prefill_align=4,
                            max_new_tokens=6,
                            prefix_cache_bytes=1 << 22)

    with PrefillDecodeRouter(
            [EngineReplica(_pd_engine(), name="obs-p0")],
            [EngineReplica(_pd_engine(), name="obs-d0")],
            block_size=4) as router:
        rid = router.submit(rng.integers(0, 61, (12,)).astype(np.int32),
                            max_new_tokens=3)
        res = router.result(rid, timeout=120)
        assert res.get("error") is None, res

    # (2) async host-PS training over the real socket transport
    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(512, (8,), 4, seed=0)
    t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                 num_workers=2, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.01,
                 worker_optimizer="adam")
    t.train(data)

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    metrics_path = tel.metrics.write_jsonl(out / "metrics.jsonl")
    trace_path = tel.tracer.write_chrome_trace(out / "trace.json")
    telemetry.disable()
    return metrics_path, trace_path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--metrics", default=None,
                    help="metrics JSONL (MetricsRegistry.write_jsonl)")
    ap.add_argument("--trace", default=None,
                    help="Chrome trace JSON "
                         "(Tracer.write_chrome_trace)")
    ap.add_argument("--smoke", action="store_true",
                    help="run tiny engine + host-PS workloads and "
                         "report on their artifacts (tier-1 mode)")
    ap.add_argument("--out-dir", default=None,
                    help="--smoke artifact directory (temp default)")
    ap.add_argument("--out", default=None,
                    help="also write the report to this file")
    args = ap.parse_args()

    if args.smoke:
        out_dir = args.out_dir or tempfile.mkdtemp(prefix="dkt_obs_")
        args.metrics, args.trace = smoke_run(out_dir)
    elif not (args.metrics or args.trace):
        ap.error("pass --metrics and/or --trace, or --smoke")

    report = build_report(args.metrics, args.trace)

    if args.smoke:
        # the end-to-end exporter contract tier-1 pins: serving
        # metrics, per-bucket compile counters, PS commit spans and
        # per-worker round spans all visible in one report
        for needle in ("serving_ttft_seconds", "serving_queue_depth",
                       "serving_slot_occupancy", "compiles_total",
                       "ps_commits_total", "ps_commit",
                       "worker_round", "ps_wire_bytes_total",
                       "serving_inter_token_seconds",
                       "serving_kv_pages_shipped_total",
                       "serving_handoff_requeue_total"):
            assert needle in report, f"report lacks {needle}:\n{report}"
        trace = json.load(open(args.trace))
        commit_tids = {e["tid"] for e in trace["traceEvents"]
                       if e.get("ph") == "X"
                       and e["name"] == "ps_commit"}
        round_tids = {e["tid"] for e in trace["traceEvents"]
                      if e.get("ph") == "X"
                      and e["name"] == "worker_round"}
        # socket arm: commits land on PS handler threads — tracks
        # DISTINCT from the worker threads' round spans
        assert commit_tids and round_tids
        assert commit_tids.isdisjoint(round_tids), (commit_tids,
                                                    round_tids)
        report += "\nsmoke: ok"

    print(report)
    if args.out:
        pathlib.Path(args.out).write_text(report + "\n")


if __name__ == "__main__":
    main()

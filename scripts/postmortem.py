"""Postmortem — reconstruct the last N seconds before a crash from the
flight recorder's on-disk ring and cross-check it against durable
state (ISSUE 6 tentpole 2).

The in-memory trace ring dies with its process; the flight recorder
(``distkeras_tpu.flight_recorder``) survives it.  This script replays
the surviving JSONL window ending at the crash marker (the last
``ps_kill`` event, or the newest event when no kill was recorded),
prints a per-kind timeline, derives the last ACKED commit seq per
worker from the recorded ``commit`` events, and — given the PS
snapshot the dead server was writing — cross-checks that against the
snapshot's dedupe table (``checkpoint.ps_snapshot_info``'s
``last_acked``): a mismatch means commits were applied after the last
durable snapshot (data at risk), agreement proves the restart resumes
exactly where the flight recorder says the crash happened.

When the ring holds ``ps_promote`` events (a replicated PS group,
ISSUE 10), the report additionally reconstructs the failover story:
one line per fencing epoch — who was primary, why it took over, the
commit-log seq it resumed from and where its reign ended — plus how
many stale writers each epoch fenced (``ps_fenced``), cross-checked
against the promoted replica's snapshot epoch
(``ps_snapshot_info``'s ``epoch``).

When the ring holds elastic-scaling events (ISSUE 14 —
``autoscale_decision``, ``shard_split`` / ``shard_merge``,
``shard_migrate_begin`` / ``_cutover`` / ``_abort``, ``replica_add`` /
``replica_drain``), the report also replays the scaling story: every
autoscaler decision (suppressed ones included, with the breaching
signal and value) and every topology change, in wall-clock order.

When the ring holds traffic-drill events (ISSUE 18 — ``sim_phase``,
``sim_kill``, windowed ``chaos`` fires, ``slo_state``,
``drill_converged``), the report replays the drill story too: load
phase changes, scheduled kills, fault-window fires, SLO transitions,
and how long capacity took to converge back to target.

Modes:

* ``--flight DIR [--seconds 30] [--snapshot ps.snap]`` — report on an
  existing recorder directory.
* ``--smoke`` — self-contained crash proof (the tier-1 registration):
  records a real host-PS run with ``snapshot_every=1``, ``kill()``s
  the server mid-stream, warm-restarts it from the snapshot, and
  asserts the postmortem's last-acked seqs match the restarted
  server's dedupe state exactly.
"""

from __future__ import annotations

import argparse
import collections
import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


# ---- reconstruction ----------------------------------------------------

def failover_story(events: list[dict]) -> list[dict]:
    """The replicated-PS failover timeline: one entry per fencing
    epoch, derived from the fsynced ``ps_promote`` flights — which
    node was primary (its worker port), why it took over (``reason``:
    bootstrap / failover / manual), the commit-log seq it resumed
    from, and where its reign ended (the NEXT epoch's takeover seq —
    the two being equal is the commits-lost=0 proof).  ``ps_fenced``
    events are attached to the epoch that won them: a deposed
    primary records the ``newer_epoch`` that fenced it; demoted and
    standby records carry the winning epoch directly."""
    promotes = sorted((e for e in events if e["kind"] == "ps_promote"),
                      key=lambda e: int(e["epoch"]))
    story = []
    for i, e in enumerate(promotes):
        nxt = promotes[i + 1] if i + 1 < len(promotes) else None
        epoch = int(e["epoch"])
        story.append({
            "epoch": epoch,
            "primary_port": int(e["port"]),
            "reason": e.get("reason"),
            "took_over_at_seq": int(e["last_applied"]),
            "reign_ended_at_seq": (int(nxt["last_applied"])
                                   if nxt else None),
            "fenced": sum(
                1 for f in events if f["kind"] == "ps_fenced"
                and int(f.get("newer_epoch", f.get("epoch", -1)))
                == epoch),
        })
    return story


def scaling_story(events: list[dict]) -> list[dict]:
    """The elastic-scaling timeline (ISSUE 14): one entry per scaling
    event — autoscaler decisions (executed AND suppressed, with the
    breaching signal), shard splits/merges, live migrations
    (begin/cutover/abort), and gateway replica membership changes —
    in wall-clock order, so an operator can replay exactly why the
    topology is what it is."""
    out = []
    for e in sorted((e for e in events if e["kind"] in (
            "autoscale_decision", "shard_split", "shard_merge",
            "shard_migrate_begin", "shard_migrate_cutover",
            "shard_migrate_abort", "replica_add", "replica_drain")),
            key=lambda e: e["wall_s"]):
        k = e["kind"]
        if k == "autoscale_decision":
            what = (f"{e['domain']}: {e['action']}"
                    + (f" on {e['signal']}={e['value']:.4g}"
                       if e.get("signal") else " (idle)")
                    + (" executed" if e.get("executed")
                       else f" suppressed ({e.get('reason')})"))
        elif k == "shard_split":
            what = (f"shard {e['shard']} split at leaf {e['at']} "
                    f"-> map v{e['version']}")
        elif k == "shard_merge":
            what = (f"shards {e['shards']} merged "
                    f"-> map v{e['version']}")
        elif k == "shard_migrate_begin":
            what = (f"shard {e['shard']} migrating "
                    f"{e['src']} -> {e['dst']}")
        elif k == "shard_migrate_cutover":
            what = (f"shard {e['shard']} cut over to node {e['dst']} "
                    f"(epoch {e['epoch']}, {e['latency_s'] * 1e3:.1f}"
                    f"ms) -> map v{e['version']}")
        elif k == "shard_migrate_abort":
            what = (f"shard {e['shard']} move to node {e['dst']} "
                    f"ABORTED ({e.get('error')}); old owner "
                    f"un-fenced")
        else:  # replica_add / replica_drain
            what = (f"replica {e['replica']} "
                    f"{'admitted' if k == 'replica_add' else 'drained'}"
                    f" (fleet now {e['total']})")
        out.append({"wall_s": e["wall_s"], "kind": k, "what": what})
    return out


def drill_story(events: list[dict]) -> list[dict]:
    """The traffic-drill timeline (ISSUE 18): load phases
    (``sim_phase`` — base/flash-crowd transitions), scheduled kills
    (``sim_kill``), transport fault windows firing (``chaos`` events
    with ``window=True``), SLO state transitions (``slo_state``), and
    capacity convergence (``drill_converged``) — the "what did the
    load do, what did we break, how fast did capacity catch up" story
    beside ``scaling_story``'s verb-level view."""
    out = []
    for e in sorted((e for e in events if e["kind"] in (
            "sim_phase", "sim_kill", "slo_state", "drill_converged")
            or (e["kind"] == "chaos" and e.get("window"))),
            key=lambda e: e["wall_s"]):
        k = e["kind"]
        if k == "sim_phase":
            what = (f"load phase -> {e['phase']} "
                    f"(trace t={e['sim_t']:.2f}s)")
        elif k == "sim_kill":
            what = (f"scheduled kill: {e['target']} "
                    f"(trace t={e['sim_t']:.2f}s)")
        elif k == "slo_state":
            what = (f"SLO {e.get('previous')} -> {e['state']}"
                    + (f" on {','.join(e['breaches'])}"
                       if e.get("breaches") else ""))
        elif k == "drill_converged":
            what = (f"capacity converged to target {e['target']} "
                    f"after {e['seconds']:.2f}s "
                    f"(trace t={e['sim_t']:.2f}s)")
        else:  # chaos window fault
            what = f"fault window fired: {e['fault']} (op {e['op']})"
        out.append({"wall_s": e["wall_s"], "kind": k, "what": what})
    return out


def reconstruct(flight_dir: str, seconds: float = 30.0,
                snapshot: str | None = None) -> dict:
    """The postmortem: crash marker, event window, per-worker
    last-acked seqs, and (with a snapshot) the durable cross-check."""
    from distkeras_tpu.checkpoint import ps_snapshot_info
    from distkeras_tpu.flight_recorder import FlightRecorder

    events = FlightRecorder(flight_dir).read_events()
    if not events:
        raise SystemExit(f"no flight events under {flight_dir}")
    kills = [e for e in events if e["kind"] == "ps_kill"]
    crash = kills[-1] if kills else events[-1]
    window = [e for e in events
              if crash["wall_s"] - seconds <= e["wall_s"]
              <= crash["wall_s"]]
    # ACKED means APPLIED-and-replied or deduped-and-replied: both
    # kinds prove the worker's seq reached the dedupe table
    acked: dict[str, int] = {}
    for e in window:
        if e["kind"] in ("commit", "commit_dedup"):
            w, seq = str(e["worker"]), int(e["seq"])
            acked[w] = max(acked.get(w, seq), seq)
    report = {
        "crash": crash,
        "window_s": seconds,
        "events": window,
        "kinds": dict(collections.Counter(e["kind"] for e in window)),
        "flight_last_acked": acked,
    }
    story = failover_story(window)
    if story:
        report["failover_story"] = story
    scaling = scaling_story(window)
    if scaling:
        report["scaling_story"] = scaling
    drill = drill_story(window)
    if drill:
        report["drill_story"] = drill
    if snapshot is not None:
        info = ps_snapshot_info(snapshot)
        report["snapshot"] = info
        report["acked_match"] = (
            {w: int(s) for w, s in info["last_acked"].items()}
            == {w: int(s) for w, s in acked.items()})
        if story:
            # the promoted replica's snapshot must have been taken
            # under the newest epoch the flight ring proves won
            report["epoch_match"] = (
                int(info.get("epoch", 0)) == story[-1]["epoch"])
    return report


def render(report: dict) -> str:
    crash = report["crash"]
    lines = [
        "distkeras_tpu postmortem",
        f"crash marker: {crash['kind']} at wall "
        f"{crash['wall_s']:.3f} (pid {crash['pid']})",
        f"window: last {report['window_s']:g}s — "
        f"{len(report['events'])} events",
    ]
    for kind, n in sorted(report["kinds"].items(),
                          key=lambda kv: -kv[1]):
        lines.append(f"  {kind:<16} n={n}")
    lines.append("last acked commit seq per worker (flight): "
                 + json.dumps(report["flight_last_acked"],
                              sort_keys=True))
    for reign in report.get("failover_story", []):
        end = reign["reign_ended_at_seq"]
        lines.append(
            f"epoch {reign['epoch']}: primary :{reign['primary_port']}"
            f" ({reign['reason']}) seq {reign['took_over_at_seq']}"
            + (f" -> {end}" if end is not None else " -> crash/end")
            + (f", fenced {reign['fenced']} stale writer(s)"
               if reign["fenced"] else ""))
    scaling = report.get("scaling_story", [])
    if scaling:
        lines.append(f"scaling story ({len(scaling)} events):")
        t0 = scaling[0]["wall_s"]
        for s in scaling:
            lines.append(f"  +{s['wall_s'] - t0:7.3f}s {s['what']}")
    drill = report.get("drill_story", [])
    if drill:
        lines.append(f"drill story ({len(drill)} events):")
        t0 = drill[0]["wall_s"]
        for s in drill:
            lines.append(f"  +{s['wall_s'] - t0:7.3f}s {s['what']}")
    if "snapshot" in report:
        info = report["snapshot"]
        lines.append(
            f"snapshot: commits={info['num_commits']} "
            f"last_acked={json.dumps(info['last_acked'], sort_keys=True)}")
        lines.append("cross-check: "
                     + ("MATCH — restart resumes exactly at the "
                        "recorded crash point"
                        if report["acked_match"] else
                        "MISMATCH — commits applied after the last "
                        "durable snapshot"))
        if "epoch_match" in report:
            lines.append(
                "epoch cross-check: "
                + (f"MATCH — snapshot taken under the winning epoch "
                   f"{info['epoch']}"
                   if report["epoch_match"] else
                   f"MISMATCH — snapshot epoch {info['epoch']} != "
                   f"newest promoted epoch "
                   f"{report['failover_story'][-1]['epoch']}"))
    tail = report["events"][-8:]
    lines.append(f"final {len(tail)} events before the crash:")
    for e in tail:
        detail = {k: v for k, v in e.items()
                  if k not in ("kind", "wall_s", "mono_s", "pid",
                               "rec_seq")}
        lines.append(f"  +{e['wall_s'] - crash['wall_s']:+.3f}s "
                     f"{e['kind']:<14} {json.dumps(detail)}")
    return "\n".join(lines)


# ---- the smoke run -----------------------------------------------------

def smoke(out_dir: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distkeras_tpu import flight_recorder
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSClient, PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule

    out = pathlib.Path(out_dir)
    flight_dir = out / "flight"
    snap = out / "ps.snap"
    flight_recorder.start(flight_dir)

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    model = ModelSpec.from_config(mlp).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    center = jax.tree_util.tree_map(np.asarray, variables["params"])

    ps = HostParameterServer(DownpourRule(), center,
                             snapshot_path=snap, snapshot_every=1)
    srv = PSServer(ps, center).start()
    client = PSClient("127.0.0.1", srv.address[1], 0, center)
    client.pull()
    delta = jax.tree_util.tree_map(
        lambda x: np.full_like(x, 0.01), center)
    for seq in range(6):
        client.commit(delta, seq=seq)
    client.commit(delta, seq=5)  # lost-ack retry: deduped, recorded

    srv.kill()  # crash: flight ring fsynced, sockets die
    client.close()

    srv2 = PSServer.restart_from(snap, DownpourRule(), center)
    try:
        restarted = {str(w): int(s)
                     for w, s in srv2.ps.last_acked_seqs().items()}
    finally:
        srv2.stop()
    flight_recorder.stop()

    report = reconstruct(str(flight_dir), seconds=30.0,
                         snapshot=str(snap))
    print(render(report))

    # THE acceptance cross-check: the flight recorder's last-acked
    # seqs == the snapshot's dedupe table == the restarted server's
    assert report["acked_match"], report
    assert report["flight_last_acked"] == restarted, (
        report["flight_last_acked"], restarted)
    assert report["crash"]["kind"] == "ps_kill"
    assert report["kinds"].get("commit") == 6
    assert report["kinds"].get("commit_dedup") == 1
    assert report["kinds"].get("snapshot", 0) >= 6
    print("smoke: ok")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--flight", default=None,
                    help="flight-recorder directory to reconstruct")
    ap.add_argument("--seconds", type=float, default=30.0,
                    help="window width before the crash marker")
    ap.add_argument("--snapshot", default=None,
                    help="PS snapshot to cross-check against")
    ap.add_argument("--smoke", action="store_true",
                    help="self-contained kill/restart proof "
                         "(tier-1 mode)")
    ap.add_argument("--out-dir", default=None,
                    help="--smoke artifact directory (temp default)")
    args = ap.parse_args()

    if args.smoke:
        smoke(args.out_dir or tempfile.mkdtemp(prefix="dkt_pm_"))
        return
    if not args.flight:
        ap.error("pass --flight DIR (or --smoke)")
    print(render(reconstruct(args.flight, seconds=args.seconds,
                             snapshot=args.snapshot)))


if __name__ == "__main__":
    main()

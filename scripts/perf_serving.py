"""Serving A/B under mixed-length traffic: continuous batching vs
run-to-completion bucketed streaming (PERF.md §23).

Workload: ``--requests`` LM requests with prompt lengths drawn from the
``prefill_align`` grid in [--prompt-lo, --prompt-hi] and output budgets
drawn uniformly in [--new-lo, --new-hi]; ``--rate`` paces arrivals as a
Poisson process (default: full backlog at t=0, the saturated-server
throughput measurement).  Three arms over the SAME workload + params:

- ``baseline``  — ``StreamingGenerator`` (run-to-completion per-length
  buckets): every row decodes the GLOBAL --new-hi budget and finished
  rows drain with their batch;
- ``single``    — ``DecodeEngine`` with ONE max_len envelope: isolates
  the slot-refill win (finished rows evicted/replaced between steps,
  per-request budgets honored);
- ``bucketed``  — ``DecodeEngine`` with --buckets envelopes: adds the
  static-cache-law win (short requests pay a short envelope's step).

Reported per arm: aggregate goodput tokens/s (sum of REQUESTED output
tokens / wall), raw generated tokens/s, p50/p95 queue-to-first-token
and per-token completion latency.  All shapes are warmed up before the
timed run so compile time (the one-time cost; bounded per §23) never
pollutes the steady-state numbers.  Greedy; the smoke mode asserts the
continuous arms' tokens equal the baseline's per request.

Usage:  PYTHONPATH=/root/repo python scripts/perf_serving.py
        [--smoke] [--arms baseline,single,bucketed] [--rate 8]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import numpy as np


def build_workload(args):
    rng = np.random.default_rng(args.seed)
    grid = np.arange(args.prompt_lo, args.prompt_hi + 1,
                     args.prefill_align)
    grid = grid[grid + args.new_hi <= args.max_len]
    if len(grid) == 0:
        raise SystemExit("no prompt length fits max_len with --new-hi")
    lengths = rng.choice(grid, size=args.requests)
    budgets = rng.integers(args.new_lo, args.new_hi + 1,
                           size=args.requests)
    if args.rate:
        arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                             size=args.requests))
    else:
        arrivals = np.zeros(args.requests)
    return [{"prompt": rng.integers(0, args.vocab,
                                    (int(t),)).astype(np.int32),
             "n_new": int(n), "arrival": float(a)}
            for t, n, a in zip(lengths, budgets, arrivals)]


def _percentiles(xs):
    return (round(float(np.percentile(xs, 50)), 4),
            round(float(np.percentile(xs, 95)), 4))


def run_baseline(spec, variables, work, args):
    """Run-to-completion bucketed streaming.  Completion times are the
    GENEROUS per-bucket-flush accounting (when the compiled flush
    returns), not in-order yield time."""
    from distkeras_tpu.streaming import StreamingGenerator

    sg = StreamingGenerator(spec, variables,
                            max_new_tokens=args.new_hi,
                            batch_size=args.baseline_batch)
    # warmup: compile every prompt-length bucket once (excluded)
    lengths = sorted({len(w["prompt"]) for w in work})
    warm = [{"prompt": next(w["prompt"] for w in work
                            if len(w["prompt"]) == t)}
            for t in lengths]
    list(sg(iter(warm)))

    t_flush: dict[int, float] = {}
    orig = sg._run_bucket

    def timed_bucket(items, n_flush):
        out = orig(items, n_flush)
        now = time.perf_counter() - t0
        for i, _ in items:
            t_flush[i] = now
        return out

    sg._run_bucket = timed_bucket
    t_consume: dict[int, float] = {}

    def paced_rows():
        for i, w in enumerate(work):
            wait = w["arrival"] - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(wait)
            t_consume[i] = time.perf_counter() - t0
            yield {"prompt": w["prompt"], "i": i}

    t0 = time.perf_counter()
    n_done = sum(1 for _ in sg(paced_rows()))
    wall = time.perf_counter() - t0
    assert n_done == len(work)
    lat_first, lat_tok = [], []
    for i, w in enumerate(work):
        # run-to-completion: the first token is only observable when
        # the whole flush returns
        done = t_flush[i] - w["arrival"]
        lat_first.append(done)
        # per-token latency divides by tokens actually committed: the
        # baseline generator runs every request to the uniform new_hi
        lat_tok.append(done / args.new_hi)
    return {"wall_s": wall, "lat_first": lat_first, "lat_tok": lat_tok,
            "raw_tokens": len(work) * args.new_hi}


def run_continuous(spec, variables, work, args, buckets):
    from distkeras_tpu.serving import DecodeEngine

    eng = DecodeEngine(spec, variables, slots=args.slots,
                       buckets=buckets,
                       prefill_align=args.prefill_align,
                       steps_per_sync=args.steps_per_sync)
    # warmup: compile every (bucket, padded length) prefill the
    # workload can touch + every bucket's step program (excluded from
    # the timed run).  A length that fits several envelopes is routed
    # to each in turn by choosing a budget that overflows the smaller
    # ones.
    lengths = sorted({len(w["prompt"]) for w in work})
    warm, prev = [], 0
    for pool in eng._pools:
        for t in lengths:
            n = max(2, prev - t + 1)  # >=2: the step program runs too
            if t + n <= pool.env and eng._route(t, n).env == pool.env:
                warm.append({"prompt": np.zeros((t,), np.int32),
                             "max_new_tokens": n})
        prev = pool.env
    list(eng.run(warm))

    results = []
    t0 = time.perf_counter()
    i = 0
    while i < len(work) or eng.has_work():
        now = time.perf_counter() - t0
        while i < len(work) and work[i]["arrival"] <= now:
            eng.submit(work[i]["prompt"],
                       max_new_tokens=work[i]["n_new"],
                       request_id=i)
            i += 1
        if not eng.has_work():
            if i < len(work):
                time.sleep(max(0.0, work[i]["arrival"] - now))
            continue
        results.extend(eng.step())
    wall = time.perf_counter() - t0
    assert len(results) == len(work)
    lat_first, lat_tok, toks = [], [], {}
    for r in results:
        w = work[r["request_id"]]
        lat_first.append((r["t_first"] - t0) - w["arrival"])
        lat_tok.append(((r["t_finish"] - t0) - w["arrival"])
                       / max(len(r["tokens"]), 1))
        toks[r["request_id"]] = r["tokens"]
    return {"wall_s": wall, "lat_first": lat_first, "lat_tok": lat_tok,
            "raw_tokens": sum(w["n_new"] for w in work),
            "tokens": toks, "compiles": dict(eng.compile_counts)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shapes + token-parity assertions "
                         "(the tier-1 registration)")
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--kv-dtype", default="int8", choices=["int8", "none"])
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--max-len", type=int, default=2048)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--requests", type=int, default=128)
    ap.add_argument("--prompt-lo", type=int, default=128)
    ap.add_argument("--prompt-hi", type=int, default=1024)
    ap.add_argument("--new-lo", type=int, default=16)
    ap.add_argument("--new-hi", type=int, default=256)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="Poisson arrival rate (req/s); 0 = full "
                         "backlog at t=0 (saturated throughput)")
    ap.add_argument("--slots", type=int, default=16,
                    help="continuous slots per bucket")
    ap.add_argument("--baseline-batch", type=int, default=16)
    ap.add_argument("--buckets", default="512,1024,2048",
                    help="envelope lengths for the bucketed arm")
    ap.add_argument("--prefill-align", type=int, default=128)
    ap.add_argument("--steps-per-sync", type=int, default=16,
                    help="decode steps per dispatch (raise through "
                         "high-RTT links; admission granularity)")
    ap.add_argument("--arms", default="baseline,single,bucketed")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="enable telemetry and dump a Perfetto-"
                         "loadable Chrome trace of the run here")
    ap.add_argument("--metrics", default=None, metavar="PATH",
                    help="enable telemetry and dump the metrics "
                         "registry as JSONL here")
    args = ap.parse_args()

    if args.smoke:
        # tiny CPU shapes; exercises Poisson pacing + all three arms
        args.layers, args.d_model, args.heads = 1, 32, 2
        args.kv_heads, args.kv_dtype, args.vocab = 1, "none", 61
        args.max_len, args.prompt_lo, args.prompt_hi = 32, 4, 12
        args.new_lo, args.new_hi, args.requests = 2, 6, 12
        args.slots, args.baseline_batch = 3, 3
        args.buckets, args.prefill_align = "16,32", 4
        args.steps_per_sync, args.rate = 2, 200.0

    from distkeras_tpu.models import model_config, ModelSpec
    import jax
    import jax.numpy as jnp

    # telemetry consumer: enabled BEFORE engine construction so the
    # trace-time compile counters see every program.  Smoke always
    # enables it — tier-1 then exercises the instrumented serving
    # paths end to end.
    tel = None
    if args.trace or args.metrics or args.smoke:
        from distkeras_tpu import telemetry

        tel = telemetry.enable()

    spec = model_config(
        "transformer_lm", (args.max_len,), input_dtype="int32",
        vocab_size=args.vocab, num_layers=args.layers,
        d_model=args.d_model, num_heads=args.heads,
        max_len=args.max_len, dtype=args.dtype,
        num_kv_heads=args.kv_heads or None,
        kv_cache_dtype=None if args.kv_dtype == "none" else args.kv_dtype)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, 8), jnp.int32))

    work = build_workload(args)
    goodput_tokens = sum(w["n_new"] for w in work)
    buckets = [int(b) for b in args.buckets.split(",")]
    arms = args.arms.split(",")
    out = {"metric": "lm_serving_mixed_traffic",
           "model": f"lm L{args.layers} d{args.d_model} "
                    f"kvh{args.kv_heads} {args.kv_dtype}",
           "requests": args.requests,
           "prompt": [args.prompt_lo, args.prompt_hi],
           "new": [args.new_lo, args.new_hi],
           "rate": args.rate, "slots": args.slots,
           "steps_per_sync": args.steps_per_sync,
           "goodput_tokens": int(goodput_tokens), "arms": {}}
    runs = {}
    for arm in arms:
        if arm == "baseline":
            runs[arm] = run_baseline(spec, variables, work, args)
        elif arm == "single":
            runs[arm] = run_continuous(spec, variables, work, args,
                                       [args.max_len])
        elif arm == "bucketed":
            runs[arm] = run_continuous(spec, variables, work, args,
                                       buckets)
        else:
            raise SystemExit(f"unknown arm {arm!r}")
        r = runs[arm]
        p50f, p95f = _percentiles(r["lat_first"])
        p50t, p95t = _percentiles(r["lat_tok"])
        out["arms"][arm] = {
            "wall_s": round(r["wall_s"], 3),
            "goodput_tok_s": round(goodput_tokens / r["wall_s"], 1),
            "raw_tok_s": round(r["raw_tokens"] / r["wall_s"], 1),
            "queue_to_first_p50_s": p50f,
            "queue_to_first_p95_s": p95f,
            "per_token_p50_s": p50t, "per_token_p95_s": p95t,
        }
        if "compiles" in r:
            out["arms"][arm]["n_programs"] = len(r["compiles"])

    if "baseline" in runs:
        base = out["arms"]["baseline"]["goodput_tok_s"]
        for arm in ("single", "bucketed"):
            if arm in runs:
                out["arms"][arm]["speedup_vs_baseline"] = round(
                    out["arms"][arm]["goodput_tok_s"] / base, 3)

    if tel is not None:
        # registry-side view of the same run: TTFT percentiles from
        # the histogram (bucket resolution), total generated tokens,
        # the bounded compiled-program set
        ttft = tel.metrics.histogram("serving_ttft_seconds")
        snap = tel.metrics.snapshot()
        out["telemetry"] = {
            "ttft_p50_s": ttft.percentile(0.5),
            "ttft_p95_s": ttft.percentile(0.95),
            "requests_finished": ttft.count,
            "tokens_total": tel.metrics.sum_counter(
                "serving_tokens_total"),
            "compiled_programs": sum(
                1 for k in snap["counters"]
                if k.startswith("compiles_total")),
        }
        if args.metrics:
            tel.metrics.write_jsonl(args.metrics)
        if args.trace:
            tel.tracer.write_chrome_trace(args.trace)

    if args.smoke:
        # greedy parity: each continuous arm's tokens are the
        # baseline generation truncated to the request's budget
        from distkeras_tpu.models import generate

        for i, w in enumerate(work):
            want = np.asarray(generate(
                model, variables, w["prompt"][None, :],
                max_new_tokens=w["n_new"]))[0, len(w["prompt"]):]
            for arm in ("single", "bucketed"):
                if arm in runs:
                    got = runs[arm]["tokens"][i]
                    assert np.array_equal(got, want), (arm, i, got,
                                                       want)
        # the registry saw the run: finished requests + live gauges
        assert out["telemetry"]["requests_finished"] > 0
        assert out["telemetry"]["tokens_total"] > 0
        assert any(k.startswith("serving_slot_occupancy")
                   for k in tel.metrics.snapshot()["gauges"])
        out["smoke_parity"] = "ok"
    print(json.dumps(out))


if __name__ == "__main__":
    main()

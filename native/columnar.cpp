// Native columnar ETL kernels (C ABI, loaded via ctypes —
// distkeras_tpu/native.py builds this with g++ on first use).
//
// The reference is pure Python and delegates its host-side data work to
// Spark executors (SURVEY.md §2.2 "no native components"); the rebuild's
// hot host-side ETL loops — categorical hashing, affine feature scaling,
// sparse->dense scatter — run here instead of through numpy's
// per-column-fold / fancy-indexing paths.  Kernels are deliberately
// dependency-free scalar loops: -O3 autovectorizes the inner loops, and
// semantics exactly match the numpy reference implementations in
// data/transformers.py (tests assert parity).

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// FNV-1a (64-bit) over each row's bytes, reduced mod num_buckets.
// data: [n, width] row-major fixed-width byte matrix (numpy 'S' dtype
// buffer); lengths[i] gives row i's real byte count.
void fnv1a_bucket(const uint8_t* data, int64_t n, int64_t width,
                  const int64_t* lengths, uint64_t num_buckets,
                  int32_t* out) {
  const uint64_t kOffset = 0xcbf29ce484222325ULL;
  const uint64_t kPrime = 0x100000001b3ULL;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* row = data + i * width;
    const int64_t len = lengths[i];
    uint64_t h = kOffset;
    for (int64_t j = 0; j < len; ++j) {
      h = (h ^ static_cast<uint64_t>(row[j])) * kPrime;
    }
    out[i] = static_cast<int32_t>(h % num_buckets);
  }
}

// Column-wise affine map: out[i,c] = f32(in[i,c] * scale[c] + shift[c]).
// Covers MinMax (scale = range_ratio/span, shift = new_min - min*scale)
// and StandardScale (scale = 1/(std+eps), shift = -mean*scale); the
// f64 accumulate matches the numpy paths' broadcast-to-f64 behavior.
void affine_scale(const float* in, int64_t rows, int64_t cols,
                  const double* scale, const double* shift, float* out) {
  for (int64_t i = 0; i < rows; ++i) {
    const float* src = in + i * cols;
    float* dst = out + i * cols;
    for (int64_t c = 0; c < cols; ++c) {
      dst[c] = static_cast<float>(
          static_cast<double>(src[c]) * scale[c] + shift[c]);
    }
  }
}

// Sparse (indices, values) padded pairs -> dense rows.
// idx: [rows, nnz] (pad entries < 0 ignored), out: [rows, dim] zeroed
// by the caller.
void dense_scatter(const int64_t* idx, const float* val, int64_t rows,
                   int64_t nnz, int64_t dim, float* out) {
  for (int64_t i = 0; i < rows; ++i) {
    float* dst = out + i * dim;
    for (int64_t j = 0; j < nnz; ++j) {
      const int64_t k = idx[i * nnz + j];
      if (k >= 0 && k < dim) {
        dst[k] = val[i * nnz + j];
      }
    }
  }
}

// ---- CSV fast lane (GIL-free parse for the out-of-core text path) ----
//
// The Python reference (`Dataset.from_csv`) is csv.reader + per-cell
// int()/float() — GIL-bound, so the segment-prefetch thread cannot
// overlap it with training dispatch.  These kernels tokenize and
// type-convert inside ctypes calls (GIL released), with semantics
// matched to the Python path (tests assert column-for-column parity).

// Tokenize a plain (unquoted) delimited buffer into per-cell
// (offset, length) pairs.  Scans data[skip..nbytes): one row per
// '\n'-terminated line (final unterminated line included), a trailing
// '\r' stripped, EMPTY lines skipped (csv.reader yields [] for them —
// the Python path drops falsy rows).  Every kept row must have exactly
// `cols` fields; on a mismatch returns -(1-based line number counted
// from `skip`).  Returns the number of data rows filled; `off`/`lens`
// are caller-allocated for the line-count upper bound.
int64_t csv_index(const char* data, int64_t nbytes, int64_t skip,
                  char delim, int64_t cols, int64_t* off,
                  int32_t* lens) {
  int64_t row = 0;
  int64_t line_no = 0;
  int64_t i = skip;
  while (i < nbytes) {
    int64_t j = i;
    while (j < nbytes && data[j] != '\n') ++j;
    int64_t end = j;
    if (end > i && data[end - 1] == '\r') --end;
    ++line_no;
    if (end > i) {
      int64_t c = 0;
      int64_t f = i;
      for (int64_t k = i; k <= end; ++k) {
        if (k == end || data[k] == delim) {
          if (c < cols) {
            off[row * cols + c] = f;
            lens[row * cols + c] = static_cast<int32_t>(k - f);
          }
          ++c;
          f = k + 1;
        }
      }
      if (c != cols) return -line_no;
      ++row;
    }
    i = j + 1;
  }
  return row;
}

// Numeric conversion for one column of a csv_index'd buffer.
// Fills iout AND fout; returns 0 when every cell parses as an int64,
// 1 when every cell parses as a double (fout valid), or -(row+1) at
// the first cell that is neither — the caller then takes the string
// path.  Matches Python int()/float() semantics for plain decimal
// spellings; hex ('0x..') and digit-underscore spellings are treated
// as strings (the Python path is strictened to agree — see
// Dataset.from_csv).
int64_t csv_parse_numeric(const char* data, const int64_t* off,
                          const int32_t* lens, int64_t rows,
                          int64_t cols, int64_t c, int64_t* iout,
                          double* fout) {
  char stack_buf[128];
  char* heap_buf = nullptr;
  int64_t heap_cap = 0;
  int all_int = 1;
  for (int64_t r = 0; r < rows; ++r) {
    const char* s = data + off[r * cols + c];
    int64_t len = lens[r * cols + c];
    while (len > 0 && (*s == ' ' || *s == '\t')) { ++s; --len; }
    while (len > 0 && (s[len - 1] == ' ' || s[len - 1] == '\t')) --len;
    if (len == 0) {
      free(heap_buf);
      return -(r + 1);
    }
    for (int64_t k = 0; k < len; ++k) {
      if (s[k] == 'x' || s[k] == 'X' || s[k] == '_') {
        free(heap_buf);
        return -(r + 1);
      }
    }
    char* buf = stack_buf;
    if (len >= static_cast<int64_t>(sizeof(stack_buf))) {
      if (len + 1 > heap_cap) {
        free(heap_buf);
        heap_cap = 2 * (len + 1);
        heap_buf = static_cast<char*>(malloc(heap_cap));
        if (heap_buf == nullptr) return -(r + 1);
      }
      buf = heap_buf;
    }
    memcpy(buf, s, len);
    buf[len] = '\0';
    char* endp = nullptr;
    if (all_int) {
      errno = 0;
      long long v = strtoll(buf, &endp, 10);
      if (endp == buf + len && errno == 0) {
        iout[r] = static_cast<int64_t>(v);
        fout[r] = static_cast<double>(v);
        continue;
      }
      // not an int (or overflowed past int64): re-parse everything
      // seen so far as doubles and continue on the float path —
      // matching the Python column-level int->float fallback
      all_int = 0;
      for (int64_t rr = 0; rr < r; ++rr) {
        fout[rr] = static_cast<double>(iout[rr]);
      }
    }
    errno = 0;
    double d = strtod(buf, &endp);
    if (endp != buf + len) {
      free(heap_buf);
      return -(r + 1);
    }
    (void)d;
    fout[r] = d;
  }
  free(heap_buf);
  return all_int ? 0 : 1;
}

// Copy one column's cells into a fixed-width, zero-padded byte matrix
// (numpy 'S' layout) for the string-column path.
void csv_fill_bytes(const char* data, const int64_t* off,
                    const int32_t* lens, int64_t rows, int64_t cols,
                    int64_t c, int64_t width, uint8_t* out) {
  for (int64_t r = 0; r < rows; ++r) {
    const char* s = data + off[r * cols + c];
    int64_t len = lens[r * cols + c];
    if (len > width) len = width;
    uint8_t* dst = out + r * width;
    memcpy(dst, s, len);
    if (len < width) memset(dst + len, 0, width - len);
  }
}

}  // extern "C"

// Native columnar ETL kernels (C ABI, loaded via ctypes —
// distkeras_tpu/native.py builds this with g++ on first use).
//
// The reference is pure Python and delegates its host-side data work to
// Spark executors (SURVEY.md §2.2 "no native components"); the rebuild's
// hot host-side ETL loops — categorical hashing, affine feature scaling,
// sparse->dense scatter — run here instead of through numpy's
// per-column-fold / fancy-indexing paths.  Kernels are deliberately
// dependency-free scalar loops: -O3 autovectorizes the inner loops, and
// semantics exactly match the numpy reference implementations in
// data/transformers.py (tests assert parity).

#include <cstdint>

extern "C" {

// FNV-1a (64-bit) over each row's bytes, reduced mod num_buckets.
// data: [n, width] row-major fixed-width byte matrix (numpy 'S' dtype
// buffer); lengths[i] gives row i's real byte count.
void fnv1a_bucket(const uint8_t* data, int64_t n, int64_t width,
                  const int64_t* lengths, uint64_t num_buckets,
                  int32_t* out) {
  const uint64_t kOffset = 0xcbf29ce484222325ULL;
  const uint64_t kPrime = 0x100000001b3ULL;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* row = data + i * width;
    const int64_t len = lengths[i];
    uint64_t h = kOffset;
    for (int64_t j = 0; j < len; ++j) {
      h = (h ^ static_cast<uint64_t>(row[j])) * kPrime;
    }
    out[i] = static_cast<int32_t>(h % num_buckets);
  }
}

// Column-wise affine map: out[i,c] = f32(in[i,c] * scale[c] + shift[c]).
// Covers MinMax (scale = range_ratio/span, shift = new_min - min*scale)
// and StandardScale (scale = 1/(std+eps), shift = -mean*scale); the
// f64 accumulate matches the numpy paths' broadcast-to-f64 behavior.
void affine_scale(const float* in, int64_t rows, int64_t cols,
                  const double* scale, const double* shift, float* out) {
  for (int64_t i = 0; i < rows; ++i) {
    const float* src = in + i * cols;
    float* dst = out + i * cols;
    for (int64_t c = 0; c < cols; ++c) {
      dst[c] = static_cast<float>(
          static_cast<double>(src[c]) * scale[c] + shift[c]);
    }
  }
}

// Sparse (indices, values) padded pairs -> dense rows.
// idx: [rows, nnz] (pad entries < 0 ignored), out: [rows, dim] zeroed
// by the caller.
void dense_scatter(const int64_t* idx, const float* val, int64_t rows,
                   int64_t nnz, int64_t dim, float* out) {
  for (int64_t i = 0; i < rows; ++i) {
    float* dst = out + i * dim;
    for (int64_t j = 0; j < nnz; ++j) {
      const int64_t k = idx[i * nnz + j];
      if (k >= 0 && k < dim) {
        dst[k] = val[i * nnz + j];
      }
    }
  }
}

}  // extern "C"

"""Pipeline parallelism: GPipe tick-loop parity against sequential
stage application (forward + gradients), microbatch-count invariance,
and a dp x pp training step (SURVEY.md §2.3: PP absent in reference —
beyond-reference capability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distkeras_tpu.parallel.pipeline import pipeline_apply
from distkeras_tpu.utils import shard_map

D = 16  # homogeneous stage width


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(scale=0.5, size=(n_stages, D, D)),
                         jnp.float32),
        "b": jnp.asarray(rng.normal(size=(n_stages, D)), jnp.float32),
    }


def _sequential(params, x):
    for s in range(params["w"].shape[0]):
        x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


def _pipelined(mesh, n_micro):
    def fn(params, x):
        return pipeline_apply(_stage_fn, params, x, axis_name="stage",
                              num_microbatches=n_micro)

    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(P("stage"), P()), out_specs=P()))


@pytest.mark.parametrize("n_micro", [1, 4, 8])
def test_pipeline_matches_sequential_forward(devices, n_micro):
    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("stage",))
    params = _stacked_params(n_stages)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, D)),
                    jnp.float32)
    got = _pipelined(mesh, n_micro)(params, x)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x)),
                               rtol=2e-6, atol=2e-6)


def test_pipeline_gradients_match_sequential(devices):
    n_stages, n_micro = 4, 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("stage",))
    params = _stacked_params(n_stages, seed=2)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, D)),
                    jnp.float32)
    tgt = jnp.asarray(np.random.default_rng(4).normal(size=(8, D)),
                      jnp.float32)

    pipe = _pipelined(mesh, n_micro)
    g_pipe = jax.grad(lambda p: jnp.mean((pipe(p, x) - tgt) ** 2))(
        params)
    g_seq = jax.grad(
        lambda p: jnp.mean((_sequential(p, x) - tgt) ** 2))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=2e-5, atol=2e-6)


def test_dp_pp_training_step_converges(devices):
    """(2 workers, 4 stages) mesh: batch sharded over workers, stages
    pipelined — a joint dp x pp training step optimizes."""
    import optax
    from jax import lax

    n_stages = 4
    grid = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(grid, ("workers", "stage"))
    params = _stacked_params(n_stages, seed=5)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)
    tgt = jnp.tanh(x @ jnp.ones((D, D)) * 0.1)  # learnable target

    def loss_fn(params, x, tgt):
        out = pipeline_apply(_stage_fn, params, x, axis_name="stage",
                             num_microbatches=4)
        return lax.pmean(jnp.mean((out - tgt) ** 2), "workers")

    sharded_loss = shard_map(
        loss_fn, mesh=mesh,
        in_specs=(P("stage"), P("workers"), P("workers")),
        out_specs=P())

    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, tgt):
        loss, g = jax.value_and_grad(sharded_loss)(params, x, tgt)
        upd, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, upd), opt_state, loss

    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, x, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_indivisible_microbatches_raise(devices):
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("stage",))
    params = _stacked_params(4)
    x = jnp.zeros((6, D), jnp.float32)
    with pytest.raises(ValueError, match="microbatch"):
        _pipelined(mesh, 4)(params, x)

"""Autoregressive KV-cache generation (``models.generate``) — the
decode path must be EXACTLY the training-mode function: the prompt pass
must reproduce full-forward logits, and cached greedy decoding must
equal the naive generate-by-reforwarding loop token for token."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import ModelSpec, generate, model_config

jax.config.update("jax_platforms", "cpu")


def _model(max_len=32, vocab=37, **kw):
    spec = model_config("transformer_lm", (max_len,),
                        input_dtype="int32", vocab_size=vocab,
                        num_layers=2, d_model=32, num_heads=2,
                        max_len=max_len, dtype="float32", **kw)
    model = ModelSpec.from_config(spec).build()
    tokens = jnp.zeros((2, max_len), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    return spec, model, variables


def test_prompt_pass_matches_full_forward():
    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(1), (2, 9), 0, 37)
    want = model.apply(variables, prompt)
    dec = model.clone(decode=True)
    got, _ = dec.apply({"params": variables["params"]}, prompt,
                       mutable=["cache"])
    # decode mode returns the LAST position's logits only ([B, 1, V])
    assert got.shape == (2, 1, 37)
    np.testing.assert_allclose(np.asarray(got[:, 0]),
                               np.asarray(want[:, -1]),
                               rtol=2e-5, atol=2e-5)


def test_greedy_matches_naive_reforward_loop():
    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(2), (2, 5), 0, 37)
    n_new = 7
    got = generate(model, variables, prompt, max_new_tokens=n_new)

    seq = prompt
    for _ in range(n_new):
        logits = model.apply(variables, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(jnp.int32)],
                              axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))


def test_single_token_and_jit():
    spec, model, variables = _model()
    prompt = jnp.ones((1, 3), jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=1)
    assert out.shape == (1, 4)
    jit_gen = jax.jit(lambda v, p: generate(
        model, v, p, max_new_tokens=4))
    out_j = jit_gen(variables, prompt)
    out_e = generate(model, variables, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(out_j), np.asarray(out_e))


def test_sampling_reproducible_and_in_vocab():
    spec, model, variables = _model()
    prompt = jnp.zeros((3, 2), jnp.int32)
    kw = dict(max_new_tokens=6, temperature=0.8, top_k=5,
              rng=jax.random.key(3))
    a = generate(model, variables, prompt, **kw)
    b = generate(model, variables, prompt, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (3, 8)
    assert (np.asarray(a) >= 0).all() and (np.asarray(a) < 37).all()
    # a different key must be able to produce a different draw
    c = generate(model, variables, prompt,
                 **{**kw, "rng": jax.random.key(99)})
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_config_dict_input_and_spec_input():
    spec, model, variables = _model()
    prompt = jnp.zeros((1, 2), jnp.int32)
    a = generate(model, variables, prompt, max_new_tokens=2)
    b = generate(spec, variables, prompt, max_new_tokens=2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_capacity_and_arg_validation():
    spec, model, variables = _model(max_len=16)
    prompt = jnp.zeros((1, 10), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generate(model, variables, prompt, max_new_tokens=7)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, variables, prompt, max_new_tokens=0)
    with pytest.raises(ValueError, match="MoE"):
        generate(model.clone(num_experts=4), variables, prompt,
                 max_new_tokens=1)
    with pytest.raises(ValueError, match="rng"):
        generate(model, variables, prompt, max_new_tokens=2,
                 temperature=0.5)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, variables, prompt, max_new_tokens=2,
                 temperature=0.5, top_k=1000, rng=jax.random.key(0))
    with pytest.raises(TypeError, match="TransformerLM"):
        from distkeras_tpu.models import MLP

        generate(MLP(hidden=(4,), num_classes=2), variables, prompt,
                 max_new_tokens=1)


def test_attention_spellings_share_the_decode_path():
    """flash/blockwise are execution spellings of the same params —
    generate() serves them identically to the dense-trained model."""
    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(5), (2, 4), 0, 37)
    want = generate(model, variables, prompt, max_new_tokens=3)
    for spelling in ({"flash_attn": True}, {"blockwise_attn": True}):
        got = generate(model.clone(**spelling), variables, prompt,
                       max_new_tokens=3)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))


def test_scan_blocks_rejected_with_pointer():
    spec, model, variables = _model()
    with pytest.raises(ValueError, match="scan_blocks"):
        generate(model.clone(scan_blocks=True), variables,
                 jnp.zeros((1, 2), jnp.int32), max_new_tokens=1)


@pytest.mark.parametrize("kv_heads", [None, 2],
                         ids=["mha", "gqa"])
def test_tensor_parallel_decode_matches_single_device(kv_heads):
    """TP serving needs no dedicated decode API: shard the params with
    the trainer-side TP rules and jit generate — GSPMD propagates the
    head shardings into the per-layer KV caches and the scan.  GQA
    composes: the num_kv_heads axis shards like the full head axis,
    into the smaller caches."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    from distkeras_tpu import mesh as mesh_lib
    from distkeras_tpu.parallel import tensor_parallel as tp

    # TP-friendly dims: heads and vocab must divide model_parallel=2
    spec, model, variables = _model(vocab=36, num_kv_heads=kv_heads)
    prompt = jax.random.randint(jax.random.key(4), (2, 6), 0, 36)
    want = np.asarray(generate(model, variables, prompt,
                               max_new_tokens=5))
    mesh = mesh_lib.create_mesh(1, model_parallel=2)
    shardings = tp.tree_shardings(mesh, variables,
                                  tp.rules_for("transformer_lm"))
    v_tp = jax.device_put(variables, shardings)
    got = np.asarray(jax.jit(lambda v, p: generate(
        model, v, p, max_new_tokens=5))(v_tp, prompt))
    np.testing.assert_array_equal(got, want)


def test_cache_overflow_poisons_with_nan():
    """Direct decode use past max_len cannot raise (the index is
    traced) — it must fail LOUD via NaN, never silently clamp."""
    spec, model, variables = _model(max_len=8)
    dec = model.clone(decode=True)
    params = {"params": variables["params"]}
    prompt = jnp.zeros((1, 6), jnp.int32)
    logits, state = dec.apply(params, prompt, mutable=["cache"])
    assert np.isfinite(np.asarray(logits)).all()
    tok = jnp.zeros((1, 1), jnp.int32)
    for step in range(3):  # indices 6, 7 ok; 8 overflows
        logits, state = dec.apply({**params, "cache": state["cache"]},
                                  tok, mutable=["cache"])
        finite = np.isfinite(np.asarray(logits)).all()
        assert finite == (step < 2), (step, finite)


def test_eos_stops_row_and_pads_rest():
    """eos_id: the stop token appears, everything after is pad_id, and
    rows stop independently; shapes stay static."""
    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(6), (2, 4), 0, 37)
    base = np.asarray(generate(model, variables, prompt,
                               max_new_tokens=8))
    gen = base[:, 4:]
    # pick an eos row 0 emits but row 1 never does, so the rows stop
    # independently
    candidates = [int(t) for t in gen[0] if t not in gen[1]]
    assert candidates, "degenerate sample; adjust seed"
    eos, pad = candidates[0], 36  # pad within vocab (checked)
    out = np.asarray(generate(model, variables, prompt,
                              max_new_tokens=8, eos_id=eos,
                              pad_id=pad))
    got = out[:, 4:]
    # row 0: prefix matches greedy up to and incl. eos, then pad
    stop = int(np.argwhere(gen[0] == eos)[0][0])
    np.testing.assert_array_equal(got[0, :stop + 1],
                                  gen[0, :stop + 1])
    assert (got[0, stop + 1:] == pad).all()
    # row 1 never emits eos and is untouched
    np.testing.assert_array_equal(got[1], gen[1])
    assert out.shape == base.shape  # static shapes

    with pytest.raises(ValueError, match="eos_id"):
        generate(model, variables, prompt, max_new_tokens=2,
                 eos_id=99)
    with pytest.raises(ValueError, match="pad_id"):
        generate(model, variables, prompt, max_new_tokens=2,
                 eos_id=eos, pad_id=99)


def test_beam_one_equals_greedy():
    from distkeras_tpu.models.generate import beam_search

    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(8), (2, 4), 0, 37)
    greedy = np.asarray(generate(model, variables, prompt,
                                 max_new_tokens=6))
    seq, scores = beam_search(model, variables, prompt,
                              max_new_tokens=6, num_beams=1)
    np.testing.assert_array_equal(np.asarray(seq), greedy)
    assert scores.shape == (2,) and np.isfinite(np.asarray(scores)).all()


def test_beam_score_at_least_greedy():
    """The width-4 beam's sequence log-prob must be >= greedy's (it
    explores a superset of greedy's path), and its reported score must
    equal the teacher-forced log-prob of its own sequence."""
    from distkeras_tpu.models.generate import beam_search

    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(9), (2, 4), 0, 37)
    n_new = 6

    def seq_logprob(seq):
        logits = model.apply(variables, seq).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        t0 = prompt.shape[1]
        tot = 0.0
        for t in range(t0, seq.shape[1]):
            tot = tot + logp[jnp.arange(seq.shape[0]), t - 1,
                             seq[:, t]]
        return np.asarray(tot)

    greedy = jnp.asarray(generate(model, variables, prompt,
                                  max_new_tokens=n_new))
    beam, scores = beam_search(model, variables, prompt,
                               max_new_tokens=n_new, num_beams=4)
    lp_greedy = seq_logprob(greedy)
    lp_beam = seq_logprob(jnp.asarray(beam))
    assert (lp_beam >= lp_greedy - 1e-4).all(), (lp_beam, lp_greedy)
    np.testing.assert_allclose(np.asarray(scores), lp_beam, rtol=1e-4,
                               atol=1e-4)


def test_beam_eos_and_jit():
    from distkeras_tpu.models.generate import beam_search

    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(10), (1, 3), 0, 37)
    # eos = greedy's FIRST token (the highest first-step logprob): a
    # beam finishing there freezes at the max single-step score, which
    # strictly dominates any longer continuation at length_penalty=0 —
    # so the winner MUST be the eos-terminated beam (no vacuous pass)
    eos = int(np.asarray(generate(model, variables, prompt,
                                  max_new_tokens=1))[0, 3])
    seq, scores = beam_search(model, variables, prompt,
                              max_new_tokens=6, num_beams=3,
                              eos_id=eos, pad_id=36)
    s = np.asarray(seq)[0, 3:]
    assert s[0] == eos, s
    assert (s[1:] == 36).all(), s
    # jit wrapper produces identical output
    jseq, jscores = jax.jit(lambda v, p: beam_search(
        model, v, p, max_new_tokens=6, num_beams=3, eos_id=eos,
        pad_id=36))(variables, prompt)
    np.testing.assert_array_equal(np.asarray(jseq), np.asarray(seq))
    np.testing.assert_allclose(np.asarray(jscores),
                               np.asarray(scores), rtol=1e-6)
    with pytest.raises(ValueError, match="length_penalty"):
        beam_search(model, variables, prompt, max_new_tokens=2,
                    num_beams=2, length_penalty=-1.0)


def test_top_p_nucleus_sampling():
    """top_p restricts draws to the smallest prefix of the sorted
    distribution reaching that mass; a tiny top_p reduces to greedy."""
    spec, model, variables = _model()
    prompt = jnp.zeros((4, 3), jnp.int32)
    # top_p -> 0+ keeps only the argmax token: equals greedy for any rng
    greedy = generate(model, variables, prompt, max_new_tokens=5)
    tiny = generate(model, variables, prompt, max_new_tokens=5,
                    temperature=1.0, top_p=1e-6,
                    rng=jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(tiny), np.asarray(greedy))
    # top_p=1.0 is unrestricted sampling: reproducible, in-vocab
    kw = dict(max_new_tokens=5, temperature=1.0, top_p=1.0,
              rng=jax.random.key(2))
    a = generate(model, variables, prompt, **kw)
    b = generate(model, variables, prompt, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) < 37).all()
    # composes with top_k; invalid values rejected
    c = generate(model, variables, prompt, max_new_tokens=3,
                 temperature=0.9, top_k=10, top_p=0.9,
                 rng=jax.random.key(3))
    assert c.shape == (4, 6)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, variables, prompt, max_new_tokens=2,
                 temperature=1.0, top_p=1.5, rng=jax.random.key(0))


def test_flash_and_blockwise_prefill_match_full_forward():
    """VERDICT r4 #3: decode's prompt pass runs through the resolved
    attention kernel (flash/blockwise) instead of a dense read of the
    whole cache — and must still reproduce full-forward logits.
    Prefill kernels engage at 128-aligned prompt lengths."""
    spec, model, variables = _model(max_len=192)
    prompt = jax.random.randint(jax.random.key(11), (2, 128), 0, 37)
    want = model.apply(variables, prompt)
    for spelling in ({"flash_attn": True}, {"blockwise_attn": True},
                     {"attn": "blockwise"}):
        dec = model.clone(decode=True, **spelling)
        got, state = dec.apply({"params": variables["params"]},
                               prompt, mutable=["cache"])
        np.testing.assert_allclose(np.asarray(got[:, 0]),
                                   np.asarray(want[:, -1]),
                                   rtol=2e-5, atol=2e-5)
        # the cache is filled exactly as the dense prefill fills it,
        # so subsequent T=1 steps continue correctly
        tok = jnp.argmax(got[:, -1].astype(jnp.float32),
                         axis=-1)[:, None].astype(jnp.int32)
        nxt, _ = dec.apply(
            {"params": variables["params"], "cache": state["cache"]},
            tok, mutable=["cache"])
        full = jnp.concatenate([prompt, tok], axis=1)
        want2 = model.apply(variables, full)
        np.testing.assert_allclose(np.asarray(nxt[:, 0]),
                                   np.asarray(want2[:, -1]),
                                   rtol=2e-5, atol=2e-5)


def test_kernel_prefill_mid_stream_chunk_poisons_with_nan():
    """A multi-token chunk at cache position > 0 would need
    cross-chunk attention the prefill kernel does not compute — it
    must fail LOUD (NaN), never silently drop the prefix."""
    spec, model, variables = _model(max_len=384)
    dec = model.clone(decode=True, flash_attn=True)
    params = {"params": variables["params"]}
    logits, state = dec.apply(params, jnp.zeros((1, 128), jnp.int32),
                              mutable=["cache"])
    assert np.isfinite(np.asarray(logits)).all()
    logits, _ = dec.apply({**params, "cache": state["cache"]},
                          jnp.zeros((1, 128), jnp.int32),
                          mutable=["cache"])
    assert not np.isfinite(np.asarray(logits)).any()


def test_unaligned_prompts_serve_via_dense_fallback():
    """Serving prompts have arbitrary lengths; the blocked prefill
    kernels only take 128-aligned chunks, so every other length must
    fall back to the dense cache read — generate() must NEVER raise
    over a prompt length (regression: round-5 review finding)."""
    spec, model, variables = _model(max_len=256)
    for spelling in ({"flash_attn": True}, {"blockwise_attn": True}):
        m = model.clone(**spelling)
        for t in (1, 7, 130, 200):
            prompt = jax.random.randint(jax.random.key(t), (1, t),
                                        0, 37)
            want = generate(model, variables, prompt,
                            max_new_tokens=3)
            got = generate(m, variables, prompt, max_new_tokens=3)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))


def test_gqa_generate_matches_naive_reforward_loop():
    """num_kv_heads (GQA): the grouped decode path must agree token
    for token with the training-mode forward of the SAME params."""
    spec, model, variables = _model(num_kv_heads=1)
    kernel = variables["params"]["Block_0"]["SelfAttention_0"]["key"][
        "kernel"]
    assert kernel.shape == (32, 1, 16)  # K/V project to 1 head
    prompt = jax.random.randint(jax.random.key(12), (2, 5), 0, 37)
    got = generate(model, variables, prompt, max_new_tokens=6)
    seq = prompt
    for _ in range(6):
        logits = model.apply(variables, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(jnp.int32)],
                              axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))


def test_gqa_validates_head_divisibility():
    spec, model, variables = _model()
    bad = model.clone(num_kv_heads=3)  # 2 heads % 3 != 0
    with pytest.raises(ValueError, match="num_kv_heads"):
        bad.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))


def test_int8_kv_cache_close_to_full_precision():
    """kv_cache_dtype="int8": the cache stores int8 + f32 scales; the
    prompt-pass logits stay within the quantization error bound of
    the full-precision decode, and (for this well-conditioned tiny
    model) greedy tokens are unchanged."""
    spec, model, variables = _model()
    prompt = jax.random.randint(jax.random.key(13), (2, 9), 0, 37)
    want = model.apply(variables, prompt)
    dec = model.clone(decode=True, kv_cache_dtype="int8")
    got, state = dec.apply({"params": variables["params"]}, prompt,
                           mutable=["cache"])
    cache = state["cache"]["Block_0"]["SelfAttention_0"]
    assert cache["cached_key"].dtype == jnp.int8
    assert cache["key_scale"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got[:, 0]),
                               np.asarray(want[:, -1]),
                               rtol=0.05, atol=0.05)
    base = generate(model, variables, prompt, max_new_tokens=5)
    quant = generate(model.clone(kv_cache_dtype="int8"), variables,
                     prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(quant))
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        model.clone(kv_cache_dtype="fp4").init(
            jax.random.key(0), jnp.zeros((1, 4), jnp.int32))


def test_gqa_int8_compose_in_generate():
    """GQA × int8 cache: both levers together still greedy-decode the
    same tokens as the full-precision model on this tiny LM."""
    spec, model, variables = _model(num_kv_heads=1)
    prompt = jax.random.randint(jax.random.key(14), (2, 6), 0, 37)
    base = generate(model, variables, prompt, max_new_tokens=5)
    both = generate(model.clone(kv_cache_dtype="int8"), variables,
                    prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(both))

"""Out-of-core training data (data/sharded.py): metadata-only header
reads, epoch streaming, and the equivalence contracts with the
in-memory path (VERDICT.md round-2 Missing #2)."""

import numpy as np
import pytest

from distkeras_tpu.data import Dataset, ShardedDataset, datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import ADAG, SingleTrainer, SyncTrainer


def _make(tmp_path, rows=512, shards=4, feat=(6,), classes=4, seed=0):
    full = datasets.synthetic_classification(rows, feat, classes,
                                             seed=seed)
    paths = full.to_npz_shards(str(tmp_path / "part"),
                               rows_per_shard=rows // shards)
    return full, paths


def test_metadata_without_loading(tmp_path):
    full, paths = _make(tmp_path)
    sd = Dataset.from_npz_shards(str(tmp_path / "part-*.npz"))
    assert isinstance(sd, ShardedDataset)
    assert len(sd) == len(full)
    assert sd.num_shards == 4
    assert sd.column_names == sorted(full.column_names)
    assert sd.shard_rows == [128, 128, 128, 128]
    # materialized content round-trips
    np.testing.assert_array_equal(sd.to_dataset()["label"],
                                  full["label"])


def test_epoch_segments_cover_every_row_once(tmp_path):
    full, paths = _make(tmp_path)
    sd = ShardedDataset(paths)
    seen = []
    for seg in sd.epoch_segments(seed=3):
        assert len(seg) == 128  # one shard at a time
        seen.append(np.asarray(seg["features"]))
    got = np.sort(np.concatenate(seen), axis=0)
    want = np.sort(np.asarray(full["features"]), axis=0)
    np.testing.assert_array_equal(got, want)
    # deterministic in seed; different across seeds
    a = [np.asarray(s["label"]) for s in sd.epoch_segments(seed=3)]
    b = [np.asarray(s["label"]) for s in sd.epoch_segments(seed=3)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = np.concatenate(
        [np.asarray(s["label"]) for s in sd.epoch_segments(seed=4)])
    assert not np.array_equal(np.concatenate(a), c)


def test_single_shard_training_is_bit_identical(tmp_path):
    """One shard file == the in-memory epoch (same shuffle permutation),
    so training is bit-identical — the equivalence contract."""
    full, _ = _make(tmp_path, rows=256, shards=1)
    path = full.to_npz(str(tmp_path / "whole.npz"))
    sd = ShardedDataset([path])
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(8,))
    kw = dict(worker_optimizer="sgd", learning_rate=0.05,
              batch_size=32, num_epoch=2, seed=0)

    t_mem = SingleTrainer(cfg, **kw)
    t_mem.train(full)
    t_ooc = SingleTrainer(cfg, **kw)
    t_ooc.train(sd)
    for a, b in zip(
            np.asarray(t_mem.history["epoch_loss"]),
            np.asarray(t_ooc.history["epoch_loss"])):
        assert a == b, (a, b)
    import jax

    for pa, pb in zip(
            jax.tree_util.tree_leaves(t_mem.trained_variables),
            jax.tree_util.tree_leaves(t_ooc.trained_variables)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_multi_shard_single_trainer_converges(tmp_path):
    full, paths = _make(tmp_path, rows=1024, shards=4)
    sd = ShardedDataset(paths)
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(16,))
    t = SingleTrainer(cfg, worker_optimizer="adam", learning_rate=5e-3,
                      batch_size=32, num_epoch=3, seed=0)
    t.train(sd)
    losses = t.history["epoch_loss"]
    assert losses[-1] < losses[0] * 0.8, losses


def test_multi_shard_sync_and_ps_trainers(tmp_path):
    full, paths = _make(tmp_path, rows=1024, shards=4)
    sd = ShardedDataset(paths)
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(16,))
    s = SyncTrainer(cfg, num_workers=4, batch_size=16, num_epoch=2,
                    learning_rate=0.05, seed=0)
    s.train(sd)
    assert s.history["epoch_loss"][-1] < s.history["epoch_loss"][0]

    a = ADAG(cfg, num_workers=4, communication_window=2, batch_size=8,
             num_epoch=2, learning_rate=0.05, seed=0)
    a.train(sd)
    assert a.history["epoch_loss"][-1] < a.history["epoch_loss"][0]
    # 4 segments x (256 rows / 4 workers / batch 8 = 8 batches -> 4
    # rounds each) = 16 rounds/epoch over 2 epochs
    assert len(a.history["round_loss"]) == 32


def test_ps_checkpoint_resume_out_of_core(tmp_path):
    """Kill/resume mid-epoch across segment boundaries is bitwise
    deterministic (global round numbering)."""
    full, paths = _make(tmp_path, rows=1024, shards=4)
    sd = ShardedDataset(paths)
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(8,))
    kw = dict(num_workers=4, communication_window=2, batch_size=8,
              num_epoch=2, learning_rate=0.05, seed=0)

    full_run = ADAG(cfg, **kw)
    full_run.train(sd)

    ck = str(tmp_path / "ck")
    part = ADAG(cfg, checkpoint_dir=ck, checkpoint_every_rounds=3, **kw)

    class Stop(Exception):
        pass

    calls = {"n": 0}
    orig = ADAG._record

    def bomb(self, **kwargs):
        orig(self, **kwargs)
        if "round_loss" in kwargs:
            calls["n"] += 1
            if calls["n"] == 5:
                raise Stop()

    ADAG._record = bomb
    try:
        with pytest.raises(Stop):
            part.train(sd)
    finally:
        ADAG._record = orig
    resumed = ADAG(cfg, **kw)
    resumed.train(sd, resume_from=ck)
    import jax

    for pa, pb in zip(
            jax.tree_util.tree_leaves(full_run.trained_variables),
            jax.tree_util.tree_leaves(resumed.trained_variables)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_tiny_tail_shard_is_recorded_not_silent(tmp_path):
    """A shard too small to give every worker a batch is dropped — and
    the drop lands in history, never silently."""
    full = datasets.synthetic_classification(512 + 7, (6,), 4, seed=0)
    paths = full.to_npz_shards(str(tmp_path / "p"), rows_per_shard=256)
    sd = ShardedDataset(paths)  # 256, 256, 7
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(8,))
    a = ADAG(cfg, num_workers=4, communication_window=2, batch_size=8,
             num_epoch=1, learning_rate=0.05, seed=0)
    a.train(sd)
    assert a.history["skipped_segment_rows"] == [7]


def test_checkpoint_at_segment_boundary_fires_mid_epoch(tmp_path):
    """checkpoint_every_rounds aligned with segment boundaries must
    still produce mid-epoch saves (deferred to the next segment), and
    resuming from one is bitwise-deterministic."""
    full, paths = _make(tmp_path, rows=1024, shards=4)
    sd = ShardedDataset(paths)
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(8,))
    # 4 rounds per segment; every=4 lands exactly on each boundary
    kw = dict(num_workers=4, communication_window=2, batch_size=8,
              num_epoch=1, learning_rate=0.05, seed=0)
    ck = str(tmp_path / "ckb")
    t = ADAG(cfg, checkpoint_dir=ck, checkpoint_every_rounds=4, **kw)

    from distkeras_tpu import checkpoint as ckpt_mod

    saved_cursors = []
    orig_save = ckpt_mod.save_checkpoint

    def spy(path, state, cursor):
        saved_cursors.append(dict(cursor))
        return orig_save(path, state, cursor)

    ckpt_mod.save_checkpoint = spy
    try:
        t.train(sd)
    finally:
        ckpt_mod.save_checkpoint = orig_save
    # at least one mid-epoch boundary save happened (round 4, 8, or 12)
    saved_rounds = {c.get("round") for c in saved_cursors
                    if c.get("epoch") == 0}
    assert saved_rounds & {4, 8, 12}, saved_cursors

    full_run = ADAG(cfg, **kw)
    full_run.train(sd)
    import jax

    for pa, pb in zip(
            jax.tree_util.tree_leaves(full_run.trained_variables),
            jax.tree_util.tree_leaves(t.trained_variables)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def _write_csv_shards(tmp_path, shards=3, rows_per=128, seed=0):
    """Criteo-ish delimited shards: numeric columns + int label."""
    rng = np.random.default_rng(seed)
    paths = []
    for s in range(shards):
        p = tmp_path / f"data-{s:03d}.csv"
        with open(p, "w") as fh:
            fh.write("f0,f1,f2,label\n")
            labels = rng.integers(0, 3, size=rows_per)
            feats = rng.normal(size=(rows_per, 3)) + labels[:, None]
            for row, y in zip(feats, labels):
                fh.write(",".join(f"{v:.5f}" for v in row)
                         + f",{y}\n")
        paths.append(str(p))
    return paths


def test_csv_shards_metadata_and_streaming(tmp_path):
    paths = _write_csv_shards(tmp_path)
    sd = Dataset.from_csv_shards(str(tmp_path / "data-*.csv"))
    assert sd.num_shards == 3 and len(sd) == 384
    assert sd.column_names == ["f0", "f1", "f2", "label"]
    assert sd.shard_rows == [128, 128, 128]
    seg = next(iter(sd.epoch_segments(seed=0)))
    assert seg["label"].dtype == np.int64
    assert seg["f0"].dtype == np.float32


def test_csv_shards_train_through_etl_map(tmp_path):
    """The Criteo workflow out-of-core: CSV shards -> per-shard
    Assemble transform -> async PS trainer."""
    from distkeras_tpu.data import AssembleTransformer

    paths = _write_csv_shards(tmp_path, shards=4, rows_per=256)
    sd = Dataset.from_csv_shards(paths)
    assemble = AssembleTransformer(["f0", "f1", "f2"])
    cfg = model_config("mlp", (3,), num_classes=3, hidden=(16,))
    t = ADAG(cfg, num_workers=4, communication_window=2, batch_size=8,
             num_epoch=3, learning_rate=0.05, seed=0)
    t.train(sd.map(assemble.transform))
    h = t.history["epoch_loss"]
    assert h[-1] < h[0] * 0.8, h


def test_csv_shard_dtype_anchor(tmp_path):
    """Shard 0 anchors the schema: integer-looking shards widen to a
    float anchor (no jit retrace on dtype drift); a non-numeric token
    raises naming the shard and column; a leading blank line doesn't
    desync the header scan."""
    (tmp_path / "s-0.csv").write_text(
        "\nx,label\n1.5,0\n2.5,1\n")  # blank first line + floats
    (tmp_path / "s-1.csv").write_text("x,label\n1,0\n2,1\n")  # ints
    sd = Dataset.from_csv_shards(str(tmp_path / "s-*.csv"))
    assert sd.shard_rows == [2, 2]
    assert sd.load_shard(1)["x"].dtype == np.float32  # widened
    (tmp_path / "s-2.csv").write_text("x,label\nNA,0\n2,1\n")
    sd2 = Dataset.from_csv_shards(str(tmp_path / "s-*.csv"))
    with pytest.raises(ValueError, match="s-2.*'x'|'x'.*s-2"):
        sd2.load_shard(2)
    # string columns with different max widths across shards are the
    # normal categorical shape, not dtype drift
    (tmp_path / "c-0.csv").write_text("cat,label\nab,0\ncd,1\n")
    (tmp_path / "c-1.csv").write_text("cat,label\nabcde,0\nx,1\n")
    sdc = Dataset.from_csv_shards(str(tmp_path / "c-*.csv"))
    assert sdc.load_shard(1)["cat"].dtype.kind == "U"
    # duplicate header columns fail at construction (anchor parse)
    (tmp_path / "d-0.csv").write_text("a,a\n1,2\n")
    with pytest.raises(ValueError, match="duplicate"):
        Dataset.from_csv_shards(str(tmp_path / "d-0.csv"))


def test_csv_shard_guards(tmp_path):
    _write_csv_shards(tmp_path)
    # mismatched header across shards fails at construction
    bad = tmp_path / "data-999.csv"
    bad.write_text("a,b\n1,2\n")
    with pytest.raises(ValueError, match="header"):
        Dataset.from_csv_shards(str(tmp_path / "data-*.csv"))
    bad.unlink()
    # a row-count-changing map fn fails loudly at load
    sd = Dataset.from_csv_shards(str(tmp_path / "data-*.csv"))
    clipped = sd.map(lambda ds: ds.take(5))
    with pytest.raises(ValueError, match="row count"):
        clipped.load_shard(0)


def test_sharded_guards(tmp_path):
    full, paths = _make(tmp_path)
    with pytest.raises(ValueError, match="no files match"):
        Dataset.from_npz_shards(str(tmp_path / "nope-*.npz"))
    # mismatched columns across shards
    Dataset({"x": np.zeros((4, 2))}).to_npz(str(tmp_path / "bad.npz"))
    with pytest.raises(ValueError, match="columns"):
        ShardedDataset([paths[0], str(tmp_path / "bad.npz")])
    # mismatched row shape
    Dataset({"features": np.zeros((4, 9), np.float32),
             "label": np.zeros((4,), np.int64)}).to_npz(
        str(tmp_path / "badshape.npz"))
    with pytest.raises(ValueError, match="row shape"):
        ShardedDataset([paths[0], str(tmp_path / "badshape.npz")])
    # a dataset too small for any window raises, not hangs
    from distkeras_tpu.trainers import DOWNPOUR

    tiny = ShardedDataset(
        datasets.synthetic_classification(8, (6,), 4, seed=0)
        .to_npz_shards(str(tmp_path / "tiny"), rows_per_shard=4))
    t = DOWNPOUR(model_config("mlp", (6,), num_classes=4, hidden=(8,)),
                 num_workers=2, fidelity="host", batch_size=8,
                 num_epoch=1, learning_rate=0.01)
    with pytest.raises(ValueError, match="communication window"):
        t.train(tiny)


def test_host_arm_streams_sharded_dataset(tmp_path):
    """The faithful concurrent arm (free-running threads + host PS)
    streams shard files too: segments walked in the same deterministic
    order by every worker, one segment repartition shared across
    threads."""
    from distkeras_tpu.trainers import DOWNPOUR

    full = datasets.synthetic_classification(1024, (6,), 4, seed=0)
    paths = full.to_npz_shards(str(tmp_path / "h"), rows_per_shard=256)
    sd = ShardedDataset(paths)
    t = DOWNPOUR(model_config("mlp", (6,), num_classes=4, hidden=(16,)),
                 num_workers=4, communication_window=2, batch_size=8,
                 num_epoch=3, learning_rate=0.01, seed=0,
                 fidelity="host", transport="socket")
    t.train(sd)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0], h
    # every round got served: 4 segments x (256/4/8=8 batches -> 4
    # rounds) x 3 epochs x 4 workers commits
    assert len(t.history["staleness"][0]) == 4 * 4 * 3 * 4


def test_segment_prefetch_is_bit_identical(tmp_path, monkeypatch):
    """One-deep IO prefetch overlaps shard loads with compute but must
    not change the segment plan or any result bit (VERDICT r3 #2)."""
    import jax

    full, paths = _make(tmp_path, rows=1024, shards=4)
    sd = ShardedDataset(paths)
    cfg = model_config("mlp", (6,), num_classes=4, hidden=(16,))

    def train(cls, prefetch, **kw):
        monkeypatch.setenv("DKT_SEGMENT_PREFETCH", prefetch)
        t = cls(cfg, batch_size=8, num_epoch=2, learning_rate=0.05,
                seed=0, **kw)
        t.train(sd)
        return t

    for cls, kw in [(SingleTrainer, {}),
                    (ADAG, dict(num_workers=4,
                                communication_window=2))]:
        off = train(cls, "0", **kw)
        on = train(cls, "1", **kw)
        assert (off.history["epoch_loss"]
                == on.history["epoch_loss"]), cls.__name__
        for a, b in zip(
                jax.tree_util.tree_leaves(off.trained_variables),
                jax.tree_util.tree_leaves(on.trained_variables)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))


def test_host_arm_segment_build_failure_raises_not_hangs(tmp_path):
    """A shard whose load raises must fail the whole job loudly: the
    builder poisons the cache entry before firing the event, so the
    other workers re-raise instead of waiting forever (ADVICE r3)."""
    from distkeras_tpu.trainers import DOWNPOUR

    full = datasets.synthetic_classification(512, (6,), 4, seed=0)
    paths = full.to_npz_shards(str(tmp_path / "p"), rows_per_shard=256)

    boom = RuntimeError("etl exploded")

    def bad_fn(ds):
        raise boom

    sd = ShardedDataset(paths).map(bad_fn)
    t = DOWNPOUR(model_config("mlp", (6,), num_classes=4, hidden=(8,)),
                 num_workers=4, communication_window=2, batch_size=8,
                 num_epoch=1, learning_rate=0.01, seed=0,
                 fidelity="host")
    with pytest.raises(RuntimeError):
        t.train(sd)


def test_host_arm_records_skipped_runt_shard(tmp_path):
    """A runt shard that can't fill a batch per worker is recorded in
    the host arm's history too, never silently dropped."""
    from distkeras_tpu.trainers import DOWNPOUR

    full = datasets.synthetic_classification(512 + 6, (6,), 4, seed=0)
    paths = full.to_npz_shards(str(tmp_path / "r"), rows_per_shard=256)
    sd = ShardedDataset(paths)  # 256, 256, 6
    t = DOWNPOUR(model_config("mlp", (6,), num_classes=4, hidden=(8,)),
                 num_workers=2, communication_window=2, batch_size=8,
                 num_epoch=1, learning_rate=0.01, seed=0,
                 fidelity="host")
    t.train(sd)
    assert t.history["skipped_segment_rows"] == [6]


def test_prefetch_feeder_exits_when_consumer_abandons():
    """An abandoned epoch iterator (train error, interrupt) must not
    leave the daemon feeder blocked holding loaded segments (ADVICE-
    style leak): closing the generator cancels the feeder."""
    import threading
    import time

    from distkeras_tpu.trainers import _prefetch_iter

    started = threading.Event()

    def loads():
        for i in range(100):
            started.set()
            yield np.zeros(4) + i

    before = set(threading.enumerate())
    it = _prefetch_iter(loads(), depth=1)
    next(it)
    assert started.is_set()
    it.close()  # consumer walks away mid-stream
    deadline = time.monotonic() + 5.0
    alive: list = []
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "dkt-segment-prefetch" and t.is_alive()
                 and t not in before]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, "prefetch feeder still alive after close()"

"""Ring attention (sequence parallelism) vs dense attention — exactness
of the online-softmax ring accumulation, gradients through the ring
(reverse ppermute), and the full sequence-parallel TransformerLM."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.models.transformer import dense_causal_attention
from distkeras_tpu.ops.losses import resolve_loss
from distkeras_tpu.parallel.ring_attention import (
    ring_attention,
    sequence_sharded_apply,
)
from distkeras_tpu.utils import shard_map

SEQ = "seq"


def _mesh(n=4):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"needs {n} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]), (SEQ,))


def _qkv(b=2, t=32, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _dense_full_attention(q, k, v, *, scale):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("q_chunk", [None, 4])
def test_ring_matches_dense(causal, q_chunk):
    mesh = _mesh()
    q, k, v = _qkv()
    scale = q.shape[-1] ** -0.5
    ring = shard_map(
        functools.partial(ring_attention, axis_name=SEQ, causal=causal,
                          q_chunk=q_chunk),
        mesh=mesh, in_specs=(P(None, SEQ), P(None, SEQ), P(None, SEQ)),
        out_specs=P(None, SEQ))
    got = np.asarray(jax.jit(ring)(q, k, v))
    ref_fn = (dense_causal_attention if causal
              else _dense_full_attention)
    want = np.asarray(ref_fn(q, k, v, scale=scale))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_indivisible_q_chunk_raises():
    mesh = _mesh()
    q, k, v = _qkv()  # t_local = 8 per device
    ring = shard_map(
        functools.partial(ring_attention, axis_name=SEQ, q_chunk=3),
        mesh=mesh, in_specs=(P(None, SEQ), P(None, SEQ), P(None, SEQ)),
        out_specs=P(None, SEQ))
    with pytest.raises(ValueError, match="q_chunk"):
        jax.jit(ring)(q, k, v)


@pytest.mark.parametrize("q_chunk", [None, 2])
def test_ring_gradients_match_dense(q_chunk):
    mesh = _mesh()
    q, k, v = _qkv(seed=1)
    probe = jax.random.normal(jax.random.key(9), q.shape)

    def ring_loss(q, k, v):
        out = shard_map(
            functools.partial(ring_attention, axis_name=SEQ,
                              q_chunk=q_chunk),
            mesh=mesh,
            in_specs=(P(None, SEQ), P(None, SEQ), P(None, SEQ)),
            out_specs=P(None, SEQ))(q, k, v)
        return jnp.sum(out * probe)

    def dense_loss(q, k, v):
        out = dense_causal_attention(q, k, v,
                                     scale=q.shape[-1] ** -0.5)
        return jnp.sum(out * probe)

    got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    want = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-5)


def _lm_spec(**over):
    cfg = dict(vocab_size=64, num_layers=2, d_model=32, num_heads=2,
               max_len=64, dtype="float32")
    cfg.update(over)
    return ModelSpec.from_config(
        model_config("transformer_lm", (32,), input_dtype="int32", **cfg))


def test_sequence_parallel_transformer_matches_dense():
    """Same params, dense single-device vs ring over 4 sequence shards."""
    mesh = _mesh()
    dense_model = _lm_spec().build()
    seq_model = _lm_spec(seq_axis=SEQ).build()

    tokens = jax.random.randint(jax.random.key(2), (2, 32), 0, 64)
    variables = dense_model.init(jax.random.key(3), tokens)

    want = np.asarray(dense_model.apply(variables, tokens))
    sp_apply = sequence_sharded_apply(
        lambda vs, toks: seq_model.apply(vs, toks), mesh, SEQ)
    got = np.asarray(jax.jit(sp_apply)(variables, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_sequence_parallel_training_grads_match_dense():
    """A full LM training gradient (xent over next tokens) computed
    sequence-parallel equals the dense gradient — the correctness basis
    for long-context training."""
    mesh = _mesh()
    dense_model = _lm_spec().build()
    seq_model = _lm_spec(seq_axis=SEQ).build()
    loss_fn = resolve_loss("sparse_categorical_crossentropy")

    data = jax.random.randint(jax.random.key(4), (2, 33), 0, 64)
    tokens, targets = data[:, :-1], data[:, 1:]
    variables = dense_model.init(jax.random.key(5), tokens)

    def dense_loss(vs):
        logits = dense_model.apply(vs, tokens)
        return loss_fn(logits, targets).mean()

    def seq_loss(vs):
        def shard_loss(vs, toks, tgt):
            logits = seq_model.apply(vs, toks)
            local = loss_fn(logits, tgt).mean()
            return jax.lax.pmean(local, SEQ)

        sharded = shard_map(
            shard_loss, mesh=mesh,
            in_specs=(P(), P(None, SEQ), P(None, SEQ)),
            out_specs=P())
        return sharded(vs, tokens, targets)

    want_l, want_g = jax.jit(jax.value_and_grad(dense_loss))(variables)
    got_l, got_g = jax.jit(jax.value_and_grad(seq_loss))(variables)
    np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
    flat_w, _ = jax.tree_util.tree_flatten(want_g)
    flat_g, _ = jax.tree_util.tree_flatten(got_g)
    for g, w in zip(flat_g, flat_w):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("q_chunk", [None, 4, 8])
def test_blockwise_matches_dense(causal, q_chunk):
    """Device-local blockwise (flash-style) attention — the ring
    machinery with no ring — is exact vs dense, fwd and grad."""
    from distkeras_tpu.parallel.ring_attention import blockwise_attention

    q, k, v = _qkv()
    scale = q.shape[-1] ** -0.5
    dense = (dense_causal_attention if causal
             else _dense_full_attention)
    want = np.asarray(dense(q, k, v, scale=scale))
    got = np.asarray(jax.jit(functools.partial(
        blockwise_attention, causal=causal, q_chunk=q_chunk))(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def loss_block(q, k, v):
        o = blockwise_attention(q, k, v, causal=causal,
                                q_chunk=q_chunk)
        return (o * o).sum()

    def loss_dense(q, k, v):
        o = dense(q, k, v, scale=scale)
        return (o * o).sum()

    got_g = jax.jit(jax.grad(loss_block, argnums=(0, 1, 2)))(q, k, v)
    want_g = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-4)


def test_transformer_blockwise_matches_dense():
    """TransformerLM(blockwise_attn=True) — the JSON-able spelling —
    equals the dense-attention twin on one device."""
    dense_model = _lm_spec().build()
    block_spec = _lm_spec(blockwise_attn=True, attn_q_chunk=8)
    import json

    # the knob must survive a config round-trip (it is how checkpoints
    # and trainers carry it)
    block_model = ModelSpec.from_config(
        json.loads(json.dumps(block_spec.to_config()))).build()
    tokens = jax.random.randint(jax.random.key(11), (2, 32), 0, 64)
    variables = dense_model.init(jax.random.key(12), tokens)
    want = np.asarray(dense_model.apply(variables, tokens))
    got = np.asarray(jax.jit(
        lambda vs, t: block_model.apply(vs, t))(variables, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_blockwise_lm_trains_through_async_ps():
    """Integration: the emulated async-PS family trains a
    blockwise-attention TransformerLM (vmapped worker states over the
    flash path's custom VJP + lax.map) — the single-chip long-context
    model composes with every trainer arm."""
    from distkeras_tpu.data import datasets
    from distkeras_tpu.trainers import ADAG

    data = datasets.lm_synth(256, seq_len=16, vocab_size=32, seed=0)
    cfg = model_config("transformer_lm", (16,), input_dtype="int32",
                       vocab_size=32, num_layers=1, d_model=32,
                       num_heads=4, max_len=16, dtype="float32",
                       blockwise_attn=True, attn_q_chunk=8)
    t = ADAG(cfg, loss="sparse_categorical_crossentropy",
             num_workers=4, communication_window=2, batch_size=8,
             num_epoch=2, learning_rate=3e-3, worker_optimizer="adam",
             seed=0)
    t.train(data)
    h = t.history["epoch_loss"]
    assert np.isfinite(h).all()
    assert h[-1] < h[0], h


def test_transformer_attn_q_chunk_matches_dense():
    """TransformerLM(seq_axis=..., attn_q_chunk=...) — chunked ring
    attention through the full model equals the dense twin."""
    mesh = _mesh()
    dense_model = _lm_spec().build()
    seq_model = _lm_spec(seq_axis=SEQ, attn_q_chunk=4).build()

    tokens = jax.random.randint(jax.random.key(8), (2, 32), 0, 64)
    variables = dense_model.init(jax.random.key(9), tokens)
    want = np.asarray(dense_model.apply(variables, tokens))
    sp_apply = sequence_sharded_apply(
        lambda vs, toks: seq_model.apply(vs, toks), mesh, SEQ)
    got = np.asarray(jax.jit(sp_apply)(variables, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


# ---- impl="flash": Pallas hop kernels under the ring ----


@pytest.mark.parametrize("causal", [True, False])
def test_flash_impl_ring_matches_dense(causal):
    """ring_attention(impl='flash') — per-hop Pallas kernels with the
    online-softmax state carried across hops — equals dense attention.
    The Pallas interpreter needs shard_map(check_vma=False) (JAX
    interpreter limitation; sequence_sharded_apply already does)."""
    mesh = _mesh()
    q, k, v = _qkv()
    scale = q.shape[-1] ** -0.5
    ring = shard_map(
        functools.partial(ring_attention, axis_name=SEQ, causal=causal,
                          impl="flash", block_q=8, block_k=8),
        mesh=mesh, in_specs=(P(None, SEQ),) * 3,
        out_specs=P(None, SEQ), check_vma=False)
    got = np.asarray(jax.jit(ring)(q, k, v))
    ref_fn = (dense_causal_attention if causal
              else _dense_full_attention)
    want = np.asarray(ref_fn(q, k, v, scale=scale))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_flash_impl_ring_gradients_match_dense():
    mesh = _mesh()
    q, k, v = _qkv()
    scale = q.shape[-1] ** -0.5
    probe = jax.random.normal(jax.random.key(21), q.shape, jnp.float32)
    ring = shard_map(
        functools.partial(ring_attention, axis_name=SEQ, causal=True,
                          impl="flash", block_q=8, block_k=8),
        mesh=mesh, in_specs=(P(None, SEQ),) * 3,
        out_specs=P(None, SEQ), check_vma=False)
    gf = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ring(q, k, v) * probe),
        (0, 1, 2)))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(
            dense_causal_attention(q, k, v, scale=scale) * probe),
        (0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5, err_msg=name)


def test_flash_impl_degenerate_ring_single_device():
    """axis_name=None, impl='flash': the n=1 ring runs the hop kernels
    device-locally and matches dense — the kernels' single-chip
    smoke path (compiled on real TPU, interpreted off it)."""
    q, k, v = _qkv()
    got = ring_attention(q, k, v, axis_name=None, impl="flash",
                         block_q=8, block_k=16)
    want = dense_causal_attention(q, k, v, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_impl_unknown_rejected():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="impl"):
        ring_attention(q, k, v, axis_name=None, impl="mosaic")

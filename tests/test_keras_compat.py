"""Keras ingestion shim: forward parity against real keras models,
weight-list mapping, trainer integration, and clear unsupported-layer
errors (reference surface: serialize_keras_model / deserialize_keras_model,
SURVEY.md §2.1 Utils + §3.5)."""

import json

import numpy as np
import pytest

import jax

from distkeras_tpu.compat import from_keras, from_keras_json
from distkeras_tpu.data import datasets
from distkeras_tpu.trainers import SingleTrainer

keras = pytest.importorskip("keras")


def _keras_mlp():
    m = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dropout(0.0),
        keras.layers.Dense(4, activation="softmax"),
    ])
    return m


def _keras_convnet():
    return keras.Sequential([
        keras.layers.Input((12, 12, 3)),
        keras.layers.Conv2D(8, 3, activation="relu", padding="same"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Conv2D(8, 3, strides=2, padding="valid"),
        keras.layers.Activation("relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(5),
    ])


@pytest.mark.parametrize("maker,shape", [
    (_keras_mlp, (8,)),
    (_keras_convnet, (12, 12, 3)),
])
def test_forward_parity_with_keras(maker, shape):
    m = maker()
    spec, variables = from_keras(m)
    assert spec.input_shape == shape
    x = np.random.default_rng(0).normal(size=(4, *shape)).astype(
        np.float32)
    want = np.asarray(m(x))
    got = np.asarray(spec.build().apply(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ingested_model_trains():
    spec, variables = from_keras(_keras_mlp())
    data = datasets.synthetic_classification(512, (8,), 4, seed=1)
    t = SingleTrainer(spec.to_config(), worker_optimizer="adam",
                      learning_rate=3e-3, batch_size=32, num_epoch=3,
                      loss="categorical_crossentropy")
    t.train(data, initial_variables=variables)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0], h


def test_spec_survives_json_roundtrip():
    spec, _ = from_keras(_keras_mlp())
    rebuilt = json.loads(json.dumps(spec.to_config()))
    from distkeras_tpu.models import ModelSpec

    spec2 = ModelSpec.from_config(rebuilt)
    x = np.zeros((2, 8), np.float32)
    v = spec2.build().init(jax.random.key(0), x)
    assert spec2.build().apply(v, x).shape == (2, 4)


def test_batchnorm_and_embedding_mapping():
    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8),
        keras.layers.BatchNormalization(),
        keras.layers.Activation("relu"),
        keras.layers.Dense(3),
    ])
    spec, variables = from_keras(m)
    assert "batch_stats" in variables
    x = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
    want = np.asarray(m(x, training=False))
    got = np.asarray(spec.build().apply(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_unsupported_layer_raises_by_name():
    arch = {"class_name": "Sequential", "config": {"layers": [
        {"class_name": "ConvLSTM2D", "config": {"filters": 8}}]}}
    with pytest.raises(NotImplementedError, match="ConvLSTM2D"):
        from_keras_json(json.dumps(arch), input_shape=(5, 3))


def _functional_lenet():
    inp = keras.Input((12, 12, 1))
    h = keras.layers.Conv2D(6, 5, activation="relu",
                            padding="same")(inp)
    h = keras.layers.MaxPooling2D(2)(h)
    h = keras.layers.Conv2D(16, 3, activation="relu")(h)
    h = keras.layers.Flatten()(h)
    h = keras.layers.Dense(32, activation="relu")(h)
    out = keras.layers.Dense(10)(h)
    return keras.Model(inp, out)


def _functional_lstm():
    inp = keras.Input((7,))
    h = keras.layers.Embedding(30, 8)(inp)
    h = keras.layers.LSTM(12)(h)
    out = keras.layers.Dense(3)(h)
    return keras.Model(inp, out)


@pytest.mark.parametrize("maker,shape,x_int", [
    (_functional_lenet, (12, 12, 1), False),
    (_functional_lstm, (7,), True),
])
def test_functional_linear_chain_parity(maker, shape, x_int):
    """Single-input single-output functional Model graphs ingest with
    forward parity vs keras (VERDICT.md r2 Missing #1)."""
    m = maker()
    spec, variables = from_keras(m)
    assert spec.input_shape == shape
    rng = np.random.default_rng(0)
    if x_int:
        x = rng.integers(0, 30, size=(4, *shape)).astype(np.int32)
    else:
        x = rng.normal(size=(4, *shape)).astype(np.float32)
    want = np.asarray(m(x))
    got = np.asarray(spec.build().apply(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_functional_ingested_trains():
    spec, variables = from_keras(_functional_lenet())
    data = datasets.synthetic_classification(256, (12, 12, 1), 10,
                                             seed=2)
    t = SingleTrainer(spec.to_config(), worker_optimizer="adam",
                      learning_rate=3e-3, batch_size=32, num_epoch=2,
                      loss="categorical_crossentropy")
    t.train(data, initial_variables=variables)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0], h


@pytest.fixture()
def _f32_matmuls():
    # keras/TF computes true f32; pin jax's matmul precision so DAG
    # parity asserts numerics, not the platform's bf16-style default
    with jax.default_matmul_precision("float32"):
        yield


def test_functional_dag_with_merge_ingests(_f32_matmuls):
    """Branch + Add merge (a residual MLP) round-trips through the
    keras_graph family with forward parity."""
    inp = keras.Input((8,))
    a = keras.layers.Dense(8, activation="relu", name="left")(inp)
    b = keras.layers.Dense(8, name="right")(a)
    res = keras.layers.Add(name="the_merge")([a, b])
    out = keras.layers.Dense(3)(keras.layers.Activation("relu")(res))
    m = keras.Model(inp, out)
    spec, variables = from_keras(m)
    assert spec.to_config()["family"] == "keras_graph"
    x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("merge,klass", [
    ("concat", "Concatenate"),
    ("average", "Average"),
    ("maximum", "Maximum"),
    ("subtract", "Subtract"),
    ("multiply", "Multiply"),
])
def test_functional_merge_layers_parity(_f32_matmuls, merge, klass):
    inp = keras.Input((6,))
    a = keras.layers.Dense(5, activation="tanh")(inp)
    b = keras.layers.Dense(5)(inp)
    join = getattr(keras.layers, klass)()([a, b])
    out = keras.layers.Dense(2)(join)
    m = keras.Model(inp, out)
    spec, variables = from_keras(m)
    x = np.random.default_rng(3).normal(size=(4, 6)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-5, atol=1e-5)


def test_functional_multi_input_wide_deep(_f32_matmuls):
    """A two-input wide&deep-style model ingests as one concatenated
    features array with per-input column slices — the reference-era
    Criteo shape."""
    wide = keras.Input((5,), name="wide")
    deep = keras.Input((7,), name="deep")
    d = keras.layers.Dense(6, activation="relu")(deep)
    d = keras.layers.Dense(4, activation="relu")(d)
    join = keras.layers.Concatenate()([wide, d])
    out = keras.layers.Dense(2)(join)
    m = keras.Model([wide, deep], out)
    spec, variables = from_keras(m)
    assert spec.to_config()["family"] == "keras_graph"
    assert spec.input_shape == (12,)  # 5 + 7, input_layers order
    rng = np.random.default_rng(1)
    xa = rng.normal(size=(4, 5)).astype(np.float32)
    xb = rng.normal(size=(4, 7)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(
            variables, np.concatenate([xa, xb], axis=1))),
        np.asarray(m([xa, xb])), rtol=1e-5, atol=1e-5)


def test_functional_graph_spec_survives_json_roundtrip(_f32_matmuls):
    inp = keras.Input((6,))
    a = keras.layers.Dense(6)(inp)
    res = keras.layers.Add()([inp, a])
    m = keras.Model(inp, keras.layers.Dense(2)(res))
    spec, variables = from_keras(m)
    rebuilt = json.loads(json.dumps(spec.to_config()))
    from distkeras_tpu.models import ModelSpec

    spec2 = ModelSpec.from_config(rebuilt)
    x = np.random.default_rng(4).normal(size=(3, 6)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec2.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-5, atol=1e-5)


def test_ingested_dag_trains():
    inp = keras.Input((8,))
    a = keras.layers.Dense(16, activation="relu")(inp)
    b = keras.layers.Dense(16)(inp)
    out = keras.layers.Dense(4)(keras.layers.Add()([a, b]))
    spec, variables = from_keras(keras.Model(inp, out))
    data = datasets.synthetic_classification(512, (8,), 4, seed=5)
    t = SingleTrainer(spec.to_config(), worker_optimizer="adam",
                      learning_rate=3e-3, batch_size=32, num_epoch=3,
                      loss="categorical_crossentropy")
    t.train(data, initial_variables=variables)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0], h


def test_nested_sequential_submodel_parity():
    """A Sequential used as a layer inside a Sequential ingests by
    inlining its stack (weights consumed in order)."""
    inner = keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Dense(6, activation="relu"),
        keras.layers.Dense(6, activation="tanh"),
    ])
    outer = keras.Sequential([
        keras.layers.Input((8,)),
        inner,
        keras.layers.Dense(2),
    ])
    spec, variables = from_keras(outer)
    x = np.random.default_rng(2).normal(size=(5, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(outer(x)), rtol=1e-4, atol=1e-5)


def test_shared_nested_encoder_siamese_parity(_f32_matmuls):
    """The classic siamese idiom: one nested Sequential encoder called
    on two inputs — one parameter set, exact forward parity."""
    enc = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(6, activation="relu", name="e1"),
        keras.layers.Dense(6, name="e2"),
    ])
    a = keras.Input((4,), name="left")
    b = keras.Input((4,), name="right")
    joined = keras.layers.Concatenate()([enc(a), enc(b)])
    m = keras.Model([a, b], keras.layers.Dense(2)(joined))
    spec, variables = from_keras(m)
    assert spec.to_config()["family"] == "keras_graph"
    rng = np.random.default_rng(3)
    xa = rng.normal(size=(5, 4)).astype(np.float32)
    xb = rng.normal(size=(5, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(
            variables, np.concatenate([xa, xb], axis=1))),
        np.asarray(m([xa, xb])), rtol=1e-5, atol=1e-5)


def test_nested_functional_submodel_parity(_f32_matmuls):
    """VERDICT r4 #8: a functional Model (with internal branches and a
    merge) used as a layer ingests by replaying its DAG inline — exact
    forward parity, weights consumed at the submodel's position."""
    inner_in = keras.Input((6,))
    a = keras.layers.Dense(6, activation="relu")(inner_in)
    b = keras.layers.Dense(6, activation="tanh")(inner_in)
    inner = keras.Model(inner_in, keras.layers.Add()([a, b]))
    outer_in = keras.Input((6,))
    m = keras.Model(outer_in,
                    keras.layers.Dense(2)(inner(outer_in)))
    spec, variables = from_keras(m)
    x = np.random.default_rng(4).normal(size=(5, 6)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-5, atol=1e-5)
    # the spec (carrying the inner graph) survives JSON round-trip
    rebuilt = json.loads(json.dumps(spec.to_config()))
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(
            __import__("distkeras_tpu.models", fromlist=["ModelSpec"]
                       ).ModelSpec.from_config(rebuilt).build().apply(
                           variables, x)),
        rtol=1e-6, atol=1e-6)


def test_shared_nested_functional_siamese_parity(_f32_matmuls):
    """One nested functional encoder called on two inputs — one
    parameter set (keras sharing semantics), exact parity."""
    enc_in = keras.Input((4,))
    h = keras.layers.Dense(6, activation="relu")(enc_in)
    enc = keras.Model(enc_in, keras.layers.Dense(6)(h))
    a = keras.Input((4,), name="left")
    b = keras.Input((4,), name="right")
    joined = keras.layers.Concatenate()([enc(a), enc(b)])
    m = keras.Model([a, b], keras.layers.Dense(2)(joined))
    spec, variables = from_keras(m)
    rng = np.random.default_rng(5)
    xa = rng.normal(size=(5, 4)).astype(np.float32)
    xb = rng.normal(size=(5, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(
            variables, np.concatenate([xa, xb], axis=1))),
        np.asarray(m([xa, xb])), rtol=1e-5, atol=1e-5)


def test_nested_functional_multi_output_rejected():
    """A nested submodel's call site is one tensor in, one out — a
    multi-output inner model cannot ingest and must say so."""
    inner_in = keras.Input((4,))
    inner = keras.Model(inner_in, [keras.layers.Dense(3)(inner_in),
                                   keras.layers.Dense(2)(inner_in)])
    outer_in = keras.Input((4,))
    outs = inner(outer_in)
    m = keras.Model(outer_in,
                    keras.layers.Concatenate()(list(outs)))
    # rejected by the graph walker's multi-output-layer guard (the
    # nested model is one layer with two output tensors)
    with pytest.raises(NotImplementedError, match="multi-output"):
        from_keras(m)


def test_multi_input_unrecorded_shape_rejected():
    """A multi-input model whose input has None dims past the batch
    axis cannot compute slice widths — it must raise, not ingest a
    garbage slicing."""
    arch = {
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"name": "a", "class_name": "InputLayer",
                 "config": {"batch_input_shape": [None, None]},
                 "inbound_nodes": []},
                {"name": "b", "class_name": "InputLayer",
                 "config": {"batch_input_shape": [None, 3]},
                 "inbound_nodes": []},
                {"name": "cat", "class_name": "Concatenate",
                 "config": {"axis": -1},
                 "inbound_nodes": [[["a", 0, 0, {}],
                                    ["b", 0, 0, {}]]]},
                {"name": "d", "class_name": "Dense",
                 "config": {"units": 2},
                 "inbound_nodes": [[["cat", 0, 0, {}]]]},
            ],
            "input_layers": [["a", 0, 0], ["b", 0, 0]],
            "output_layers": [["d", 0, 0]],
        },
    }
    with pytest.raises(NotImplementedError, match="per-sample shape"):
        from_keras_json(json.dumps(arch))


def test_multi_input_mixed_rank_parity(_f32_matmuls):
    """An image branch beside a feature branch (mixed-rank
    multi-input): inputs flatten-concatenate into one feature row;
    the image slice reshapes back before its convs."""
    a = keras.Input((4, 4, 1), name="img")
    b = keras.Input((3,), name="vec")
    ca = keras.layers.Conv2D(4, 3, padding="same",
                             activation="relu")(a)
    fa = keras.layers.Flatten()(ca)
    join = keras.layers.Concatenate()([fa, b])
    m = keras.Model([a, b], keras.layers.Dense(2)(join))
    spec, variables = from_keras(m)
    assert spec.input_shape == (4 * 4 * 1 + 3,)
    rng = np.random.default_rng(9)
    xa = rng.normal(size=(5, 4, 4, 1)).astype(np.float32)
    xb = rng.normal(size=(5, 3)).astype(np.float32)
    flat = np.concatenate([xa.reshape(5, -1), xb], axis=1)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, flat)),
        np.asarray(m([xa, xb])), rtol=1e-4, atol=1e-5)


def test_shared_layer_weight_reuse_parity(_f32_matmuls):
    """A layer called twice lowers to one flax module applied at two
    graph nodes — one parameter set, exact forward parity."""
    inp = keras.Input((4,))
    shared = keras.layers.Dense(4, activation="tanh", name="enc")
    once = shared(inp)
    twice = shared(once)           # same weights, different input
    out = keras.layers.Dense(2)(keras.layers.Add()([once, twice]))
    m = keras.Model(inp, out)
    spec, variables = from_keras(m)
    assert spec.to_config()["family"] == "keras_graph"
    # one parameter set for the shared layer, not two: enc + head only
    assert len(variables["params"]) == 2
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-5, atol=1e-5)


def test_shared_encoder_two_head_parity(_f32_matmuls):
    """Shared encoder + two output heads: the forward returns a tuple
    in output_layers order, each head matching live keras."""
    inp = keras.Input((6,))
    enc = keras.layers.Dense(8, activation="relu", name="enc")(inp)
    head_a = keras.layers.Dense(3, name="class_head")(enc)
    head_b = keras.layers.Dense(1, name="reg_head")(enc)
    m = keras.Model(inp, [head_a, head_b])
    spec, variables = from_keras(m)
    assert spec.to_config()["family"] == "keras_graph"
    x = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
    got = spec.build().apply(variables, x)
    want = m(x)
    assert isinstance(got, tuple) and len(got) == 2
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-5, atol=1e-5)


def test_multi_output_training_needs_per_head_losses():
    inp = keras.Input((4,))
    h = keras.layers.Dense(4)(inp)
    m = keras.Model(inp, [h, keras.layers.Dense(2)(h)])
    spec, variables = from_keras(m)  # ingestion itself succeeds
    with pytest.raises(ValueError, match="output heads"):
        SingleTrainer(spec.to_config(), batch_size=8, num_epoch=1,
                      learning_rate=0.1)  # single loss: rejected


def test_multi_output_model_trains_with_per_head_losses():
    """A two-head ingested DAG trains end-to-end: one loss + one label
    column per head, objective = their sum."""
    inp = keras.Input((6,))
    enc = keras.layers.Dense(16, activation="relu")(inp)
    class_head = keras.layers.Dense(3, name="cls")(enc)
    reg_head = keras.layers.Dense(1, name="reg")(enc)
    m = keras.Model(inp, [class_head, reg_head])
    spec, variables = from_keras(m)

    rng = np.random.default_rng(4)
    x = rng.normal(size=(512, 6)).astype(np.float32)
    w = rng.normal(size=(6,))
    label_cls = (x @ w > 0).astype(np.int32) + (x[:, 0] > 1)
    label_reg = (x @ w).astype(np.float32)[:, None]
    from distkeras_tpu.data.dataset import Dataset

    data = Dataset({"features": x, "cls": label_cls.astype(np.int32),
                    "reg": label_reg})
    t = SingleTrainer(
        spec.to_config(),
        loss=["sparse_categorical_crossentropy", "mse"],
        label_col=["cls", "reg"],
        worker_optimizer="adam", learning_rate=5e-3,
        batch_size=32, num_epoch=4, seed=0)
    t.train(data, initial_variables=variables)
    h = t.history["epoch_loss"]
    assert np.isfinite(h).all()
    assert h[-1] < h[0] * 0.8, h

    # the async family consumes the same per-head spelling
    from distkeras_tpu.trainers import ADAG

    a = ADAG(spec.to_config(),
             loss=["sparse_categorical_crossentropy", "mse"],
             label_col=["cls", "reg"], num_workers=4,
             communication_window=2, worker_optimizer="adam",
             learning_rate=5e-3, batch_size=16, num_epoch=2, seed=0)
    a.train(data, initial_variables=variables)
    ah = a.history["epoch_loss"]
    assert np.isfinite(ah).all() and ah[-1] < ah[0], ah


def test_keras2_era_functional_json_parses():
    """The reference era serialized functional models as class_name
    'Model' with list-style inbound_nodes."""
    arch = {
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"name": "in0", "class_name": "InputLayer",
                 "config": {"batch_input_shape": [None, 6]},
                 "inbound_nodes": []},
                {"name": "d0", "class_name": "Dense",
                 "config": {"units": 5, "activation": "relu"},
                 "inbound_nodes": [[["in0", 0, 0, {}]]]},
                {"name": "d1", "class_name": "Dense",
                 "config": {"units": 2},
                 "inbound_nodes": [[["d0", 0, 0, {}]]]},
            ],
            "input_layers": [["in0", 0, 0]],
            "output_layers": [["d1", 0, 0]],
        },
    }
    spec, _ = from_keras_json(json.dumps(arch))
    assert spec.input_shape == (6,)
    x = np.zeros((2, 6), np.float32)
    v = spec.build().init(jax.random.key(0), x)
    assert spec.build().apply(v, x).shape == (2, 2)


def test_weight_count_mismatch_raises():
    m = _keras_mlp()
    too_few = m.get_weights()[:-1]
    with pytest.raises(ValueError, match="weight list"):
        from_keras_json(m.to_json(), too_few)
    too_many = m.get_weights() + [np.zeros(3, np.float32)]
    with pytest.raises(ValueError, match="weight list"):
        from_keras_json(m.to_json(), too_many)


def test_embedding_dense_rank3_parity():
    """Dense applies to the last axis of rank-n input, as in keras."""
    m = keras.Sequential([
        keras.layers.Input((7,)),
        keras.layers.Embedding(20, 6),
        keras.layers.Dense(3, activation="tanh"),
        keras.layers.Flatten(),
        keras.layers.Dense(2),
    ])
    spec, variables = from_keras(m)
    assert spec.input_dtype == "int32"
    x = np.random.default_rng(2).integers(0, 20, size=(5, 7))
    want = np.asarray(m(x))
    got = np.asarray(spec.build().apply(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_unsupported_options_raise_clearly():
    base = {"class_name": "Sequential", "config": {"layers": [
        {"class_name": "InputLayer",
         "config": {"batch_shape": [None, 8, 8, 4]}},
        None]}}

    def arch(layer):
        import copy

        a = copy.deepcopy(base)
        a["config"]["layers"][1] = layer
        return json.dumps(a)

    # grouped/dilated Conv2D are SUPPORTED as of round 5 (see
    # test_conv_variant_parity); the remaining unsupported options
    # must still raise by name
    with pytest.raises(NotImplementedError, match="output_padding"):
        from_keras_json(arch({"class_name": "Conv2DTranspose",
                              "config": {"filters": 8,
                                         "kernel_size": 3,
                                         "output_padding": 1}}))
    with pytest.raises(NotImplementedError, match="scale=False"):
        from_keras_json(arch({"class_name": "BatchNormalization",
                              "config": {"scale": False}}))
    with pytest.raises(NotImplementedError, match="axis"):
        from_keras_json(arch({"class_name": "BatchNormalization",
                              "config": {"axis": 1}}))


def test_variable_length_input_needs_explicit_shape():
    arch = {"class_name": "Sequential", "config": {"layers": [
        {"class_name": "InputLayer",
         "config": {"batch_shape": [None, None]}},
        {"class_name": "Embedding",
         "config": {"input_dim": 10, "output_dim": 4}}]}}
    with pytest.raises(ValueError, match="input_shape"):
        from_keras_json(json.dumps(arch))
    spec, _ = from_keras_json(json.dumps(arch), input_shape=(12,))
    assert spec.input_shape == (12,)


def _keras_bilstm():
    return keras.Sequential([
        keras.layers.Input((12,)),
        keras.layers.Embedding(50, 8),
        keras.layers.Bidirectional(keras.layers.LSTM(6)),
        keras.layers.Dense(2),
    ])


def _keras_lstm_seq():
    return keras.Sequential([
        keras.layers.Input((10,)),
        keras.layers.Embedding(30, 5),
        keras.layers.LSTM(4, return_sequences=True),
        keras.layers.LSTM(3),
        keras.layers.Dense(2),
    ])


@pytest.mark.parametrize("maker,shape", [
    (_keras_bilstm, (12,)),
    (_keras_lstm_seq, (10,)),
])
def test_lstm_forward_parity_with_keras(maker, shape):
    """The reference's IMDB workflow shape: Embedding -> (Bi)LSTM ->
    Dense, exact forward parity including stacked/return_sequences."""
    m = maker()
    spec, variables = from_keras(m)
    rng = np.random.default_rng(3)
    x = rng.integers(1, 30, size=(4, *shape)).astype(np.int32)
    want = np.asarray(m(x))
    got = np.asarray(spec.build().apply(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def _keras_gru():
    return keras.Sequential([
        keras.layers.Input((11,)),
        keras.layers.Embedding(40, 6),
        keras.layers.GRU(5),
        keras.layers.Dense(2),
    ])


def _keras_gru_stack():
    return keras.Sequential([
        keras.layers.Input((9,)),
        keras.layers.Embedding(30, 4),
        keras.layers.GRU(4, return_sequences=True),
        keras.layers.Bidirectional(keras.layers.GRU(3)),
        keras.layers.Dense(2),
    ])


def _keras_simple_rnn():
    return keras.Sequential([
        keras.layers.Input((8,)),
        keras.layers.Embedding(25, 4),
        keras.layers.SimpleRNN(6, activation="tanh"),
        keras.layers.Dense(2),
    ])


@pytest.mark.parametrize("maker,shape,vocab", [
    (_keras_gru, (11,), 40),
    (_keras_gru_stack, (9,), 30),
    (_keras_simple_rnn, (8,), 25),
])
def test_gru_simplernn_forward_parity(maker, shape, vocab):
    """GRU (keras reset_after=True == flax GRUCell with folded gate
    biases), Bidirectional(GRU), and SimpleRNN: exact forward parity
    with live keras."""
    m = maker()
    spec, variables = from_keras(m)
    rng = np.random.default_rng(7)
    x = rng.integers(1, vocab, size=(4, *shape)).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-4, atol=1e-5)


def test_gru_reset_after_false_rejected():
    with pytest.raises(NotImplementedError, match="reset_after"):
        from_keras(keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Embedding(10, 4),
            keras.layers.GRU(3, reset_after=False),
        ]))


def test_conv1d_separable_forward_parity(_f32_matmuls):
    """Conv1D over sequences and SeparableConv2D (depthwise grouped
    conv + pointwise, keras weight layout re-folded)."""
    m1 = keras.Sequential([
        keras.layers.Input((16, 3)),
        keras.layers.Conv1D(6, 4, strides=2, padding="same",
                            activation="relu"),
        keras.layers.Conv1D(4, 3, padding="valid"),
        keras.layers.Flatten(),
        keras.layers.Dense(2),
    ])
    spec, variables = from_keras(m1)
    x = np.random.default_rng(5).normal(size=(4, 16, 3)).astype(
        np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m1(x)), rtol=1e-4, atol=1e-5)

    m2 = keras.Sequential([
        keras.layers.Input((10, 10, 3)),
        keras.layers.SeparableConv2D(8, 3, padding="same",
                                     depth_multiplier=2,
                                     activation="relu"),
        keras.layers.SeparableConv2D(4, 3, strides=2),
        keras.layers.Flatten(),
        keras.layers.Dense(2),
    ])
    spec2, v2 = from_keras(m2)
    x2 = np.random.default_rng(6).normal(size=(2, 10, 10, 3)).astype(
        np.float32)
    np.testing.assert_allclose(
        np.asarray(spec2.build().apply(v2, x2)),
        np.asarray(m2(x2)), rtol=1e-4, atol=1e-4)


def test_lstm_unsupported_variants_raise():
    with pytest.raises(NotImplementedError, match="mask_zero"):
        from_keras(keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Embedding(10, 4, mask_zero=True),
            keras.layers.LSTM(3),
        ]))
    with pytest.raises(NotImplementedError, match="merge_mode"):
        from_keras(keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Embedding(10, 4),
            keras.layers.Bidirectional(keras.layers.LSTM(3),
                                       merge_mode="sum"),
        ]))
    with pytest.raises(NotImplementedError, match="recurrent_activation"):
        from_keras(keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Embedding(10, 4),
            keras.layers.LSTM(3, recurrent_activation="hard_sigmoid"),
        ]))


def test_ingested_bilstm_trains():
    spec, variables = from_keras(_keras_bilstm())
    rng = np.random.default_rng(0)
    from distkeras_tpu.data.dataset import Dataset

    data = Dataset({
        "features": rng.integers(1, 50, size=(256, 12)).astype(np.int32),
        "label": rng.integers(0, 2, size=(256,)).astype(np.int32)})
    t = SingleTrainer(spec.to_config(), worker_optimizer="adam",
                      learning_rate=5e-3, batch_size=32, num_epoch=2)
    t.train(data, initial_variables=variables)
    assert np.isfinite(t.history["epoch_loss"]).all()


def test_two_head_evaluate_model(_f32_matmuls):
    """VERDICT r4 #8: evaluate_model scores a multi-output model when
    label_col names one label column per head; a scalar label_col
    still fails loudly (never silently scores head 0)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import evaluate_model

    inp = keras.Input((8,))
    h = keras.layers.Dense(16, activation="relu")(inp)
    m = keras.Model(inp, [keras.layers.Dense(3, name="head_a")(h),
                          keras.layers.Dense(2, name="head_b")(h)])
    spec, variables = from_keras(m)
    rng = np.random.default_rng(6)
    data = Dataset({
        "features": rng.normal(size=(64, 8)).astype(np.float32),
        "label_a": rng.integers(0, 3, size=64),
        "label_b": rng.integers(0, 2, size=64),
    })
    with pytest.raises(NotImplementedError, match="label_col"):
        evaluate_model(spec, variables, data, label_col="label_a")
    got = evaluate_model(spec, variables, data,
                         label_col=["label_a", "label_b"])
    assert set(got) == {"label_a", "label_b"}
    for head in got.values():
        assert 0.0 <= head["accuracy"] <= 1.0
    # per-head numbers equal the single-head math on that head's logits
    from distkeras_tpu.evaluators import metrics_from_logits
    from distkeras_tpu.predictors import ModelPredictor

    scored = ModelPredictor(spec, variables,
                            output="logits").predict(data)
    want_a = metrics_from_logits(scored["prediction_0"],
                                 data["label_a"])
    assert got["label_a"] == want_a
    # head-count mismatch is loud
    with pytest.raises(ValueError, match="heads"):
        evaluate_model(spec, variables, data,
                       label_col=["label_a", "label_b", "label_a"])


def test_nested_functional_shared_inner_layer_in_chain(_f32_matmuls):
    """Review regression: an outer CHAIN-shaped model containing a
    nested functional submodel whose inner layer is called twice
    lowers to the sequential family (memo-less apply path) — the
    inner sharing must still create ONE flax module, not crash on a
    duplicate name."""
    inner_in = keras.Input((6,))
    shared = keras.layers.Dense(6, activation="relu", name="twice")
    inner = keras.Model(inner_in,
                        keras.layers.Add()([shared(inner_in),
                                            shared(inner_in)]))
    outer_in = keras.Input((6,))
    m = keras.Model(outer_in, keras.layers.Dense(2)(inner(outer_in)))
    spec, variables = from_keras(m)
    assert spec.to_config()["family"] == "keras_sequential"
    x = np.random.default_rng(7).normal(size=(4, 6)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-5, atol=1e-5)


def test_evaluate_model_undercounted_heads_rejected(_f32_matmuls):
    """Review regression: label_col naming FEWER heads than the model
    has must raise, never silently score the first heads."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.evaluators import evaluate_model

    inp = keras.Input((8,))
    m = keras.Model(inp, [keras.layers.Dense(3)(inp),
                          keras.layers.Dense(2)(inp)])
    spec, variables = from_keras(m)
    rng = np.random.default_rng(8)
    data = Dataset({
        "features": rng.normal(size=(32, 8)).astype(np.float32),
        "label_b": rng.integers(0, 2, size=32),
    })
    with pytest.raises(ValueError, match="heads"):
        evaluate_model(spec, variables, data, label_col=["label_b"])
    # single-head model + 1-element list works (returns per-head form)
    m1 = keras.Model(inp, keras.layers.Dense(2)(inp))
    spec1, v1 = from_keras(m1)
    got = evaluate_model(spec1, v1, data, label_col=["label_b"])
    assert set(got) == {"label_b"} and "accuracy" in got["label_b"]


@pytest.mark.parametrize("make_layer", [
    lambda: keras.layers.Conv2D(6, 3, dilation_rate=2,
                                padding="same"),
    lambda: keras.layers.Conv2D(8, 3, groups=2, padding="same"),
    lambda: keras.layers.Conv1D(6, 3, dilation_rate=2,
                                padding="same"),
    lambda: keras.layers.DepthwiseConv2D(3, depth_multiplier=2,
                                         padding="same"),
    lambda: keras.layers.DepthwiseConv2D(3, strides=2),
    lambda: keras.layers.Conv2DTranspose(5, 3, strides=2,
                                         padding="same"),
    lambda: keras.layers.Conv2DTranspose(5, 4, strides=2,
                                         padding="valid"),
], ids=["dilated2d", "grouped2d", "dilated1d", "depthwise",
        "depthwise_s2", "transpose_same", "transpose_valid"])
def test_conv_variant_parity(_f32_matmuls, make_layer):
    """VERDICT r4 Missing #6: dilated / grouped / depthwise /
    transposed convolutions ingest with exact forward parity."""
    layer = make_layer()
    shape = (7,) if "Conv1D" in type(layer).__name__ else (8, 8)
    m = keras.Sequential([keras.layers.Input((*shape, 4)), layer,
                          keras.layers.Flatten(),
                          keras.layers.Dense(3)])
    spec, variables = from_keras(m)
    x = np.random.default_rng(9).normal(
        size=(3, *shape, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(spec.build().apply(variables, x)),
        np.asarray(m(x)), rtol=1e-4, atol=1e-5)

"""Fused bottleneck Pallas kernels (ops/fused_block.py) — numerics vs
the jnp oracles, VJP correctness, and fused-vs-unfused ResNet parity
with mapped parameters.  Runs in Pallas interpreter mode off-TPU.

Matmul precision is pinned to float32 in these tests: the kernels are
bit-faithful to the *operations*, but the platform's default matmul
precision (bf16-style passes) makes kernel-vs-oracle comparisons
noisy otherwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.fused_block import (bottleneck_tail_reference,
                                           conv1x1_gn_reference,
                                           fused_bottleneck_tail,
                                           fused_conv1x1_gn)


@pytest.fixture(autouse=True)
def _f32_matmuls():
    with jax.default_matmul_precision("float32"):
        yield


def _rand(rng, shape, scale=1.0):
    return jnp.asarray(rng.normal(size=shape) * scale, jnp.float32)


def test_conv1x1_gn_forward_matches_oracle():
    rng = np.random.default_rng(0)
    x = _rand(rng, (3, 4, 4, 8))
    w = _rand(rng, (8, 16), 0.3)
    gamma = _rand(rng, (16,), 0.5) + 1.0
    beta = _rand(rng, (16,), 0.1)
    for relu in (True, False):
        y = fused_conv1x1_gn(x, w, gamma, beta, groups=4, relu=relu)
        ref = conv1x1_gn_reference(x, w, gamma, beta, groups=4,
                                   relu=relu)
        assert y.shape == (3, 4, 4, 16)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=1e-5)


def test_conv1x1_gn_vjp_matches_oracle():
    rng = np.random.default_rng(1)
    x = _rand(rng, (2, 16, 8))
    w = _rand(rng, (8, 16), 0.3)
    gamma = _rand(rng, (16,), 0.5) + 1.0
    beta = _rand(rng, (16,), 0.1)

    def loss(f):
        return lambda *a: jnp.sum(
            jnp.sin(f(*a, groups=4, relu=True)))

    gk = jax.grad(loss(fused_conv1x1_gn), argnums=(0, 1, 2, 3))(
        x, w, gamma, beta)
    gr = jax.grad(loss(conv1x1_gn_reference), argnums=(0, 1, 2, 3))(
        x, w, gamma, beta)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5)


def test_bottleneck_tail_forward_and_vjp_match_oracle():
    rng = np.random.default_rng(2)
    y2 = _rand(rng, (2, 16, 8))
    w = _rand(rng, (8, 16), 0.3)
    g2 = _rand(rng, (8,), 0.3) + 1.0
    b2 = _rand(rng, (8,), 0.1)
    g3 = _rand(rng, (16,), 0.3) + 0.5
    b3 = _rand(rng, (16,), 0.1)
    res = _rand(rng, (2, 16, 16))

    out = fused_bottleneck_tail(y2, w, g2, b2, g3, b3, res,
                                groups2=4, groups3=4)
    ref = bottleneck_tail_reference(y2, w, g2, b2, g3, b3, res,
                                    groups2=4, groups3=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)

    def loss(f):
        return lambda *a: jnp.sum(
            jnp.cos(f(*a, groups2=4, groups3=4)))

    gk = jax.grad(loss(fused_bottleneck_tail),
                  argnums=tuple(range(7)))(y2, w, g2, b2, g3, b3, res)
    gr = jax.grad(loss(bottleneck_tail_reference),
                  argnums=tuple(range(7)))(y2, w, g2, b2, g3, b3, res)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5)


def test_conv1x1_gn_bf16_inputs():
    """bf16 activations/weights (the model's compute dtype) stay close
    to the f32 oracle and produce a bf16 output."""
    rng = np.random.default_rng(3)
    x = _rand(rng, (2, 16, 8)).astype(jnp.bfloat16)
    w = _rand(rng, (8, 16), 0.3).astype(jnp.bfloat16)
    gamma = jnp.ones((16,), jnp.float32)
    beta = jnp.zeros((16,), jnp.float32)
    y = fused_conv1x1_gn(x, w, gamma, beta, groups=4)
    ref = conv1x1_gn_reference(x.astype(jnp.float32),
                               w.astype(jnp.float32), gamma, beta,
                               groups=4)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(ref), atol=0.1)


def _map_block_params(unfused: dict) -> dict:
    """Unfused BottleneckBlock param tree -> FusedBottleneckBlock's."""
    def conv(name):
        k = unfused[name]["kernel"]
        return k.reshape(k.shape[-2], k.shape[-1])

    def gn(name):
        g = unfused[name]["GroupNorm_0"]
        return g["scale"], g["bias"]

    out = {"conv1": conv("Conv_0"),
           "conv2": {"kernel": unfused["Conv_1"]["kernel"]},
           "conv3": conv("Conv_2")}
    for i, tag in ((0, "gn1"), (1, "gn2"), (2, "gn3")):
        s, b = gn(f"AdaptiveGroupNorm_{i}")
        out[f"{tag}_scale"], out[f"{tag}_bias"] = s, b
    if "Conv_3" in unfused:
        out["convd"] = conv("Conv_3")
        s, b = gn("AdaptiveGroupNorm_3")
        out["gnd_scale"], out["gnd_bias"] = s, b
    return out


@pytest.mark.parametrize("strides,cin", [((1, 1), 32), ((2, 2), 16)])
def test_fused_block_matches_unfused_block(strides, cin):
    import functools

    from distkeras_tpu.models.resnet import (AdaptiveGroupNorm,
                                             BottleneckBlock,
                                             FusedBottleneckBlock)

    rng = np.random.default_rng(4)
    x = _rand(rng, (2, 8, 8, cin))
    ref_block = BottleneckBlock(
        filters=8, strides=strides,
        norm=functools.partial(AdaptiveGroupNorm, dtype=jnp.float32),
        dtype=jnp.float32)
    fused_block = FusedBottleneckBlock(filters=8, strides=strides,
                                       dtype=jnp.float32)
    vu = ref_block.init(jax.random.key(0), x)
    # gn3 scale is zero-init (identity block): perturb every param so
    # the comparison exercises non-trivial values
    vu = jax.tree.map(
        lambda p: p + 0.05 * np.float32(1.0), vu)
    vf = {"params": _map_block_params(vu["params"])}
    yu = ref_block.apply(vu, x)
    yf = fused_block.apply(vf, x)
    np.testing.assert_allclose(np.asarray(yf), np.asarray(yu),
                               atol=2e-5)

    gu = jax.grad(lambda v: jnp.sum(jnp.sin(ref_block.apply(v, x))))(vu)
    gf = jax.grad(lambda v: jnp.sum(jnp.sin(fused_block.apply(v, x))))(vf)
    gu_m = _map_block_params(gu["params"])
    for path in ("conv1", "conv3", "gn1_scale", "gn2_scale",
                 "gn3_scale", "gn3_bias"):
        np.testing.assert_allclose(
            np.asarray(gf["params"][path]), np.asarray(gu_m[path]),
            atol=3e-5, err_msg=path)


def test_fused_resnet_end_to_end_shapes_and_grads():
    """A tiny fused ResNet trains: finite loss + grads, right shapes."""
    from distkeras_tpu.models.resnet import ResNet

    model = ResNet(num_classes=5, stage_sizes=(1, 1), width=8,
                   dtype="float32", fusion="pallas_block")
    x = jnp.asarray(np.random.default_rng(5).normal(size=(2, 16, 16, 3)),
                    jnp.float32)
    variables = model.init(jax.random.key(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 5)

    def loss(v):
        lg = model.apply(v, x)
        return -jnp.mean(jax.nn.log_softmax(lg)[:, 0])

    val, grads = jax.value_and_grad(loss)(variables)
    assert np.isfinite(float(val))
    leaves = jax.tree.leaves(grads)
    assert leaves and all(np.all(np.isfinite(np.asarray(l)))
                          for l in leaves)


def test_fused_resnet_guards():
    from distkeras_tpu.models.resnet import ResNet

    x = jnp.zeros((1, 8, 8, 3))
    with pytest.raises(ValueError, match="pallas_block"):
        ResNet(stage_sizes=(1,), bottleneck=False, width=8,
               fusion="pallas_block").init(jax.random.key(0), x)
    with pytest.raises(ValueError, match="unknown fusion"):
        ResNet(stage_sizes=(1,), width=8,
               fusion="blocked").init(jax.random.key(0), x)

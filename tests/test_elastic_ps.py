"""Elastic parameter server (``parallel.elastic_ps``) + the SLO
autoscaler (ISSUE 14): every reshard verb — split, merge, migrate —
lands byte-identical to a static-K run under a seeded schedule; a
lost-ack retry across a cutover dedupes exactly-once on whatever
shard now owns each leaf; a receiver killed mid-move aborts cleanly
(source un-fenced, zero commits lost); ``ResilientPSClient`` rides
fence/stale rejections without burning its retry budget; the
``SLOWatchdog`` hysteresis and the ``Autoscaler`` decision table
(breach → action, cooldown, bounds, idle scale-down, verb-error
capture) run against injected clocks; the gateway's elastic
membership verbs admit warm and drain safe; and the DOWNPOUR socket
arm survives a K=2→3 split plus a live migration MID-TRAINING with a
final center byte-identical to an unmolested fixed-topology run.

The whole module runs under ``racecheck.enable()`` — the migration
suite must be race-clean, not just pass."""

import importlib.util
import threading
from pathlib import Path

import jax
import numpy as np
import pytest

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.data import datasets
from distkeras_tpu.gateway import ServingGateway
from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.parallel.elastic_ps import (
    ElasticPSClient,
    ElasticPSGroup,
    MigrationAborted,
    ShardMap,
    fetch_shard_map,
)
from distkeras_tpu.parallel.host_ps import (
    HostParameterServer,
    PSShardFencedError,
    ResilientPSClient,
    pack_params,
)
from distkeras_tpu.parallel.update_rules import (
    AdagRule,
    DownpourRule,
    DynSGDRule,
    ElasticRule,
)
from distkeras_tpu.trainers import AEASGD, DOWNPOUR

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(1024, (8,), 4, seed=0)


def _init_center():
    import jax.numpy as jnp
    model = ModelSpec.from_config(MLP).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    return jax.tree_util.tree_map(np.asarray, variables["params"])

DELTA_RULES = [DownpourRule(), AdagRule(), DynSGDRule()]


@pytest.fixture(autouse=True)
def _racecheck():
    """Every lock in elastic_ps is a racecheck factory: the whole
    suite (migration included) runs instrumented and fails on any
    race/order/deadlock report."""
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


def _params(seed=0, shapes=((3, 4), (4,), (8, 2), (5,), (2, 2, 2))):
    rng = np.random.default_rng(seed)
    return {f"w{i}": rng.normal(size=s).astype(np.float32)
            for i, s in enumerate(shapes)}


def _schedule(n_workers=3, n_commits=12, seed=7):
    """A fixed seeded commit schedule: (worker, delta) pairs — seqs
    are stamped per worker by whoever replays it."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_commits):
        w = int(rng.integers(n_workers))
        d = {k: rng.normal(size=v.shape).astype(np.float32) * 1e-2
             for k, v in _params(0).items()}
        out.append((w, d))
    return out


def _elastic_clients(grp, template, n, retries=2, base_id=0):
    return [ResilientPSClient.for_elastic(
        [grp.addresses[0]], worker_id=base_id + w, template=template,
        retries=retries, backoff_base=1e-4, seed=w)
        for w in range(n)]


def _widest(grp):
    plan = grp.map.plan
    return max(range(len(plan)), key=lambda s: len(plan[s]))


# -- byte-identity of the reshard verbs --------------------------------


@pytest.mark.parametrize("rule", DELTA_RULES,
                         ids=lambda r: type(r).__name__)
def test_split_merge_migrate_byte_identical_to_static(rule):
    """ISSUE 14 acceptance: a seeded serial schedule interleaved with
    a split, a merge, AND a live migration lands on the same bytes as
    the unsharded reference — clocks and staleness law included (the
    children inherit the parent's clocks at the quiescent boundary,
    the merge re-unions them, the move ships them verbatim)."""
    center = _params(0)
    ref = HostParameterServer(rule, center)
    grp = ElasticPSGroup(rule, center, num_shards=2, num_servers=1)
    try:
        clients = _elastic_clients(grp, center, 3)
        for w in range(3):
            ref.pull(w)
            clients[w].pull()
        sched = _schedule()
        seqs = {w: 0 for w in range(3)}
        for i, (w, d) in enumerate(sched):
            if i == 4:
                grp.split(_widest(grp))          # K=2 -> 3
            elif i == 7:
                grp.merge(0, 1)                  # K=3 -> 2
            elif i == 9:
                dst = grp.add_server()
                grp.migrate(_widest(grp), dst)   # cross-server move
            ref.commit(w, d, seq=seqs[w])
            seqs[w] += 1
            clients[w].commit(d)
        assert pack_params(ref.center) == pack_params(grp.center)
        assert grp.num_commits == len(sched)
        for c in clients:
            c.close()
    finally:
        grp.stop()


def test_elastic_family_byte_identical_across_reshard():
    """The elastic family (whole-local-tree lerp, ``local=`` riding
    the wire) reshards exactly too: split + migrate mid-schedule, the
    center AND every worker's pulled local tree match the unsharded
    reference byte for byte."""
    rule = ElasticRule(alpha=0.3)
    center = _params(0)
    ref = HostParameterServer(rule, center)
    grp = ElasticPSGroup(rule, center, num_shards=2, num_servers=1)
    try:
        clients = _elastic_clients(grp, center, 2, base_id=10)
        locals_ref = {w: ref.pull(w) for w in range(2)}
        locals_el = {w: clients[w].pull() for w in range(2)}
        rng = np.random.default_rng(3)
        for i in range(8):
            if i == 3:
                grp.split(_widest(grp))
            elif i == 6:
                grp.migrate(0, grp.add_server())
            w = int(rng.integers(2))
            step = jax.tree_util.tree_map(
                lambda x: np.asarray(
                    x + rng.normal(size=x.shape).astype(x.dtype)
                    * 0.1), locals_ref[w])
            locals_ref[w] = ref.commit(w, step, step, seq=i)
            locals_el[w] = clients[w].commit(step, step)
        assert pack_params(ref.center) == pack_params(grp.center)
        for w in range(2):
            assert (pack_params(locals_ref[w])
                    == pack_params(locals_el[w]))
        for c in clients:
            c.close()
    finally:
        grp.stop()


# -- exactly-once across the cutover -----------------------------------


def test_lost_ack_retry_dedupes_across_cutover():
    """The lost-ack shape, aggravated: commit seq=N acks, the ack is
    'lost', the shard MIGRATES to a brand-new server, and the retry
    of seq=N against the new owner serves the cached reply byte-for-
    byte without applying twice (the per-leaf dedupe table travelled
    with the move)."""
    tel = telemetry.enable()
    try:
        center = _params(0)
        grp = ElasticPSGroup(AdagRule(), center, num_shards=2,
                             num_servers=1)
        try:
            c = ElasticPSClient(grp.addresses, worker_id=0,
                                template=center)
            c.pull()
            d = jax.tree_util.tree_map(np.ones_like, center)
            r1 = c.commit(d, seq=0)
            assert grp.num_commits == 1
            dst = grp.add_server()
            grp.migrate(0, dst)
            # the client still routes via the old map: the retired
            # source rejects carrying the NEW map — adopt and go again
            with pytest.raises(PSShardFencedError) as exc:
                c.commit(d, seq=0)
            assert exc.value.map_obj is not None
            c.apply_shard_map(exc.value.map_obj)
            r2 = c.commit(d, seq=0)  # the retry, on the new owner
            assert grp.num_commits == 1  # never applied twice
            for k in center:
                np.testing.assert_array_equal(r1[k], r2[k])
            assert tel.metrics.counter(
                "ps_commit_dedup_total").value >= 1
            c.commit(d, seq=1)  # a FRESH seq still applies
            assert grp.num_commits == 2
            c.close()
        finally:
            grp.stop()
    finally:
        telemetry.disable()


def test_fence_refresh_spares_the_retry_budget():
    """A reshard under a live ``ResilientPSClient`` costs map
    refreshes (``ps_shard_fence_refresh_total``), never transport
    retries: with retries=0 the client sails through a split AND a
    migration."""
    tel = telemetry.enable()
    try:
        center = _params(0)
        grp = ElasticPSGroup(DownpourRule(), center, num_shards=2,
                             num_servers=1)
        try:
            c = ResilientPSClient.for_elastic(
                grp.addresses, worker_id=0, template=center,
                retries=0)
            c.pull()
            d = jax.tree_util.tree_map(np.ones_like, center)
            c.commit(d)
            grp.split(_widest(grp))
            c.commit(d)
            grp.migrate(0, grp.add_server())
            c.commit(d)
            assert grp.num_commits == 3
            assert c.retry_count == 0
            assert tel.metrics.counter(
                "ps_shard_fence_refresh_total").value >= 1
            assert tel.metrics.counter(
                "ps_map_refresh_total").value >= 2
            c.close()
        finally:
            grp.stop()
    finally:
        telemetry.disable()


def test_receiver_kill_aborts_migration_cleanly(tmp_path):
    """Chaos acceptance: the RECEIVING server dies mid-move — cutover
    raises ``MigrationAborted``, the source un-fences and keeps
    serving, zero commits lost, and the abort is flight-recorded."""
    tel = telemetry.enable()
    flight_recorder.start(str(tmp_path / "flight"))
    try:
        center = _params(0)
        grp = ElasticPSGroup(AdagRule(), center, num_shards=2,
                             num_servers=1)
        try:
            c = ResilientPSClient.for_elastic(
                grp.addresses, worker_id=0, template=center,
                retries=2, backoff_base=1e-4)
            c.pull()
            d = jax.tree_util.tree_map(np.ones_like, center)
            for _ in range(3):
                c.commit(d)
            doomed = grp.add_server()
            grp.start_migration(0, doomed)
            grp.servers[doomed].kill()
            # the nastiest timing: the courier already streamed
            # everything and went QUIET before the kill, so drain
            # alone would pass — only the finalize round-trip can
            # notice the corpse before the map flips onto it
            with pytest.raises(MigrationAborted):
                grp.cutover(0, timeout=10.0)
            assert tel.metrics.counter(
                "elastic_migrations_aborted_total").value == 1
            # old topology still serves: same owner, commits land
            assert grp.map.version == 1
            for _ in range(2):
                c.commit(d)
            assert grp.num_commits == 5  # commits lost == 0
            stats = grp.shard_stats()
            assert not any(s["fenced"] for s in stats.values())
            kinds = [e["kind"] for e in
                     flight_recorder.active().read_events()]
            assert "shard_migrate_begin" in kinds
            assert "shard_migrate_abort" in kinds
            assert "shard_migrate_cutover" not in kinds
            c.close()
        finally:
            grp.stop()
    finally:
        flight_recorder.stop()
        telemetry.disable()


def test_migration_under_concurrent_load_exactly_once():
    """The race-clean migration suite: worker threads hammer commits
    while the control plane splits and live-migrates under them —
    every logical commit lands exactly once (the racecheck fixture
    holds the suite to race-free, not merely passing)."""
    center = _params(0)
    grp = ElasticPSGroup(AdagRule(), center, num_shards=2,
                         num_servers=2, placement="spread")
    n_workers, n_commits = 3, 8
    try:
        passed = threading.Barrier(n_workers + 1)
        errors: list = []

        def run(w):
            try:
                c = ResilientPSClient.for_elastic(
                    grp.addresses, worker_id=100 + w,
                    template=center, retries=4, backoff_base=1e-4,
                    seed=w)
                c.pull()
                rng = np.random.default_rng(w)
                passed.wait(timeout=30)
                for _ in range(n_commits):
                    d = {k: rng.normal(size=v.shape).astype(
                        np.float32) * 1e-3
                        for k, v in center.items()}
                    c.commit(d)
                c.close()
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=run, args=(w,))
                   for w in range(n_workers)]
        for t in threads:
            t.start()
        passed.wait(timeout=30)
        grp.split(_widest(grp))
        dst = grp.add_server()
        grp.migrate(0, dst)
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert grp.num_commits == n_workers * n_commits
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree_util.tree_leaves(grp.center))
    finally:
        grp.stop()


# -- the versioned map & control-plane edges ---------------------------


def test_shard_map_roundtrip_and_canonical_ids():
    m = ShardMap(3, [[2, 5], [0, 1]], [("a", 1), ("b", 2)], [0, 7])
    m2 = ShardMap.from_obj(m.to_obj())
    assert (m2.version, m2.plan, m2.owners, m2.epochs) == \
        (3, [[2, 5], [0, 1]], [("a", 1), ("b", 2)], [0, 7])
    with pytest.raises(ValueError, match="arity"):
        ShardMap(1, [[0]], [("a", 1)], [0, 1])
    # group-side renumbering law: ids sort by first leaf index
    grp = ElasticPSGroup(AdagRule(), _params(0), num_shards=3)
    try:
        firsts = [p[0] for p in grp.map.plan]
        assert firsts == sorted(firsts)
        grp.split(_widest(grp))
        firsts = [p[0] for p in grp.map.plan]
        assert firsts == sorted(firsts)
        assert grp.map.version == 2
        fetched = fetch_shard_map(*grp.addresses[0])
        assert fetched.to_obj() == grp.map.to_obj()
    finally:
        grp.stop()


def test_reshard_verb_validation():
    grp = ElasticPSGroup(AdagRule(), _params(0), num_shards=2,
                         num_servers=2, placement="spread")
    try:
        one_leaf = min(range(grp.num_shards),
                       key=lambda s: len(grp.map.plan[s]))
        if len(grp.map.plan[one_leaf]) == 1:
            with pytest.raises(ValueError, match="cannot split"):
                grp.split(one_leaf)
        with pytest.raises(ValueError, match="itself"):
            grp.merge(0, 0)
        with pytest.raises(ValueError, match="different"):
            grp.merge(0, 1)  # spread placement: distinct owners
        with pytest.raises(ValueError, match="already lives"):
            grp.migrate(0, 0)
        with pytest.raises(ValueError, match="no migration"):
            grp.cutover(0)
        dst = grp.add_server()
        grp.start_migration(0, dst)
        with pytest.raises(ValueError, match="already migrating"):
            grp.start_migration(0, dst)
        grp.cutover(0, timeout=10.0)
    finally:
        grp.stop()


# -- SLO watchdog hysteresis -------------------------------------------


def _depth_watchdog(tel, sustain):
    tel.metrics.gauge("serving_queue_depth").set(0)
    return telemetry.SLOWatchdog(
        tel.metrics, thresholds={"queue_depth": (8.0, 1e9)},
        sustain_secs=sustain)


def test_watchdog_sustain_holds_both_directions():
    """A transition (breach AND recovery) must hold for
    ``sustain_secs`` across evaluations before it commits; a single
    noisy sample flips nothing."""
    tel = telemetry.enable()
    try:
        wd = _depth_watchdog(tel, sustain=5.0)
        depth = tel.metrics.gauge("serving_queue_depth")
        assert wd.evaluate(now_s=0.0)["state"] == "ok"
        depth.set(20)
        v = wd.evaluate(now_s=1.0)   # arms the window
        assert (v["state"], v["raw_state"]) == ("ok", "degraded")
        assert wd.evaluate(now_s=4.0)["state"] == "ok"
        assert wd.evaluate(now_s=6.5)["state"] == "degraded"
        depth.set(0)                 # recovery is held too
        assert wd.evaluate(now_s=7.0)["state"] == "degraded"
        assert wd.evaluate(now_s=11.0)["state"] == "degraded"
        assert wd.evaluate(now_s=12.1)["state"] == "ok"
    finally:
        telemetry.disable()


def test_watchdog_noisy_sample_rearms_the_window():
    """A candidate that vanishes before its window elapses disarms;
    re-appearing restarts the clock from the new sighting."""
    tel = telemetry.enable()
    try:
        wd = _depth_watchdog(tel, sustain=5.0)
        depth = tel.metrics.gauge("serving_queue_depth")
        depth.set(20)
        wd.evaluate(now_s=0.0)       # pending degraded since t=0
        depth.set(0)
        assert wd.evaluate(now_s=1.0)["state"] == "ok"  # disarmed
        depth.set(20)
        wd.evaluate(now_s=2.0)       # re-armed at t=2
        assert wd.evaluate(now_s=6.9)["state"] == "ok"
        assert wd.evaluate(now_s=7.1)["state"] == "degraded"
    finally:
        telemetry.disable()


def test_watchdog_default_edge_trigger_and_validation():
    tel = telemetry.enable()
    try:
        wd = _depth_watchdog(tel, sustain=0.0)
        tel.metrics.gauge("serving_queue_depth").set(20)
        assert wd.evaluate(now_s=0.0)["state"] == "degraded"
        tel.metrics.gauge("serving_queue_depth").set(0)
        assert wd.evaluate(now_s=0.1)["state"] == "ok"
        with pytest.raises(ValueError, match="unknown SLO signal"):
            telemetry.SLOWatchdog(tel.metrics,
                                  thresholds={"nope": (1, 2)})
        with pytest.raises(ValueError, match="must not exceed"):
            telemetry.SLOWatchdog(tel.metrics,
                                  thresholds={"queue_depth": (9, 3)})
        with pytest.raises(ValueError, match="sustain_secs"):
            telemetry.SLOWatchdog(tel.metrics, sustain_secs=-1)
    finally:
        telemetry.disable()


# -- the autoscaler decision table -------------------------------------


def _breach(signal, value=0.5, level="critical"):
    return {"state": level, "raw_state": level,
            "signals": {signal: value},
            "breaches": {signal: {"value": value, "level": level,
                                  "degraded_at": 0.0,
                                  "critical_at": 0.1}}}


_QUIET = {"state": "ok", "raw_state": "ok", "signals": {},
          "breaches": {}}


def _scaler(tel, **kw):
    wd = telemetry.SLOWatchdog(tel.metrics)
    kw.setdefault("cooldown_s", 30.0)
    kw.setdefault("idle_sustain_s", 60.0)
    return telemetry.Autoscaler(wd, **kw)


def test_autoscaler_breach_to_action_and_bounds():
    tel = telemetry.enable()
    try:
        k = {"n": 2}
        sc = _scaler(tel, split_shard=lambda: None,
                     shard_count=lambda: k["n"], max_shards=4)
        d, = sc.decide(_breach("ps_lock_wait"), now_s=0.0)
        assert (d["domain"], d["action"], d["executed"]) == \
            ("ps", "split", True)
        assert d["signal"] == "ps_lock_wait" and d["reason"] is None
        k["n"] = 4  # at the bound: suppressed, reason says so
        d, = sc.decide(_breach("ps_lock_wait"), now_s=0.0)
        assert not d["executed"] and d["reason"] == "bounds"
        # a breach outside the domain's signal set decides nothing
        assert sc.decide(_breach("shed_rate"), now_s=0.0) == []
    finally:
        telemetry.disable()


def test_autoscaler_cooldown_suppresses_then_releases():
    tel = telemetry.enable()
    try:
        calls = []
        sc = _scaler(tel, split_shard=lambda: calls.append("s"),
                     shard_count=lambda: 1 + len(calls))
        d, = sc.step(_breach("ps_lock_wait"), now_s=0.0)
        assert d["executed"] and calls == ["s"]
        d, = sc.step(_breach("ps_lock_wait"), now_s=10.0)
        assert not d["executed"] and d["reason"] == "cooldown"
        assert calls == ["s"]
        d, = sc.step(_breach("ps_lock_wait"), now_s=31.0)
        assert d["executed"] and calls == ["s", "s"]
    finally:
        telemetry.disable()


def test_autoscaler_idle_scales_down_after_sustain():
    tel = telemetry.enable()
    try:
        merges = []
        sc = _scaler(tel, split_shard=lambda: None,
                     merge_shards=lambda: merges.append(1),
                     shard_count=lambda: 3, min_shards=1,
                     cooldown_s=0.0)
        sc.step(_QUIET, now_s=0.0)   # seeds the idle clock
        assert sc.decide(_QUIET, now_s=30.0) == []
        d, = sc.step(_QUIET, now_s=61.0)
        assert (d["action"], d["executed"]) == ("merge", True)
        assert merges == [1]
        # a breach resets the idle clock
        sc.step(_breach("ps_lock_wait"), now_s=62.0)
        assert sc.decide(_QUIET, now_s=100.0) == []
    finally:
        telemetry.disable()


def test_autoscaler_gateway_domain_and_verb_error(tmp_path):
    """The gateway domain spawns on queue-depth breach; a verb that
    raises is captured as ``reason="error: ..."`` — recorded, never
    fatal — and every decision lands in the counter + flight ring."""
    tel = telemetry.enable()
    flight_recorder.start(str(tmp_path / "flight"))
    try:
        def boom():
            raise RuntimeError("no capacity")

        sc = _scaler(tel, spawn_replica=boom,
                     replica_count=lambda: 1, max_replicas=3)
        d, = sc.step(_breach("queue_depth", value=300.0), now_s=0.0)
        assert (d["domain"], d["action"]) == ("gateway", "spawn")
        assert not d["executed"]
        assert d["reason"].startswith("error:")
        assert tel.metrics.counter(
            "autoscale_decisions_total", domain="gateway",
            action="spawn").value == 1
        ev = [e for e in flight_recorder.active().read_events()
              if e["kind"] == "autoscale_decision"]
        assert len(ev) == 1 and ev[0]["reason"].startswith("error:")
    finally:
        flight_recorder.stop()
        telemetry.disable()


def test_autoscaler_constructor_validation():
    tel = telemetry.enable()
    try:
        wd = telemetry.SLOWatchdog(tel.metrics)
        with pytest.raises(ValueError, match="come as a pair"):
            telemetry.Autoscaler(wd, split_shard=lambda: None)
        with pytest.raises(ValueError, match="come as a pair"):
            telemetry.Autoscaler(wd, spawn_replica=lambda: None)
        with pytest.raises(ValueError, match="unknown SLO signal"):
            telemetry.Autoscaler(wd, ps_scale_signals=("bogus",))
    finally:
        telemetry.disable()


# -- gateway elastic membership ----------------------------------------


class _FakeServingReplica:
    def __init__(self, name, value=0.0):
        self.name = name
        self.alive = True
        self._vars = {"params": {"w": np.full(
            (2,), value, np.float32)}}
        self.swapped = None
        self.quiesced = False
        self.dispatched: list = []

    def start(self):
        return self

    def load(self):
        return 0

    def dispatch(self, spec, on_result):
        self.dispatched.append(spec["request_id"])
        on_result({"request_id": spec["request_id"],
                   "prompt": spec["prompt"],
                   "tokens": np.asarray([1], np.int32)})

    def health(self):
        return {"alive": self.alive, "state": "ok", "load": 0}

    def variables(self):
        return self._vars

    def swap(self, v):
        self.swapped = v
        self._vars = v

    def quiesce(self, timeout):
        self.quiesced = True
        return True


def test_gateway_add_replica_warms_from_live_peer(tmp_path):
    flight_recorder.start(str(tmp_path / "flight"))
    try:
        a = _FakeServingReplica("a", value=7.0)
        with ServingGateway([a], policy="round_robin") as gw:
            b = _FakeServingReplica("b", value=0.0)
            gw.add_replica(b)
            # admitted warm: the newcomer carries the fleet's weights
            np.testing.assert_array_equal(
                b.swapped["params"]["w"], a._vars["params"]["w"])
            assert gw.healthz()["replicas"]["b"]["alive"]
            for r in [gw.submit([1, 2]) for _ in range(4)]:
                gw.result(r, timeout=5)
            assert b.dispatched  # it takes traffic
            with pytest.raises(ValueError, match="already"):
                gw.add_replica(_FakeServingReplica("b"))
        kinds = [e["kind"] for e in
                 flight_recorder.active().read_events()]
        assert "replica_add" in kinds
    finally:
        flight_recorder.stop()


def test_gateway_remove_replica_quiesces_and_guards(tmp_path):
    flight_recorder.start(str(tmp_path / "flight"))
    try:
        a = _FakeServingReplica("a")
        b = _FakeServingReplica("b")
        with ServingGateway([a, b], policy="round_robin") as gw:
            gone = gw.remove_replica("b")
            assert gone is b and b.quiesced
            assert "b" not in gw.healthz()["replicas"]
            with pytest.raises(ValueError, match="no replica"):
                gw.remove_replica("b")
            with pytest.raises(ValueError, match="last routable"):
                gw.remove_replica("a")
            gw.result(gw.submit([1]), timeout=5)  # still serving
        kinds = [e["kind"] for e in
                 flight_recorder.active().read_events()]
        assert "replica_drain" in kinds
    finally:
        flight_recorder.stop()


# -- the scaling story -------------------------------------------------


def test_postmortem_scaling_story_replays_in_order():
    pm_path = (Path(__file__).resolve().parent.parent
               / "scripts" / "postmortem.py")
    spec = importlib.util.spec_from_file_location("_dkt_pm_el",
                                                  pm_path)
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    events = [
        {"kind": "shard_migrate_cutover", "wall_s": 30.0, "shard": 0,
         "dst": ["h", 2], "epoch": 17, "version": 3,
         "latency_s": 0.004},
        {"kind": "autoscale_decision", "wall_s": 10.0, "domain": "ps",
         "action": "split", "signal": "ps_lock_wait", "value": 0.02,
         "count": 1, "executed": True, "reason": None},
        {"kind": "commit", "wall_s": 11.0, "worker": 0},  # filtered
        {"kind": "shard_split", "wall_s": 12.0, "shard": 1, "at": 2,
         "version": 2, "sizes": [2, 2]},
        {"kind": "autoscale_decision", "wall_s": 40.0,
         "domain": "gateway", "action": "spawn",
         "signal": "queue_depth", "value": 12.0, "count": 2,
         "executed": False, "reason": "cooldown"},
        {"kind": "replica_add", "wall_s": 50.0, "replica": "auto0",
         "total": 2},
    ]
    story = pm.scaling_story(events)
    assert [e["wall_s"] for e in story] == [10.0, 12.0, 30.0, 40.0,
                                            50.0]
    texts = [e["what"] for e in story]
    assert "ps: split on ps_lock_wait=0.02 executed" in texts[0]
    assert "split at leaf 2" in texts[1] and "v2" in texts[1]
    assert "cut over" in texts[2] and "epoch 17" in texts[2]
    assert "suppressed (cooldown)" in texts[3]
    assert "replica auto0 admitted (fleet now 2)" in texts[4]


# -- trainer-level zero-downtime proof ---------------------------------


def _wait_commits(grp, n, deadline_s=60.0, stop=None):
    t0 = telemetry.now()
    while grp.num_commits < n:
        if stop is not None and stop.is_set():
            return False
        if telemetry.now() - t0 > deadline_s:
            raise TimeoutError(
                f"stuck at {grp.num_commits}/{n} commits")
        import time
        time.sleep(0.002)
    return True


def _downpour(grp, **kw):
    return DOWNPOUR(MLP, fidelity="host", transport="socket",
                    num_workers=1, communication_window=2,
                    batch_size=16, num_epoch=1, learning_rate=0.01,
                    seed=0, worker_retries=10, ps_elastic=True,
                    ps_address=grp.addresses[0], **kw)


def test_trainer_mid_training_reshard_byte_identical():
    """The tentpole acceptance, end to end on the socket arm: a
    K=2→3 split and a live cross-server migration land MID-TRAINING
    under a single-worker DOWNPOUR run, and the final center is
    byte-identical to the same run against an unmolested fixed-K
    group (additive rule + inherited clocks = the reshard is
    invisible to the math)."""
    center = _init_center()
    ref_grp = ElasticPSGroup(DownpourRule(), center, num_shards=2,
                             num_servers=1)
    dut_grp = ElasticPSGroup(DownpourRule(), center, num_shards=2,
                             num_servers=1)
    try:
        ops = {}
        done = threading.Event()

        def reshard():
            if not _wait_commits(dut_grp, 2, stop=done):
                return
            ops["at_split"] = dut_grp.num_commits
            dut_grp.split(_widest(dut_grp))
            if not _wait_commits(dut_grp, 5, stop=done):
                return
            dst = dut_grp.add_server()
            dut_grp.migrate(_widest(dut_grp), dst)
            ops["migrated"] = True

        driver = threading.Thread(target=reshard)
        driver.start()
        try:
            dut = _downpour(dut_grp)
            dut.train(DATA)
        finally:
            done.set()
            driver.join(timeout=60)
        assert ops.get("migrated"), (
            "the reshard thread never completed its migration")
        ref = _downpour(ref_grp)
        ref.train(DATA)
        rounds = len(ref.history["round_loss"])
        assert ops["at_split"] < rounds  # genuinely mid-training
        assert ref_grp.num_commits == dut_grp.num_commits == rounds
        assert dut_grp.num_shards == 3
        assert pack_params(ref_grp.center) == \
            pack_params(dut_grp.center)
        assert pack_params(ref.trained_variables["params"]) == \
            pack_params(dut.trained_variables["params"])
    finally:
        ref_grp.stop()
        dut_grp.stop()


def test_aeasgd_trains_against_elastic_group_k2():
    """The elastic FAMILY (whole-tree lerp) over the elastic WIRE at
    K=2 — the composition the pre-ISSUE-14 gate forbade twice over —
    trains to a finite loss against an external group."""
    center = _init_center()
    grp = ElasticPSGroup(ElasticRule(alpha=0.5), center,
                         num_shards=2, num_servers=2,
                         placement="spread")
    try:
        t = AEASGD(MLP, fidelity="host", transport="socket",
                   num_workers=2, communication_window=2,
                   batch_size=16, num_epoch=1, seed=0,
                   worker_retries=6, ps_elastic=True,
                   ps_address=grp.addresses[0])
        t.train(DATA)
        assert np.isfinite(t.history["round_loss"][-1])
        assert grp.num_commits == len(t.history["round_loss"])
    finally:
        grp.stop()


def test_autoscaler_defers_while_the_gateway_is_busy():
    """ISSUE 18 fix: while a rolling update / migration is in flight
    (``busy()`` truthy) the autoscaler records its decision but defers
    the verb — and deferral costs one tick, NOT a cooldown window, so
    the very next quiet-gateway tick executes."""
    tel = telemetry.enable()
    try:
        calls = []
        busy = {"v": True}
        sc = _scaler(tel, spawn_replica=lambda: calls.append(1),
                     replica_count=lambda: 1 + len(calls),
                     max_replicas=4, busy=lambda: busy["v"])
        d, = sc.step(_breach("queue_depth", value=300.0), now_s=0.0)
        assert not d["executed"]
        assert d["reason"] == "deferred: busy" and calls == []
        assert tel.metrics.counter("autoscale_deferred_total",
                                   domain="gateway").value == 1
        # cooldown_s is 30 here: if the deferral had counted as an
        # action, this tick would report "cooldown" instead of acting
        busy["v"] = False
        d, = sc.step(_breach("queue_depth", value=300.0), now_s=1.0)
        assert d["executed"] and calls == [1]
        # quiesced gateway: the guard never fires on empty decisions
        assert sc.step(_QUIET, now_s=2.0) == []
        assert tel.metrics.counter("autoscale_deferred_total",
                                   domain="gateway").value == 1
    finally:
        telemetry.disable()

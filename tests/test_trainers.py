"""Trainer family: convergence smoke tests on learnable synthetic data,
faithful-vs-fast fidelity equivalence, staleness telemetry, mesh placement
(SURVEY.md §4: the rebuild's analogue of the reference's MNIST-notebook
integration tests, run on the 8-virtual-device CPU mesh)."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    AveragingTrainer,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
    SyncTrainer,
)

MLP = model_config("mlp", (8,), num_classes=4, hidden=(32,))
DATA = datasets.synthetic_classification(2048, (8,), 4, seed=0)


def _first_last(history_key, trainer):
    h = trainer.history[history_key]
    return h[0], h[-1]


def test_single_trainer_converges():
    t = SingleTrainer(MLP, worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=64, num_epoch=3)
    variables = t.train(DATA)
    first, last = _first_last("epoch_loss", t)
    assert last < first * 0.7, t.history
    assert t.training_time > 0
    assert "params" in variables


def test_sync_trainer_uses_mesh_and_converges(devices):
    t = SyncTrainer(MLP, num_workers=8, worker_optimizer="adam",
                    learning_rate=3e-3, batch_size=16, num_epoch=3)
    t.train(DATA)
    first, last = _first_last("epoch_loss", t)
    assert last < first * 0.7, t.history
    assert t.num_workers == 8


@pytest.mark.parametrize("cls", [DOWNPOUR, ADAG, DynSGD, AEASGD, EAMSGD])
@pytest.mark.parametrize("fidelity", ["faithful", "fast"])
def test_async_family_converges(cls, fidelity):
    kwargs = dict(num_workers=4, communication_window=4, batch_size=32,
                  num_epoch=3, learning_rate=0.05, fidelity=fidelity)
    if cls in (AEASGD, EAMSGD):
        kwargs["rho"] = 5.0
        kwargs["learning_rate"] = 0.02
    t = cls(MLP, **kwargs)
    t.train(DATA)
    losses = t.history["round_loss"]
    assert losses[-1] < losses[0] * 0.8, (cls.__name__, losses[:3],
                                          losses[-3:])
    # staleness telemetry: every round records a permutation of 0..W-1
    stal = np.asarray(t.history["staleness"])
    assert stal.shape[1] == 4
    assert np.array_equal(np.sort(stal[0]), np.arange(4))


def test_faithful_and_fast_center_match_for_linear_rules():
    """One round of DOWNPOUR: the fast path's center must equal the
    faithful path's exactly (the sum of deltas is order-free)."""
    results = {}
    for fidelity in ("faithful", "fast"):
        t = DOWNPOUR(MLP, num_workers=4, communication_window=2,
                     batch_size=32, num_epoch=1, learning_rate=0.05,
                     fidelity=fidelity, seed=3)
        # limit to exactly one round of data
        sub = DATA.take(4 * 2 * 32)
        t.train(sub)
        results[fidelity] = jax.device_get(
            t.trained_variables["params"])
    flat_a = jax.tree_util.tree_leaves(results["faithful"])
    flat_b = jax.tree_util.tree_leaves(results["fast"])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_elastic_fast_faithful_gap_bounded():
    """Quantify the elastic fast-vs-faithful gap (VERDICT.md round-1 Weak
    #3: the fast path is exact-in-expectation only for the elastic family
    — only pull timing differs).  On identical data/seed the trained
    parameters must agree within a small relative L2 bound, and both must
    converge."""
    results = {}
    for fidelity in ("faithful", "fast"):
        t = AEASGD(MLP, num_workers=4, communication_window=2,
                   batch_size=32, num_epoch=2, rho=2.5,
                   learning_rate=0.02, fidelity=fidelity, seed=5)
        t.train(DATA.take(1024))
        results[fidelity] = t
    for t in results.values():
        losses = t.history["round_loss"]
        assert losses[-1] < losses[0], losses
    fa = jax.tree_util.tree_leaves(
        results["faithful"].trained_variables["params"])
    fb = jax.tree_util.tree_leaves(
        results["fast"].trained_variables["params"])
    num = np.sqrt(sum(float(np.sum((a - b) ** 2))
                      for a, b in zip(fa, fb)))
    den = np.sqrt(sum(float(np.sum(np.square(a))) for a in fa))
    rel_gap = num / den
    # pull-timing skew is O(alpha) per round; empirically ~1e-2 here
    assert rel_gap < 0.05, rel_gap


def test_dynsgd_staleness_scaling_changes_result():
    """DynSGD must differ from DOWNPOUR on identical data/seed (staleness
    scaling is real)."""
    common = dict(num_workers=4, communication_window=2, batch_size=32,
                  num_epoch=1, learning_rate=0.05, seed=0)
    a = DOWNPOUR(MLP, **common)
    b = DynSGD(MLP, **common)
    a.train(DATA.take(1024))
    b.train(DATA.take(1024))
    la = jax.tree_util.tree_leaves(a.trained_variables["params"])
    lb = jax.tree_util.tree_leaves(b.trained_variables["params"])
    assert any(not np.allclose(x, y) for x, y in zip(la, lb))


def test_ensemble_trainer_returns_list():
    t = EnsembleTrainer(MLP, num_models=2, worker_optimizer="adam",
                        learning_rate=3e-3, batch_size=32, num_epoch=1)
    models = t.train(DATA)
    assert isinstance(models, list) and len(models) == 2
    la = jax.tree_util.tree_leaves(models[0]["params"])
    lb = jax.tree_util.tree_leaves(models[1]["params"])
    assert any(not np.allclose(x, y) for x, y in zip(la, lb))


def test_averaging_trainer_averages():
    t = AveragingTrainer(MLP, num_workers=2, worker_optimizer="adam",
                         learning_rate=3e-3, batch_size=32, num_epoch=1)
    variables = t.train(DATA)
    assert "params" in variables


def test_async_trainer_with_dropout_model():
    """Dropout rngs flow per worker (distinct streams)."""
    cfg = model_config("mlp", (8,), num_classes=4, hidden=(32,),
                       dropout_rate=0.3)
    t = ADAG(cfg, num_workers=2, communication_window=2, batch_size=32,
             num_epoch=1, learning_rate=0.05)
    t.train(DATA.take(512))
    assert len(t.history["round_loss"]) >= 1


def test_errors_on_tiny_dataset():
    t = ADAG(MLP, num_workers=4, communication_window=8, batch_size=64,
             num_epoch=1)
    with pytest.raises(ValueError):
        t.train(DATA.take(128))


def test_member_parallel_ensemble_on_mesh():
    """Members train concurrently inside one vmapped program sharded
    over the 8-device mesh (round-1 ran them sequentially)."""
    t = EnsembleTrainer(MLP, num_models=8, worker_optimizer="adam",
                        learning_rate=5e-3, batch_size=16, num_epoch=2)
    models = t.train(DATA)
    assert len(models) == 8
    assert len(t.history["member_loss"][-1]) == 8
    first, last = t.history["epoch_loss"][0], t.history["epoch_loss"][-1]
    assert last < first, t.history["epoch_loss"]
    # distinct inits -> distinct members
    la = jax.tree_util.tree_leaves(models[0]["params"])
    lb = jax.tree_util.tree_leaves(models[7]["params"])
    assert any(not np.allclose(x, y) for x, y in zip(la, lb))


def test_learning_rate_schedules():
    from distkeras_tpu.workers import resolve_schedule

    sched = resolve_schedule({"schedule": "cosine", "init_value": 0.1,
                              "decay_steps": 10})
    assert abs(float(sched(0)) - 0.1) < 1e-7
    assert float(sched(10)) < 1e-7
    with pytest.raises(KeyError):
        resolve_schedule({"schedule": "nope"})

    # end-to-end: a dict schedule through a trainer converges
    data = datasets.synthetic_classification(512, (8,), 4, seed=0)
    cfg = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    t = SingleTrainer(cfg, worker_optimizer="momentum", batch_size=32,
                      num_epoch=3,
                      learning_rate={"schedule": "warmup_cosine",
                                     "init_value": 0.0,
                                     "peak_value": 0.1,
                                     "warmup_steps": 8,
                                     "decay_steps": 48})
    t.train(data)
    losses = t.history["epoch_loss"]
    assert losses[-1] < losses[0], losses

    # the elastic family needs a scalar lr for alpha = lr * rho
    with pytest.raises(ValueError, match="scalar learning_rate"):
        AEASGD(cfg, num_workers=2,
               learning_rate={"schedule": "cosine", "init_value": 0.1,
                              "decay_steps": 10}).allocate_rule()


def test_numpy_scalar_learning_rate_passes_through():
    from distkeras_tpu.workers import resolve_optimizer, resolve_schedule
    import jax.numpy as jnp

    assert resolve_schedule(np.float32(1e-3)) == np.float32(1e-3)
    resolve_optimizer("adam", np.float32(1e-3))
    resolve_optimizer("sgd", jnp.asarray(1e-2))  # 0-d array scalar
    t = AEASGD(MLP, num_workers=2, learning_rate=np.float32(0.01))
    assert abs(t.alpha - 0.05) < 1e-7  # rho=5.0 default


def test_profile_dir_writes_trace(tmp_path):
    data = datasets.synthetic_classification(128, (8,), 4, seed=0)
    t = SingleTrainer(MLP, batch_size=32, num_epoch=1,
                      learning_rate=0.05, profile_dir=str(tmp_path))
    t.train(data)
    profiles = list(tmp_path.rglob("*.xplane.pb"))
    assert profiles, list(tmp_path.rglob("*"))


def test_lr_law_guardrail():
    """VERDICT r4 #7: the measured per-family lr laws (PARITY.md) are
    enforced by the library, not just documented — a config whose
    effective per-round lr exceeds the measured stability scale warns
    (with the law and the fix), lr_law='scale' applies the law, and
    lr_law='off' silences it."""
    import warnings

    cfg = model_config("mlp", (4,), num_classes=2, hidden=(4,))

    def caught(make):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            make()
        return [str(x.message) for x in w
                if issubclass(x.category, UserWarning)]

    # DOWNPOUR at the PARITY collapse config (W*w = 16, lr 0.05) warns
    msgs = caught(lambda: DOWNPOUR(
        cfg, num_workers=4, communication_window=4,
        learning_rate=0.05))
    assert len(msgs) == 1 and "num_workers * communication_window" \
        in msgs[0], msgs
    # every family's law names its own factor
    assert "num_workers" in caught(lambda: ADAG(
        cfg, num_workers=8, learning_rate=0.05))[0]
    assert "communication_window" in caught(lambda: DynSGD(
        cfg, communication_window=8, learning_rate=0.05))[0]
    assert "momentum" in caught(lambda: EAMSGD(
        cfg, num_workers=2, learning_rate=0.05))[0]
    # the elastic exchange is lr-neutral (measured): AEASGD never warns
    assert caught(lambda: AEASGD(
        cfg, num_workers=8, communication_window=8,
        learning_rate=0.05)) == []
    # law-scaled configs are quiet
    assert caught(lambda: DOWNPOUR(
        cfg, num_workers=4, communication_window=4,
        learning_rate=0.05 / 16)) == []
    # scale applies the family law; off silences
    t = DOWNPOUR(cfg, num_workers=4, communication_window=4,
                          learning_rate=0.05, lr_law="scale")
    assert abs(t.learning_rate - 0.05 / 16) < 1e-12
    assert caught(lambda: DOWNPOUR(
        cfg, num_workers=4, communication_window=4,
        learning_rate=0.05, lr_law="off")) == []
    with pytest.raises(ValueError, match="lr_law"):
        DOWNPOUR(cfg, lr_law="sometimes")


def test_commit_overlap_pipelined_round():
    """VERDICT r4 #2: commit_overlap=True pipelines round k-1's commit
    scan against round k's window (one jitted program, independent
    subgraphs).  Semantics: uniform +W staleness, which must (a) be
    reported in the telemetry, (b) still converge on par with the
    in-order emulator, and (c) end every epoch fully flushed."""
    common = dict(num_workers=4, communication_window=2, batch_size=32,
                  num_epoch=3, learning_rate=0.0125, seed=0)
    from distkeras_tpu.evaluators import evaluate_model

    base = ADAG(MLP, **common)
    acc_base = evaluate_model(base.model, base.train(DATA),
                              DATA)["accuracy"]
    over = ADAG(MLP, commit_overlap=True, **common)
    acc_over = evaluate_model(over.model, over.train(DATA),
                              DATA)["accuracy"]
    # same data/budget: the +W staleness costs at most a few points
    assert acc_over >= acc_base - 0.05, (acc_over, acc_base)
    # telemetry reports the TRUE commit depth: one full round behind
    assert sorted(over.history["staleness"][0]) == [4, 5, 6, 7]
    assert sorted(base.history["staleness"][0]) == [0, 1, 2, 3]
    # the trained center includes the final (flushed) round: the PS
    # clock counts every commit
    rounds = len(over.history["round_loss"])
    assert int(over.parameter_server_state.clock) == 4 * rounds

    # staleness-aware rule runs too (staleness_offset path)
    dyn = DynSGD(MLP, commit_overlap=True, **common)
    dyn.train(DATA)
    assert sorted(dyn.history["staleness"][0]) == [4, 5, 6, 7]


def test_commit_overlap_validation():
    """The pipeline exists only where it is semantically sound: the
    elastic family's commit reads the committing worker's current
    locals (read-modify-write against the window — nothing to
    overlap), checkpointing would snapshot a center missing the
    pending round, and the fast/host fidelities have no separate
    commit phase."""
    common = dict(num_workers=2, communication_window=2, batch_size=32,
                  num_epoch=1, learning_rate=0.01)
    with pytest.raises(ValueError, match="elastic|delta"):
        AEASGD(MLP, commit_overlap=True, **common).train(DATA)
    with pytest.raises(ValueError, match="fidelity"):
        DOWNPOUR(MLP, commit_overlap=True, fidelity="fast", **common)
    with pytest.raises(ValueError, match="checkpoint"):
        DOWNPOUR(MLP, commit_overlap=True, checkpoint_every_rounds=2,
                 **common)
    with pytest.raises(ValueError, match="resume"):
        DOWNPOUR(MLP, commit_overlap=True, **common).train(
            DATA, resume_from="/tmp/nonexistent")

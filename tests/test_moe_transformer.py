"""MoE TransformerLM: the dense-einsum (GShard-form) MoE FFN inside the
model zoo — trainable by every trainer, expert-parallel via the TP
rules, aux load-balance loss through the "losses" collection."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.trainers import SingleTrainer, SyncTrainer

MOE_LM = model_config("transformer_lm", (16,), input_dtype="int32",
                      vocab_size=32, num_layers=2, d_model=32,
                      num_heads=4, max_len=16, dtype="float32",
                      num_experts=4, expert_capacity_factor=2.0)
DATA = datasets.lm_synth(512, seq_len=16, vocab_size=32, seed=21)


def test_moe_lm_has_expert_params_and_aux_losses():
    spec = ModelSpec.from_config(MOE_LM)
    variables = spec.build().init(jax.random.key(0),
                                  np.zeros((2, 16), np.int32))
    moe = variables["params"]["Block_0"]["moe"]
    assert moe["w_in"].shape == (4, 32, 128)
    assert moe["router"].shape == (32, 4)
    assert "losses" in variables
    leaves = jax.tree_util.tree_leaves(variables["losses"])
    assert len(leaves) == 2  # one aux loss per block


def test_moe_lm_trains_with_aux_loss():
    t = SingleTrainer(MOE_LM, loss="sparse_categorical_crossentropy",
                      worker_optimizer="adam", learning_rate=3e-3,
                      batch_size=32, num_epoch=2)
    t.train(DATA)
    h = t.history["epoch_loss"]
    assert np.isfinite(h).all() and h[-1] < h[0], h


def test_moe_lm_expert_parallel_matches_dp(devices):
    """model_parallel=2 shards the expert axes (EP via the TP rules):
    identical losses to the replicated run."""
    def run(mp):
        t = SyncTrainer(MOE_LM, num_workers=2, model_parallel=mp,
                        loss="sparse_categorical_crossentropy",
                        worker_optimizer="adam", learning_rate=3e-3,
                        batch_size=16, num_epoch=2)
        t.train(DATA)
        return t.history["epoch_loss"]

    dp, ep = run(1), run(2)
    np.testing.assert_allclose(ep, dp, rtol=2e-4, atol=2e-5)


def test_moe_lm_aux_loss_actually_contributes():
    """Zeroing the aux weight changes the objective: the 'losses'
    collection is really in the training loss."""
    import jax.numpy as jnp

    from distkeras_tpu.models import build_model
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    model = build_model(MOE_LM)
    tokens = np.random.default_rng(3).integers(
        0, 32, size=(8, 16)).astype(np.int32)
    batch = {"features": jnp.asarray(tokens),
             "label": jnp.asarray(np.roll(tokens, -1, 1))}
    tx = resolve_optimizer("adam", 1e-3)
    variables = model.init(jax.random.key(1), tokens)
    state = TrainState.create(variables, tx, jax.random.key(2))
    step = make_train_step(model, "sparse_categorical_crossentropy",
                           tx)
    _, metrics = jax.jit(step)(state, batch)
    # recompute the bare xent without aux: must differ by the sown sum
    from distkeras_tpu.ops.losses import resolve_loss

    logits, ms = model.apply(
        {k: v for k, v in variables.items() if k != "losses"},
        batch["features"], train=True,
        rngs={"dropout": jax.random.fold_in(state.rng, 0)},
        mutable=list(state.model_state))
    bare = resolve_loss("sparse_categorical_crossentropy")(
        logits, batch["label"])
    aux = sum(jax.tree_util.tree_leaves(ms.get("losses", {})))
    # metrics report task loss and aux separately; the objective that
    # produced the gradients is their sum
    np.testing.assert_allclose(float(metrics["loss"]), float(bare),
                               rtol=1e-6)
    np.testing.assert_allclose(float(metrics["aux_loss"]), float(aux),
                               rtol=1e-6)
    assert float(aux) > 0.0


def test_aux_loss_survives_params_only_initial_variables():
    """A state built from params-only variables (no init-time 'losses'
    collection) still trains with the aux loss — 'losses' is always
    mutable in the train step."""
    import jax.numpy as jnp

    from distkeras_tpu.models import build_model
    from distkeras_tpu.workers import (TrainState, make_train_step,
                                       resolve_optimizer)

    model = build_model(MOE_LM)
    tokens = np.random.default_rng(5).integers(
        0, 32, size=(8, 16)).astype(np.int32)
    batch = {"features": jnp.asarray(tokens),
             "label": jnp.asarray(np.roll(tokens, -1, 1))}
    tx = resolve_optimizer("adam", 1e-3)
    variables = model.init(jax.random.key(4), tokens)
    params_only = {"params": variables["params"]}  # losses dropped
    state = TrainState.create(params_only, tx, jax.random.key(5))
    step = make_train_step(model, "sparse_categorical_crossentropy",
                           tx)
    new_state, metrics = jax.jit(step)(state, batch)
    assert float(metrics["aux_loss"]) > 0.0
    # carry structure unchanged (params-only model_state stays empty)
    assert new_state.model_state == {}


def test_bad_expert_top_k_raises():
    spec = ModelSpec.from_config({**MOE_LM, "kwargs": {
        **MOE_LM["kwargs"], "expert_top_k": 9}})
    with pytest.raises(ValueError, match="expert_top_k"):
        spec.build().init(jax.random.key(0),
                          np.zeros((2, 16), np.int32))


def test_moe_composes_with_sequence_parallelism(devices):
    """TransformerLM(seq_axis=..., num_experts=...): ring attention over
    the mesh with per-device local MoE routing — matches the dense
    single-device MoE model exactly when capacity doesn't bind."""
    from distkeras_tpu.parallel.ring_attention import (
        sequence_sharded_apply)
    from jax.sharding import Mesh

    cfg = dict(input_dtype="int32", vocab_size=32, num_layers=2,
               d_model=32, num_heads=4, max_len=32, dtype="float32",
               num_experts=4, expert_capacity_factor=4.0)
    dense = ModelSpec.from_config(
        model_config("transformer_lm", (32,), **cfg)).build()
    seq = ModelSpec.from_config(
        model_config("transformer_lm", (32,), seq_axis="seq",
                     **cfg)).build()
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))

    tokens = jax.random.randint(jax.random.key(6), (2, 32), 0, 32)
    variables = dense.init(jax.random.key(7), tokens)
    want = np.asarray(dense.apply(variables, tokens))
    sp = sequence_sharded_apply(
        lambda vs, toks: seq.apply(vs, toks), mesh, "seq")
    got = np.asarray(jax.jit(sp)(variables, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

"""Block-paged KV allocator + QoS scheduler (``paging`` + the engine's
``kv_pages`` arm, ISSUE 13): the paged lowering gathers slot pages into
the exact envelope layout and runs the UNCHANGED legacy programs, so
greedy tokens must be BYTE-IDENTICAL to the envelope pools — across
admission orders, through preempt→swap→readmit cycles, and under
weight swaps — while the allocator enforces priority classes and
per-tenant quotas and the compile guard pins a bounded paged program
set."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.models import ModelSpec, generate, model_config
from distkeras_tpu.paging import PageAllocator, pages_for
from distkeras_tpu.serving import DecodeEngine

jax.config.update("jax_platforms", "cpu")

MAXLEN, VOCAB = 32, 37


def _model(num_layers=1, **kw):
    spec = model_config("transformer_lm", (MAXLEN,),
                        input_dtype="int32", vocab_size=VOCAB,
                        num_layers=num_layers, d_model=32, num_heads=2,
                        max_len=MAXLEN, dtype="float32", **kw)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, MAXLEN), jnp.int32))
    return model, variables


def _prompts(lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, (t,)).astype(np.int32)
            for t in lengths]


def _want(model, variables, prompt, n_new, **kw):
    return np.asarray(generate(model, variables, prompt[None, :],
                               max_new_tokens=n_new, **kw)
                      )[0, len(prompt):]


# ---------------------------------------------------------------------
# allocator unit surface
# ---------------------------------------------------------------------


def test_allocator_freelist_and_quota():
    a = PageAllocator(6, 4, tenant_quota={"t0": 3})
    assert a.n_free == 6 and pages_for(9, 4) == 3
    p0 = a.alloc(3, "t0")
    assert p0 == [1, 2, 3]  # deterministic pop order
    assert a.alloc(1, "t0") is None          # quota, not capacity
    assert not a.fits_quota(1, "t0") and a.fits_quota(3, "t1")
    p1 = a.alloc(2, "t1")                    # unlisted tenant: unbounded
    assert p1 == [4, 5] and a.n_free == 1
    a.free(p0, "t0")
    assert a.n_free == 4 and a.fits_quota(3, "t0")
    assert a.stats()["allocated_total"] == 5
    assert a.stats()["freed_total"] == 3


# ---------------------------------------------------------------------
# parity: the tentpole acceptance bar
# ---------------------------------------------------------------------


def test_paged_matches_envelope_any_admission_order():
    """Byte-identical greedy tokens, envelope pool vs paged pool, for
    the same ragged workload in BOTH admission orders — the gather →
    legacy-program → scatter lowering is structurally exact."""
    model, variables = _model()
    prompts = _prompts([5, 9, 3, 7, 5, 11, 4, 6])
    n_new = [4, 7, 3, 6, 5, 8, 2, 7]
    reqs = [{"prompt": p, "max_new_tokens": n, "i": i}
            for i, (p, n) in enumerate(zip(prompts, n_new))]
    kw = dict(slots=3, buckets=[16, 32], prefill_align=4,
              steps_per_sync=2)
    env = DecodeEngine(model, variables, **kw)
    base = {r["i"]: r["tokens"] for r in env.run(reqs)}
    pag = DecodeEngine(model, variables, kv_pages=24, **kw)
    fwd = {r["i"]: r["tokens"] for r in pag.run(reqs)}
    rev = {r["i"]: r["tokens"] for r in pag.run(list(reversed(reqs)),
                                                ordered=False)}
    for i in base:
        np.testing.assert_array_equal(fwd[i], base[i])
        np.testing.assert_array_equal(rev[i], base[i])
    assert pag.free_pages() == 24  # everything returned to the pool
    assert env.free_pages() is None


def test_preempt_swap_readmit_is_byte_identical():
    """The seeded preemption drill: a late high-priority arrival is
    admitted by preempting low-priority work (pages swapped to host),
    the victim readmits page-exact, and EVERY request still produces
    the envelope-identical greedy tokens."""
    model, variables = _model()
    pl = _prompts([9, 9, 5])
    tel = telemetry.enable()
    try:
        eng = DecodeEngine(model, variables, slots=3, buckets=[32],
                           prefill_align=4, steps_per_sync=2,
                           kv_pages=8)
        eng.submit(pl[0], max_new_tokens=12, priority=0,
                   meta={"i": 0})
        eng.submit(pl[1], max_new_tokens=12, priority=0,
                   meta={"i": 1})
        out = list(eng.step())  # both low-pri admitted + decoding
        eng.submit(pl[2], max_new_tokens=10, priority=2,
                   meta={"i": 2})
        while eng.has_work():
            out.extend(eng.step())
        res = {r["i"]: r for r in out}
        for i, n in [(0, 12), (1, 12), (2, 10)]:
            assert "error" not in res[i]
            np.testing.assert_array_equal(
                res[i]["tokens"], _want(model, variables, pl[i], n))
        snap = tel.metrics.snapshot()["counters"]
        assert sum(v for k, v in snap.items()
                   if k.startswith("serving_preemptions_total")) >= 1
        assert snap.get("serving_readmissions_total", 0) >= 1
        assert snap.get("serving_pages_swapped_total", 0) >= 1
        # ledger balance: every allocated page came back
        assert (snap["serving_pages_allocated_total"]
                == snap["serving_pages_freed_total"])
        assert eng.free_pages() == 8
    finally:
        telemetry.disable()


def test_recompute_preemption_finishes_every_request():
    """``preemption="recompute"`` re-prefills prompt + generated as an
    extended prompt instead of holding host bytes; the drill still
    completes every request with its full token budget."""
    model, variables = _model()
    pl = _prompts([9, 9, 5])
    eng = DecodeEngine(model, variables, slots=3, buckets=[32],
                       prefill_align=4, steps_per_sync=2, kv_pages=8,
                       preemption="recompute")
    eng.submit(pl[0], max_new_tokens=12, priority=0, meta={"i": 0})
    eng.submit(pl[1], max_new_tokens=12, priority=0, meta={"i": 1})
    out = list(eng.step())
    eng.submit(pl[2], max_new_tokens=10, priority=2, meta={"i": 2})
    while eng.has_work():
        out.extend(eng.step())
    res = {r["i"]: r for r in out}
    for i, n in [(0, 12), (1, 12), (2, 10)]:
        assert "error" not in res[i], res[i].get("error")
        assert len(res[i]["tokens"]) == n
    # the high-priority arrival (never preempted) is exact
    np.testing.assert_array_equal(res[2]["tokens"],
                                  _want(model, variables, pl[2], 10))


def test_preemption_none_sheds_the_grower():
    """With preemption off, pool exhaustion sheds the growing request
    as ``error="kv_pages_exhausted"`` instead of parking it.  Each
    request's WORST-CASE footprint fits the pool alone (so admission
    accepts both), but jointly they exhaust it mid-decode."""
    model, variables = _model()
    pl = _prompts([9, 9])
    eng = DecodeEngine(model, variables, slots=2, buckets=[32],
                       prefill_align=4, steps_per_sync=2, kv_pages=6,
                       preemption="none")
    eng.submit(pl[0], max_new_tokens=7, meta={"i": 0})
    eng.submit(pl[1], max_new_tokens=7, meta={"i": 1})
    out = []
    while eng.has_work():
        out.extend(eng.step())
    assert len(out) == 2
    res = {r["i"]: r for r in out}
    errs = [r for r in out if "error" in r]
    assert errs and all(r["error"] == "kv_pages_exhausted"
                        for r in errs)
    # the shed request's pages freed room for the survivor, whose
    # tokens are still envelope-exact
    ok = [r for r in out if "error" not in r]
    for r in ok:
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, pl[r["i"]], 7))
    assert eng.free_pages() == 6


# ---------------------------------------------------------------------
# prefix store + paging are one mechanism
# ---------------------------------------------------------------------


def test_paged_prefix_and_chunked_prefill_parity():
    """Prefix hits install straight into pages (segment shape == page
    shape) and chunked prefill runs through the page tables; greedy
    tokens still match solo generate()."""
    model, variables = _model()
    rng = np.random.default_rng(5)
    shared = rng.integers(0, VOCAB, (12,)).astype(np.int32)
    ps = [np.concatenate([shared,
                          rng.integers(0, VOCAB, (k,)
                                       ).astype(np.int32)])
          for k in [3, 5, 2, 6]]
    eng = DecodeEngine(model, variables, slots=2, buckets=[32],
                       prefill_align=4, steps_per_sync=2, kv_pages=16,
                       prefix_cache_bytes=1 << 20, prefill_chunk=8)
    outs = list(eng.run([{"prompt": p, "max_new_tokens": 6, "i": i}
                         for i, p in enumerate(ps)]))
    for r in outs:
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, ps[r["i"]], 6))
    st = eng.prefix_stats()
    assert st["hits"] > 0  # later arrivals reused donated pages
    assert eng.free_pages() == 16


def test_weight_swap_invalidates_parked_swap_kv():
    """A ``swap_variables`` while a request is parked invalidates its
    host-swapped KV exactly like the prefix store: readmission
    degrades to recompute under the NEW weights and the request still
    finishes cleanly (never resumes stale KV)."""
    model, variables = _model()
    variables2 = model.init(jax.random.key(9),
                            jnp.zeros((2, MAXLEN), jnp.int32))
    pl = _prompts([9, 9, 5])
    eng = DecodeEngine(model, variables, slots=3, buckets=[32],
                       prefill_align=4, steps_per_sync=2, kv_pages=8)
    eng.submit(pl[0], max_new_tokens=12, priority=0, meta={"i": 0})
    eng.submit(pl[1], max_new_tokens=12, priority=0, meta={"i": 1})
    out = list(eng.step())
    eng.submit(pl[2], max_new_tokens=10, priority=2, meta={"i": 2})
    out.extend(eng.step())  # growth/admission preempts a low-pri
    assert eng.paging_stats()["parked"] >= 1
    eng.swap_variables(variables2)
    while eng.has_work():
        out.extend(eng.step())
    res = {r["i"]: r for r in out}
    for i in (0, 1, 2):
        assert "error" not in res[i], res[i].get("error")
    assert eng.free_pages() == 8


# ---------------------------------------------------------------------
# QoS semantics
# ---------------------------------------------------------------------


def test_tenant_quota_blocks_only_the_hog():
    """A tenant at its page quota waits while OTHER tenants keep
    admitting through the same pool — quota blocks are skipped, not
    head-of-line."""
    model, variables = _model()
    pl = _prompts([5, 5, 5])
    eng = DecodeEngine(model, variables, slots=3, buckets=[32],
                       prefill_align=4, steps_per_sync=2, kv_pages=12,
                       tenant_quota={"hog": 3})
    eng.submit(pl[0], max_new_tokens=4, tenant="hog", meta={"i": 0})
    eng.submit(pl[1], max_new_tokens=4, tenant="hog", meta={"i": 1})
    eng.submit(pl[2], max_new_tokens=4, tenant="other", meta={"i": 2})
    out = []
    while eng.has_work():
        out.extend(eng.step())
    res = {r["i"]: r for r in out}
    for i in (0, 1, 2):
        assert "error" not in res[i]
        np.testing.assert_array_equal(
            res[i]["tokens"], _want(model, variables, pl[i], 4))
    used = eng.paging_stats()["tenants"]
    assert used == {}  # all quota returned


def test_parked_deadline_expires_into_an_error_result():
    """The satellite deadline fix: a preempted request's deadline
    keeps ticking while parked and expires into the same
    ``deadline_exceeded`` error row as a queued request.  The parked
    deadline is backdated directly so the test is deterministic under
    arbitrary compile-time skew."""
    model, variables = _model()
    pl = _prompts([9, 9, 5])
    eng = DecodeEngine(model, variables, slots=3, buckets=[32],
                       prefill_align=4, steps_per_sync=2, kv_pages=8)
    eng.submit(pl[0], max_new_tokens=12, priority=0, deadline=60.0,
               meta={"i": 0})
    eng.submit(pl[1], max_new_tokens=12, priority=0, deadline=60.0,
               meta={"i": 1})
    out = list(eng.step())
    # the high-priority arrival preempts a low-pri request when its
    # page table grows past the free pool (not at admission)
    eng.submit(pl[2], max_new_tokens=10, priority=2, meta={"i": 2})
    for _ in range(8):
        out.extend(eng.step())
        if eng.paging_stats()["parked"] >= 1:
            break
    assert eng.paging_stats()["parked"] >= 1
    for req in eng._parked:  # expire IN PLACE while parked
        req.deadline = telemetry.now() - 1.0
    while eng.has_work():
        out.extend(eng.step())
    res = {r["i"]: r for r in out}
    assert "error" not in res[2]
    np.testing.assert_array_equal(
        res[2]["tokens"], _want(model, variables, pl[2], 10))
    expired = [r for r in (res[0], res[1]) if "error" in r]
    assert expired and all(r["error"] == "deadline_exceeded"
                           for r in expired)
    assert eng.free_pages() == 8


def test_submit_validation_paged():
    model, variables = _model()
    eng = DecodeEngine(model, variables, slots=2, buckets=[32],
                       prefill_align=4, kv_pages=4,
                       tenant_quota={"small": 2})
    p = _prompts([5])[0]
    with pytest.raises(ValueError, match="kv_pages"):
        eng.submit(p, max_new_tokens=20)  # worst case: 8 pages > 4
    with pytest.raises(ValueError, match="tenant_quota"):
        eng.submit(p, max_new_tokens=4, tenant="small")
    with pytest.raises(ValueError, match="priority"):
        eng.submit(p, max_new_tokens=2, priority=3)
    with pytest.raises(ValueError, match="priority"):
        eng.submit(p, max_new_tokens=2, priority="high")


def test_knob_validation():
    model, variables = _model()
    with pytest.raises(ValueError, match="kv_pages"):
        DecodeEngine(model, variables, kv_pages=0)
    with pytest.raises(ValueError, match="page_size"):
        DecodeEngine(model, variables, kv_pages=4, page_size=0)
    with pytest.raises(ValueError, match="whole number of pages"):
        DecodeEngine(model, variables, buckets=[32], kv_pages=4,
                     page_size=5)
    with pytest.raises(ValueError, match="prefill_align"):
        DecodeEngine(model, variables, kv_pages=4, prefill_align=4,
                     page_size=8, prefix_cache_bytes=1 << 20)
    with pytest.raises(ValueError, match="preemption"):
        DecodeEngine(model, variables, kv_pages=4, prefill_align=4,
                     preemption="maybe")
    with pytest.raises(ValueError, match="recompute_below"):
        DecodeEngine(model, variables, kv_pages=4, prefill_align=4,
                     recompute_below=-1)
    with pytest.raises(ValueError, match="tenant_quota"):
        DecodeEngine(model, variables, kv_pages=4, prefill_align=4,
                     tenant_quota=0)


# ---------------------------------------------------------------------
# compile guard: the paged program set is bounded too
# ---------------------------------------------------------------------


def test_paged_compile_guard_steady_state():
    """One ``paged_step`` trace per bucket, one ``paged_prefill`` per
    (bucket, padded length); re-running ragged workloads in shuffled
    orders — preemptions included — compiles NOTHING new."""
    tel = telemetry.enable()
    try:
        model, variables = _model()
        eng = DecodeEngine(model, variables, slots=2,
                           buckets=[16, 32], prefill_align=8,
                           max_new_tokens=4, kv_pages=10)
        mk = lambda ls, seed: [{"prompt": p}  # noqa: E731
                               for p in _prompts(ls, seed=seed)]
        list(eng.run(mk([3, 9, 5, 14, 7, 2, 11, 8], 11)))
        m = tel.metrics
        assert m.counter("compiles_total", kind="paged_step",
                         bucket=16).value == 1
        assert m.counter("compiles_total", kind="paged_step",
                         bucket=32).value == 1
        for labels, c in m.collect("compiles_total",
                                   kind="paged_prefill"):
            assert c.value == 1, labels
        # the legacy kinds never trace on a paged engine
        assert not m.collect("compiles_total", kind="step")
        assert not m.collect("compiles_total", kind="prefill")
        before = {k: v for k, v
                  in m.snapshot()["counters"].items()
                  if k.startswith("compiles_total")}
        list(eng.run(mk([8, 11, 2, 7, 14, 5, 9, 3], 12)))
        list(eng.run(mk([7, 7, 3, 9, 2], 13)))
        after = {k: v for k, v
                 in m.snapshot()["counters"].items()
                 if k.startswith("compiles_total")}
        assert after == before
    finally:
        telemetry.disable()

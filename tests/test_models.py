"""Model zoo: config round-trip, init, jitted forward shapes (SURVEY.md §4:
the reference only had notebook smoke tests; we unit-test each family)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import ModelSpec, build_model, model_config
from distkeras_tpu.utils import tree_size

CONFIGS = {
    "mlp": model_config("mlp", (28, 28), num_classes=10, hidden=(64, 32)),
    "convnet": model_config("convnet", (32, 32, 3), num_classes=10,
                            widths=(8, 16), dense=32),
    "resnet": model_config("resnet", (32, 32, 3), num_classes=10,
                           stage_sizes=(1, 1), width=8, dtype="float32"),
    "bilstm": model_config("bilstm", (16,), input_dtype="int32",
                           vocab_size=100, embed_dim=8, hidden_dim=8,
                           num_classes=2),
    "wide_deep": model_config("wide_deep", (13 + 26,), num_dense=13,
                              num_categorical=26, vocab_size=50,
                              embed_dim=4, deep=(16,), num_classes=2),
    "transformer_lm": model_config("transformer_lm", (16,),
                                   input_dtype="int32", vocab_size=64,
                                   num_layers=2, d_model=32, num_heads=2,
                                   max_len=32, dtype="float32"),
}

NUM_OUT = {"mlp": 10, "convnet": 10, "resnet": 10, "bilstm": 2,
           "wide_deep": 2, "transformer_lm": 64}


@pytest.mark.parametrize("family", sorted(CONFIGS))
def test_forward_shape_and_jit(family):
    spec = ModelSpec.from_config(CONFIGS[family])
    model = spec.build()
    x = spec.example_input(batch_size=2)
    if spec.input_dtype == "int32":
        x = np.ones_like(x)
    variables = model.init(jax.random.key(0), jnp.asarray(x))
    fwd = jax.jit(lambda v, x: model.apply(v, x))
    out = fwd(variables, jnp.asarray(x))
    assert out.shape[0] == 2
    assert out.shape[-1] == NUM_OUT[family]
    assert out.dtype == jnp.float32  # logits always f32
    assert np.all(np.isfinite(np.asarray(out)))


def test_config_roundtrip_builds_same_model():
    cfg = CONFIGS["mlp"]
    spec = ModelSpec.from_config(cfg)
    assert spec.to_config() == cfg
    m1, m2 = build_model(cfg), spec.build()
    assert m1 == m2  # flax modules are frozen dataclasses


def test_resnet50_param_count():
    # Standard ResNet-50 has ~25.6M params; group-norm variant is close.
    from distkeras_tpu.models import ResNet50
    model = ResNet50(num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0),
                           jnp.zeros((1, 224, 224, 3))))
    n = tree_size(variables["params"])
    assert 24e6 < n < 27e6, n


def test_batchnorm_resnet_has_batch_stats():
    from distkeras_tpu.models import ResNet
    model = ResNet(num_classes=10, stage_sizes=(1, 1), width=8,
                   norm="batch", dtype="float32")
    variables = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)))
    assert "batch_stats" in variables
    out, mutated = model.apply(variables, jnp.ones((2, 32, 32, 3)),
                               train=True, mutable=["batch_stats"])
    assert "batch_stats" in mutated


def test_unknown_family_raises():
    with pytest.raises(KeyError):
        build_model({"family": "nope", "input_shape": [1]})


def test_bilstm_padding_invariant():
    """Same sequence padded to different lengths -> same logits."""
    from distkeras_tpu.models import BiLSTMClassifier
    model = BiLSTMClassifier(vocab_size=50, embed_dim=8, hidden_dim=8,
                             num_classes=2)
    short = np.array([[1, 2, 3, 0, 0]])
    long = np.array([[1, 2, 3, 0, 0, 0, 0, 0]])
    variables = model.init(jax.random.key(0), jnp.asarray(short))
    np.testing.assert_allclose(
        np.asarray(model.apply(variables, jnp.asarray(short))),
        np.asarray(model.apply(variables, jnp.asarray(long))),
        atol=1e-5)


def test_transformer_rejects_overlong_sequence():
    from distkeras_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=32, num_layers=1, d_model=16,
                          num_heads=2, max_len=4, dtype="float32")
    with pytest.raises(ValueError, match="max_len"):
        model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))


def test_attention_rejects_indivisible_heads():
    from distkeras_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=32, num_layers=1, d_model=15,
                          num_heads=2, max_len=8, dtype="float32")
    with pytest.raises(ValueError, match="divisible"):
        model.init(jax.random.key(0), jnp.ones((1, 4), jnp.int32))


def test_config_json_roundtrip_preserves_tuples():
    from distkeras_tpu.utils import (deserialize_model_config,
                                     serialize_model_config)
    cfg = CONFIGS["mlp"]
    wire = deserialize_model_config(serialize_model_config(cfg))
    m1 = build_model(cfg)
    m2 = ModelSpec.from_config(wire).build()
    assert m1 == m2
    hash(m2)  # usable as a static jit argument


def test_space_to_depth_stem_is_exact_relayout():
    """stem='space_to_depth' with the folded kernel reproduces the
    7x7/s2 stem to float tolerance (same math over the same receptive
    field, MXU-friendlier layout; summation order differs)."""
    from distkeras_tpu.models import ResNet
    from distkeras_tpu.models.resnet import s2d_stem_kernel

    kw = dict(num_classes=10, stage_sizes=(1, 1), width=8,
              norm="group", dtype="float32")
    conv = ResNet(stem="conv", **kw)
    s2d = ResNet(stem="space_to_depth", **kw)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    variables = conv.init(jax.random.key(1), x)
    params = dict(variables["params"])
    params["Conv_0"] = {
        "kernel": s2d_stem_kernel(params["Conv_0"]["kernel"])}
    np.testing.assert_allclose(
        np.asarray(s2d.apply({"params": params}, x)),
        np.asarray(conv.apply(variables, x)), rtol=1e-5, atol=1e-5)


def test_transformer_remat_blocks_is_exact():
    """remat_blocks=True recomputes instead of storing — same params,
    bitwise-same forward, same gradients."""
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import ModelSpec, model_config

    cfg = model_config("transformer_lm", (16,), input_dtype="int32",
                       vocab_size=32, num_layers=2, d_model=32,
                       num_heads=2, max_len=16, dtype="float32")
    base = ModelSpec.from_config(cfg).build()
    remat = base.clone(remat_blocks=True)
    tokens = jax.random.randint(jax.random.key(0), (2, 16), 0, 32)
    variables = base.init(jax.random.key(1), tokens)
    np.testing.assert_array_equal(
        np.asarray(base.apply(variables, tokens)),
        np.asarray(remat.apply(variables, tokens)))

    def loss(m, v):
        return jnp.mean(m.apply(v, tokens).astype(jnp.float32) ** 2)

    g1 = jax.grad(lambda v: loss(base, v))(variables)
    g2 = jax.grad(lambda v: loss(remat, v))(variables)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_attn_auto_resolution_follows_measured_recipe():
    """attn="auto" (the default) applies PERF.md §17's measured
    per-shape recipe: dense below T=1024 (and for unaligned T),
    blockwise at T=1024-class shapes, flash at T>=2048 on TPU (the
    blockwise path substitutes off-TPU, where the Mosaic kernels
    would run interpreted)."""
    import functools
    from distkeras_tpu.models import TransformerLM

    m = TransformerLM(max_len=65536)
    assert m.attn == "auto"
    assert m._local_attn_fn(256) is None          # dense below 1024
    assert m._local_attn_fn(1000) is None         # unaligned -> dense
    bw = m._local_attn_fn(1024)
    assert isinstance(bw, functools.partial)
    assert "blockwise" in bw.func.__name__
    long = m._local_attn_fn(4096)                 # CPU: blockwise subs
    on_tpu = jax.devices()[0].platform == "tpu"
    want = "flash" if on_tpu else "blockwise"
    assert want in long.func.__name__
    # explicit spellings override auto
    assert m.clone(attn="dense")._local_attn_fn(4096) is None
    fl = m.clone(attn="flash")._local_attn_fn(64)
    assert "flash" in fl.func.__name__
    # booleans override the attn field; attn_fn is strongest
    assert "flash" in m.clone(
        flash_attn=True)._local_attn_fn(64).func.__name__
    sentinel = lambda q, k, v, scale: q  # noqa: E731
    assert m.clone(attn_fn=sentinel)._local_attn_fn(4096) is sentinel
    with pytest.raises(ValueError, match="attn="):
        m.clone(attn="fancy").init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32))


def test_attn_auto_equals_dense_at_small_t():
    """Below the blockwise threshold the default model is bitwise the
    dense one — auto cannot perturb small-shape users."""
    from distkeras_tpu.models import TransformerLM

    m = TransformerLM(vocab_size=41, num_layers=1, d_model=32,
                      num_heads=4, max_len=64, dtype="float32")
    toks = jax.random.randint(jax.random.key(0), (2, 24), 0, 41)
    v = m.init(jax.random.key(1), toks)
    np.testing.assert_array_equal(
        np.asarray(m.apply(v, toks)),
        np.asarray(m.clone(attn="dense").apply(v, toks)))


def test_attn_auto_picks_blockwise_at_1024_and_matches_dense():
    """At T=1024 the default model runs the blockwise spelling (the
    measured winner at this shape) and agrees with dense numerics up
    to f32 reduction order."""
    from distkeras_tpu.models import TransformerLM

    m = TransformerLM(vocab_size=41, num_layers=1, d_model=32,
                      num_heads=4, max_len=1024, dtype="float32")
    toks = jax.random.randint(jax.random.key(2), (1, 1024), 0, 41)
    v = m.init(jax.random.key(3), toks)
    auto = m.apply(v, toks)
    np.testing.assert_array_equal(
        np.asarray(auto),
        np.asarray(m.clone(attn="blockwise").apply(v, toks)))
    np.testing.assert_allclose(
        np.asarray(auto),
        np.asarray(m.clone(attn="dense").apply(v, toks)),
        rtol=2e-4, atol=2e-4)

"""Streaming inference: parity with batch prediction, tail padding,
latency-bounded flushing (reference Kafka demo analogue, SURVEY.md §2.1
Examples)."""

import jax
import numpy as np

from distkeras_tpu.data import datasets
from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.streaming import StreamingPredictor

CFG = model_config("mlp", (6,), num_classes=3, hidden=(16,))
DATA = datasets.synthetic_classification(100, (6,), 3, seed=4)


def _variables():
    spec = ModelSpec.from_config(CFG)
    return spec.build().init(jax.random.key(0),
                             np.zeros((2, 6), np.float32))


def _rows(n=100):
    feats = np.asarray(DATA["features"])
    return [{"id": i, "features": feats[i]} for i in range(n)]


def test_stream_matches_batch_prediction():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=16,
                            output="prob")
    out = list(sp.predict_stream(iter(_rows())))
    assert [r["id"] for r in out] == list(range(100))  # order kept
    want = np.asarray(
        ModelPredictor(CFG, variables, output="prob",
                       num_shards=1).predict(DATA)["prediction"])
    got = np.stack([r["prediction"] for r in out])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_ragged_tail_is_padded_not_recompiled():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=64)
    out = list(sp.predict_stream(iter(_rows(70))))  # 64 + ragged 6
    assert len(out) == 70
    if hasattr(sp._forward, "_cache_size"):  # private jax API; best-effort
        # the compiled forward saw exactly one shape
        assert sp._forward._cache_size() == 1


def test_flush_every_bounds_latency():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=64,
                            flush_every=8)

    def trickle():
        for r in _rows(20):
            yield r

    seen = []
    gen = sp.predict_stream(trickle())
    for r in gen:
        seen.append(r)
        if len(seen) == 8:
            break
    # 8 rows out after only 8 rows in (never waited for a full 64)
    assert [r["id"] for r in seen] == list(range(8))


def test_call_dispatches_dataset_and_kwargs_guard():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=16)
    ds_out = sp(DATA)  # Dataset -> parent batch-predict contract
    assert "prediction" in ds_out.columns
    import pytest as _pytest

    with _pytest.raises(TypeError, match="num_shards"):
        StreamingPredictor(CFG, variables, num_shards=2)


def test_streaming_serves_keras_ingested_model():
    """Composition: a Keras model ingested via compat feeds the
    streaming predictor directly."""
    import pytest

    keras = pytest.importorskip("keras")

    from distkeras_tpu.compat import from_keras

    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    spec, variables = from_keras(m)
    sp = StreamingPredictor(spec, variables, batch_size=8)
    rows = _rows(20)
    out = list(sp.predict_stream(iter(rows)))
    assert len(out) == 20
    want = np.asarray(m(np.stack([r["features"] for r in rows])))
    got = np.stack([r["prediction"] for r in out])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_streaming_serves_multi_output_model():
    """A two-head ingested DAG streams one key per head
    (``prediction_0/1``), matching ModelPredictor's column-per-head
    contract row for row."""
    import json

    from distkeras_tpu.compat import from_keras_json
    from distkeras_tpu.data import Dataset

    arch = {"class_name": "Model", "config": {"name": "m", "layers": [
        {"name": "in0", "class_name": "InputLayer",
         "config": {"batch_input_shape": [None, 6]},
         "inbound_nodes": []},
        {"name": "enc", "class_name": "Dense",
         "config": {"units": 8, "activation": "relu"},
         "inbound_nodes": [[["in0", 0, 0, {}]]]},
        {"name": "a", "class_name": "Dense", "config": {"units": 3},
         "inbound_nodes": [[["enc", 0, 0, {}]]]},
        {"name": "b", "class_name": "Dense", "config": {"units": 1},
         "inbound_nodes": [[["enc", 0, 0, {}]]]},
    ], "input_layers": [["in0", 0, 0]],
       "output_layers": [["a", 0, 0], ["b", 0, 0]]}}
    spec, _ = from_keras_json(json.dumps(arch))
    variables = spec.build().init(jax.random.key(1),
                                  np.zeros((2, 6), np.float32))
    sp = StreamingPredictor(spec, variables, batch_size=16)
    rows = _rows(37)
    out = list(sp.predict_stream(iter(rows)))
    assert len(out) == 37
    assert out[0]["prediction_0"].shape == (3,)
    assert out[0]["prediction_1"].shape == (1,)
    batch = ModelPredictor(spec, variables, batch_size=16).predict(
        Dataset({"features": np.stack([r["features"]
                                       for r in rows])}))
    np.testing.assert_allclose(
        np.stack([r["prediction_0"] for r in out]),
        batch["prediction_0"], atol=1e-6)


# ---- StreamingGenerator (LM serving over models.generate) ----

LM_CFG = model_config("transformer_lm", (24,), input_dtype="int32",
                      vocab_size=32, num_layers=1, d_model=32,
                      num_heads=2, max_len=24, dtype="float32")


def _lm_variables():
    spec = ModelSpec.from_config(LM_CFG)
    return spec.build().init(jax.random.key(1),
                             np.zeros((2, 8), np.int32))


def _prompt_rows(lengths):
    rng = np.random.default_rng(3)
    return [{"id": i, "prompt": rng.integers(0, 32, (t,)).astype(np.int32)}
            for i, t in enumerate(lengths)]


def test_generator_stream_matches_direct_generate():
    from distkeras_tpu.models import generate
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([6] * 10)
    sg = StreamingGenerator(LM_CFG, variables, max_new_tokens=5,
                            batch_size=4)
    out = list(sg.generate_stream(iter(rows)))
    assert [r["id"] for r in out] == list(range(10))  # order kept
    model = ModelSpec.from_config(LM_CFG).build()
    prompts = np.stack([r["prompt"] for r in rows])
    want = np.asarray(generate(model, variables, prompts,
                               max_new_tokens=5))[:, 6:]
    got = np.stack([r["generated"] for r in out])
    # greedy; the tail micro-batch (2 rows padded to 4) must not
    # change results
    np.testing.assert_array_equal(got, want)


def test_generator_mixed_prompt_lengths():
    from distkeras_tpu.models import generate
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([4, 7, 4, 7, 4])
    sg = StreamingGenerator(LM_CFG, variables, max_new_tokens=6,
                            batch_size=5)
    out = list(sg(iter(rows)))
    assert [r["id"] for r in out] == list(range(5))
    model = ModelSpec.from_config(LM_CFG).build()
    for r in out:
        t_p = len(r["prompt"])
        want = np.asarray(generate(
            model, variables, r["prompt"][None, :],
            max_new_tokens=6))[0, t_p:]
        np.testing.assert_array_equal(r["generated"], want)
        assert r["generated"].shape == (6,)


def test_generator_sampling_replay_reproducible():
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([5] * 6)
    kw = dict(max_new_tokens=4, batch_size=3, temperature=0.9,
              top_k=8, seed=11)
    sg = StreamingGenerator(LM_CFG, variables, **kw)
    a = [r["generated"] for r in sg(iter(rows))]
    # replay on the SAME instance must reproduce (per-stream counter;
    # the compile cache persists across streams)
    b = [r["generated"] for r in sg(iter(rows))]
    np.testing.assert_array_equal(np.stack(a), np.stack(b))
    c = [r["generated"] for r in
         StreamingGenerator(LM_CFG, variables,
                            **{**kw, "seed": 12})(iter(rows))]
    assert not np.array_equal(np.stack(a), np.stack(c))
    assert all((g >= 0).all() and (g < 32).all() for g in a)


def test_generator_compiles_once_per_length():
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    sg = StreamingGenerator(LM_CFG, variables, max_new_tokens=3,
                            batch_size=4)
    list(sg(iter(_prompt_rows([4, 4, 4, 4, 6, 6, 6, 6]))))
    assert sg._generate._cache_size() == 2  # one shape per length
    list(sg(iter(_prompt_rows([4, 6, 4, 6]))))
    assert sg._generate._cache_size() == 2  # reused, no new entries


def test_generator_full_bucket_flushes_before_stream_end():
    """A same-length bucket reaching batch_size flushes on its own —
    mixed buffers never pad every fragment to batch_size."""
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    calls = []
    sg = StreamingGenerator(LM_CFG, variables, max_new_tokens=2,
                            batch_size=3)
    orig = sg._run_bucket
    sg._run_bucket = lambda items, n: (
        calls.append((len(items), len(items[0][1]["prompt"])))
        or orig(items, n))
    out = list(sg(iter(_prompt_rows([4, 6, 4, 6, 4, 6]))))
    assert [r["id"] for r in out] == list(range(6))
    # both buckets filled exactly to batch_size: no padded fragments
    assert sorted(calls) == [(3, 4), (3, 6)]


def test_generator_flush_every_bounds_oldest_row():
    """The latency bound tracks the OLDEST buffered row: a majority
    length filling its own bucket must not starve a minority row."""
    import pytest

    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    calls = []
    sg = StreamingGenerator(LM_CFG, variables, max_new_tokens=2,
                            batch_size=2, flush_every=3)
    orig = sg._run_bucket
    sg._run_bucket = lambda items, n: (
        calls.append((len(items), len(items[0][1]["prompt"])))
        or orig(items, n))
    # one len-5 row, then a trickle of len-7 rows whose bucket keeps
    # filling (and flushing) on its own
    out = list(sg(iter(_prompt_rows([5, 7, 7, 7, 7, 7]))))
    assert [r["id"] for r in out] == list(range(6))
    # the len-5 row must flush after waiting through 3 consumed rows
    # (padded single-row bucket), NOT at end-of-stream
    assert calls.index((1, 5)) <= 2, calls

    # an unservable prompt is rejected at consume time, by row
    sg2 = StreamingGenerator(LM_CFG, variables, max_new_tokens=8,
                             batch_size=2)
    rows = _prompt_rows([5, 20, 5])  # 20 + 8 > max_len=24
    with pytest.raises(ValueError, match="row 1"):
        list(sg2(iter(rows)))


def test_generator_continuous_engine_matches_bucketed_greedy():
    """engine='continuous' is a drop-in: same rows, same in-order
    delivery, same fixed-length greedy outputs as the bucketed
    run-to-completion path — and no recompiles over a second ragged
    stream (the slot pool persists across streams)."""
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([4, 7, 4, 9, 7, 4, 5, 8])
    kw = dict(max_new_tokens=5, batch_size=3)
    want = list(StreamingGenerator(LM_CFG, variables, **kw)(iter(rows)))
    sg = StreamingGenerator(LM_CFG, variables, engine="continuous",
                            engine_options={"prefill_align": 4},
                            **kw)
    got = list(sg(iter(rows)))
    assert [r["id"] for r in got] == list(range(8))
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a["generated"], b["generated"])
        assert b["generated"].shape == (5,)
    counts = dict(sg._engine.compile_counts)
    list(sg(iter(_prompt_rows([8, 5, 4, 9]))))
    assert dict(sg._engine.compile_counts) == counts

    import pytest

    with pytest.raises(ValueError, match="engine"):
        StreamingGenerator(LM_CFG, variables, max_new_tokens=2,
                           engine="orca")
    with pytest.raises(ValueError, match="num_beams"):
        StreamingGenerator(LM_CFG, variables, max_new_tokens=2,
                           engine="continuous", num_beams=2)
    # unservable rows still fail at consume time, naming the row
    sgc = StreamingGenerator(LM_CFG, variables, max_new_tokens=8,
                             engine="continuous",
                             engine_options={"prefill_align": 4})
    with pytest.raises(ValueError, match="row 1"):
        list(sgc(iter(_prompt_rows([5, 20, 5]))))


def test_generator_continuous_eos_pads_like_bucketed():
    """eos-finished continuous rows deliver the same padded
    fixed-length arrays the bucketed mode produces."""
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([5, 5, 5, 5])
    base = list(StreamingGenerator(LM_CFG, variables,
                                   max_new_tokens=6,
                                   batch_size=4)(iter(rows)))
    gen = np.stack([r["generated"] for r in base])
    eos = None  # a token some row emits mid-sequence, others don't
    for tok in set(gen[:, :-1].ravel().tolist()):
        hits = [int(np.argwhere(g == tok)[0][0]) if tok in g else None
                for g in gen]
        if any(h is not None and h < 5 for h in hits) \
                and any(h is None for h in hits):
            eos = int(tok)
            break
    if eos is None:
        import pytest

        pytest.skip("degenerate greedy sample: no discriminating token")
    kw = dict(max_new_tokens=6, batch_size=4, eos_id=eos, pad_id=30)
    want = list(StreamingGenerator(LM_CFG, variables, **kw)(iter(rows)))
    got = list(StreamingGenerator(LM_CFG, variables,
                                  engine="continuous",
                                  engine_options={"prefill_align": 4},
                                  **kw)(iter(rows)))
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a["generated"], b["generated"])


def test_generator_continuous_sampling_replay_reproducible():
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([5] * 6)
    kw = dict(max_new_tokens=4, batch_size=3, temperature=0.9,
              top_k=8, seed=11, engine="continuous",
              engine_options={"prefill_align": 4})
    sg = StreamingGenerator(LM_CFG, variables, **kw)
    a = [r["generated"] for r in sg(iter(rows))]
    # replay on the SAME instance reproduces (the engine key stream
    # rewinds per stream; compiled programs persist)
    b = [r["generated"] for r in sg(iter(rows))]
    np.testing.assert_array_equal(np.stack(a), np.stack(b))
    assert all((g >= 0).all() and (g < 32).all() for g in a)


def test_generator_beam_strategy():
    """num_beams>1 streams beam-decoded rows (+ a score key) equal to
    direct beam_search, with the same bucketing/order machinery."""
    from distkeras_tpu.models.generate import beam_search
    from distkeras_tpu.streaming import StreamingGenerator

    variables = _lm_variables()
    rows = _prompt_rows([5, 7, 5])
    sg = StreamingGenerator(LM_CFG, variables, max_new_tokens=4,
                            batch_size=2, num_beams=3)
    out = list(sg(iter(rows)))
    assert [r["id"] for r in out] == [0, 1, 2]
    model = ModelSpec.from_config(LM_CFG).build()
    for r in out:
        t_p = len(r["prompt"])
        want, score = beam_search(model, variables,
                                  r["prompt"][None, :],
                                  max_new_tokens=4, num_beams=3)
        np.testing.assert_array_equal(r["generated"],
                                      np.asarray(want)[0, t_p:])
        np.testing.assert_allclose(r["generated_score"],
                                   float(np.asarray(score)[0]),
                                   rtol=1e-5)

    import pytest

    with pytest.raises(ValueError, match="temperature"):
        StreamingGenerator(LM_CFG, variables, max_new_tokens=2,
                           num_beams=2, temperature=0.5)

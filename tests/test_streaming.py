"""Streaming inference: parity with batch prediction, tail padding,
latency-bounded flushing (reference Kafka demo analogue, SURVEY.md §2.1
Examples)."""

import jax
import numpy as np

from distkeras_tpu.data import datasets
from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.predictors import ModelPredictor
from distkeras_tpu.streaming import StreamingPredictor

CFG = model_config("mlp", (6,), num_classes=3, hidden=(16,))
DATA = datasets.synthetic_classification(100, (6,), 3, seed=4)


def _variables():
    spec = ModelSpec.from_config(CFG)
    return spec.build().init(jax.random.key(0),
                             np.zeros((2, 6), np.float32))


def _rows(n=100):
    feats = np.asarray(DATA["features"])
    return [{"id": i, "features": feats[i]} for i in range(n)]


def test_stream_matches_batch_prediction():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=16,
                            output="prob")
    out = list(sp.predict_stream(iter(_rows())))
    assert [r["id"] for r in out] == list(range(100))  # order kept
    want = np.asarray(
        ModelPredictor(CFG, variables, output="prob",
                       num_shards=1).predict(DATA)["prediction"])
    got = np.stack([r["prediction"] for r in out])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_ragged_tail_is_padded_not_recompiled():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=64)
    out = list(sp.predict_stream(iter(_rows(70))))  # 64 + ragged 6
    assert len(out) == 70
    if hasattr(sp._forward, "_cache_size"):  # private jax API; best-effort
        # the compiled forward saw exactly one shape
        assert sp._forward._cache_size() == 1


def test_flush_every_bounds_latency():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=64,
                            flush_every=8)

    def trickle():
        for r in _rows(20):
            yield r

    seen = []
    gen = sp.predict_stream(trickle())
    for r in gen:
        seen.append(r)
        if len(seen) == 8:
            break
    # 8 rows out after only 8 rows in (never waited for a full 64)
    assert [r["id"] for r in seen] == list(range(8))


def test_call_dispatches_dataset_and_kwargs_guard():
    variables = _variables()
    sp = StreamingPredictor(CFG, variables, batch_size=16)
    ds_out = sp(DATA)  # Dataset -> parent batch-predict contract
    assert "prediction" in ds_out.columns
    import pytest as _pytest

    with _pytest.raises(TypeError, match="num_shards"):
        StreamingPredictor(CFG, variables, num_shards=2)


def test_streaming_serves_keras_ingested_model():
    """Composition: a Keras model ingested via compat feeds the
    streaming predictor directly."""
    import pytest

    keras = pytest.importorskip("keras")

    from distkeras_tpu.compat import from_keras

    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(3, activation="softmax"),
    ])
    spec, variables = from_keras(m)
    sp = StreamingPredictor(spec, variables, batch_size=8)
    rows = _rows(20)
    out = list(sp.predict_stream(iter(rows)))
    assert len(out) == 20
    want = np.asarray(m(np.stack([r["features"] for r in rows])))
    got = np.stack([r["prediction"] for r in out])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_streaming_serves_multi_output_model():
    """A two-head ingested DAG streams one key per head
    (``prediction_0/1``), matching ModelPredictor's column-per-head
    contract row for row."""
    import json

    from distkeras_tpu.compat import from_keras_json
    from distkeras_tpu.data import Dataset

    arch = {"class_name": "Model", "config": {"name": "m", "layers": [
        {"name": "in0", "class_name": "InputLayer",
         "config": {"batch_input_shape": [None, 6]},
         "inbound_nodes": []},
        {"name": "enc", "class_name": "Dense",
         "config": {"units": 8, "activation": "relu"},
         "inbound_nodes": [[["in0", 0, 0, {}]]]},
        {"name": "a", "class_name": "Dense", "config": {"units": 3},
         "inbound_nodes": [[["enc", 0, 0, {}]]]},
        {"name": "b", "class_name": "Dense", "config": {"units": 1},
         "inbound_nodes": [[["enc", 0, 0, {}]]]},
    ], "input_layers": [["in0", 0, 0]],
       "output_layers": [["a", 0, 0], ["b", 0, 0]]}}
    spec, _ = from_keras_json(json.dumps(arch))
    variables = spec.build().init(jax.random.key(1),
                                  np.zeros((2, 6), np.float32))
    sp = StreamingPredictor(spec, variables, batch_size=16)
    rows = _rows(37)
    out = list(sp.predict_stream(iter(rows)))
    assert len(out) == 37
    assert out[0]["prediction_0"].shape == (3,)
    assert out[0]["prediction_1"].shape == (1,)
    batch = ModelPredictor(spec, variables, batch_size=16).predict(
        Dataset({"features": np.stack([r["features"]
                                       for r in rows])}))
    np.testing.assert_allclose(
        np.stack([r["prediction_0"] for r in out]),
        batch["prediction_0"], atol=1e-6)

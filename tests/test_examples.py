"""Every example script runs end-to-end at tiny shapes (VERDICT r4 #4:
the examples had zero automated coverage — one API rename would break
them silently).

Each script is executed as a real subprocess — exactly how a user runs
it — on a small virtual CPU mesh (``--devices``, the reference's
``local[N]`` analogue), with rows/epochs shrunk to smoke size.  The
scripts' own internal assertions (convergence, decode parity, finite
losses) run too, so this is an integration pass over the whole public
surface, mirroring the reference's notebooks-as-integration-tests
strategy (SURVEY.md §4)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"
SCRIPTS = REPO / "scripts"

# perf/measurement scripts that advertise a --smoke mode run it here
# at tiny CPU shapes — the same no-silent-rot contract as CASES.
SMOKE_SCRIPTS = {
    "chaos_report.py": ["--smoke"],
    "check_protocol.py": ["--smoke"],
    "lint_static.py": ["--smoke"],
    "obs_report.py": ["--smoke"],
    "perf_attrib.py": ["--smoke"],
    "perf_capacity.py": ["--smoke"],
    "perf_elastic.py": ["--smoke"],
    "perf_gateway.py": ["--smoke"],
    "perf_hier.py": ["--smoke"],
    "perf_host_ps.py": ["--smoke"],
    "perf_mesh_comm.py": ["--smoke"],
    "perf_paging.py": ["--smoke"],
    "perf_prefill_decode.py": ["--smoke"],
    "perf_prefix.py": ["--smoke"],
    "perf_ps_flagship.py": ["--smoke"],
    "perf_regress.py": ["--smoke"],
    "perf_roofline.py": ["--smoke"],
    "perf_serving.py": ["--smoke"],
    "perf_spec.py": ["--smoke"],
    "postmortem.py": ["--smoke"],
    "trace_merge.py": ["--smoke"],
}
# registered but out of tier-1: the roofline smoke sweeps many op
# shapes and runs minutes-long on the CI CPU (run with -m slow)
SLOW_SMOKE = {"perf_roofline.py"}

# script -> tiny-shape args (every script also gets --devices 4).
# Sizes respect each script's internal assertions: convergence checks
# keep enough epochs/rows to actually converge.
CASES = {
    "cifar_convnet_adag.py": ["--rows", "256", "--epochs", "1"],
    "compare_trainers.py": ["--rows", "512", "--epochs", "1"],
    "criteo_widedeep.py": ["--rows", "512", "--epochs", "1"],
    "elastic_training.py": ["--rows", "768", "--epochs", "1"],
    "imagenet_resnet_aeasgd.py": ["--rows", "64", "--epochs", "1",
                                  "--batch-size", "4",
                                  "--image-size", "32",
                                  "--resnet", "18"],
    "imdb_bilstm_dynsgd.py": ["--rows", "256", "--epochs", "1"],
    "keras_import.py": ["--rows", "512", "--epochs", "1"],
    "lm_blockwise_attention.py": ["--rows", "128"],
    "lm_generate.py": ["--rows", "256", "--new-tokens", "8"],
    "lm_seq_parallel.py": ["--rows", "128", "--epochs", "1"],
    "mnist_mlp.py": ["--rows", "1024", "--epochs", "1",
                     "--batch-size", "32", "--trainer", "adag"],
    "out_of_core.py": ["--rows", "1024", "--epochs", "1"],
    "pipeline_lm.py": ["--rows", "128", "--epochs", "1",
                       "--stages", "2", "--layers", "2"],
    "pipeline_moe.py": ["--steps", "5"],
    "streaming_inference.py": ["--rows", "256", "--epochs", "1",
                               "--stream-rows", "50"],
}


def test_every_example_is_covered():
    """A new example must be added to CASES (or this fails loudly)."""
    scripts = {p.name for p in EXAMPLES.glob("*.py")} - {"_common.py"}
    assert scripts == set(CASES), (
        f"examples/ and CASES disagree: "
        f"missing={scripts - set(CASES)} stale={set(CASES) - scripts}")


def test_every_smoke_script_is_covered():
    """A scripts/*.py that grows a --smoke mode must be registered in
    SMOKE_SCRIPTS (or this fails loudly) — same contract as CASES."""
    smoke = {p.name for p in SCRIPTS.glob("*.py")
             if "--smoke" in p.read_text()}
    assert smoke == set(SMOKE_SCRIPTS), (
        f"scripts/ with --smoke and SMOKE_SCRIPTS disagree: "
        f"missing={smoke - set(SMOKE_SCRIPTS)} "
        f"stale={set(SMOKE_SCRIPTS) - smoke}")


@pytest.mark.parametrize("script", [
    pytest.param(s, marks=([pytest.mark.slow] if s in SLOW_SMOKE
                           else []))
    for s in sorted(SMOKE_SCRIPTS)])
def test_smoke_script_runs(script):
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(SCRIPTS / script), *SMOKE_SCRIPTS[script]],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(REPO))
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout[-4000:]}\n"
        f"--- stderr ---\n{proc.stderr[-4000:]}")


@pytest.mark.parametrize("script", sorted(CASES))
def test_example_runs(script):
    env = dict(os.environ, PYTHONPATH=str(REPO))
    # the virtual mesh must be sized before jax initializes in the
    # child; the scripts' own --devices handling does exactly that
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES / script), "--devices", "4",
         *CASES[script]],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(REPO))
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout[-4000:]}\n"
        f"--- stderr ---\n{proc.stderr[-4000:]}")

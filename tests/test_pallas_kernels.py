"""Numerics tests for the Pallas kernels (interpret mode on CPU) against
pure-jnp oracles, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.pallas_kernels import (
    fused_group_norm,
    group_norm_reference,
)


def _inputs(b=2, h=4, w=4, c=16, seed=0, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    x = jax.random.normal(k1, (b, h, w, c), dtype)
    gamma = jax.random.normal(k2, (c,), jnp.float32) * 0.5 + 1.0
    beta = jax.random.normal(k3, (c,), jnp.float32) * 0.1
    return x, gamma, beta


@pytest.mark.parametrize("relu", [False, True])
@pytest.mark.parametrize("groups", [4, 8])
def test_fused_group_norm_forward_matches_reference(groups, relu):
    x, gamma, beta = _inputs()
    got = fused_group_norm(x, gamma, beta, groups=groups, relu=relu,
                           interpret=True)
    want = group_norm_reference(x, gamma, beta, groups=groups, relu=relu)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("relu", [False, True])
def test_fused_group_norm_grads_match_reference(relu):
    x, gamma, beta = _inputs(c=8)
    groups = 4

    def loss_kernel(x, gamma, beta):
        y = fused_group_norm(x, gamma, beta, groups=groups, relu=relu,
                             interpret=True)
        return jnp.sum(jnp.sin(y))  # non-trivial cotangent

    def loss_ref(x, gamma, beta):
        y = group_norm_reference(x, gamma, beta, groups=groups, relu=relu)
        return jnp.sum(jnp.sin(y))

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g).reshape(w.shape),
                                   np.asarray(w), rtol=1e-4, atol=1e-5)


def test_fused_group_norm_bf16_io():
    x, gamma, beta = _inputs(dtype=jnp.bfloat16)
    got = fused_group_norm(x, gamma, beta, groups=4, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = group_norm_reference(x, gamma, beta, groups=4)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2)


def test_fused_group_norm_rejects_indivisible_groups():
    x, gamma, beta = _inputs(c=10)
    with pytest.raises(ValueError):
        fused_group_norm(x, gamma, beta, groups=4, interpret=True)


def test_resnet_group_pallas_norm_is_reachable():
    """norm='group_pallas' selects the kernel through the public model
    config surface (auto-interpret off-TPU)."""
    from distkeras_tpu.models import build_model, model_config

    cfg = model_config("resnet", (16, 16, 3), num_classes=4,
                       stage_sizes=(1,), bottleneck=False, width=16,
                       norm="group_pallas", dtype="float32")
    model = build_model(cfg)
    x = jnp.ones((2, 16, 16, 3))
    v = model.init(jax.random.key(0), x)
    out = model.apply(v, x, train=False)
    assert out.shape == (2, 4)
    assert np.all(np.isfinite(np.asarray(out)))

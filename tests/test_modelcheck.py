"""Protocol model checker (ISSUE 11): the explorer's scheduling
semantics on toy models (Choose forking, Recv FIFO blocking, timer and
crash budgets, footprint POR, state dedup, preemption bounding, replay
byte-for-byte, counterexample minimization), then the real protocol
models: every scenario explores clean at smoke bounds and every seeded
unsafe mutant yields a minimized, replayable counterexample breaking
exactly the invariant the mutant table predicts."""

import subprocess
import sys
from pathlib import Path

import pytest

from distkeras_tpu.analysis import protomodel
from distkeras_tpu.analysis.modelcheck import (Choose, Explorer, Model,
                                               Recv, Step, Timer, check)

REPO = Path(__file__).resolve().parent.parent

# the scenario bounds used by ``scripts/check_protocol.py --smoke`` —
# tier-1-sized; the full bounds run via the script's default mode
SMOKE = {"max_depth": 10, "max_states": 3_000}


class W:
    """Tiny dict-backed world with the fingerprint the explorer needs."""

    def __init__(self, **kw):
        self.d = dict(kw)

    def fingerprint(self):
        return tuple(sorted(self.d.items()))


# ---- explorer semantics on toy models --------------------------------

def test_choose_forks_every_option():
    """Each Choose option becomes its own branch; the chosen value is
    sent back into the generator."""
    def actor(ctx):
        got = yield Choose("pick", ["a", "b", "c"])
        ctx.world.d.setdefault("seen", set()).add(got)
        ctx.world.d["last"] = got
        yield Step("after")

    picks = set()

    def spy(w):
        if "last" in w.d:
            picks.add(w.d["last"])
        return None

    m = Model(lambda: W()).actor("p", actor).invariant("spy", spy)
    rep = check(m, max_depth=4)
    assert rep.violation is None
    assert picks == {"a", "b", "c"}


def test_recv_blocks_until_send_and_is_fifo():
    """Recv disables the actor while the channel is empty; messages
    arrive in send order."""
    def producer(ctx):
        yield Step("p1")
        ctx.send("ch", 1)
        yield Step("p2")
        ctx.send("ch", 2)

    def consumer(ctx):
        a = yield Recv("ch")
        b = yield Recv("ch")
        ctx.world.d["got"] = (a, b)

    orders = set()

    def spy(w):
        if "got" in w.d:
            orders.add(w.d["got"])
        return None

    m = (Model(lambda: W()).actor("prod", producer)
         .actor("cons", consumer).invariant("spy", spy))
    rep = check(m, max_depth=8)
    assert rep.violation is None
    assert orders == {(1, 2)}  # FIFO: never (2, 1)


def test_timer_budget_bounds_firings():
    """A Timer fires at most ``timer_budget`` times per execution."""
    def ticker(ctx):
        while True:
            yield Timer("tick")
            ctx.world.d["fires"] = ctx.world.d.get("fires", 0) + 1

    seen = set()

    def spy(w):
        seen.add(w.d.get("fires", 0))
        return None

    m = Model(lambda: W()).actor("t", ticker).invariant("spy", spy)
    m.timer_budget = 2
    rep = check(m, max_depth=10)
    assert rep.violation is None
    assert seen == {0, 1, 2}  # never a third firing


def test_crash_budget_and_hook():
    """crash:<name> transitions appear only while budget remains; the
    on_crash hook gets the ctx and mutates the world."""
    def actor(ctx):
        while True:
            yield Step("work")

    def on_crash(ctx):
        ctx.world.d["crashed"] = True

    crash_worlds = set()

    def spy(w):
        crash_worlds.add(w.d.get("crashed", False))
        return None

    m = (Model(lambda: W()).actor("a", actor).invariant("spy", spy)
         .allow_crash("a", on_crash, budget=1))
    rep = check(m, max_depth=5)
    assert rep.violation is None
    assert crash_worlds == {False, True}


def test_footprint_por_prunes_disjoint_actors():
    """Two actors with disjoint static footprints commute — POR must
    explore far fewer executions than the full interleaving product,
    without losing the invariant check."""
    def writer(key):
        def fn(ctx):
            for _ in range(3):
                yield Step(f"w:{key}", footprint=[key])
                ctx.world.d[key] = ctx.world.d.get(key, 0) + 1
        return fn

    def build(with_footprints):
        def mk(key):
            def fn(ctx):
                for _ in range(3):
                    yield Step(
                        f"w:{key}",
                        footprint=[key] if with_footprints else None)
                    ctx.world.d[key] = ctx.world.d.get(key, 0) + 1
            return fn
        return (Model(lambda: W()).actor("x", mk("x"))
                .actor("y", mk("y"))
                .invariant("bounded",
                           lambda w: None if w.d.get("x", 0) <= 3
                           else "x overflow"))

    por = check(build(True), max_depth=10)
    full = check(build(False), max_depth=10)
    assert por.violation is None and full.violation is None
    assert por.executions < full.executions


def test_state_dedup_collapses_diamonds():
    """Confluent interleavings reconverge; dedup prunes the rejoin."""
    def inc(key):
        def fn(ctx):
            yield Step(f"i:{key}")
            ctx.world.d[key] = 1
        return fn

    m = (Model(lambda: W()).actor("a", inc("a")).actor("b", inc("b")))
    rep = check(m, max_depth=6)
    assert rep.violation is None
    assert rep.pruned_dedup >= 1  # a=1,b=1 reached via both orders


def test_preemption_bound_limits_switches():
    """max_preemptions=0 forbids switching away from a still-enabled
    actor — strictly fewer executions than the unbounded run."""
    def spin(name):
        def fn(ctx):
            for k in range(3):
                yield Step(f"s{k}")
                # record the interleaving so states stay distinct
                ctx.world.d["trace"] = (
                    ctx.world.d.get("trace", "") + name)
        return fn

    def build():
        return (Model(lambda: W()).actor("a", spin("a"))
                .actor("b", spin("b")))

    bounded = check(build(), max_depth=8, max_preemptions=0)
    free = check(build(), max_depth=8)
    assert bounded.executions < free.executions


def test_violation_minimized_and_replays():
    """A seeded violation comes back as the SHORTEST schedule and
    replays byte-for-byte through Explorer.replay."""
    def actor(ctx):
        yield Step("a")
        yield Step("b")
        ctx.world.d["bad"] = True
        yield Step("c")

    def filler(ctx):
        for _ in range(4):
            yield Step("noise")

    m = (Model(lambda: W()).actor("m", actor).actor("f", filler)
         .invariant("no-bad",
                    lambda w: "bad set" if w.d.get("bad") else None))
    ex = Explorer(m, max_depth=10)
    rep = ex.run()
    v = rep.violation
    assert v is not None and v.invariant == "no-bad"
    # minimal: exactly the two steps of "m" that set the flag
    assert v.schedule.split() == ["m/a", "m/b"]
    rv = ex.replay(v.schedule)
    assert rv is not None
    assert rv.schedule == v.schedule
    assert rv.invariant == "no-bad"


def test_replay_rejects_disabled_token():
    def actor(ctx):
        yield Step("only")

    ex = Explorer(Model(lambda: W()).actor("a", actor))
    with pytest.raises(KeyError, match="not enabled"):
        ex.replay("a/only a/only")


def test_max_states_truncates():
    def spin(ctx):
        while True:
            bit = yield Choose("c", [0, 1])
            # distinct world per choice history: the tree can't dedup
            ctx.world.d["path"] = ctx.world.d.get("path", "") + str(bit)

    rep = check(Model(lambda: W()).actor("a", spin),
                max_depth=30, max_states=20)
    assert rep.truncated >= 1
    assert rep.states <= 21


# ---- protocol scenarios ----------------------------------------------

@pytest.mark.parametrize("scenario", sorted(protomodel.SCENARIOS))
def test_scenario_explores_clean(scenario):
    """Every protocol scenario is violation-free at smoke bounds (the
    full bounds run in ``scripts/check_protocol.py``'s default mode)."""
    model, _bounds = protomodel.build(scenario)
    rep = check(model, **SMOKE)
    assert rep.violation is None, str(rep.violation)
    assert rep.states > 10  # actually explored, not vacuously empty


@pytest.mark.parametrize("mutant", sorted(protomodel.MUTANTS))
def test_mutant_yields_replayable_counterexample(mutant):
    """Flipping one protocol guard must surface a counterexample
    breaking exactly the invariant the MUTANTS table predicts, and the
    minimized schedule must replay byte-for-byte on a fresh explorer
    over the same mutated model."""
    _desc, scenario, expected_inv = protomodel.MUTANTS[mutant]
    model, bounds = protomodel.build(scenario, mutants=(mutant,))
    ex = Explorer(model, **bounds)
    rep = ex.run()
    v = rep.violation
    assert v is not None, f"mutant {mutant} not caught"
    assert v.invariant == expected_inv, (
        f"mutant {mutant} broke {v.invariant}, expected {expected_inv}")
    fresh_model, _ = protomodel.build(scenario, mutants=(mutant,))
    rv = Explorer(fresh_model).replay(v.schedule)
    assert rv is not None, f"{mutant}: schedule did not replay"
    assert rv.invariant == expected_inv
    assert rv.schedule == v.schedule


def test_unmutated_rewind_tolerates_stale_primary():
    """The durability invariant is scoped by ack epoch: the stale,
    still-partitioned old primary missing a commit acked under a HIGHER
    epoch is the tolerated fenced-on-contact transient, not a
    violation (the invariant only binds primaries at >= the acking
    epoch)."""
    model, _ = protomodel.build("rewind")
    rep = check(model, max_depth=8, max_states=2_000)
    assert rep.violation is None, str(rep.violation)


def test_elect_is_the_production_function():
    """The model imports ``elect`` from the runtime module rather than
    re-implementing it — checking the model checks the real tiebreak."""
    from distkeras_tpu.parallel import replicated_ps
    assert protomodel.elect is replicated_ps.elect
    assert protomodel.mint_epoch is replicated_ps.mint_epoch


def test_metrics_snapshot_feeds_perf_regress(tmp_path):
    """``--metrics-out`` writes a registry snapshot that
    ``perf_regress.from_registry`` can gate on, exactly like
    ``lint_static.py``'s finding counters."""
    import importlib.util
    snap = tmp_path / "mc.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_protocol.py"),
         "--scenario", "split", "--max-depth", "8",
         "--metrics-out", str(snap)],
        capture_output=True, text=True, timeout=120, cwd=str(REPO))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    spec = importlib.util.spec_from_file_location(
        "perf_regress", REPO / "scripts" / "perf_regress.py")
    pr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pr)
    cands = pr.from_registry(str(snap), "mc_states_per_sec",
                             "modelcheck_states_explored_total", 10.0)
    assert len(cands) == 1
    assert cands[0]["value"] > 0  # states explored flowed through


def test_check_protocol_replay_cli():
    """The printed counterexample replays from the CLI: --replay with
    the schedule string reproduces the same invariant and exits 2."""
    mutant = "no-dedupe-repl"
    _desc, scenario, expected_inv = protomodel.MUTANTS[mutant]
    model, bounds = protomodel.build(scenario, mutants=(mutant,))
    v = Explorer(model, **bounds).run().violation
    assert v is not None
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_protocol.py"),
         "--replay", v.schedule, "--scenario", scenario,
         "--with-mutant", mutant],
        capture_output=True, text=True, timeout=120,
        cwd=str(REPO))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert expected_inv in proc.stdout

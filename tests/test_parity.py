"""Async-vs-sync convergence parity (BASELINE.md primary metric) at CI
scale: the emulated-staleness async trainers must match the synchronous
control arm's held-out accuracy on an identical data/epoch budget."""

import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.evaluators import evaluate_model
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import (ADAG, AEASGD, EAMSGD, DynSGD,
                                    SyncTrainer)

CFG = model_config("mlp", (16,), num_classes=8, hidden=(32,))
_FULL = datasets.synthetic_classification(3072, (16,), 8, seed=0)
_IDX = np.arange(len(_FULL))
TRAIN = _FULL.filter(_IDX < 2048)
EVAL = _FULL.filter(_IDX >= 2048)


def _accuracy(trainer) -> float:
    trainer.train(TRAIN)
    return evaluate_model(trainer.model, trainer.trained_variables,
                          EVAL, batch_size=512)["accuracy"]


# The elastic family (AEASGD/EAMSGD) runs at the SAME learning rate as
# every other arm: the round-2 parity artifact down-tuned AEASGD to
# lr=0.02 and recorded a -6.3-point "regression" that a rho x lr sweep
# showed was pure lr under-convergence — at the shared lr the elastic
# pull costs nothing at any rho in [1, 10] (PARITY.md).
@pytest.mark.parametrize("cls", [ADAG, DynSGD, AEASGD, EAMSGD])
def test_async_matches_sync_on_same_budget(cls):
    common = dict(batch_size=32, num_epoch=3, learning_rate=0.05, seed=0)
    sync_acc = _accuracy(SyncTrainer(CFG, num_workers=4, **common))
    extra = {"rho": 2.5} if issubclass(cls, AEASGD) else {}
    async_acc = _accuracy(cls(CFG, num_workers=4,
                              communication_window=2, **common, **extra))
    assert sync_acc > 0.7, sync_acc  # the control arm itself must learn
    assert async_acc > sync_acc - 0.10, (sync_acc, async_acc)


# Conv-scale parity: the staleness-equivalence claim must hold for
# convolutional gradient geometry too (SURVEY.md §7 hard part #1), not
# just the MLP the original artifact ran.  Kept tiny: XLA:CPU lowers the
# emulator's batched-parameter convs through a slow path (PERF.md §10);
# the full-size conv table in PARITY.md runs on the TPU.
CONV_CFG = model_config("convnet", (8, 8, 3), num_classes=4,
                        widths=(8,), dense=16)
_CONV_FULL = datasets.synthetic_classification(1536, (8, 8, 3), 4,
                                               seed=3)
_CONV_IDX = np.arange(len(_CONV_FULL))
CONV_TRAIN = _CONV_FULL.filter(_CONV_IDX < 1024)
CONV_EVAL = _CONV_FULL.filter(_CONV_IDX >= 1024)


# Recurrent-scale parity: the third gradient geometry (recurrence, gate
# saturation, shared weights through time — the IMDB/DynSGD baseline
# row).  adam workers: plain SGD does not learn the token-count task in
# any smoke budget (measured 0.56-0.58 vs 0.97, scripts/parity.py).
# Window 2 is the baseline shape; the full-size sweep behind it (window
# 1 matches sync to 0.2 points, an MLP-adam control shows no window-4
# gap) lives in PARITY.md's BiLSTM section.
LSTM_CFG = model_config("bilstm", (16,), input_dtype="int32",
                        vocab_size=100, embed_dim=16, hidden_dim=16,
                        num_classes=2)
_LSTM_FULL = datasets.imdb_synth(3072, seq_len=16, vocab_size=100,
                                 seed=3)
_LSTM_IDX = np.arange(len(_LSTM_FULL))
LSTM_TRAIN = _LSTM_FULL.filter(_LSTM_IDX < 2048)
LSTM_EVAL = _LSTM_FULL.filter(_LSTM_IDX >= 2048)


@pytest.mark.parametrize("cls", [ADAG, DynSGD])
def test_lstm_async_matches_sync_on_same_budget(cls):
    common = dict(batch_size=32, num_epoch=4, learning_rate=0.005,
                  seed=0, worker_optimizer="adam")
    sync = SyncTrainer(LSTM_CFG, num_workers=4, **common)
    sync.train(LSTM_TRAIN)
    sync_acc = evaluate_model(sync.model, sync.trained_variables,
                              LSTM_EVAL, batch_size=512)["accuracy"]
    t = cls(LSTM_CFG, num_workers=4, communication_window=2, **common)
    t.train(LSTM_TRAIN)
    acc = evaluate_model(t.model, t.trained_variables, LSTM_EVAL,
                         batch_size=512)["accuracy"]
    assert sync_acc > 0.7, sync_acc
    assert acc > sync_acc - 0.10, (sync_acc, acc)


@pytest.mark.parametrize("cls", [ADAG, AEASGD])
def test_conv_async_matches_sync_on_same_budget(cls):
    # lr/epochs sized so the budget actually converges: in the
    # pre-convergence transient the elastic CENTER (an EMA of workers)
    # lags by construction — measured: at lr=0.02/2ep sync itself sits
    # at 0.66 and AEASGD at 0.48-0.55, while at lr=0.05/3ep the gap is
    # <= 0.01 for every rho in [1, 5] (same shape as the MLP sweep)
    common = dict(batch_size=16, num_epoch=3, learning_rate=0.05,
                  seed=0)

    sync = SyncTrainer(CONV_CFG, num_workers=4, **common)
    sync.train(CONV_TRAIN)
    sync_acc = evaluate_model(sync.model, sync.trained_variables,
                              CONV_EVAL, batch_size=512)["accuracy"]
    extra = {"rho": 2.5} if issubclass(cls, AEASGD) else {}
    t = cls(CONV_CFG, num_workers=4, communication_window=2,
            **common, **extra)
    t.train(CONV_TRAIN)
    acc = evaluate_model(t.model, t.trained_variables, CONV_EVAL,
                         batch_size=512)["accuracy"]
    assert sync_acc > 0.5, sync_acc
    assert acc > sync_acc - 0.10, (sync_acc, acc)

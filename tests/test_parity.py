"""Async-vs-sync convergence parity (BASELINE.md primary metric) at CI
scale: the emulated-staleness async trainers must match the synchronous
control arm's held-out accuracy on an identical data/epoch budget."""

import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.evaluators import evaluate_model
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import (ADAG, AEASGD, EAMSGD, DynSGD,
                                    SyncTrainer)

CFG = model_config("mlp", (16,), num_classes=8, hidden=(32,))
_FULL = datasets.synthetic_classification(3072, (16,), 8, seed=0)
_IDX = np.arange(len(_FULL))
TRAIN = _FULL.filter(_IDX < 2048)
EVAL = _FULL.filter(_IDX >= 2048)


def _accuracy(trainer) -> float:
    trainer.train(TRAIN)
    return evaluate_model(trainer.model, trainer.trained_variables,
                          EVAL, batch_size=512)["accuracy"]


# The elastic family (AEASGD/EAMSGD) runs at the SAME learning rate as
# every other arm: the round-2 parity artifact down-tuned AEASGD to
# lr=0.02 and recorded a -6.3-point "regression" that a rho x lr sweep
# showed was pure lr under-convergence — at the shared lr the elastic
# pull costs nothing at any rho in [1, 10] (PARITY.md).
@pytest.mark.parametrize("cls", [ADAG, DynSGD, AEASGD, EAMSGD])
def test_async_matches_sync_on_same_budget(cls):
    common = dict(batch_size=32, num_epoch=3, learning_rate=0.05, seed=0)
    sync_acc = _accuracy(SyncTrainer(CFG, num_workers=4, **common))
    extra = {"rho": 2.5} if issubclass(cls, AEASGD) else {}
    async_acc = _accuracy(cls(CFG, num_workers=4,
                              communication_window=2, **common, **extra))
    assert sync_acc > 0.7, sync_acc  # the control arm itself must learn
    assert async_acc > sync_acc - 0.10, (sync_acc, async_acc)

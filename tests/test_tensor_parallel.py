"""Tensor parallelism: rule->spec mapping, optimizer-state sharding
inheritance, and TP-vs-DP training parity (same numerics, GSPMD inserts
the collectives).  Beyond the reference (SURVEY.md §2.3: TP absent)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu.data import datasets
from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.parallel import tensor_parallel as tp
from distkeras_tpu.trainers import SyncTrainer
from distkeras_tpu.workers import TrainState, resolve_optimizer

LM = model_config("transformer_lm", (16,), input_dtype="int32",
                  vocab_size=32, num_layers=2, d_model=32, num_heads=4,
                  max_len=16, dtype="float32")
M = mesh_lib.MODEL_AXIS


def _lm_state():
    spec = ModelSpec.from_config(LM)
    variables = spec.build().init(
        jax.random.key(0), np.zeros((2, 16), np.int32))
    return TrainState.create(variables, resolve_optimizer("adam", 1e-3),
                             jax.random.key(1))


def test_transformer_rules_map_expected_specs(devices):
    mesh = mesh_lib.create_mesh(2, model_parallel=2)
    shardings = tp.tree_shardings(mesh, _lm_state(),
                                  tp.rules_for("transformer_lm"))
    flat = {
        tp._path_str(path): s.spec
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]}

    def spec_of(suffix):
        hits = {k: v for k, v in flat.items() if k.endswith(suffix)
                and k.startswith("params")}
        assert hits, (suffix, sorted(flat))
        specs = set(hits.values())
        assert len(specs) == 1, hits
        return specs.pop()

    assert spec_of("query/kernel") == P(None, M, None)
    assert spec_of("out/kernel") == P(M, None, None)
    assert spec_of("Dense_0/kernel") == P(None, M)
    assert spec_of("Dense_1/kernel") == P(M, None)
    assert spec_of("lm_head/kernel") == P(None, M)
    assert spec_of("LayerNorm_0/scale") == P()
    assert spec_of("Embed_0/embedding") == P()


def test_optimizer_state_inherits_param_specs(devices):
    mesh = mesh_lib.create_mesh(2, model_parallel=2)
    shardings = tp.tree_shardings(mesh, _lm_state(),
                                  tp.rules_for("transformer_lm"))
    flat = {
        tp._path_str(path): s.spec
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]}
    # Adam mu/nu mirror the param tree: same suffix => same spec.
    mu = {k: v for k, v in flat.items()
          if "mu" in k and k.endswith("query/kernel")}
    assert mu and set(mu.values()) == {P(None, M, None)}, flat


def test_bad_model_parallel_raises():
    with pytest.raises(ValueError, match="model_parallel"):
        SyncTrainer(LM, model_parallel=0)
    with pytest.raises(ValueError, match="model_parallel"):
        SyncTrainer(LM, model_parallel=-2)


def test_unknown_family_raises():
    with pytest.raises(ValueError, match="no tensor-parallel rules"):
        tp.rules_for("resnet")


def test_rank_mismatch_raises():
    with pytest.raises(ValueError, match="rank"):
        tp.spec_for("query/kernel", np.zeros((4,)),
                    tp.rules_for("transformer_lm"))


@pytest.mark.parametrize("config,loss,data", [
    (LM, "sparse_categorical_crossentropy",
     datasets.lm_synth(256, seq_len=16, vocab_size=32, seed=3)),
    (model_config("mlp", (8,), num_classes=4, hidden=(32, 32)),
     "categorical_crossentropy",
     datasets.synthetic_classification(256, (8,), 4, seed=3)),
])
def test_tp_matches_dp_training(devices, config, loss, data):
    """model_parallel=2 must reproduce the pure-DP run: same parameters,
    same data order, same update rule — only the layout differs."""
    def run(mp):
        t = SyncTrainer(config, num_workers=2, model_parallel=mp,
                        loss=loss, worker_optimizer="adam",
                        learning_rate=3e-3, batch_size=16, num_epoch=2)
        t.train(data)
        return t.history["epoch_loss"]

    dp, tp_ = run(1), run(2)
    np.testing.assert_allclose(tp_, dp, rtol=2e-4, atol=2e-5)
    assert dp[-1] < dp[0], dp


def test_ps_family_tensor_parallel_matches_dp():
    """ADAG with tensor-parallel workers over a (workers, model) mesh:
    TP is a layout change, not an algorithm change — the loss history
    must match the DP-only run of the same configuration."""
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import ADAG

    cfg = model_config("transformer_lm", (16,), input_dtype="int32",
                       vocab_size=64, num_layers=1, d_model=32,
                       num_heads=2, max_len=16, dtype="float32")
    data = datasets.lm_synth(256, seq_len=16, vocab_size=64, seed=0)
    kwargs = dict(num_workers=4, communication_window=2, batch_size=8,
                  num_epoch=1, learning_rate=1e-2,
                  loss="sparse_categorical_crossentropy",
                  worker_optimizer="adam", seed=3)
    dp = ADAG(cfg, **kwargs)
    dp.train(data)
    tp = ADAG(cfg, model_parallel=2, **kwargs)
    tp.train(data)
    np.testing.assert_allclose(tp.history["round_loss"],
                               dp.history["round_loss"],
                               rtol=2e-4, atol=2e-5)
    assert (dp.history["round_loss"][-1]
            < dp.history["round_loss"][0]), dp.history["round_loss"]
    # the TP run really places params on the model axis
    from distkeras_tpu.mesh import MODEL_AXIS

    specs = {
        str(getattr(leaf, "sharding", None) and leaf.sharding.spec)
        for leaf in jax.tree_util.tree_leaves(
            tp.trained_variables["params"])}
    assert any(MODEL_AXIS in s for s in specs), specs


def test_ps_family_tp_rejects_host_fidelity():
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import ADAG

    cfg = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    with pytest.raises(ValueError, match="DP-only"):
        ADAG(cfg, fidelity="host", model_parallel=2)


def test_stacked_shardings_check_unstacked_rank():
    """Rule rank errors fire against the UNSTACKED leaf, matching the
    non-stacked path (a too-long spec must not slip past the guard
    because of the worker axis)."""
    import jax.numpy as jnp

    from distkeras_tpu.mesh import MODEL_AXIS

    mesh = mesh_lib.create_mesh(4, model_parallel=2)
    stacked = {"bias": jnp.zeros((4, 6))}  # [W, n]: unstacked rank 1
    with pytest.raises(ValueError, match="rank-2 spec"):
        tp.stacked_tree_shardings(
            mesh, stacked, ((r"bias$", P(None, MODEL_AXIS)),))
    # a correct rank-1 rule prepends the worker axis
    sh = tp.stacked_tree_shardings(
        mesh, stacked, ((r"bias$", P(MODEL_AXIS)),))
    assert sh["bias"].spec == P(mesh_lib.WORKER_AXIS, MODEL_AXIS)

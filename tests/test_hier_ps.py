"""Hierarchical aggregation tier (ISSUE 20): GroupLeader fold law and
byte parity vs the flat topology, exactly-once under chaos on the
leader hop, leader-death degradation to direct-to-root, and the
trainer's ``ps_groups`` arm — the whole suite under the lockset race
detector."""

import threading
import time

import jax
import numpy as np
import pytest

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.analysis import racecheck
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel.faults import ChaosTransport
from distkeras_tpu.parallel.hier_ps import (
    HIER_LEADER_BASE,
    GroupLeader,
    HierPSServer,
    LeaderRoute,
    resilient_hier_client,
)
from distkeras_tpu.parallel.host_ps import (
    HostParameterServer,
    PSClient,
    PSServer,
)
from distkeras_tpu.parallel.sharded_ps import ShardedParameterServer
from distkeras_tpu.parallel.update_rules import (
    DownpourRule,
    DynSGDRule,
    ElasticRule,
)
from distkeras_tpu.trainers import DOWNPOUR

jax.config.update("jax_platforms", "cpu")

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(512, (8,), 4, seed=0)


@pytest.fixture(autouse=True)
def _racecheck():
    """Leader fold/flush state is lock-heavy concurrent code: run the
    whole suite under the lockset race + deadlock detector and fail on
    any report."""
    racecheck.enable()
    yield
    reports = racecheck.disable()
    assert not reports, "\n".join(str(r) for r in reports)


def _dyadic_center(leaves=3, dim=8, seed=0):
    """Center leaves that are multiples of 2^-6: with dyadic payloads
    every f32 sum is exact in ANY association order, so byte equality
    across topologies tests the protocol, not float reassociation."""
    rng = np.random.default_rng(seed)
    return {f"w{i}": (rng.integers(-512, 512, size=(dim, dim))
                      * 2.0 ** -6).astype(np.float32)
            for i in range(leaves)}


def _dyadic_delta(center, w, r):
    val = np.float32((((w * 7 + r) % 13) - 6) * 2.0 ** -6)
    return {k: np.full_like(v, val) for k, v in center.items()}


def _expected_center(center, workers, rounds):
    out = {k: v.copy() for k, v in center.items()}
    for w in range(workers):
        for r in range(rounds):
            d = _dyadic_delta(center, w, r)
            out = {k: out[k] + d[k] for k in out}
    return out


def _run_workers(center, addrs_of, workers, rounds, client_of=None):
    """``workers`` socket threads, each pull + the seeded dyadic
    commit schedule; raises the first worker error."""
    barrier = threading.Barrier(workers)
    errs = []

    def worker(w):
        try:
            if client_of is not None:
                client = client_of(w)
            else:
                client = PSClient(*addrs_of(w), w, center)
            client.pull()
            barrier.wait()
            for r in range(rounds):
                if client_of is not None:
                    # ResilientPSClient stamps its own commit seqs
                    client.commit(_dyadic_delta(center, w, r))
                else:
                    client.commit(_dyadic_delta(center, w, r), seq=r)
            client.close()
        except Exception as e:
            errs.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]


def _hier_topology(center, rule, groups, group_size,
                   aggregate_window=None):
    ps = HostParameterServer(rule, center)
    root = HierPSServer(ps, center).start()
    leaders = [GroupLeader(type(rule)(), center, root.address,
                           group_id=gi,
                           aggregate_window=(aggregate_window
                                             or group_size)).start()
               for gi in range(groups)]
    return ps, root, leaders


def test_flat_and_hier_centers_are_byte_identical():
    """The tentpole parity claim: the same seeded dyadic schedule
    through the flat single-root PS and the 2-leader tree lands on
    byte-identical centers, with the root applying every logical
    commit but seeing only W/g upstream messages."""
    center = _dyadic_center()
    W, G, R = 6, 2, 3
    g = W // G

    flat_ps = HostParameterServer(DownpourRule(), center)
    flat_srv = PSServer(flat_ps, center).start()
    _run_workers(center, lambda w: flat_srv.address, W, R)
    flat_srv.stop()

    ps, root, leaders = _hier_topology(center, DownpourRule(), G, g)
    _run_workers(center, lambda w: leaders[w // g].address, W, R)
    for lead in leaders:
        lead.drain()
        lead.stop()
    root.stop()

    exp = _expected_center(center, W, R)
    for k in center:
        assert (np.asarray(ps.center[k]).tobytes()
                == np.asarray(flat_ps.center[k]).tobytes()
                == exp[k].tobytes()), k
    assert ps.num_commits == flat_ps.num_commits == W * R
    assert sum(l.num_upstream for l in leaders) == W * R // g
    assert sum(l.num_commits for l in leaders) == W * R
    # the root's staleness record carries the leaders' per-worker
    # vectors — one entry per logical commit, same as flat
    assert len(ps.staleness_log) == W * R


def test_dynsgd_fold_carries_staleness_vector_byte_exactly():
    """DynSGD scales each payload by 1/(staleness+1) at commit time;
    the leader must apply that scaling per CONSTITUENT with its own
    leader-local staleness before summing, and ship the staleness
    vector upstream — byte-exact against the hand-rolled law, with
    the root logging the vector."""
    center = _dyadic_center(seed=1)
    ps, root, leaders = _hier_topology(center, DynSGDRule(), 1, 3)
    lead = leaders[0]
    # all three pull at clock 0, then commit in order: worker i
    # commits at leader clock i -> staleness i
    for w in range(3):
        lead.pull(w)
    # the hand-rolled hier law: the fold accumulates from zero with
    # tree_axpy's exact association (alpha cast to the leaf dtype
    # BEFORE the multiply), then the root adds the finished fold to
    # the center — 1/3 is non-dyadic, so the association order is
    # part of the contract being pinned here
    fold = {k: np.zeros_like(v) for k, v in center.items()}
    for i in range(3):
        d = _dyadic_delta(center, i, 0)
        lead.commit(i, d, seq=0)
        a = np.float32(1.0) / np.float32(i + 1)
        fold = {k: a * d[k] + fold[k] for k in fold}
    exp = {k: center[k] + fold[k] for k in center}
    lead.drain()
    lead.stop()
    root.stop()
    for k in center:
        assert (np.asarray(ps.center[k]).tobytes()
                == exp[k].astype(np.float32).tobytes()), k
    assert list(ps.staleness_log) == [0, 1, 2]
    assert ps.num_commits == 3


def test_elastic_family_is_rejected_everywhere():
    """Hier is delta-family only: params-kind payloads have no
    closed-form sum, so the leader constructor, both servers'
    ``commit_group``, and the trainer kwarg all refuse."""
    center = _dyadic_center()
    with pytest.raises(ValueError, match="delta"):
        GroupLeader(ElasticRule(alpha=0.1), center, ("127.0.0.1", 1))
    host = HostParameterServer(ElasticRule(alpha=0.1), center)
    with pytest.raises(ValueError, match="delta"):
        host.commit_group(HIER_LEADER_BASE, center, [0], [0], seq=0)
    sharded = ShardedParameterServer(ElasticRule(alpha=0.1), center, 2)
    with pytest.raises(ValueError, match="delta"):
        sharded.commit_group(HIER_LEADER_BASE, center, [0], [0],
                             seq=0)


def test_upstream_retry_is_deduped_at_the_root():
    """A lost-ack leader retry re-sends the SAME window seq; the root
    must hand back the cached center without double-applying — the
    exactly-once hinge of the whole tier."""
    center = _dyadic_center()
    rule = DownpourRule()
    ps = HostParameterServer(rule, center)
    fold = _dyadic_delta(center, 0, 0)
    first = ps.commit_group(HIER_LEADER_BASE, fold, [0, 1], [0, 1],
                            seq=7)
    again = ps.commit_group(HIER_LEADER_BASE, fold, [0, 1], [0, 1],
                            seq=7)
    assert ps.num_commits == 2  # one window of two constituents
    for k in center:
        assert (np.asarray(first[k]).tobytes()
                == np.asarray(again[k]).tobytes())
    # sharded root: same dedupe, all shards advance exactly once
    sh = ShardedParameterServer(rule, center, 2)
    sh.commit_group(HIER_LEADER_BASE, fold, [0, 1], [0, 1], seq=3)
    sh.commit_group(HIER_LEADER_BASE, fold, [0, 1], [0, 1], seq=3)
    assert sh.num_commits == 2
    assert [s.num_commits for s in sh._shards] == [2, 2]
    # the deduped retry applied NOTHING: one window's fold, once
    for k in center:
        np.testing.assert_array_equal(
            np.asarray(sh.center[k]),
            center[k] + fold[k])


# every entry sets skip_ops itself (same sweep shape as
# test_faults.py): partition must cover the startup connects, the
# rate classes fault established exchanges
SWEEP = {
    "reset": dict(reset_rate=0.2, max_injections=4, skip_ops=6),
    "truncate": dict(truncate_rate=0.2, max_injections=4, skip_ops=6),
    "delay": dict(delay_rate=0.15, delay_s=0.02, skip_ops=6),
    "partition": dict(partition_at=0, partition_ops=6),
}


@pytest.mark.parametrize("fault", sorted(SWEEP))
def test_chaos_on_the_leader_hop_stays_exactly_once(fault):
    """``ChaosTransport(target_ports=<leader ports>)`` attacks ONLY
    the worker->leader hop of a 2-leader topology: every fault class
    must leave the run exactly-once — root logical commits == W*R and
    the final center equal to the exact dyadic sum — whether the
    workers retried in place (transient faults on a live leader) or
    degraded to direct-to-root (the partition window kills the
    probe too)."""
    center = _dyadic_center()
    W, G, R = 4, 2, 3
    g = W // G
    ps, root, leaders = _hier_topology(center, DownpourRule(), G, g)
    ports = {lead.address[1] for lead in leaders}
    with ChaosTransport(seed=11, target_ports=ports,
                        **SWEEP[fault]) as ct:
        _run_workers(
            center, None, W, R,
            client_of=lambda w: resilient_hier_client(
                leaders[w // g].address, root.address, worker_id=w,
                template=center, retries=10, seed=101 * w,
                use_seq=True))
    for lead in leaders:
        lead.drain()
        lead.stop()
    root.stop()
    assert ct.counts[fault] > 0, ct.counts  # the class really fired
    assert ps.num_commits == W * R
    exp = _expected_center(center, W, R)
    for k in center:
        assert np.asarray(ps.center[k]).tobytes() == exp[k].tobytes()


def test_leader_death_degrades_workers_to_direct_to_root(tmp_path):
    """Kill a leader mid-run: its workers fail over to the root
    within one retry (degraded, not down), the ``leader_down`` flight
    event and failover counter fire, and — because the dead leader
    was drained first — the final center is byte-identical to the
    full dyadic sum."""
    center = _dyadic_center()
    W, G, R = 4, 2, 4
    g = W // G
    flight_recorder.start(tmp_path / "fdr")
    tel = telemetry.enable()
    try:
        ps, root, leaders = _hier_topology(center, DownpourRule(),
                                           G, g)
        clients = [resilient_hier_client(
            leaders[w // g].address, root.address, worker_id=w,
            template=center, retries=10, seed=w, use_seq=True)
            for w in range(W)]
        for c in clients:
            c.pull()
        for w, c in enumerate(clients):
            for r in range(2):
                c.commit(_dyadic_delta(center, w, r))
        # flush the doomed leader's window, then crash it: nothing
        # acked is lost, so parity must hold end to end
        leaders[0].drain()
        leaders[0].kill()
        for w, c in enumerate(clients):
            for r in range(2, R):
                c.commit(_dyadic_delta(center, w, r))
        routes = [c.replicas for c in clients]
        for c in clients:
            c.close()
        for lead in leaders[1:]:
            lead.drain()
            lead.stop()
        root.stop()
    finally:
        snap = tel.metrics.snapshot()
        telemetry.disable()
        flight_recorder.stop()
    # group 0's workers failed over exactly once each; group 1's never
    assert all(r.failovers >= 1 for r in routes[:g])
    assert all(r.failovers == 0 for r in routes[g:])
    fails = sum(v for k, v in snap["counters"].items()
                if k.startswith("ps_leader_failovers_total"))
    assert fails >= g
    events = flight_recorder.FlightRecorder(
        tmp_path / "fdr").read_events()
    downs = [e for e in events if e["kind"] == "leader_down"]
    assert {e["leader_port"] for e in downs} == {
        leaders[0].address[1]}
    assert ps.num_commits == W * R
    exp = _expected_center(center, W, R)
    for k in center:
        assert np.asarray(ps.center[k]).tobytes() == exp[k].tobytes()


def test_trainer_ps_groups_arm_end_to_end():
    """The trainer's topology kwarg: a hierarchical DOWNPOUR run
    trains to a finite loss, records the fan-in history keys, and
    composes with wire compression on the worker->leader hop."""
    t = DOWNPOUR(MLP, fidelity="host", transport="socket",
                 ps_groups=[(None, [0, 1]), (None, [2, 3])],
                 num_workers=4, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.01,
                 compression="int8", worker_timeout=5.0)
    t.train(DATA)
    h = t.history
    assert np.isfinite(h["epoch_loss"]).all()
    assert "worker_failures" not in h
    ups = h["ps_upstream_commits"][-1]
    assert ups > 0
    assert h["ps_fanin_reduction"][-1] == pytest.approx(2.0)
    assert h["ps_leader_failovers"][-1] == 0
    # every logical commit reached the root exactly once
    ps = t.parameter_server_state
    assert ps.num_commits == len(h["round_loss"])
    assert ps.num_commits == 2 * ups
    # the compressed wire really ran
    assert h["commit_wire_bytes"][-1] > 0
    assert h["commit_wire_bytes"][-1] < h["commit_raw_bytes"][-1]


def test_trainer_validation_rejects_bad_groupings():
    kw = dict(fidelity="host", num_workers=4,
              communication_window=2, batch_size=16, num_epoch=1,
              learning_rate=0.01)
    with pytest.raises(ValueError, match="socket"):
        DOWNPOUR(MLP, transport="inprocess",
                 ps_groups=[(None, [0, 1])], **kw)
    with pytest.raises(ValueError, match="two ps_groups"):
        DOWNPOUR(MLP, transport="socket",
                 ps_groups=[(None, [0, 1]), (None, [1, 2])], **kw)
    with pytest.raises(ValueError, match="out of range"):
        DOWNPOUR(MLP, transport="socket", ps_groups=[(None, [4])],
                 **kw)
    with pytest.raises(ValueError, match="mutually exclusive"):
        DOWNPOUR(MLP, transport="socket", ps_groups=[(None, [0])],
                 ps_replicas=[("127.0.0.1", 1)], **kw)

"""Child program for the multi-host integration test: joins a 2-process
jax.distributed cluster (4 virtual CPU devices per process -> 8 global),
trains SyncTrainer and ADAG on the deterministically-generated dataset,
and prints one JSON line of results for the parent to compare."""

import json
import os

import numpy as np

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import ADAG, SyncTrainer


def main():
    mesh_lib.initialize_cluster()  # env-driven (deploy.launch_local)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4

    data = datasets.synthetic_classification(1024, (8,), 4, seed=0)
    cfg = model_config("mlp", (8,), num_classes=4, hidden=(16,))

    sync = SyncTrainer(cfg, num_workers=8, batch_size=8, num_epoch=2,
                       learning_rate=0.05)
    sync.train(data)

    adag = ADAG(cfg, num_workers=8, communication_window=2,
                batch_size=8, num_epoch=1, learning_rate=0.05)
    adag.train(data)

    # Fewer workers than global devices: the mesh must still span both
    # processes (regression: a device-prefix mesh landed entirely on
    # process 0 — crash on process 1, silent half-data training on 0).
    small = SyncTrainer(cfg, num_workers=4, batch_size=8, num_epoch=1,
                        learning_rate=0.05)
    small.train(data)

    # Tensor parallelism across hosts: (4 workers, 2 model) mesh, the
    # param/optimizer state sharded by the Megatron rules and assembled
    # per-process through the sharding-tree path of
    # global_batch_from_local.  Same config as `small` except layout,
    # so the losses must agree.
    tp = SyncTrainer(cfg, num_workers=4, model_parallel=2, batch_size=8,
                     num_epoch=1, learning_rate=0.05)
    tp.train(data)

    # Multi-host sharded checkpointing: a TP run killed at 1/2 epochs
    # writes the orbax per-shard layout; resuming reproduces the
    # uninterrupted 2-epoch run's history.
    tp_resume_match = None
    ckpt_dir = os.environ.get("DKT_CKPT_DIR")
    if ckpt_dir:
        tp_kwargs = dict(num_workers=4, model_parallel=2, batch_size=8,
                         learning_rate=0.05)
        full = SyncTrainer(cfg, num_epoch=2, **tp_kwargs)
        full.train(data)
        part = SyncTrainer(cfg, num_epoch=1, checkpoint_dir=ckpt_dir,
                           **tp_kwargs)
        part.train(data)
        resumed = SyncTrainer(cfg, num_epoch=2, **tp_kwargs)
        resumed.train(data, resume_from=ckpt_dir)
        tp_resume_match = (resumed.history["epoch_loss"]
                           == full.history["epoch_loss"])

    # Multi-host sharded checkpointing of the async PS family: worker
    # states live sharded across both processes, so the checkpoint is
    # the per-shard orbax layout; kill-at-1/2-epochs + resume must
    # reproduce the uninterrupted run's telemetry exactly.
    ps_resume_match = None
    if ckpt_dir:
        ps_dir = os.path.join(ckpt_dir, "ps_family")
        ps_kwargs = dict(num_workers=8, communication_window=2,
                         batch_size=8, learning_rate=0.05)
        ps_full = ADAG(cfg, num_epoch=2, **ps_kwargs)
        ps_full.train(data)

        class _Stop(Exception):
            pass

        # crash mid-epoch-2, right after the round-2 sharded save, so
        # the resume exercises start_round>0 + seeded history on the
        # per-shard layout (both processes kill at the same cursor)
        ps_part = ADAG(cfg, num_epoch=2, checkpoint_dir=ps_dir,
                       checkpoint_every_rounds=2, **ps_kwargs)
        orig_save = ps_part._maybe_save

        def _saving(state, cursor):
            orig_save(state, cursor)
            if cursor.get("epoch") == 1 and cursor.get("round") == 2:
                raise _Stop

        ps_part._maybe_save = _saving
        try:
            ps_part.train(data)
            raise AssertionError("kill point never reached")
        except _Stop:
            pass
        ps_resumed = ADAG(cfg, num_epoch=2, **ps_kwargs)
        ps_resumed.train(data, resume_from=ps_dir)
        ps_resume_match = (
            ps_resumed.history["round_loss"]
            == ps_full.history["round_loss"]
            and ps_resumed.history["epoch_loss"]
            == ps_full.history["epoch_loss"]
            and ps_resumed.history["staleness"]
            == ps_full.history["staleness"])

    # Async PS with tensor-parallel workers across hosts: (4 workers,
    # 2 model) mesh spanning both processes, worker states born
    # sharded, PS center sharded by the TP specs — the losses must
    # match the DP-only ADAG run of the same shape when algorithmic
    # config matches (here we just require identical telemetry on both
    # processes and convergence: the DP run above uses 8 workers, so
    # cross-checking is within this arm only).
    ps_tp = ADAG(cfg, num_workers=4, model_parallel=2,
                 communication_window=2, batch_size=8, num_epoch=1,
                 learning_rate=0.05)
    ps_tp.train(data)

    # Cross-host faithful PS (design 5a over real TCP): process 0
    # hosts the server, both processes run 2 of the 4 workers; every
    # process must report identical global telemetry and center.
    from distkeras_tpu.trainers import DOWNPOUR

    host_ps = DOWNPOUR(cfg, fidelity="host", transport="socket",
                       num_workers=4, communication_window=2,
                       batch_size=8, num_epoch=1, learning_rate=0.01,
                       worker_optimizer="adam")
    host_ps.train(data)
    host_center_sum = float(sum(
        np.abs(v).sum() for v in jax.tree_util.tree_leaves(
            host_ps.trained_variables["params"])))

    print(json.dumps({
        "process": jax.process_index(),
        "sync_epoch_loss": [round(x, 6)
                            for x in sync.history["epoch_loss"]],
        "adag_round_loss": [round(x, 6)
                            for x in adag.history["round_loss"]],
        "adag_staleness": adag.history["staleness"][-1],
        "small_sync_loss": [round(x, 6)
                            for x in small.history["epoch_loss"]],
        "tp_sync_loss": [round(x, 6)
                         for x in tp.history["epoch_loss"]],
        "tp_resume_match": tp_resume_match,
        "ps_resume_match": ps_resume_match,
        "ps_tp_round_loss": [round(x, 6)
                             for x in ps_tp.history["round_loss"]],
        "ps_tp_staleness": sorted(ps_tp.history["staleness"][-1]),
        "host_ps_epoch_loss": [round(x, 6) for x in
                               host_ps.history["epoch_loss"]],
        "host_ps_commits": len(host_ps.history["staleness"][-1]),
        "host_ps_local_rounds": len(host_ps.history["round_loss"]),
        "host_ps_center_sum": round(host_center_sum, 6),
    }))


if __name__ == "__main__":
    main()

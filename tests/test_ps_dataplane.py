"""On-chip compiled PS data plane (ISSUE 12 + 16): mesh-tier parity
against the emulated closed form, the one-compile-per-(round-shape x
comm-config) guard, the partition-rule resolver, the tier registry's
validation surface, and the ISSUE 16 comm-compression / async-dispatch
contracts:

* on-chip codec law parity vs the host ``compression.py`` oracles —
  int8 ``q`` is BITWISE equal and the scale matches to rtol 1e-6 (the
  host codec computes ``amax/127`` in float64, the device in float32);
  the bf16 delta cast is the exact ``Bf16Codec`` law;
* the int8 round end-to-end equals the closed-form oracle "fast round
  run from the dequantized center, delta folded into the exact
  center" to the standard 2e-5 parity tolerance (exact because the
  on-chip ``segment_max`` + ``pmax`` reproduces the global per-leaf
  ``max|x|`` bit-for-bit);
* the metrics ring + async driver is byte-identical to the eager
  ``sync=True`` oracle under ``metrics_every in {1, 4}``.

Parity runs on the MLP: matmuls are batching-stable on CPU, so the
mesh tier's per-device window must match the emulated tier's vmapped
window to float tolerance.  (Convs are NOT batching-stable on the CPU
backend — the flagship smoke documents that.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu import telemetry
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel import ps_dataplane
from distkeras_tpu.parallel.ps_emulator import (
    commit_permutation,
    flush_pending,
    make_pipelined_round_fn,
    make_round_fn,
)
from distkeras_tpu.parallel.tiers import TIERS, resolve_tier, tiers_with
from distkeras_tpu.parallel.update_rules import RULES
from distkeras_tpu.trainers import AEASGD, DOWNPOUR
from distkeras_tpu.workers import (
    TrainState,
    make_train_step,
    resolve_optimizer,
)
from jax.sharding import PartitionSpec as P

MLP = model_config("mlp", (8,), num_classes=4, hidden=(32,))
DATA = datasets.synthetic_classification(2048, (8,), 4, seed=0)


def _setup(rule_name, W, rounds=3, window=2, batch=4):
    """Shared harness: model, rule, seeded batches/permutations, and
    fresh emulated + mesh states started from the same center."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    model = Tiny()
    tx = resolve_optimizer("momentum", 0.05)
    rule = RULES[rule_name]()
    variables = model.init(jax.random.key(0), jnp.ones((2, 8)))
    center = variables["params"]
    step = make_train_step(model, "sparse_categorical_crossentropy", tx)

    def make_worker(rng):
        return TrainState.create({"params": center}, tx, rng)

    keys = jax.random.split(jax.random.key(1), W)
    rngd = np.random.RandomState(0)
    batches = [
        {"features": jnp.asarray(rngd.randn(W, window, batch, 8),
                                 jnp.float32),
         "label": jnp.asarray(rngd.randint(0, 4, (W, window, batch)),
                              jnp.int32)}
        for _ in range(rounds)]
    pkey = jax.random.key(2)
    perms = []
    for _ in range(rounds):
        pkey, sub = jax.random.split(pkey)
        perms.append(commit_permutation(sub, W))
    ws = jax.vmap(make_worker)(keys)
    ps = rule.init_state(center)
    return rule, step, center, ws, ps, batches, perms, make_worker, keys


def _assert_tree_close(a, b, msg=""):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=1e-6, err_msg=msg)


@pytest.mark.parametrize("rule_name", ["downpour", "adag", "dynsgd"])
@pytest.mark.parametrize("W", [2, 4])
def test_mesh_round_matches_fast(rule_name, W):
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup(rule_name, W)
    rf = jax.jit(make_round_fn(rule, step, "fast"))
    ref_metrics = []
    for b, p in zip(batches, perms):
        ps, ws, met = rf(ps, ws, b, p)
        ref_metrics.append(jax.device_get(met))

    placement = mesh_lib.place_workers(W)
    dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh, center)
    mps, mws = dp.to_device(rule.init_state(center),
                            jax.vmap(make_worker)(keys))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    drv = ps_dataplane.MeshRoundDriver(dp, mps, mws, sync=True)
    for (b, p), ref in zip(zip(batches, perms), ref_metrics):
        drv.dispatch(jax.device_put(b, row), jax.device_put(p, rep))
        (met,) = drv.poll()
        _assert_tree_close(ref["loss"], met["loss"], rule_name)
        _assert_tree_close(ref["grad_norm"], met["grad_norm"],
                           rule_name)
        np.testing.assert_array_equal(np.asarray(ref["staleness"]),
                                      np.asarray(met["staleness"]))
    mps = drv.mps
    assert int(mps.clock) == int(ps.clock)
    _assert_tree_close(ps.center, dp.center(mps), rule_name)
    # exported state round-trips into the public PSState shape
    exported = dp.export_ps_state(mps)
    _assert_tree_close(ps.center, exported.center)
    assert int(exported.clock) == int(ps.clock)


@pytest.mark.parametrize("rule_name", ["downpour", "adag", "dynsgd"])
@pytest.mark.parametrize("W", [2, 4])
def test_mesh_pipelined_matches_emulated(rule_name, W):
    """The +W-offset pipelined contract, including the final
    ``flush_pending`` drain of the carried commit."""
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup(rule_name, W)
    rf = jax.jit(make_pipelined_round_fn(rule, step))
    pend = jax.tree_util.tree_map(jnp.zeros_like, ws.params)
    pperm, valid = jnp.arange(W), jnp.asarray(False)
    ref_metrics = []
    for b, p in zip(batches, perms):
        ps, ws, met, pend, pperm, valid = rf(ps, ws, b, p, pend,
                                             pperm, valid)
        ref_metrics.append(jax.device_get(met))
    ps = flush_pending(rule, ps, pend, pperm, W)

    placement = mesh_lib.place_workers(W)
    dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                    pipelined=True)
    mps, mws = dp.to_device(rule.init_state(center),
                            jax.vmap(make_worker)(keys))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    drv = ps_dataplane.MeshRoundDriver(dp, mps, mws, sync=True)
    for (b, p), ref in zip(zip(batches, perms), ref_metrics):
        drv.dispatch(jax.device_put(b, row), jax.device_put(p, rep))
        (met,) = drv.poll()
        _assert_tree_close(ref["loss"], met["loss"], rule_name)
        np.testing.assert_array_equal(np.asarray(ref["staleness"]),
                                      np.asarray(met["staleness"]))
    drv.flush_pipeline()
    mps = drv.mps
    assert int(mps.clock) == int(ps.clock)
    _assert_tree_close(ps.center, dp.center(mps), rule_name)


def test_one_compiled_program_per_round_shape():
    """The public trace counter proves the whole round is ONE compiled
    program reused across rounds; a new worker count is a new shape
    and exactly one more trace."""
    tel = telemetry.enable()
    try:
        for i, W in enumerate((4, 2)):
            (rule, step, center, ws, ps, batches, perms, make_worker,
             keys) = _setup("dynsgd", W)
            placement = mesh_lib.place_workers(W)
            dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh,
                                            center)
            mps, mws = dp.to_device(rule.init_state(center),
                                    jax.vmap(make_worker)(keys))
            row = mesh_lib.batch_sharding(placement.mesh)
            rep = mesh_lib.replicated_sharding(placement.mesh)
            ring = dp.init_ring()
            for r, (b, p) in enumerate(zip(batches, perms)):
                mps, mws, ring = dp.round(mps, mws,
                                          jax.device_put(b, row),
                                          jax.device_put(p, rep),
                                          ring, dp.slot_index(r))
            counters = tel.metrics.snapshot()["counters"]
            key = 'ps_round_compiles_total{fidelity="mesh"}'
            assert counters.get(key) == i + 1, counters
    finally:
        telemetry.disable()


def test_one_compiled_program_per_comm_config():
    """Each comm knob combination is its own program (the knobs change
    the lowered collectives), but cycling the metrics ring slot — a
    traced replicated scalar — must NOT retrace."""
    tel = telemetry.enable()
    try:
        (rule, step, center, ws, ps, batches, perms, make_worker,
         keys) = _setup("downpour", 2, rounds=3)
        placement = mesh_lib.place_workers(2)
        row = mesh_lib.batch_sharding(placement.mesh)
        rep = mesh_lib.replicated_sharding(placement.mesh)
        configs = [{}, {"comm_dtype": "bfloat16"},
                   {"comm_codec": "int8"},
                   {"comm_dtype": "bfloat16", "comm_codec": "int8",
                    "metrics_every": 2}]
        for i, kw in enumerate(configs):
            dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh,
                                            center, **kw)
            mps, mws = dp.to_device(rule.init_state(center),
                                    jax.vmap(make_worker)(keys))
            ring = dp.init_ring()
            for r, (b, p) in enumerate(zip(batches, perms)):
                mps, mws, ring = dp.round(
                    mps, mws, jax.device_put(b, row),
                    jax.device_put(p, rep), ring, dp.slot_index(r))
            counters = tel.metrics.snapshot()["counters"]
            key = 'ps_round_compiles_total{fidelity="mesh"}'
            assert counters.get(key) == i + 1, counters
    finally:
        telemetry.disable()


def test_trainer_mesh_matches_fast_end_to_end():
    def run(fidelity, **kw):
        t = DOWNPOUR(MLP, fidelity=fidelity, num_workers=4,
                     communication_window=4, batch_size=32,
                     num_epoch=1, learning_rate=0.005, seed=3, **kw)
        return t, t.train(DATA)

    tf_, vf = run("fast")
    tm, vm = run("mesh")
    _assert_tree_close(vf["params"], vm["params"])
    assert tf_.history["staleness"] == tm.history["staleness"]
    np.testing.assert_allclose(tf_.history["round_loss"],
                               tm.history["round_loss"],
                               rtol=2e-5, atol=1e-6)
    _assert_tree_close(tf_.parameter_server_state.center,
                       tm.parameter_server_state.center)
    assert int(tf_.parameter_server_state.clock) == \
        int(tm.parameter_server_state.clock)


def test_trainer_mesh_overlap_matches_faithful_pipelined():
    def run(fidelity):
        t = DOWNPOUR(MLP, fidelity=fidelity, num_workers=4,
                     communication_window=4, batch_size=32,
                     num_epoch=1, learning_rate=0.005, seed=3,
                     commit_overlap=True)
        return t, t.train(DATA)

    tf_, vf = run("faithful")
    tm, vm = run("mesh")
    _assert_tree_close(vf["params"], vm["params"])
    assert tf_.history["staleness"] == tm.history["staleness"]


# ---- ISSUE 16: on-chip comm compression -------------------------------

def test_int8_law_matches_host_codec():
    """The on-chip quantizer IS the ``Int8Codec`` law: ``q`` bitwise
    equal; scale to rtol 1e-6 (f32 vs the host codec's f64 ``amax/127``
    — the one documented divergence)."""
    from distkeras_tpu.parallel.compression import Int8Codec

    rng = np.random.RandomState(3)
    cases = [rng.randn(257).astype(np.float32) * 0.37,
             np.zeros(16, np.float32),           # all-zero -> scale 1.0
             np.asarray([127.0, -127.0, 1e-8], np.float32)]
    for arr in cases:
        q, s = jax.device_get(
            ps_dataplane.quantize_int8(jnp.asarray(arr)))
        enc = Int8Codec().encode_leaf(arr)
        np.testing.assert_array_equal(q, np.frombuffer(enc["q"],
                                                       np.int8))
        np.testing.assert_allclose(float(s), enc["s"], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(ps_dataplane.dequantize_int8(jnp.asarray(q), s)),
            np.frombuffer(enc["q"], np.int8).astype(np.float32)
            * enc["s"], rtol=1e-6)


def test_bf16_cast_matches_host_codec():
    """The delta wire narrowing is the exact ``Bf16Codec`` cast law
    (round-to-nearest-even)."""
    from distkeras_tpu.parallel.compression import Bf16Codec

    arr = (np.random.RandomState(4).randn(513) * 0.11).astype(
        np.float32)
    dev = np.asarray(
        jnp.asarray(arr).astype(jnp.bfloat16).astype(jnp.float32))
    codec = Bf16Codec()
    host = codec.decode_leaf(codec.encode_leaf(arr), arr.shape,
                             np.float32)
    np.testing.assert_array_equal(dev, host)


@pytest.mark.parametrize("rule_name", ["downpour", "dynsgd"])
def test_mesh_int8_round_matches_quantized_oracle(rule_name):
    """End-to-end int8 arm vs the closed-form oracle: each round the
    workers see ``Cq`` (the per-leaf int8 round-trip of the exact
    center — exact because on-chip ``segment_max`` + ``pmax`` computes
    the same global per-leaf ``max|x|``), and the resulting delta folds
    into the EXACT center.  So ``C' = C + (fast_round(center=Cq) - Cq)``
    to the standard 2e-5 parity tolerance."""
    W = 4
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup(rule_name, W)
    placement = mesh_lib.place_workers(W)
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                    comm_codec="int8")
    mps, mws = dp.to_device(rule.init_state(center),
                            jax.vmap(make_worker)(keys))
    drv = ps_dataplane.MeshRoundDriver(dp, mps, mws, sync=True)

    rf = jax.jit(make_round_fn(rule, step, "fast"))
    quant_rt = jax.jit(lambda t: jax.tree_util.tree_map(
        lambda x: ps_dataplane.dequantize_int8(
            *ps_dataplane.quantize_int8(x)), t))
    ps_ref, ws_ref = ps, jax.vmap(make_worker)(keys)
    for b, p in zip(batches, perms):
        drv.dispatch(jax.device_put(b, row), jax.device_put(p, rep))
        cq = quant_rt(ps_ref.center)
        ps_q, ws_ref, met_ref = rf(ps_ref._replace(center=cq), ws_ref,
                                   b, p)
        new_center = jax.tree_util.tree_map(
            lambda c, pq, q: c + (pq - q), ps_ref.center, ps_q.center,
            cq)
        ps_ref = ps_q._replace(center=new_center)
        (met,) = drv.poll()
        _assert_tree_close(met_ref["loss"], met["loss"], rule_name)
        np.testing.assert_array_equal(
            np.asarray(met_ref["staleness"]), met["staleness"])
    assert int(drv.mps.clock) == int(ps_ref.clock)
    _assert_tree_close(ps_ref.center, dp.center(drv.mps), rule_name)


def test_mesh_bf16_round_close_to_f32():
    """The bf16 delta wire reduces IN bf16 (the wire is the
    reduction), so end-to-end tolerance vs the f32 arm is the bf16
    mantissa (~3 decimal digits) scaled by the per-round delta — much
    looser than the 2e-5 parity bar, and documented as such."""
    W = 4
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup("downpour", W)
    placement = mesh_lib.place_workers(W)
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    finals = {}
    for dt in ("float32", "bfloat16"):
        dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh,
                                        center, comm_dtype=dt)
        mps, mws = dp.to_device(rule.init_state(center),
                                jax.vmap(make_worker)(keys))
        drv = ps_dataplane.MeshRoundDriver(dp, mps, mws, sync=True)
        for b, p in zip(batches, perms):
            drv.dispatch(jax.device_put(b, row),
                         jax.device_put(p, rep))
        assert int(drv.mps.clock) == W * len(batches)
        finals[dt] = jax.device_get(dp.center(drv.mps))
    for la, lb in zip(jax.tree_util.tree_leaves(finals["float32"]),
                      jax.tree_util.tree_leaves(finals["bfloat16"])):
        np.testing.assert_allclose(la, lb, rtol=0, atol=5e-3)


@pytest.mark.parametrize("metrics_every", [1, 4])
def test_async_driver_byte_identical_to_sync(metrics_every):
    """Tentpole 3 acceptance: ring contents under ``metrics_every`` in
    {1, 4} match the per-round fetch EXACTLY, and the async driver's
    end state is byte-identical to the synchronous oracle (same
    programs, same buffers — only the fetch schedule differs).  With
    rounds=3 and metrics_every=4 the ring never fills, so ``drain()``
    also covers the partial-ring path."""
    W, rounds = 4, 3
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup("dynsgd", W, rounds=rounds)
    placement = mesh_lib.place_workers(W)
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)

    def run(sync, me):
        dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh,
                                        center, metrics_every=me)
        mps, mws = dp.to_device(rule.init_state(center),
                                jax.vmap(make_worker)(keys))
        drv = ps_dataplane.MeshRoundDriver(dp, mps, mws, sync=sync)
        got = []
        for b, p in zip(batches, perms):
            drv.dispatch(jax.device_put(b, row),
                         jax.device_put(p, rep))
            got += drv.poll()
        got += drv.drain()
        return dp, drv, got

    dp_s, drv_s, met_s = run(True, 1)
    dp_a, drv_a, met_a = run(False, metrics_every)
    assert len(met_s) == len(met_a) == rounds
    for a, b in zip(met_s, met_a):
        for k in ("loss", "grad_norm", "staleness"):
            np.testing.assert_array_equal(a[k], b[k])
    for la, lb in zip(
            jax.tree_util.tree_leaves(dp_s.center(drv_s.mps)),
            jax.tree_util.tree_leaves(dp_a.center(drv_a.mps))):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert int(drv_s.mps.clock) == int(drv_a.mps.clock)


def test_comm_bytes_accounting_and_telemetry():
    """Static wire accounting: both knobs shrink their collective and
    the saving lands on ``ps_round_comm_bytes_saved_total`` once per
    dispatched round; the driver's ring reads land on
    ``ps_metrics_fetches_total`` (1 per ``metrics_every`` rounds plus
    the final partial drain)."""
    W, rounds = 2, 3
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup("downpour", W, rounds=rounds)
    placement = mesh_lib.place_workers(W)
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)

    f32 = ps_dataplane.MeshDataplane(rule, step, placement.mesh,
                                     center)
    assert f32.comm_bytes_saved_per_round == 0
    both = ps_dataplane.MeshDataplane(
        rule, step, placement.mesh, center, comm_dtype="bfloat16",
        comm_codec="int8", metrics_every=2)
    assert both.comm_bytes_per_round["gather"] < \
        f32.comm_bytes_per_round["gather"]
    assert both.comm_bytes_per_round["scatter"] < \
        f32.comm_bytes_per_round["scatter"]
    assert both.comm_bytes_saved_per_round > 0

    tel = telemetry.enable()
    try:
        mps, mws = both.to_device(rule.init_state(center),
                                  jax.vmap(make_worker)(keys))
        drv = ps_dataplane.MeshRoundDriver(both, mps, mws)
        for b, p in zip(batches, perms):
            drv.dispatch(jax.device_put(b, row),
                         jax.device_put(p, rep))
        drv.drain()
        counters = tel.metrics.snapshot()["counters"]
        saved_key = ('ps_round_comm_bytes_saved_total'
                     '{fidelity="mesh"}')
        assert counters[saved_key] == \
            rounds * both.comm_bytes_saved_per_round, counters
        # 3 rounds @ metrics_every=2: one full ring + one partial
        assert counters["ps_metrics_fetches_total"] == 2, counters
    finally:
        telemetry.disable()


def test_comm_knob_validation():
    (rule, step, center, *_rest) = _setup("downpour", 2)
    placement = mesh_lib.place_workers(2)
    with pytest.raises(ValueError, match="comm_dtype"):
        ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                   comm_dtype="float16")
    with pytest.raises(ValueError, match="comm_codec"):
        ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                   comm_codec="int4")
    with pytest.raises(ValueError, match="metrics_every"):
        ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                   metrics_every=0)


def test_trainer_comm_knobs_need_comm_compression_tier():
    """Non-default comm knobs on a tier without the capability must
    raise, naming the tiers that DO lower comm compression."""
    for kw in ({"comm_dtype": "bfloat16"}, {"comm_codec": "int8"},
               {"metrics_every": 4}):
        with pytest.raises(ValueError, match="mesh"):
            DOWNPOUR(MLP, fidelity="fast", num_workers=2,
                     learning_rate=0.005, **kw)
    # default values are fine everywhere
    DOWNPOUR(MLP, fidelity="fast", num_workers=2, learning_rate=0.005,
             comm_dtype="float32", comm_codec=None, metrics_every=1)


def test_trainer_mesh_metrics_every_history_identical():
    """Batching the metrics fetch must not change WHAT is recorded —
    only when it crosses to the host."""
    def run(**kw):
        t = DOWNPOUR(MLP, fidelity="mesh", num_workers=4,
                     communication_window=4, batch_size=32,
                     num_epoch=1, learning_rate=0.005, seed=3, **kw)
        v = t.train(DATA)
        return t, v

    t1, v1 = run()
    t4, v4 = run(metrics_every=4)
    assert t1.history["staleness"] == t4.history["staleness"]
    np.testing.assert_array_equal(t1.history["round_loss"],
                                  t4.history["round_loss"])
    for la, lb in zip(jax.tree_util.tree_leaves(v1["params"]),
                      jax.tree_util.tree_leaves(v4["params"])):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_trainer_mesh_int8_trains():
    """The compressed arm end-to-end through the trainer: loss stays
    finite and the run completes (parity is covered at the dataplane
    level; the trainer path exercises knob plumbing + driver)."""
    t = DOWNPOUR(MLP, fidelity="mesh", num_workers=4,
                 communication_window=4, batch_size=32, num_epoch=1,
                 learning_rate=0.005, seed=3, comm_codec="int8",
                 comm_dtype="bfloat16")
    t.train(DATA)
    assert np.isfinite(t.history["round_loss"]).all()


# ---- partition-rule resolver ------------------------------------------

def test_match_partition_rules_regex_and_scalars():
    tree = {"dense": {"kernel": jnp.zeros((4, 8)),
                      "bias": jnp.zeros((8,))},
            "scale": jnp.zeros(())}
    specs = ps_dataplane.match_partition_rules(
        ((r".*bias", P()), (r".*", P(mesh_lib.WORKER_AXIS))), tree)
    assert specs["dense"]["kernel"] == P(mesh_lib.WORKER_AXIS)
    assert specs["dense"]["bias"] == P()
    assert specs["scale"] == P()  # scalars never shard


def test_match_partition_rules_unmatched_leaf_raises():
    with pytest.raises(ValueError, match="dense/kernel"):
        ps_dataplane.match_partition_rules(
            ((r"nothing", P()),), {"dense": {"kernel": jnp.zeros((4,))}})


# ---- tier registry + trainer validation -------------------------------

def test_tier_registry():
    assert set(TIERS) == {"host", "faithful", "fast", "mesh"}
    assert resolve_tier("mesh").data_plane == "mesh"
    with pytest.raises(ValueError, match="valid lowering tiers"):
        resolve_tier("bogus")
    assert tiers_with("deterministic") == ["faithful", "fast", "mesh"]
    assert tiers_with("concurrent") == ["host"]
    assert tiers_with("comm_compression") == ["mesh"]


def test_unknown_fidelity_lists_tiers():
    with pytest.raises(ValueError, match="valid lowering tiers"):
        DOWNPOUR(MLP, fidelity="bogus", num_workers=2,
                 learning_rate=0.005)


def test_mesh_tier_rejects_checkpointing():
    t = DOWNPOUR(MLP, fidelity="mesh", num_workers=2, batch_size=32,
                 communication_window=2, num_epoch=1,
                 learning_rate=0.005, checkpoint_dir="/tmp/never")
    with pytest.raises(NotImplementedError, match="checkpointing "
                                                  "tiers"):
        t.train(DATA)


def test_mesh_tier_rejects_model_parallel():
    with pytest.raises(ValueError, match="tensor-parallel tiers"):
        DOWNPOUR(MLP, fidelity="mesh", num_workers=2, model_parallel=2,
                 learning_rate=0.005)


def test_mesh_tier_needs_one_device_per_worker():
    t = DOWNPOUR(MLP, fidelity="mesh", num_workers=16, batch_size=8,
                 communication_window=2, num_epoch=1,
                 learning_rate=0.003)
    with pytest.raises(ValueError, match="does not fit"):
        t.train(DATA)


def test_mesh_tier_rejects_elastic_family():
    t = AEASGD(MLP, fidelity="mesh", num_workers=2, batch_size=32,
               communication_window=2, num_epoch=1,
               learning_rate=0.005)
    with pytest.raises(ValueError, match="elastic"):
        t.train(DATA)

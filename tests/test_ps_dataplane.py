"""On-chip compiled PS data plane (ISSUE 12): mesh-tier parity against
the emulated closed form, the one-compile-per-round-shape guard, the
partition-rule resolver, and the tier registry's validation surface.

Parity runs on the MLP: matmuls are batching-stable on CPU, so the
mesh tier's per-device window must match the emulated tier's vmapped
window to float tolerance.  (Convs are NOT batching-stable on the CPU
backend — the flagship smoke documents that.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import mesh as mesh_lib
from distkeras_tpu import telemetry
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel import ps_dataplane
from distkeras_tpu.parallel.ps_emulator import (
    commit_permutation,
    flush_pending,
    make_pipelined_round_fn,
    make_round_fn,
)
from distkeras_tpu.parallel.tiers import TIERS, resolve_tier, tiers_with
from distkeras_tpu.parallel.update_rules import RULES
from distkeras_tpu.trainers import AEASGD, DOWNPOUR
from distkeras_tpu.workers import (
    TrainState,
    make_train_step,
    resolve_optimizer,
)
from jax.sharding import PartitionSpec as P

MLP = model_config("mlp", (8,), num_classes=4, hidden=(32,))
DATA = datasets.synthetic_classification(2048, (8,), 4, seed=0)


def _setup(rule_name, W, rounds=3, window=2, batch=4):
    """Shared harness: model, rule, seeded batches/permutations, and
    fresh emulated + mesh states started from the same center."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    model = Tiny()
    tx = resolve_optimizer("momentum", 0.05)
    rule = RULES[rule_name]()
    variables = model.init(jax.random.key(0), jnp.ones((2, 8)))
    center = variables["params"]
    step = make_train_step(model, "sparse_categorical_crossentropy", tx)

    def make_worker(rng):
        return TrainState.create({"params": center}, tx, rng)

    keys = jax.random.split(jax.random.key(1), W)
    rngd = np.random.RandomState(0)
    batches = [
        {"features": jnp.asarray(rngd.randn(W, window, batch, 8),
                                 jnp.float32),
         "label": jnp.asarray(rngd.randint(0, 4, (W, window, batch)),
                              jnp.int32)}
        for _ in range(rounds)]
    pkey = jax.random.key(2)
    perms = []
    for _ in range(rounds):
        pkey, sub = jax.random.split(pkey)
        perms.append(commit_permutation(sub, W))
    ws = jax.vmap(make_worker)(keys)
    ps = rule.init_state(center)
    return rule, step, center, ws, ps, batches, perms, make_worker, keys


def _assert_tree_close(a, b, msg=""):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-5, atol=1e-6, err_msg=msg)


@pytest.mark.parametrize("rule_name", ["downpour", "adag", "dynsgd"])
@pytest.mark.parametrize("W", [2, 4])
def test_mesh_round_matches_fast(rule_name, W):
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup(rule_name, W)
    rf = jax.jit(make_round_fn(rule, step, "fast"))
    ref_metrics = []
    for b, p in zip(batches, perms):
        ps, ws, met = rf(ps, ws, b, p)
        ref_metrics.append(jax.device_get(met))

    placement = mesh_lib.place_workers(W)
    dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh, center)
    mps, mws = dp.to_device(rule.init_state(center),
                            jax.vmap(make_worker)(keys))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    for (b, p), ref in zip(zip(batches, perms), ref_metrics):
        mps, mws, met = dp.round(mps, mws,
                                 jax.device_put(b, row),
                                 jax.device_put(p, rep))
        _assert_tree_close(ref["loss"], met["loss"], rule_name)
        _assert_tree_close(ref["grad_norm"], met["grad_norm"],
                           rule_name)
        np.testing.assert_array_equal(np.asarray(ref["staleness"]),
                                      np.asarray(met["staleness"]))
    assert int(mps.clock) == int(ps.clock)
    _assert_tree_close(ps.center, dp.center(mps), rule_name)
    # exported state round-trips into the public PSState shape
    exported = dp.export_ps_state(mps)
    _assert_tree_close(ps.center, exported.center)
    assert int(exported.clock) == int(ps.clock)


@pytest.mark.parametrize("rule_name", ["downpour", "adag", "dynsgd"])
@pytest.mark.parametrize("W", [2, 4])
def test_mesh_pipelined_matches_emulated(rule_name, W):
    """The +W-offset pipelined contract, including the final
    ``flush_pending`` drain of the carried commit."""
    (rule, step, center, ws, ps, batches, perms, make_worker,
     keys) = _setup(rule_name, W)
    rf = jax.jit(make_pipelined_round_fn(rule, step))
    pend = jax.tree_util.tree_map(jnp.zeros_like, ws.params)
    pperm, valid = jnp.arange(W), jnp.asarray(False)
    ref_metrics = []
    for b, p in zip(batches, perms):
        ps, ws, met, pend, pperm, valid = rf(ps, ws, b, p, pend,
                                             pperm, valid)
        ref_metrics.append(jax.device_get(met))
    ps = flush_pending(rule, ps, pend, pperm, W)

    placement = mesh_lib.place_workers(W)
    dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh, center,
                                    pipelined=True)
    mps, mws = dp.to_device(rule.init_state(center),
                            jax.vmap(make_worker)(keys))
    row = mesh_lib.batch_sharding(placement.mesh)
    rep = mesh_lib.replicated_sharding(placement.mesh)
    mpend = dp.init_pending()
    mpperm = jax.device_put(jnp.arange(W, dtype=jnp.int32), rep)
    mvalid = jax.device_put(jnp.asarray(False), rep)
    for (b, p), ref in zip(zip(batches, perms), ref_metrics):
        mps, mws, met, mpend, mpperm, mvalid = dp.round(
            mps, mws, jax.device_put(b, row), jax.device_put(p, rep),
            mpend, mpperm, mvalid)
        _assert_tree_close(ref["loss"], met["loss"], rule_name)
        np.testing.assert_array_equal(np.asarray(ref["staleness"]),
                                      np.asarray(met["staleness"]))
    mps = dp.flush(mps, mpend, mpperm)
    assert int(mps.clock) == int(ps.clock)
    _assert_tree_close(ps.center, dp.center(mps), rule_name)


def test_one_compiled_program_per_round_shape():
    """The public trace counter proves the whole round is ONE compiled
    program reused across rounds; a new worker count is a new shape
    and exactly one more trace."""
    tel = telemetry.enable()
    try:
        for i, W in enumerate((4, 2)):
            (rule, step, center, ws, ps, batches, perms, make_worker,
             keys) = _setup("dynsgd", W)
            placement = mesh_lib.place_workers(W)
            dp = ps_dataplane.MeshDataplane(rule, step, placement.mesh,
                                            center)
            mps, mws = dp.to_device(rule.init_state(center),
                                    jax.vmap(make_worker)(keys))
            row = mesh_lib.batch_sharding(placement.mesh)
            rep = mesh_lib.replicated_sharding(placement.mesh)
            for b, p in zip(batches, perms):
                mps, mws, _ = dp.round(mps, mws,
                                       jax.device_put(b, row),
                                       jax.device_put(p, rep))
            counters = tel.metrics.snapshot()["counters"]
            key = 'ps_round_compiles_total{fidelity="mesh"}'
            assert counters.get(key) == i + 1, counters
    finally:
        telemetry.disable()


def test_trainer_mesh_matches_fast_end_to_end():
    def run(fidelity, **kw):
        t = DOWNPOUR(MLP, fidelity=fidelity, num_workers=4,
                     communication_window=4, batch_size=32,
                     num_epoch=1, learning_rate=0.005, seed=3, **kw)
        return t, t.train(DATA)

    tf_, vf = run("fast")
    tm, vm = run("mesh")
    _assert_tree_close(vf["params"], vm["params"])
    assert tf_.history["staleness"] == tm.history["staleness"]
    np.testing.assert_allclose(tf_.history["round_loss"],
                               tm.history["round_loss"],
                               rtol=2e-5, atol=1e-6)
    _assert_tree_close(tf_.parameter_server_state.center,
                       tm.parameter_server_state.center)
    assert int(tf_.parameter_server_state.clock) == \
        int(tm.parameter_server_state.clock)


def test_trainer_mesh_overlap_matches_faithful_pipelined():
    def run(fidelity):
        t = DOWNPOUR(MLP, fidelity=fidelity, num_workers=4,
                     communication_window=4, batch_size=32,
                     num_epoch=1, learning_rate=0.005, seed=3,
                     commit_overlap=True)
        return t, t.train(DATA)

    tf_, vf = run("faithful")
    tm, vm = run("mesh")
    _assert_tree_close(vf["params"], vm["params"])
    assert tf_.history["staleness"] == tm.history["staleness"]


# ---- partition-rule resolver ------------------------------------------

def test_match_partition_rules_regex_and_scalars():
    tree = {"dense": {"kernel": jnp.zeros((4, 8)),
                      "bias": jnp.zeros((8,))},
            "scale": jnp.zeros(())}
    specs = ps_dataplane.match_partition_rules(
        ((r".*bias", P()), (r".*", P(mesh_lib.WORKER_AXIS))), tree)
    assert specs["dense"]["kernel"] == P(mesh_lib.WORKER_AXIS)
    assert specs["dense"]["bias"] == P()
    assert specs["scale"] == P()  # scalars never shard


def test_match_partition_rules_unmatched_leaf_raises():
    with pytest.raises(ValueError, match="dense/kernel"):
        ps_dataplane.match_partition_rules(
            ((r"nothing", P()),), {"dense": {"kernel": jnp.zeros((4,))}})


# ---- tier registry + trainer validation -------------------------------

def test_tier_registry():
    assert set(TIERS) == {"host", "faithful", "fast", "mesh"}
    assert resolve_tier("mesh").data_plane == "mesh"
    with pytest.raises(ValueError, match="valid lowering tiers"):
        resolve_tier("bogus")
    assert tiers_with("deterministic") == ["faithful", "fast", "mesh"]
    assert tiers_with("concurrent") == ["host"]


def test_unknown_fidelity_lists_tiers():
    with pytest.raises(ValueError, match="valid lowering tiers"):
        DOWNPOUR(MLP, fidelity="bogus", num_workers=2,
                 learning_rate=0.005)


def test_mesh_tier_rejects_checkpointing():
    t = DOWNPOUR(MLP, fidelity="mesh", num_workers=2, batch_size=32,
                 communication_window=2, num_epoch=1,
                 learning_rate=0.005, checkpoint_dir="/tmp/never")
    with pytest.raises(NotImplementedError, match="checkpointing "
                                                  "tiers"):
        t.train(DATA)


def test_mesh_tier_rejects_model_parallel():
    with pytest.raises(ValueError, match="tensor-parallel tiers"):
        DOWNPOUR(MLP, fidelity="mesh", num_workers=2, model_parallel=2,
                 learning_rate=0.005)


def test_mesh_tier_needs_one_device_per_worker():
    t = DOWNPOUR(MLP, fidelity="mesh", num_workers=16, batch_size=8,
                 communication_window=2, num_epoch=1,
                 learning_rate=0.003)
    with pytest.raises(ValueError, match="does not fit"):
        t.train(DATA)


def test_mesh_tier_rejects_elastic_family():
    t = AEASGD(MLP, fidelity="mesh", num_workers=2, batch_size=32,
               communication_window=2, num_epoch=1,
               learning_rate=0.005)
    with pytest.raises(ValueError, match="elastic"):
        t.train(DATA)

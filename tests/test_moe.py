"""Expert parallelism: all_to_all-dispatched Switch MoE parity against
a dense per-token reference, capacity-drop accounting, and a training
smoke test (SURVEY.md §2.3: EP absent in reference — beyond-reference
capability)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from distkeras_tpu.parallel.moe import (
    MoEAux,
    MoEParams,
    init_moe_params,
    moe_apply,
    moe_pspecs,
)
from distkeras_tpu.utils import shard_map

D, H, E = 8, 16, 8  # d_model, hidden, experts


def _params(seed=0):
    return init_moe_params(jax.random.key(seed), D, H, E)


def _dense_reference(params: MoEParams, x):
    """Per-token top-1 MoE with no capacity limit, no parallelism."""
    probs = jax.nn.softmax(x @ params.router, axis=-1)
    gate = probs.max(axis=-1)
    idx = probs.argmax(axis=-1)

    def ffn(e, tok):
        h = jax.nn.relu(tok @ params.w_in[e] + params.b_in[e])
        return h @ params.w_out[e] + params.b_out[e]

    outs = jax.vmap(lambda e, tok, g: g * ffn(e, tok))(
        idx, x, gate)
    return outs


def _ep_apply(mesh, params, x, capacity_factor):
    def fn(p, x):
        out, aux = moe_apply(p, x, axis_name="expert",
                             capacity_factor=capacity_factor)
        return out, aux

    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(moe_pspecs("expert"), P("expert")),
        out_specs=(P("expert"), MoEAux(P(), P()))))(params, x)


def test_ep_matches_dense_reference(devices):
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(32, D)),
                    jnp.float32)
    # generous capacity: nothing dropped, so EP == dense per-token
    out, aux = _ep_apply(mesh, params, x, capacity_factor=float(E))
    assert float(aux.dropped_fraction) == 0.0
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense_reference(params, x)),
                               rtol=2e-5, atol=2e-6)
    assert np.isfinite(float(aux.load_balance_loss))


def test_capacity_drops_are_reported(devices):
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params(seed=3)
    # Route EVERY token to expert 0 (router selects column 0 for any
    # all-positive input): with tight capacity most are dropped.
    params = params._replace(
        router=jnp.zeros((D, E)).at[:, 0].set(1.0))
    x = jnp.asarray(
        np.abs(np.random.default_rng(2).normal(size=(32, D))),
        jnp.float32)
    out, aux = _ep_apply(mesh, params, x, capacity_factor=1.0)
    assert float(aux.dropped_fraction) > 0.5
    # dropped tokens produce zero output (gate residual), kept ones not
    assert np.isfinite(np.asarray(out)).all()


def test_moe_trains(devices):
    """Joint router+expert training through the all_to_alls."""
    import optax

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params(seed=5)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(64, D)), jnp.float32)
    tgt = jnp.asarray(np.sin(np.asarray(x)), jnp.float32)

    from jax import lax

    def loss_fn(p, x, tgt):
        out, aux = moe_apply(p, x, axis_name="expert",
                             capacity_factor=2.0)
        local = jnp.mean((out - tgt) ** 2)
        return (lax.pmean(local, "expert")
                + 0.01 * aux.load_balance_loss)

    sharded = shard_map(
        loss_fn, mesh=mesh,
        in_specs=(moe_pspecs("expert"), P("expert"),
                  P("expert")),
        out_specs=P())

    tx = optax.adam(3e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, opt_state, x, tgt):
        loss, g = jax.value_and_grad(sharded)(p, x, tgt)
        upd, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(p, upd), opt_state, loss

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, x, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_bf16_tokens_route_exactly(devices):
    """Routing bookkeeping stays f32 even for bf16 tokens (bf16 cumsum
    would corrupt capacity slots past 256 tokens/expert)."""
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params(seed=7)
    x32 = jnp.asarray(np.random.default_rng(8).normal(size=(32, D)),
                      jnp.float32)
    out16, aux16 = _ep_apply(mesh, jax.tree_util.tree_map(
        lambda v: v.astype(jnp.bfloat16), params),
        x32.astype(jnp.bfloat16), capacity_factor=float(E))
    assert out16.dtype == jnp.bfloat16
    assert float(aux16.dropped_fraction) == 0.0
    want = _dense_reference(params, x32)
    np.testing.assert_allclose(
        np.asarray(out16, dtype=np.float32), np.asarray(want),
        rtol=0.1, atol=0.1)  # bf16 compute tolerance; routing exact


def _dense_topk_reference(params: MoEParams, x, k):
    """Per-token top-k MoE, no capacity limit, renormalized gates."""
    from jax import lax

    probs = jax.nn.softmax(x @ params.router, axis=-1)
    top_p, top_i = lax.top_k(probs, k)
    gates = top_p / top_p.sum(axis=-1, keepdims=True)

    def ffn(e, tok):
        h = jax.nn.relu(tok @ params.w_in[e] + params.b_in[e])
        return h @ params.w_out[e] + params.b_out[e]

    def one_token(tok, idxs, gs):
        return sum(gs[j] * ffn(idxs[j], tok) for j in range(k))

    return jax.vmap(one_token)(x, top_i, gates)


def test_top2_matches_dense_reference(devices):
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params(seed=11)
    x = jnp.asarray(np.random.default_rng(12).normal(size=(32, D)),
                    jnp.float32)

    def fn(p, x):
        return moe_apply(p, x, axis_name="expert",
                         capacity_factor=float(E), top_k=2)

    out, aux = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(moe_pspecs("expert"), P("expert")),
        out_specs=(P("expert"), MoEAux(P(), P()))))(params, x)
    assert float(aux.dropped_fraction) == 0.0
    want = _dense_topk_reference(params, x, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_top2_second_choice_drops_first(devices):
    """Capacity pressure drops later choices before earlier ones: the
    kept fraction under top_k=2 is at least the top-1 kept fraction."""
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params(seed=13)
    params = params._replace(
        router=jnp.zeros((D, E)).at[:, 0].set(1.0).at[:, 1].set(0.5))
    x = jnp.asarray(
        np.abs(np.random.default_rng(14).normal(size=(32, D))),
        jnp.float32)

    def run(k):
        def fn(p, x):
            return moe_apply(p, x, axis_name="expert",
                             capacity_factor=1.0, top_k=k)

        return jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(moe_pspecs("expert"), P("expert")),
            out_specs=(P("expert"), MoEAux(P(), P()))))(params, x)

    _, aux1 = run(1)
    _, aux2 = run(2)
    assert float(aux2.dropped_fraction) > 0.0
    assert np.isfinite(float(aux2.load_balance_loss))
    # later choices fill capacity after earlier ones: the k=2 run keeps
    # at least as many assignments as the whole k=1 run (its first
    # choices alone fill at least that much)
    t = 32
    kept1 = (1.0 - float(aux1.dropped_fraction)) * t
    kept2 = (1.0 - float(aux2.dropped_fraction)) * 2 * t
    assert kept2 >= kept1 - 1e-3, (kept1, kept2)


def test_bad_top_k_raises(devices):
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("expert",))
    params = _params(seed=15)
    x = jnp.zeros((8, D), jnp.float32)

    def fn(p, x):
        return moe_apply(p, x, axis_name="expert", top_k=0)

    with np.testing.assert_raises(Exception):
        jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(moe_pspecs("expert"), P("expert")),
            out_specs=(P("expert"), MoEAux(P(), P()))))(params, x)

"""History-key lint (ISSUE 2 satellite): every ``history[...]`` key a
trainer emits must have a row in the docs/API.md "Trainer history
keys" table — keys like ``detected_idle_workers`` or
``commit_wire_bytes`` were previously discoverable only by reading
trainers.py.  The collection runs one representative trainer per
history-emitting code path (sequential, sync-DP, emulated PS with
out-of-core segments, the chaos-path host arm, members, eval hook) and
fails on any UNDOCUMENTED emitted key; a core set is also required to
actually appear, so the table cannot go stale silently."""

import pathlib
import time

import jax
import pytest

from distkeras_tpu.analysis import surfaces
from distkeras_tpu.data import datasets
from distkeras_tpu.models import model_config
from distkeras_tpu.trainers import (
    ADAG,
    DOWNPOUR,
    EnsembleTrainer,
    SingleTrainer,
    SyncTrainer,
)

jax.config.update("jax_platforms", "cpu")

DOCS = pathlib.Path(__file__).resolve().parent.parent / "docs/API.md"

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(512, (8,), 4, seed=0)


def documented_keys() -> set[str]:
    """First-column backticked keys of the history-key table — parsed
    by the shared ``analysis/surfaces`` extractor, the same parser
    ``scripts/lint_static.py`` runs repo-wide."""
    keys = surfaces.documented_history_keys(DOCS.read_text())
    assert keys, ("docs/API.md lacks the 'Trainer history keys' table "
                  "(or it parsed empty)")
    return keys


class _Bomb(Exception):
    pass


def _collect_emitted() -> set[str]:
    emitted: set[str] = set()

    def run(trainer, data=DATA, **kw):
        trainer.train(data, **kw)
        emitted.update(trainer.history.keys())
        return trainer

    run(SingleTrainer(MLP, batch_size=32, num_epoch=1),
        eval_dataset=DATA.take(128))
    run(SyncTrainer(MLP, num_workers=2, batch_size=16, num_epoch=1))
    run(EnsembleTrainer(MLP, num_models=2, batch_size=32, num_epoch=1))

    # emulated PS over a sharded dataset with a runt shard: covers
    # round_loss/staleness plus the skip/drop bookkeeping keys
    import tempfile

    from distkeras_tpu.data.dataset import Dataset

    with tempfile.TemporaryDirectory() as d:
        DATA.take(130).to_npz_shards(f"{d}/part", rows_per_shard=64)
        sharded = Dataset.from_npz_shards(f"{d}/part*.npz")
        run(ADAG(MLP, num_workers=4, communication_window=2,
                 batch_size=8, num_epoch=1, learning_rate=5e-3,
                 fidelity="faithful"), data=sharded)

    # host arm chaos paths in one run: a transient failure (retry), a
    # hard failure (tolerated death), a stall (watchdog detection),
    # wire compression (byte totals), and periodic PS warm-restart
    # snapshots (the fault-tolerance key)
    state = {"transient": True, "stall": True}

    def injector(w, epoch, r):
        if w == 0 and r == 1 and state.pop("transient", False):
            raise _Bomb("transient")
        if w == 1:
            raise _Bomb("hard")
        if w == 2 and r == 1 and state.pop("stall", False):
            time.sleep(1.2)

    with tempfile.TemporaryDirectory() as d:
        run(DOWNPOUR(MLP, fidelity="host", num_workers=3,
                     communication_window=2, batch_size=16, num_epoch=1,
                     learning_rate=0.01, worker_optimizer="adam",
                     worker_retries=1, max_worker_failures=1,
                     worker_timeout=0.3, fault_injector=injector,
                     compression="int8",
                     ps_snapshot_path=f"{d}/ps.snap",
                     ps_snapshot_every=4))

    # sharded host arm over the socket wire: the version-delta pull
    # savings keys (ISSUE 4)
    run(DOWNPOUR(MLP, fidelity="host", transport="socket", ps_shards=2,
                 num_workers=2, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.01,
                 commit_overlap=True))

    # hierarchical host arm: group leaders fold worker windows into
    # single upstream commits (the fan-in reduction keys, ISSUE 20)
    run(DOWNPOUR(MLP, fidelity="host", transport="socket",
                 ps_groups=[(None, [0, 1]), (None, [2, 3])],
                 num_workers=4, communication_window=2, batch_size=8,
                 num_epoch=1, learning_rate=0.01))
    return emitted


def test_serving_prefix_telemetry_keys_are_documented():
    """ISSUE 8 lint, rebuilt on the ISSUE 9 AST extractor: every
    telemetry name the serving layer emits (metric names, span names,
    flight kinds) must appear in docs/API.md.  The extraction is the
    same ``analysis/surfaces`` pass ``scripts/lint_static.py`` runs
    repo-wide, so a renamed emission breaks the lint, not just the
    docs — and this test pins the prefix-cache core surface so the
    extractor itself cannot silently go blind."""
    src = (DOCS.parent.parent
           / "distkeras_tpu/serving.py").read_text()
    surface = surfaces.extract_source(src, "distkeras_tpu/serving.py")
    emitted = (set(surface.metrics) | set(surface.spans)
               | set(surface.flight_kinds))
    # the full prefix surface must actually be extracted...
    core = {"serving_prefix_hits_total", "serving_prefix_misses_total",
            "serving_prefix_evictions_total",
            "serving_prefix_invalidations_total",
            "serving_prefill_tokens_saved_total",
            "serving_prefix_hit_rate", "prefix_copy", "prefill_chunk",
            "prefix_invalidate"}
    assert core <= emitted, sorted(core - emitted)
    # the flight kind is classified as a kind (table-row check), not
    # as a loose docs word
    assert "prefix_invalidate" in surface.flight_kinds
    # ...and the whole serving surface must be documented (flight
    # kinds specifically as rows of the kind table)
    findings = surfaces.check_docs(surface, DOCS.read_text())
    assert not findings, "\n".join(str(f) for f in findings)


def test_every_emitted_history_key_is_documented():
    documented = documented_keys()
    emitted = _collect_emitted()
    undocumented = emitted - documented
    assert not undocumented, (
        f"history keys emitted but missing from the docs/API.md "
        f"'Trainer history keys' table: {sorted(undocumented)}")
    # the lint itself must keep teeth: the chaos/members/eval paths
    # above are expected to exercise at least this core set
    core = {"epoch_loss", "round_loss", "staleness",
            "segment_stall_s", "dropped_tail_batches",
            "skipped_segment_rows", "eval_accuracy", "member_loss",
            "worker_failures", "worker_round_retries",
            "commit_wire_bytes", "commit_raw_bytes", "ps_snapshots",
            "pull_shards_skipped", "pull_bytes_saved", "slo_health"}
    missing = core - emitted
    assert not missing, (
        f"collection no longer exercises core history keys: "
        f"{sorted(missing)}")
    assert core <= documented

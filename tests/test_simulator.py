"""The traffic/chaos simulator (ISSUE 18): trace generators are pure
functions of the seed with the declared statistics, replay delivers
exactly-once against a gateway, the stepped-rate search finds the knee
of a known queue, and the capacity model's fit/required() arithmetic
holds.

Everything here runs against FAKE gateways (a deterministic FIFO
queue), so the suite tests the simulator's own contracts in
milliseconds-to-seconds — the full-stack closed-loop drill lives in
``scripts/perf_capacity.py --smoke`` (test_examples.py runs it)."""

import dataclasses

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.simulator import (Arrival, CapacityModel,
                                     CapacityPoint, ChaosSchedule,
                                     ReplicaPool, TraceSpec,
                                     declared_length_quantiles,
                                     generate_trace, in_crowd,
                                     peak_rate, rate_at, replay,
                                     run_drill, stepped_rate_search)

# ---- trace generation --------------------------------------------------


def _spec(**kw):
    kw.setdefault("duration_s", 20.0)
    kw.setdefault("mean_qps", 40.0)
    return TraceSpec(**kw)


def test_trace_is_a_pure_function_of_the_seed():
    spec = _spec(diurnal_amplitude=0.3,
                 flash_crowds=((5.0, 8.0, 2.0),),
                 tenants=(("free", 0.7, 0), ("paid", 0.3, 2)))
    a = generate_trace(spec).arrivals
    b = generate_trace(spec).arrivals
    assert len(a) == len(b) > 100
    for x, y in zip(a, b):
        assert x.t == y.t and x.max_new == y.max_new
        assert x.session == y.session and x.tenant == y.tenant
        np.testing.assert_array_equal(x.prompt, y.prompt)
    c = generate_trace(dataclasses.replace(spec, seed=1)).arrivals
    assert [x.t for x in a] != [x.t for x in c]


def test_diurnal_rate_integral_matches_the_mean():
    """Period == duration, so the sinusoid integrates to zero and the
    realized arrival count must match mean_qps * duration (Poisson
    noise bounded: sd(2400) ~ 49, the 10% tolerance is ~5 sd)."""
    spec = _spec(duration_s=60.0, mean_qps=40.0,
                 diurnal_amplitude=0.6)
    n = len(generate_trace(spec).arrivals)
    assert n == pytest.approx(2400, rel=0.10)
    # and the analytic curve peaks/troughs where the phase says
    assert rate_at(spec, 15.0) == pytest.approx(64.0)
    assert rate_at(spec, 45.0) == pytest.approx(16.0)
    assert peak_rate(spec) == pytest.approx(64.0)


def test_flash_crowd_densifies_its_window():
    spec = _spec(duration_s=30.0, mean_qps=30.0,
                 flash_crowds=((10.0, 20.0, 3.0),))
    ts = [a.t for a in generate_trace(spec).arrivals]
    inside = sum(10.0 <= t < 20.0 for t in ts)
    before = sum(t < 10.0 for t in ts)
    assert inside == pytest.approx(3 * before, rel=0.25)
    assert in_crowd(spec, 15.0) and not in_crowd(spec, 5.0)


def test_heavy_tails_match_the_declared_quantiles():
    """Empirical p50/p99 of the generated lengths track the analytic
    lognormal / Pareto quantiles (clips pushed far out so they never
    bite the p99)."""
    spec = _spec(duration_s=30.0, mean_qps=300.0, prompt_median=24.0,
                 prompt_sigma=0.6, prompt_min=4, prompt_max=4096,
                 output_alpha=2.0, output_min=4, output_max=100000)
    arr = generate_trace(spec).arrivals
    assert len(arr) > 5000
    want = declared_length_quantiles(spec)
    plens = np.array([len(a.prompt) for a in arr], float)
    outs = np.array([a.max_new for a in arr], float)
    assert np.percentile(plens, 50) == pytest.approx(
        want["prompt_p50"], rel=0.10)
    assert np.percentile(plens, 99) == pytest.approx(
        want["prompt_p99"], rel=0.15)
    assert np.percentile(outs, 50) == pytest.approx(
        want["output_p50"], rel=0.10)
    assert np.percentile(outs, 99) == pytest.approx(
        want["output_p99"], rel=0.30)
    # declared ratio arithmetic: p99/p50 = 50**(1/alpha) for Pareto
    assert want["output_p99"] / want["output_p50"] == pytest.approx(
        50.0 ** (1 / spec.output_alpha))


def test_sessions_share_their_group_prefix():
    spec = _spec(sessions=10, prefix_groups=3, prefix_len=4,
                 prompt_min=6)
    arr = generate_trace(spec).arrivals
    by_session = {}
    for a in arr:
        head = tuple(a.prompt[:4].tolist())
        by_session.setdefault(a.session, set()).add(head)
    # one prefix per session, drawn from <= prefix_groups distinct
    assert all(len(heads) == 1 for heads in by_session.values())
    distinct = {next(iter(h)) for h in by_session.values()}
    assert 1 <= len(distinct) <= 3
    assert all(len(a.prompt) >= 6 for a in arr)


def test_tenant_shares_and_priorities():
    spec = _spec(duration_s=40.0,
                 tenants=(("free", 0.7, 0), ("paid", 0.3, 2)))
    arr = generate_trace(spec).arrivals
    frac = sum(a.tenant == "paid" for a in arr) / len(arr)
    assert frac == pytest.approx(0.3, abs=0.05)
    prios = {a.tenant: a.priority for a in arr}
    assert prios == {"free": 0, "paid": 2}


def test_spec_validation():
    with pytest.raises(ValueError, match="must be > 0"):
        _spec(mean_qps=0.0)
    with pytest.raises(ValueError, match="diurnal_amplitude"):
        _spec(diurnal_amplitude=1.0)
    with pytest.raises(ValueError, match="flash crowd"):
        _spec(flash_crowds=((5.0, 4.0, 2.0),))
    with pytest.raises(ValueError, match="prefix_len"):
        _spec(prefix_len=8, prompt_min=8)
    with pytest.raises(ValueError, match="session_zipf"):
        _spec(session_zipf=1.0)
    with pytest.raises(ValueError, match="positive shares"):
        _spec(tenants=(("a", 0.0, 0),))


# ---- replay against a deterministic queue ------------------------------


class _QueueGateway:
    """Single FIFO server at ``service_rate`` req/s on the wall clock
    — the textbook queue whose saturation knee the search must find."""

    def __init__(self, service_rate: float, replicas: int = 1):
        self._dt = 1.0 / float(service_rate)
        self._next_free = 0.0
        self._due: dict = {}
        self._n = 0
        self._replicas = replicas

    def submit(self, prompt, *, max_new_tokens, session=None,
               tenant=None, priority=0):
        nw = telemetry.now()
        start = max(nw, self._next_free)
        self._next_free = start + self._dt
        rid = f"r{self._n}"
        self._n += 1
        self._due[rid] = start + self._dt
        return rid

    def try_result(self, rid):
        due = self._due[rid]
        if telemetry.now() < due:
            return None
        del self._due[rid]
        return {"request_id": rid, "tokens": [0], "t_first": due,
                "error": None}

    def alive_replicas(self) -> int:
        return self._replicas


def test_replay_delivers_exactly_once():
    spec = _spec(duration_s=1.0, mean_qps=40.0)
    trace = generate_trace(spec)
    rep = replay(trace, _QueueGateway(400.0), slo_ttft_s=0.5,
                 drain_timeout_s=5.0)
    assert rep["arrivals"] == len(trace.arrivals)
    assert rep["completed"] == rep["arrivals"]
    assert rep["undrained"] == rep["errors"] == rep["duplicates"] == 0
    assert rep["slo_attainment"] == 1.0 and rep["slo_miss"] == 0
    assert rep["ttft_p95_s"] is not None
    rids = [r["request_id"] for r in rep["results"]]
    assert len(set(rids)) == len(rids)


def test_stepped_rate_search_finds_the_queue_knee():
    """A 50 req/s FIFO server must sustain the 40-rung and fail the
    160-rung — and the capped flag stays False because a rung failed.
    Margins are wide on purpose (rho 0.8 vs 3.2, SLO 15 services
    deep) so OS scheduling jitter cannot flip a rung."""
    out = stepped_rate_search(
        _QueueGateway(50.0), _spec(duration_s=1.0, mean_qps=1.0),
        slo_ttft_s=0.3, ladder=(10.0, 20.0, 40.0, 160.0),
        min_arrivals=8, max_segment_s=0.5, drain_timeout_s=5.0,
        config={"replicas": 1})
    assert out["sustainable_qps"] == 40.0 and not out["capped"]
    assert out["point"].config == {"replicas": 1}
    assert [r["ok"] for r in out["rungs"]] == [True, True, True,
                                              False]
    # a ladder the system outruns reports capped=True
    out2 = stepped_rate_search(
        _QueueGateway(400.0), _spec(duration_s=1.0, mean_qps=1.0),
        slo_ttft_s=0.25, ladder=(5.0, 10.0), min_arrivals=5,
        max_segment_s=0.5, drain_timeout_s=5.0)
    assert out2["capped"] and out2["sustainable_qps"] == 10.0


# ---- capacity model ----------------------------------------------------


def test_capacity_model_fit_and_required():
    pts = [CapacityPoint({"replicas": 1}, 40.0, 1.0, 0.01),
           CapacityPoint({"replicas": 2}, 80.0, 1.0, 0.01)]
    m = CapacityModel(pts)
    assert m.capacity(3) == pytest.approx(120.0)
    assert m.required(39.0) == 1
    assert m.required(41.0) == 2
    assert m.required(41.0, headroom=2.0) == 3  # 82 needs 3x40
    assert m.required(1e9, max_replicas=8) == 8  # unreachable: cap
    d = m.describe()
    assert d["slope"] == pytest.approx(40.0)
    assert len(d["points"]) == 2
    # single point: conservative proportional-through-origin
    m1 = CapacityModel(pts[:1])
    assert m1.capacity(2) == pytest.approx(80.0)
    with pytest.raises(ValueError, match=">= 1 point"):
        CapacityModel([])


# ---- chaos schedule + replica pool -------------------------------------


def test_chaos_schedule_kills_fire_once_at_their_time():
    killed = []
    sched = ChaosSchedule(kills=((0.0, "r0"),))
    sched.register_kill("r0", lambda: killed.append("r0"))
    assert sched.clock() == 0.0  # pre-start: the clock is parked
    sched.start()
    assert sched.poll() == ["r0"] and killed == ["r0"]
    assert sched.poll() == []  # once, not every poll
    with pytest.raises(KeyError, match="never registered"):
        ChaosSchedule(kills=((0.0, "ghost"),)).start().poll()
    with pytest.raises(ValueError, match=">= 0"):
        ChaosSchedule(kills=((-1.0, "r0"),))


def test_chaos_schedule_wires_windows_into_the_transport():
    sched = ChaosSchedule(windows=((1.0, 2.0, ("reset", "delay")),))
    ct = sched.chaos_transport(seed=7, reset_rate=0.0,
                               truncate_rate=0.0, delay_rate=0.0)
    assert ct.windows == sched.windows
    # one clock for faults AND kills (same bound method)
    assert ct._clock.__self__ is sched


class _PoolGateway:
    def __init__(self):
        self.names = ["r0"]

    def add_replica(self, rep):
        self.names.append(rep.name)

    def remove_replica(self, name):
        self.names.remove(name)

    def alive_replicas(self):
        return len(self.names)


def test_replica_pool_spawns_spares_and_drains_lifo():
    class _Rep:
        def __init__(self, name):
            self.name = name

    gw = _PoolGateway()
    pool = ReplicaPool(gw, spares=[_Rep("s1"), _Rep("s2")])
    assert pool.replica_count() == 1 and pool.spares_left() == 2
    assert pool.spawn_replica() == "s2"  # LIFO off the spare stack
    assert pool.spawn_replica() == "s1"
    assert gw.names == ["r0", "s2", "s1"]
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.spawn_replica()
    assert pool.drain_replica() == "s1"  # most recently spawned
    assert gw.names == ["r0", "s2"]
    assert pool.replica_count() == 2


# ---- drill episode accounting ------------------------------------------


def test_run_drill_opens_and_closes_deficit_episodes():
    """Target jumps to 2 inside the crowd; a stub autoscaler heals on
    its second tick — the drill must record exactly one episode,
    closed, and report converged."""
    model = CapacityModel(
        [CapacityPoint({"replicas": 1}, 40.0, 1.0, 0.01),
         CapacityPoint({"replicas": 2}, 80.0, 1.0, 0.01)])
    spec = _spec(duration_s=0.8, mean_qps=30.0,
                 flash_crowds=((0.2, 0.8, 2.0),))
    gw = _QueueGateway(500.0)

    class _Scaler:
        class watchdog:
            state = "ok"

        def step(self):
            if in_crowd(spec, (telemetry.now() - t0[0])):
                gw._replicas = 2

    t0 = [telemetry.now()]
    out = run_drill(generate_trace(spec), gw, _Scaler(), model,
                    tick_interval_s=0.05, max_replicas=2,
                    drain_timeout_s=5.0)
    assert out["episodes"] and out["converged"]
    assert all(e["closed"] and e["target"] == 2
               for e in out["episodes"])
    assert out["replay"]["undrained"] == 0
    assert any(s["target"] == 2 and s["actual"] == 2
               for s in out["samples"])


def test_run_drill_reports_an_unhealed_deficit_as_unconverged():
    model = CapacityModel(
        [CapacityPoint({"replicas": 1}, 10.0, 1.0, 0.01)])
    spec = _spec(duration_s=0.4, mean_qps=30.0)  # needs 3, has 1

    class _Inert:
        class watchdog:
            state = "critical"

        def step(self):
            pass

    out = run_drill(generate_trace(spec), _QueueGateway(500.0),
                    _Inert(), model, tick_interval_s=0.05,
                    max_replicas=4, drain_timeout_s=5.0)
    assert not out["converged"]
    assert [e["closed"] for e in out["episodes"]] == [False]

"""Shared-prefix KV cache + chunked prefill (ISSUE 8): every reuse
and scheduling optimization must be INVISIBLE in the tokens — seeded
greedy decode with the prefix store on (across admission orders,
partial-align matches, and evict-then-readmit) and with chunked
prefill on is byte-identical to the plain engine — while the
scheduler properties (decode steps interleave with a long prefill;
deadlines fire between chunks; a weight swap invalidates the store)
hold observably."""

import jax
import numpy as np
import pytest

from distkeras_tpu import flight_recorder, telemetry
from distkeras_tpu.models import ModelSpec, generate, model_config
from distkeras_tpu.serving import DecodeEngine

jax.config.update("jax_platforms", "cpu")

MAXLEN, VOCAB = 32, 37


def _model(num_layers=1, **kw):
    spec = model_config("transformer_lm", (MAXLEN,),
                        input_dtype="int32", vocab_size=VOCAB,
                        num_layers=num_layers, d_model=32, num_heads=2,
                        max_len=MAXLEN, dtype="float32", **kw)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           np.zeros((2, MAXLEN), np.int32))
    return model, variables


def _shared_prompts(n=4, shared=12, tail=6, seed=7):
    """``n`` prompts sharing a ``shared``-token head (the system-
    prompt workload the prefix store exists for)."""
    rng = np.random.default_rng(seed)
    head = rng.integers(0, VOCAB, (shared,)).astype(np.int32)
    return [np.concatenate([head, rng.integers(0, VOCAB, (tail,))
                            .astype(np.int32)]) for _ in range(n)]


def _want(model, variables, prompt, n_new):
    return np.asarray(generate(model, variables, prompt[None, :],
                               max_new_tokens=n_new))[0, len(prompt):]


def _engine(model, variables, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prefill_align", 4)
    kw.setdefault("buckets", (MAXLEN,))
    return DecodeEngine(model, variables, **kw)


def _drain(eng, prompts, n_new=5, tag="r"):
    """Submit all, run to empty, return tokens keyed by prompt index
    (any engine error fails the test)."""
    for i, p in enumerate(prompts):
        eng.submit(p, max_new_tokens=n_new, request_id=f"{tag}{i}")
    out = {}
    while eng.has_work():
        for r in eng.step():
            assert "error" not in r, r
            out[int(r["request_id"][len(tag):])] = \
                np.asarray(r["tokens"])
    return out


# ---- parity: the optimization must be invisible -----------------------


def test_prefix_cache_parity_across_admission_orders():
    """Greedy tokens with the store on == the solo ``generate``
    reference, for the warm-up wave, a reshuffled wave, and a steady-
    state wave that actually HITS (per ``prefix_stats``)."""
    model, variables = _model()
    prompts = _shared_prompts()
    refs = [_want(model, variables, p, 5) for p in prompts]
    with _engine(model, variables,
                 prefix_cache_bytes=1 << 24) as eng:
        for tag, order in (("a", range(len(prompts))),
                           ("b", reversed(range(len(prompts)))),
                           ("c", range(len(prompts)))):
            order = list(order)
            got = _drain(eng, [prompts[i] for i in order], tag=tag)
            for k, i in enumerate(order):
                np.testing.assert_array_equal(got[k], refs[i])
        st = eng.prefix_stats()
    assert st["enabled"] and st["hits"] >= len(prompts), st
    # every hit skipped whole aligned blocks of real prefill compute
    assert st["tokens_saved"] >= st["hits"] * 4, st


def test_partial_align_match_reuses_only_whole_blocks():
    """A prompt sharing 9 tokens with a cached one matches exactly
    2 whole 4-blocks (8 tokens) — the ragged remainder is prefilled —
    and still decodes byte-identically."""
    model, variables = _model()
    rng = np.random.default_rng(11)
    a = rng.integers(0, VOCAB, (13,)).astype(np.int32)
    b = np.concatenate([a[:9],
                        rng.integers(0, VOCAB, (5,)).astype(np.int32)])
    with _engine(model, variables, slots=1,
                 prefix_cache_bytes=1 << 24) as eng:
        (got_a,) = _drain(eng, [a], tag="a").values()
        saved0 = eng.prefix_stats()["tokens_saved"]
        (got_b,) = _drain(eng, [b], tag="b").values()
        st = eng.prefix_stats()
    np.testing.assert_array_equal(got_a, _want(model, variables, a, 5))
    np.testing.assert_array_equal(got_b, _want(model, variables, b, 5))
    assert st["hits"] == 1
    assert st["tokens_saved"] - saved0 == 8, st


def test_evict_then_readmit_parity_under_tiny_budget():
    """A budget too small for the workload forces LRU eviction; the
    evicted prefix re-admits (cold) with identical tokens."""
    model, variables = _model()
    prompts = _shared_prompts()
    refs = [_want(model, variables, p, 5) for p in prompts]
    with _engine(model, variables, prefix_cache_bytes=2100) as eng:
        for tag in ("a", "b"):
            got = _drain(eng, prompts, tag=tag)
            for i, r in enumerate(refs):
                np.testing.assert_array_equal(got[i], r)
        st = eng.prefix_stats()
    assert st["evictions"] > 0, st
    assert st["bytes"] <= 2100, st


def test_chunked_prefill_parity_with_and_without_store():
    model, variables = _model()
    prompts = _shared_prompts(n=3, shared=12, tail=10, seed=5)
    refs = [_want(model, variables, p, 4) for p in prompts]
    for kw in ({"prefill_chunk": 4},
               {"prefill_chunk": 8, "prefix_cache_bytes": 1 << 24}):
        with _engine(model, variables, **kw) as eng:
            for tag in ("a", "b"):
                got = _drain(eng, prompts, n_new=4, tag=tag)
                for i, r in enumerate(refs):
                    np.testing.assert_array_equal(got[i], r, err_msg=
                                                  f"{kw} wave {tag}")


def test_multilayer_parity_with_prefix_and_chunks():
    """Two layers: the per-layer segment extract/copy composes across
    the cache pytree, not just a single layer's leaves."""
    model, variables = _model(num_layers=2)
    prompts = _shared_prompts(n=3)
    refs = [_want(model, variables, p, 4) for p in prompts]
    with _engine(model, variables, slots=2, prefill_chunk=8,
                 prefix_cache_bytes=1 << 24) as eng:
        for tag in ("a", "b"):
            got = _drain(eng, prompts, n_new=4, tag=tag)
            for i, r in enumerate(refs):
                np.testing.assert_array_equal(got[i], r)
        assert eng.prefix_stats()["hits"] >= len(prompts)


def test_instant_finish_paths_under_prefix_and_chunk():
    """max_new=1 and instant-eos terminate correctly when the first
    token comes out of a chunked (possibly prefix-seeded) prefill."""
    model, variables = _model()
    (p,) = _shared_prompts(n=1, shared=12, tail=3)
    first = int(_want(model, variables, p, 1)[0])
    with _engine(model, variables, prefill_chunk=4,
                 prefix_cache_bytes=1 << 24) as eng:
        got = _drain(eng, [p, p], n_new=1, tag="a")
        for v in got.values():
            assert v.tolist() == [first]
        eng.submit(p, max_new_tokens=6, request_id="eos",
                   eos_id=first)
        while eng.has_work():
            for r in eng.step():
                assert "error" not in r
                assert r["tokens"].tolist() == [first]


# ---- scheduling properties --------------------------------------------


def test_decode_steps_interleave_with_a_long_chunked_prefill():
    """THE Sarathi property: while a max-length prompt chunk-prefills,
    the other slot keeps producing tokens — on the trace, decode_step
    spans appear BETWEEN the long request's prefill_chunk spans, and
    at most one chunk runs per engine step."""
    tel = telemetry.enable()
    try:
        model, variables = _model()
        rng = np.random.default_rng(3)
        short = rng.integers(0, VOCAB, (5,)).astype(np.int32)
        long = rng.integers(0, VOCAB, (30,)).astype(np.int32)
        with _engine(model, variables, slots=2,
                     prefill_chunk=8) as eng:
            eng.submit(short, max_new_tokens=12, request_id="short")
            eng.step()  # short's single chunk runs; it starts decoding
            eng.submit(long, max_new_tokens=2, request_id="long")
            while eng.has_work():
                eng.step()
            got_long = None
        ev = [e for e in tel.tracer.events()
              if e["name"] in ("prefill_chunk", "decode_step")]
        chunk_idx = [i for i, e in enumerate(ev)
                     if e["name"] == "prefill_chunk"
                     and e["args"].get("request_id") == "long"]
        assert len(chunk_idx) == 4  # 32 padded / 8 per chunk
        between = [e["name"] for e in ev[chunk_idx[0]:chunk_idx[-1]]]
        assert "decode_step" in between, between
    finally:
        telemetry.disable()


def test_chunked_outputs_match_reference_while_interleaved():
    model, variables = _model()
    rng = np.random.default_rng(3)
    short = rng.integers(0, VOCAB, (5,)).astype(np.int32)
    long = rng.integers(0, VOCAB, (30,)).astype(np.int32)
    out = {}
    with _engine(model, variables, slots=2, prefill_chunk=8) as eng:
        eng.submit(short, max_new_tokens=12, request_id="short")
        eng.step()
        eng.submit(long, max_new_tokens=2, request_id="long")
        while eng.has_work():
            for r in eng.step():
                assert "error" not in r, r
                out[r["request_id"]] = np.asarray(r["tokens"])
    np.testing.assert_array_equal(out["short"],
                                  _want(model, variables, short, 12))
    np.testing.assert_array_equal(out["long"],
                                  _want(model, variables, long, 2))


def test_deadline_expiry_fires_between_prefill_chunks():
    """ISSUE 8 fix: a chunked long prompt cannot ride out its own
    deadline — expiry is re-checked between chunks, frees the slot,
    and the engine keeps serving."""
    model, variables = _model()
    rng = np.random.default_rng(9)
    long = rng.integers(0, VOCAB, (28,)).astype(np.int32)
    with _engine(model, variables, slots=1, prefill_chunk=4) as eng:
        eng.submit(long, max_new_tokens=4, request_id="doomed",
                   deadline=60.0)
        results = eng.step()  # admits + runs the first chunk only
        assert results == []
        pool = eng._pools[0]
        assert pool.prefilling  # mid-prefill, several chunks left
        (slot,) = pool.prefilling
        pool.reqs[slot].deadline = telemetry.now() - 1.0  # backdate
        results = eng.step()
        assert [r.get("error") for r in results] == \
            ["deadline_exceeded"]
        assert not pool.prefilling and pool.reqs[slot] is None
        # the slot is immediately reusable, with correct tokens
        (p,) = _shared_prompts(n=1)
        got = _drain(eng, [p], n_new=3, tag="x")
        np.testing.assert_array_equal(got[0],
                                      _want(model, variables, p, 3))


def test_swap_variables_invalidates_the_prefix_store(tmp_path):
    """ISSUE 8 regression: stale KV under new weights is silently
    wrong, so a swap clears the store (counter + flight event) and
    post-swap outputs are byte-identical to a COLD engine built on
    the new weights."""
    tel = telemetry.enable()
    fr = flight_recorder.start(tmp_path / "fdr")
    try:
        model, variables = _model()
        prompts = _shared_prompts()
        v2 = jax.tree_util.tree_map(lambda x: x * 1.01, variables)
        with _engine(model, variables, prefill_chunk=8,
                     prefix_cache_bytes=1 << 24) as eng:
            _drain(eng, prompts, tag="warm")
            assert eng.prefix_stats()["nodes"] > 0
            eng.swap_variables(v2)
            st = eng.prefix_stats()
            assert st["nodes"] == 0 and st["bytes"] == 0
            assert st["invalidations"] == 1
            got = _drain(eng, prompts, tag="post")
        with _engine(model, v2, prefill_chunk=8,
                     prefix_cache_bytes=1 << 24) as cold:
            ref = _drain(cold, prompts, tag="cold")
        for i in range(len(prompts)):
            np.testing.assert_array_equal(got[i], ref[i])
        assert tel.metrics.sum_counter(
            "serving_prefix_invalidations_total") == 1
        ev = [e for e in fr.read_events()
              if e["kind"] == "prefix_invalidate"]
        assert len(ev) == 1 and ev[0]["reason"] == "weight_swap"
        assert ev[0]["nodes"] > 0
    finally:
        flight_recorder.stop()
        telemetry.disable()


def test_mid_flight_swap_never_donates_stale_kv():
    """A request admitted BEFORE a swap finishes on hybrid KV — its
    prefix must not be donated into the (post-swap) store, or the
    next matching prompt would silently decode on stale rows."""
    model, variables = _model()
    (p,) = _shared_prompts(n=1)
    v2 = jax.tree_util.tree_map(lambda x: x * 1.01, variables)
    with _engine(model, variables, slots=1,
                 prefix_cache_bytes=1 << 24) as eng:
        eng.submit(p, max_new_tokens=6, request_id="inflight")
        eng.step()          # admitted + prefilled under v1
        eng.swap_variables(v2)
        while eng.has_work():
            eng.step()      # finishes under v2: hybrid KV
        st = eng.prefix_stats()
        assert st["nodes"] == 0, st  # nothing donated
        got = _drain(eng, [p], n_new=5, tag="x")
    np.testing.assert_array_equal(got[0], _want(model, v2, p, 5))


# ---- bounded compiled set + telemetry ---------------------------------


def test_chunk_program_set_is_bounded_steady_state():
    """Chunk programs trace once per (bucket, width); the steady-state
    wave compiles NOTHING new (the §23 discipline extended to the
    segmented path)."""
    tel = telemetry.enable()
    try:
        model, variables = _model()
        prompts = _shared_prompts(n=3, shared=12, tail=10, seed=5)
        with _engine(model, variables, prefill_chunk=8,
                     prefix_cache_bytes=1 << 24) as eng:
            # wave a = all misses (chunk path); wave b = hits (copy +
            # short tail-chunk path): together they warm every program
            _drain(eng, prompts, tag="a")
            _drain(eng, prompts, tag="b")
            m = tel.metrics
            chunks = m.collect("compiles_total", kind="chunk_prefill")
            assert chunks
            for labels, c in chunks:
                assert c.value == 1, labels
            assert m.collect("compiles_total", kind="prefix_copy")
            before = {k: v for k, v in m.snapshot()["counters"].items()
                      if k.startswith("compiles_total")}
            _drain(eng, prompts, tag="c")
            _drain(eng, list(reversed(prompts)), tag="d")
            after = {k: v for k, v in m.snapshot()["counters"].items()
                     if k.startswith("compiles_total")}
        assert before == after, (
            "steady-state segmented serving compiled something new")
    finally:
        telemetry.disable()


def test_prefix_counters_and_hit_rate_gauge():
    tel = telemetry.enable()
    try:
        model, variables = _model()
        prompts = _shared_prompts()
        with _engine(model, variables,
                     prefix_cache_bytes=1 << 24) as eng:
            _drain(eng, prompts, tag="a")
            _drain(eng, prompts, tag="b")
        m = tel.metrics
        hits = m.sum_counter("serving_prefix_hits_total")
        misses = m.sum_counter("serving_prefix_misses_total")
        saved = m.sum_counter("serving_prefill_tokens_saved_total")
        assert hits >= len(prompts) and misses >= 1
        assert saved >= hits * 4
        (gauge,) = [g for (labels, g)
                    in m.collect("serving_prefix_hit_rate")]
        assert gauge.value == pytest.approx(hits / (hits + misses))
    finally:
        telemetry.disable()


# ---- knob validation --------------------------------------------------


def test_knob_validation():
    model, variables = _model()
    with pytest.raises(ValueError, match="prefill_align"):
        _engine(model, variables, prefill_chunk=3)
    with pytest.raises(ValueError, match="prefill_align"):
        _engine(model, variables, prefill_chunk=0)
    with pytest.raises(ValueError, match="prefix_cache_bytes"):
        _engine(model, variables, prefix_cache_bytes=0)

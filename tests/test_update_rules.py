"""Unit tests for the PS update-rule math (SURVEY.md §4: test update rules
as pure functions — the reference never did)."""

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.parallel import (
    AdagRule,
    DownpourRule,
    DynSGDRule,
    ElasticRule,
    apply_commit_round,
    apply_commit_round_pulls,
)


def _params(val=0.0):
    return {"w": jnp.full((3,), val), "b": jnp.full((2, 2), val)}


def _leaf(tree):
    return np.asarray(tree["w"])


def test_downpour_commit_adds_delta():
    rule = DownpourRule()
    st = rule.init_state(_params(1.0))
    st = rule.commit(st, _params(0.5), jnp.int32(0))
    np.testing.assert_allclose(_leaf(st.center), 1.5)
    assert int(st.clock) == 1


def test_adag_normalizes_by_window():
    rule = AdagRule()
    delta = rule.normalize_delta(_params(8.0), window=4)
    np.testing.assert_allclose(_leaf(delta), 2.0)
    st = rule.commit(rule.init_state(_params(0.0)), delta, jnp.int32(0))
    np.testing.assert_allclose(_leaf(st.center), 2.0)


def test_dynsgd_scales_by_inverse_staleness():
    rule = DynSGDRule()
    st = rule.init_state(_params(0.0))
    st = rule.commit(st, _params(1.0), jnp.int32(0))  # fresh: full step
    np.testing.assert_allclose(_leaf(st.center), 1.0)
    st = rule.commit(st, _params(1.0), jnp.int32(3))  # stale: 1/4 step
    np.testing.assert_allclose(_leaf(st.center), 1.25)


def test_elastic_symmetric_moves():
    rule = ElasticRule(alpha=0.25)
    center0 = _params(0.0)
    local = _params(4.0)
    st = rule.commit(rule.init_state(center0), local, jnp.int32(0))
    # center moves alpha of the way toward the worker...
    np.testing.assert_allclose(_leaf(st.center), 1.0)
    # ...and the worker moves alpha of the way toward the (pre-commit) center
    pulled = rule.worker_pull(local, center0, st.center)
    np.testing.assert_allclose(_leaf(pulled), 3.0)


def test_commit_round_matches_sequential_loop():
    """lax.scan round == hand-rolled sequential commits, staleness=index."""
    rule = DynSGDRule()
    st0 = rule.init_state(_params(0.0))
    n = 5
    payloads = {
        "w": jnp.stack([jnp.full((3,), float(i + 1)) for i in range(n)]),
        "b": jnp.stack([jnp.full((2, 2), float(i + 1)) for i in range(n)]),
    }
    final, pre, post = apply_commit_round(rule, st0, payloads)

    expect = rule.init_state(_params(0.0))
    pres, posts = [], []
    for i in range(n):
        payload_i = jax.tree_util.tree_map(lambda x: x[i], payloads)
        pres.append(_leaf(expect.center).copy())
        expect = rule.commit(expect, payload_i, jnp.int32(i))
        posts.append(_leaf(expect.center).copy())

    np.testing.assert_allclose(_leaf(final.center), _leaf(expect.center),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pre["w"]), np.stack(pres),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(post["w"]), np.stack(posts),
                               rtol=1e-6)
    assert int(final.clock) == n


def test_commit_round_pulls_matches_stacked_path_delta_rule():
    """In-scan pulls (O(params) path) == stacked pre/post path + pull law,
    for a delta-family rule (pull ignores local: pulled_i = post_i)."""
    rule = DynSGDRule()
    st0 = rule.init_state(_params(0.0))
    payloads = {
        "w": jnp.stack([jnp.full((3,), float(i + 1)) for i in range(5)]),
        "b": jnp.stack([jnp.full((2, 2), float(i + 1)) for i in range(5)]),
    }
    final_a, _, post = apply_commit_round(rule, st0, payloads)
    final_b, pulled = apply_commit_round_pulls(rule, st0, payloads, None)
    np.testing.assert_allclose(_leaf(final_a.center),
                               _leaf(final_b.center), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pulled["w"]),
                               np.asarray(post["w"]), rtol=1e-6)
    assert int(final_b.clock) == 5


def test_commit_round_pulls_matches_stacked_path_elastic_rule():
    """Elastic rule: pulled_i = lerp(local_i, pre_i) — the in-scan path
    must reproduce the stacked path's per-position pulls exactly."""
    rule = ElasticRule(alpha=0.25)
    st0 = rule.init_state(_params(0.0))
    n = 4
    payloads = {"w": jnp.arange(1.0, n + 1)[:, None] * jnp.ones((n, 3)),
                "b": jnp.arange(1.0, n + 1)[:, None, None]
                * jnp.ones((n, 2, 2))}
    locals_ = payloads  # elastic payload IS the local params
    final_a, pre, post = apply_commit_round(rule, st0, payloads)
    expect = jax.vmap(rule.worker_pull)(locals_, pre, post)
    final_b, pulled = apply_commit_round_pulls(rule, st0, payloads,
                                               locals_)
    np.testing.assert_allclose(_leaf(final_a.center),
                               _leaf(final_b.center), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pulled["w"]),
                               np.asarray(expect["w"]), rtol=1e-6)


def test_commit_round_is_jittable():
    rule = ElasticRule(alpha=0.5)
    st0 = rule.init_state(_params(0.0))
    payloads = {"w": jnp.ones((4, 3)), "b": jnp.ones((4, 2, 2))}
    jitted = jax.jit(lambda s, p: apply_commit_round(rule, s, p))
    final, _, _ = jitted(st0, payloads)
    # center after 4 elastic commits of x=1 from c=0: 1-(1-a)^4 = 0.9375
    np.testing.assert_allclose(_leaf(final.center), 0.9375, rtol=1e-6)


def test_flush_pending_applies_true_commit_depth():
    """ADVICE r5: the drain applies the final pending commits at their
    TRUE depth — staleness = position in the commit order only (no
    window runs ahead at the drain), so DynSGD scales commit i by
    1/(i+1), not 1/(i+1+W)."""
    from distkeras_tpu.parallel.ps_emulator import flush_pending

    rule = DynSGDRule()
    st0 = rule.init_state(_params(0.0))
    n = 4
    payloads = {
        "w": jnp.stack([jnp.full((3,), float(i + 1)) for i in range(n)]),
        "b": jnp.stack([jnp.full((2, 2), float(i + 1))
                        for i in range(n)]),
    }
    perm = jnp.arange(n)  # identity commit order
    final = flush_pending(rule, st0, payloads, perm, n)
    # center = sum_i payload_i / (i + 1) = 1/1 + 2/2 + 3/3 + 4/4 = 4
    np.testing.assert_allclose(_leaf(final.center), 4.0, rtol=1e-6)
    # the old uniform +W drain would have produced sum_i (i+1)/(i+1+W)
    stale = sum((i + 1.0) / (i + 1.0 + n) for i in range(n))
    assert not np.allclose(_leaf(final.center), stale)

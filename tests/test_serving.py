"""Continuous-batching decode engine (``serving.DecodeEngine``): slot
reuse over a persistent KV-cache pool must be INVISIBLE in the tokens —
greedy results equal ``models.generate`` per request, independent of
admission order and of which (dirty) slot a request lands in — and
steady-state serving must compile a bounded program set (the §23
claim)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import ModelSpec, generate, model_config
from distkeras_tpu.serving import DecodeEngine, ShedError

jax.config.update("jax_platforms", "cpu")

MAXLEN, VOCAB = 32, 37


def _model(num_layers=1, **kw):
    # one layer keeps the many per-test engine compiles cheap; the
    # dirty-slot test runs two layers to cover the multi-layer cache
    # pytree merge
    spec = model_config("transformer_lm", (MAXLEN,),
                        input_dtype="int32", vocab_size=VOCAB,
                        num_layers=num_layers, d_model=32, num_heads=2,
                        max_len=MAXLEN, dtype="float32", **kw)
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, MAXLEN), jnp.int32))
    return model, variables


def _prompts(lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, (t,)).astype(np.int32)
            for t in lengths]


def _want(model, variables, prompt, n_new, **kw):
    return np.asarray(generate(model, variables, prompt[None, :],
                               max_new_tokens=n_new, **kw)
                      )[0, len(prompt):]


def test_engine_matches_generate_per_request_any_admission_order():
    """Each request's greedy tokens equal a solo generate() run — the
    slot pool, right-padded prefill, and neighbors are invisible —
    and reversing the admission order changes nothing."""
    model, variables = _model()
    prompts = _prompts([5, 9, 3, 7, 5, 11, 4, 6])
    n_new = [4, 7, 3, 6, 5, 8, 2, 7]
    reqs = [{"prompt": p, "max_new_tokens": n, "i": i}
            for i, (p, n) in enumerate(zip(prompts, n_new))]
    eng = DecodeEngine(model, variables, slots=3, buckets=[16, 32],
                       prefill_align=4, steps_per_sync=2)
    fwd = {r["i"]: r["tokens"] for r in eng.run(reqs)}
    rev = {r["i"]: r["tokens"] for r in eng.run(list(reversed(reqs)),
                                                ordered=False)}
    for i, (p, n) in enumerate(zip(prompts, n_new)):
        want = _want(model, variables, p, n)
        np.testing.assert_array_equal(fwd[i], want)
        np.testing.assert_array_equal(rev[i], want)


def test_dirty_slot_readmission_is_clean():
    """More requests than slots forces every slot through
    evict -> readmit with a DIRTY cache; prefill replaces the whole
    envelope, so the reused slot's tokens still match generate()."""
    model, variables = _model(num_layers=2)
    prompts = _prompts([6, 6, 9, 4, 7, 5, 8, 6, 5], seed=7)
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=5)
    out = list(eng.run([{"prompt": p, "i": i}
                        for i, p in enumerate(prompts)]))
    assert len(out) == 9  # 9 requests through 2 slots: 7 readmissions
    for r in out:
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, prompts[r["i"]], 5))


def test_per_slot_eos_and_max_new_stop():
    """Slots stop independently: an eos-finished row is evicted (its
    tokens end AT the eos) while its neighbors keep decoding to their
    own max_new_tokens caps."""
    model, variables = _model()
    prompts = _prompts([5, 5], seed=6)
    base = [_want(model, variables, p, 8) for p in prompts]
    # an eos row 0 emits but row 1 never does (same device as the
    # generate() eos test: rows must stop independently)
    cand = [int(t) for t in base[0] if t not in base[1]]
    assert cand, "degenerate sample; adjust seed"
    eos = cand[0]
    stop = int(np.argwhere(base[0] == eos)[0][0])
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4)
    res = {r["request_id"]: r for r in eng.run(
        [{"prompt": prompts[0], "max_new_tokens": 8, "eos_id": eos},
         {"prompt": prompts[1], "max_new_tokens": 8, "eos_id": eos},
         {"prompt": prompts[1], "max_new_tokens": 3}])}
    np.testing.assert_array_equal(res[0]["tokens"],
                                  base[0][:stop + 1])
    np.testing.assert_array_equal(res[1]["tokens"], base[1])
    np.testing.assert_array_equal(res[2]["tokens"], base[1][:3])


def test_max_new_tokens_one_and_instant_eos_finish_at_prefill():
    model, variables = _model()
    (p,) = _prompts([5], seed=9)
    first = int(_want(model, variables, p, 1)[0])
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4)
    res = list(eng.run([{"prompt": p, "max_new_tokens": 1},
                        {"prompt": p, "max_new_tokens": 8,
                         "eos_id": first}]))
    np.testing.assert_array_equal(res[0]["tokens"], [first])
    np.testing.assert_array_equal(res[1]["tokens"], [first])


def test_compile_count_guard_steady_state():
    """The §23 bounded-program-set claim, pinned via the PUBLIC
    telemetry counter ``compiles_total{kind,bucket[,padded]}`` (ISSUE 2:
    compile events are registry metrics, not private engine state): one
    step program per bucket + one prefill program per (bucket, padded
    length); a second ragged workload in a DIFFERENT arrival order
    triggers ZERO new traces."""
    from distkeras_tpu import telemetry

    tel = telemetry.enable()
    try:
        model, variables = _model()
        eng = DecodeEngine(model, variables, slots=2, buckets=[16, 32],
                           prefill_align=8, max_new_tokens=4)
        lengths = [3, 9, 5, 14, 7, 2, 11, 8]
        eng_reqs = lambda ls: [{"prompt": p}  # noqa: E731
                               for p in _prompts(ls, seed=11)]
        list(eng.run(eng_reqs(lengths)))
        m = tel.metrics
        # bounded set: one step trace per bucket...
        assert m.counter("compiles_total", kind="step",
                         bucket=16).value == 1
        assert m.counter("compiles_total", kind="step",
                         bucket=32).value == 1
        # ...and one prefill trace per (bucket, padded length), padded
        # lengths multiples of prefill_align within the bucket
        prefills = m.collect("compiles_total", kind="prefill")
        assert prefills
        for labels, c in prefills:
            assert c.value == 1, labels
        shapes = {(int(l["bucket"]), int(l["padded"]))
                  for l, _ in prefills}
        assert shapes <= {(16, 8), (16, 16), (32, 8), (32, 16),
                          (32, 24), (32, 32)}
        counters_before = {
            k: v for k, v in m.snapshot()["counters"].items()
            if k.startswith("compiles_total")}
        # ragged re-arrivals, shuffled: nothing new compiles
        list(eng.run(eng_reqs(list(reversed(lengths)))))
        list(eng.run(eng_reqs([7, 7, 3, 9, 2])))
        counters_after = {
            k: v for k, v in m.snapshot()["counters"].items()
            if k.startswith("compiles_total")}
        assert counters_after == counters_before
    finally:
        telemetry.disable()


def test_bucket_routing_and_rejection():
    """A request lands in the smallest envelope that fits its padded
    prompt + budget (cheapest static cache, §18 law); an unservable
    request fails at submit() time, naming no compiled flush."""
    model, variables = _model()
    eng = DecodeEngine(model, variables, slots=2, buckets=[16, 32],
                       prefill_align=4, max_new_tokens=4)
    assert eng._route(5, 4).env == 16
    assert eng._route(13, 4).env == 32   # 13+4 > 16
    assert eng._route(5, 20).env == 32   # budget overflows 16
    with pytest.raises(ValueError, match="no bucket"):
        eng.submit(np.zeros(30, np.int32), max_new_tokens=8)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.zeros(4, np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="1-D"):
        eng.submit(np.zeros((2, 4), np.int32))
    with pytest.raises(ValueError, match="eos_id"):
        eng.submit(np.zeros(4, np.int32), max_new_tokens=2,
                   eos_id=VOCAB)


def test_gqa_int8_cache_compose_with_engine():
    """The serving levers stack: GQA + int8 slot pools still match the
    same model's generate() greedy tokens."""
    model, variables = _model(num_kv_heads=1, kv_cache_dtype="int8")
    prompts = _prompts([5, 8, 6], seed=13)
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       steps_per_sync=3, max_new_tokens=6)
    for r in eng.run([{"prompt": p, "i": i}
                      for i, p in enumerate(prompts)]):
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, prompts[r["i"]], 6))


def test_sampling_reproducible_for_fixed_seed_and_order():
    model, variables = _model()
    reqs = [{"prompt": p, "max_new_tokens": 5}
            for p in _prompts([5, 7, 5, 6], seed=17)]
    kw = dict(slots=2, prefill_align=4, temperature=0.9, top_k=8)
    eng = DecodeEngine(model, variables, seed=5, **kw)
    a = [r["tokens"] for r in eng.run(reqs)]
    eng.reset_rng()
    b = [r["tokens"] for r in eng.run(reqs)]
    for ta, tb in zip(a, b):
        np.testing.assert_array_equal(ta, tb)
    c = [r["tokens"] for r in
         DecodeEngine(model, variables, seed=6, **kw).run(reqs)]
    assert any(not np.array_equal(ta, tc) for ta, tc in zip(a, c))
    assert all((t >= 0).all() and (t < VOCAB).all() for t in a)
    with pytest.raises(RuntimeError, match="in flight"):
        eng.submit(np.zeros(4, np.int32), max_new_tokens=2)
        eng.reset_rng()


def test_as_completed_vs_ordered_delivery():
    """ordered=False yields early finishers first (a 2-token request
    admitted alongside 8-token neighbors completes before them);
    ordered=True restores submission order."""
    model, variables = _model()
    prompts = _prompts([5, 5, 5], seed=19)
    reqs = [{"prompt": prompts[0], "max_new_tokens": 8, "i": 0},
            {"prompt": prompts[1], "max_new_tokens": 2, "i": 1},
            {"prompt": prompts[2], "max_new_tokens": 8, "i": 2}]
    eng = DecodeEngine(model, variables, slots=3, prefill_align=4)
    completed = [r["i"] for r in eng.run(reqs, ordered=False)]
    assert completed[0] == 1, completed
    assert [r["i"] for r in eng.run(reqs, ordered=True)] == [0, 1, 2]


def test_slot_step_matches_scalar_decode_path():
    """Model-level contract: a slot_pos T=1 step on a [B] pool whose
    rows sit at DIFFERENT positions produces the same logits as each
    row's own scalar-index decode."""
    model, variables = _model()
    dec = model.clone(decode=True)
    params = {"params": variables["params"]}
    pa, pb = _prompts([4, 7], seed=23)
    tok = jnp.asarray([[1], [2]], jnp.int32)
    caches, want = [], []
    for p in (pa, pb):
        logits, st = dec.apply(params, jnp.asarray(p[None, :]),
                               mutable=["cache"])
        nxt, st = dec.apply({**params, "cache": st["cache"]},
                            tok[:1] if p is pa else tok[1:],
                            mutable=["cache"])
        caches.append(st["cache"])
        want.append(np.asarray(nxt[0, 0]))
    # build a 2-slot pool from the two solo caches
    pool = jax.tree_util.tree_map(
        lambda a, b: (jnp.concatenate([a, b], 0)
                      if getattr(a, "ndim", 0) >= 1 else a),
        caches[0], caches[1])
    slot_pos = jnp.asarray([len(pa), len(pb)], jnp.int32)
    got, _ = dec.apply({**params, "cache": pool}, tok,
                       slot_pos=slot_pos, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(got[:, 0]), np.stack(want),
                               rtol=2e-5, atol=2e-5)


def test_slot_pos_contract_validation():
    model, variables = _model()
    dec = model.clone(decode=True)
    params = {"params": variables["params"]}
    with pytest.raises(ValueError, match="slot_pos"):
        dec.apply(params, jnp.zeros((2, 3), jnp.int32),
                  slot_pos=jnp.zeros((2,), jnp.int32),
                  mutable=["cache"])
    with pytest.raises(ValueError, match="decode"):
        model.apply(variables, jnp.zeros((2, 1), jnp.int32),
                    slot_pos=jnp.zeros((2,), jnp.int32))
    with pytest.raises(ValueError, match="cache_envelope"):
        model.clone(cache_envelope=16).apply(
            variables, jnp.zeros((2, 4), jnp.int32))
    with pytest.raises(ValueError, match="cache_envelope"):
        model.clone(decode=True, cache_envelope=MAXLEN + 1).apply(
            params, jnp.zeros((1, 4), jnp.int32), mutable=["cache"])


def test_duplicate_inflight_request_id_rejected():
    """Mixed explicit/auto ids cannot silently collide and
    cross-deliver: a duplicate in-flight id is rejected at submit, and
    auto-assignment skips over in-flight explicit ids.  Finished ids
    become reusable."""
    model, variables = _model()
    (p,) = _prompts([5])
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=2)
    eng.submit(p, request_id=7)
    with pytest.raises(ValueError, match="in flight"):
        eng.submit(p, request_id=7)
    # the auto path must never hand out an id an explicit caller holds
    eng2 = DecodeEngine(model, variables, slots=2, prefill_align=4,
                        max_new_tokens=2)
    eng2.submit(p, request_id=0)          # occupies the first auto id
    auto = eng2.submit(p)
    assert auto != 0
    ids = {r["request_id"] for r in eng2.drain()}
    assert ids == {0, auto}
    # after finishing, the id is free again
    assert eng2.submit(p, request_id=0) == 0
    eng2.drain()


def test_queue_bound_overload_sheds_and_survivors_complete():
    """2x queue-bound overload: submits beyond slots + queue_bound shed
    with ShedError + serving_shed_total > 0; every ACCEPTED request
    still completes with correct greedy tokens (admission control
    degrades capacity, never correctness)."""
    from distkeras_tpu import telemetry

    tel = telemetry.enable()
    try:
        model, variables = _model()
        slots, bound = 2, 2
        eng = DecodeEngine(model, variables, slots=slots,
                           prefill_align=4, max_new_tokens=4,
                           queue_bound=bound)
        prompts = _prompts([5] * (2 * (slots + bound)), seed=31)
        accepted, shed = [], 0
        for i, p in enumerate(prompts):
            # keep slots saturated: admit only when a step would; the
            # queue alone absorbs up to `bound`, the rest shed
            try:
                accepted.append(eng.submit(p, request_id=i))
            except ShedError as e:
                assert e.reason == "queue_full"
                shed += 1
        assert shed > 0
        assert tel.metrics.sum_counter("serving_shed_total") == shed
        res = {r["request_id"]: r for r in eng.drain()}
        assert sorted(res) == sorted(accepted)
        for rid, r in res.items():
            assert "error" not in r
            np.testing.assert_array_equal(
                r["tokens"], _want(model, variables, prompts[rid], 4))
    finally:
        telemetry.disable()


def test_poisoned_request_isolated_as_error_result():
    """A request whose prefill raises is finished with an ``error``
    result; its neighbors' slots keep decoding to correct tokens and
    the engine keeps serving afterwards."""
    model, variables = _model()
    prompts = _prompts([5, 6, 7], seed=37)
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=4)
    pool = eng._pools[0]
    real_prefill = pool.prefill_fn

    def poisoned(variables, cache, state, prompt, slot, last_idx,
                 n_left0, eos_id, rng):
        if int(last_idx) == len(prompts[1]) - 1:  # request 1 only
            raise RuntimeError("poisoned prompt")
        return real_prefill(variables, cache, state, prompt, slot,
                            last_idx, n_left0, eos_id, rng)

    pool.prefill_fn = poisoned
    res = {r["request_id"]: r for r in eng.run(
        [{"prompt": p} for p in prompts])}
    assert "poisoned prompt" in res[1]["error"]
    assert len(res[1]["tokens"]) == 0 and res[1]["ttft"] is None
    for i in (0, 2):
        assert "error" not in res[i]
        np.testing.assert_array_equal(
            res[i]["tokens"], _want(model, variables, prompts[i], 4))
    # the engine is not stalled: it serves the next workload fine
    pool.prefill_fn = real_prefill
    (ok,) = list(eng.run([prompts[0]]))
    np.testing.assert_array_equal(
        ok["tokens"], _want(model, variables, prompts[0], 4))


def test_deadline_expires_queued_and_live_requests():
    """An already-expired queued request is shed at admission with an
    error result; a live request past its deadline frees its slot; a
    deadline-free neighbor finishes untouched."""
    model, variables = _model()
    prompts = _prompts([5, 5], seed=41)
    eng = DecodeEngine(model, variables, slots=1, prefill_align=4,
                       max_new_tokens=6)
    eng.submit(prompts[0], request_id=0)              # takes the slot
    eng.submit(prompts[1], request_id=1, deadline=1e-9)  # expires queued
    res = {r["request_id"]: r for r in eng.drain()}
    assert res[1]["error"] == "deadline_exceeded"
    assert "error" not in res[0]
    np.testing.assert_array_equal(
        res[0]["tokens"], _want(model, variables, prompts[0], 6))
    # live expiry: a decoding request past its deadline frees the slot
    # (backdate the deadline once admitted, the idle-worker idiom)
    from distkeras_tpu import telemetry

    eng.submit(prompts[0], request_id=2, deadline=3600.0)
    eng.step()                            # admitted into the slot
    (req,) = [q for q in eng._pools[0].reqs if q is not None]
    req.deadline = telemetry.now() - 1.0  # expired mid-decode
    (r,) = eng.drain()
    assert r["error"] == "deadline_exceeded"
    assert len(r["tokens"]) >= 1          # prefill had already landed
    with pytest.raises(ValueError, match="deadline"):
        eng.submit(prompts[0], deadline=0.0)


def test_drain_returns_every_inflight_and_close_cancels():
    """drain() returns exactly the in-flight set; close() cancels the
    remainder (error="engine_closed", nothing vanishes) and further
    submit/step raise."""
    model, variables = _model()
    prompts = _prompts([5, 6, 4, 7, 5], seed=43)
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=4)
    rids = [eng.submit(p) for p in prompts]
    drained = {r["request_id"] for r in eng.drain()}
    assert drained == set(rids)
    assert not eng.has_work()
    # now cancel mid-flight: 2 in slots (after one step) + 2 queued
    rids = [eng.submit(p, request_id=100 + i)
            for i, p in enumerate(prompts[:4])]
    eng.step()
    cancelled = eng.close()
    assert {r["request_id"] for r in cancelled} == set(rids)
    assert all(r["error"] == "engine_closed" for r in cancelled)
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(prompts[0])
    with pytest.raises(RuntimeError, match="closed"):
        eng.step()
    assert eng.close() == []              # idempotent


def test_streaming_continuous_backpressure_with_queue_bound():
    """StreamingGenerator(engine='continuous') over a queue_bound
    engine converts sheds into backpressure: every row still comes
    back, in order, with correct greedy tokens."""
    from distkeras_tpu.streaming import StreamingGenerator

    model, variables = _model()
    prompts = _prompts([5, 7, 5, 6, 5, 4, 6, 5], seed=47)
    gen = StreamingGenerator(
        model, variables, max_new_tokens=4, batch_size=2,
        engine="continuous",
        engine_options={"slots": 2, "prefill_align": 4,
                        "queue_bound": 1})
    out = list(gen.generate_stream(
        [{"prompt": p, "i": i} for i, p in enumerate(prompts)]))
    assert [r["i"] for r in out] == list(range(len(prompts)))
    for r in out:
        assert "generated_error" not in r
        np.testing.assert_array_equal(
            r["generated"][:4],
            _want(model, variables, prompts[r["i"]], 4))


def test_cache_envelope_bounds_chunk_and_positions():
    """A cache_envelope pool is a genuinely smaller cache: chunks
    beyond it are rejected, and decode inside it matches the
    full-envelope model (same params, positions from the same
    table)."""
    model, variables = _model()
    (p,) = _prompts([6], seed=29)
    want = _want(model, variables, p, 4)
    eng = DecodeEngine(model, variables, slots=1, buckets=[16],
                       prefill_align=4, max_new_tokens=4)
    (res,) = list(eng.run([p]))
    np.testing.assert_array_equal(res["tokens"], want)
    dec = model.clone(decode=True, cache_envelope=16)
    with pytest.raises(ValueError, match="exceeds the cache size"):
        dec.apply({"params": variables["params"]},
                  jnp.zeros((1, 20), jnp.int32), mutable=["cache"])


def test_submit_is_thread_safe_against_a_concurrent_stepper():
    """ISSUE 7 satellite: ``submit()`` from many threads while another
    thread steps the engine — the gateway's EngineReplica pattern.
    Every request is admitted exactly once and its tokens match the
    solo reference (the admission lock race this pins: queue/rid/
    dedupe mutations vs the stepping thread's admission pops)."""
    import threading
    import time

    model, variables = _model()
    eng = DecodeEngine(model, variables, slots=3, prefill_align=4,
                       max_new_tokens=4)
    prompts = _prompts([5, 7, 4, 6, 5, 3, 6, 5], seed=31)
    n_threads, per_thread = 4, 6
    results: dict = {}
    done_submitting = threading.Event()
    errors: list = []

    def stepper():
        while not done_submitting.is_set() or eng.has_work():
            for r in eng.step():
                assert r["request_id"] not in results  # exactly once
                results[r["request_id"]] = r
            time.sleep(0.001)

    def submitter(t):
        try:
            for j in range(per_thread):
                eng.submit(prompts[(t * per_thread + j) % len(prompts)],
                           request_id=f"t{t}-{j}")
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    step_thread = threading.Thread(target=stepper, daemon=True)
    step_thread.start()
    subs = [threading.Thread(target=submitter, args=(t,), daemon=True)
            for t in range(n_threads)]
    for s in subs:
        s.start()
    for s in subs:
        s.join(30)
    done_submitting.set()
    step_thread.join(60)
    assert not errors, errors
    assert len(results) == n_threads * per_thread
    for rid, r in results.items():
        t, j = (int(x) for x in rid[1:].split("-"))
        p = prompts[(t * per_thread + j) % len(prompts)]
        np.testing.assert_array_equal(r["tokens"],
                                      _want(model, variables, p, 4))
    eng.close()


def test_run_under_queue_bound_delivers_every_result():
    """ISSUE 7 satellite: ``run()`` over a queue_bound engine treats
    mid-iterable sheds as backpressure — completed results are
    delivered (never discarded), one result per item, in order."""
    model, variables = _model()
    prompts = _prompts([5, 7, 5, 6, 5, 4, 6, 5, 7, 5], seed=37)
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=4, queue_bound=1)
    out = list(eng.run([{"prompt": p, "i": i}
                        for i, p in enumerate(prompts)]))
    assert [r["i"] for r in out] == list(range(len(prompts)))
    for r in out:
        assert "error" not in r
        np.testing.assert_array_equal(
            r["tokens"], _want(model, variables, prompts[r["i"]], 4))
    eng.close()


def test_run_under_queue_bound_delivers_error_rows_too():
    """Deadline casualties under shed backpressure come back as
    ``error`` rows through ``run()`` — the whole iterable is accounted
    for even when nothing survives."""
    model, variables = _model()
    prompts = _prompts([5, 6, 5, 7, 5, 6], seed=41)
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=4, queue_bound=1,
                       deadline=1e-4)
    out = list(eng.run([{"prompt": p, "i": i}
                        for i, p in enumerate(prompts)]))
    assert [r["i"] for r in out] == list(range(len(prompts)))
    assert any(r.get("error") == "deadline_exceeded" for r in out)
    for r in out:
        if r.get("error") is None:
            np.testing.assert_array_equal(
                r["tokens"],
                _want(model, variables, prompts[r["i"]], 4))
    eng.close()

"""Unified telemetry (ISSUE 2): registry thread-safety under racing
PS-style threads, Perfetto-format trace validity, the opt-in /metrics
endpoint, and the two acceptance runs — an async host-PS (socket)
training producing ONE Perfetto-loadable trace with PS commit spans and
per-worker round spans on distinct thread tracks, and a mixed-length
``DecodeEngine`` run whose metrics snapshot holds queue-depth /
slot-occupancy gauges, a TTFT histogram, and per-bucket compile
counters."""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import telemetry

jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def tel():
    t = telemetry.enable(ring_capacity=100_000)
    yield t
    telemetry.disable()


# ---- registry ----------------------------------------------------------

def test_registry_get_or_create_and_kind_conflicts():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("a_total", bucket=16)
    assert reg.counter("a_total", bucket=16) is c
    assert reg.counter("a_total", bucket=32) is not c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("a_total", bucket=16)
    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    s = reg.series("loss")
    s.append(1.0)
    s.extend([0.5, 0.25])
    assert s.values() == [1.0, 0.5, 0.25] and len(s) == 3


def test_histogram_buckets_percentiles_and_validation():
    h = telemetry.Histogram(buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4 and snap["min"] == 0.005 \
        and snap["max"] == 5.0
    assert snap["buckets"] == {0.01: 1, 0.1: 2, 1.0: 3}
    assert h.percentile(0.5) == 0.1
    assert h.percentile(1.0) == 5.0  # beyond the last edge -> max
    assert telemetry.Histogram(buckets=(1, 2, 3)).percentile(0.5) \
        is None
    with pytest.raises(ValueError, match="strictly increasing"):
        telemetry.Histogram(buckets=(1.0, 1.0))
    with pytest.raises(ValueError, match="strictly increasing"):
        telemetry.Histogram(buckets=())


def test_registry_thread_safety_racing_ps_arm_shape():
    """The racing host-PS access pattern: N 'worker' threads and N
    'handler' threads hammer one counter, one histogram, and one
    series while a reader concurrently snapshots — final totals must
    be exact (no lost updates), snapshots must never crash."""
    reg = telemetry.MetricsRegistry()
    n_threads, n_ops = 8, 500
    stop = threading.Event()
    snaps = []

    def writer(i):
        c = reg.counter("commits_total")
        h = reg.histogram("staleness",
                          buckets=telemetry.STALENESS_BUCKETS)
        for k in range(n_ops):
            c.inc()
            h.observe(k % 7)
            reg.series("round_loss").append((i, k))
            # half the threads also race the get-or-create path
            if i % 2:
                reg.counter("wire_bytes", direction="rx").inc(10)

    def reader():
        while not stop.is_set():
            snaps.append(reg.snapshot())
            reg.prometheus_text()

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    total = n_threads * n_ops
    assert reg.counter("commits_total").value == total
    assert reg.histogram("staleness").count == total
    assert len(reg.series("round_loss")) == total
    assert reg.counter("wire_bytes", direction="rx").value == \
        (n_threads // 2) * n_ops * 10
    # concurrent snapshots were internally consistent and monotone
    counts = [s["counters"].get("commits_total", 0) for s in snaps]
    assert counts == sorted(counts)


def test_prometheus_text_and_jsonl_export(tmp_path):
    reg = telemetry.MetricsRegistry()
    reg.counter("reqs_total", bucket=16).inc(3)
    reg.gauge("depth").set(2)
    reg.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.05)
    reg.series("epoch_loss").append(0.5)
    txt = reg.prometheus_text()
    assert "# TYPE reqs_total counter" in txt
    assert 'reqs_total{bucket="16"} 3' in txt
    assert 'lat_seconds_bucket{le="0.1"} 1' in txt
    assert 'lat_seconds_bucket{le="+Inf"} 1' in txt
    assert "lat_seconds_count 1" in txt
    assert "epoch_loss_observations 1" in txt
    path = reg.write_jsonl(tmp_path / "m.jsonl")
    recs = {r["key"]: r for r in map(json.loads, open(path))}
    assert recs['reqs_total{bucket="16"}']["value"] == 3
    assert recs["epoch_loss"]["values"] == [0.5]
    assert recs["lat_seconds"]["count"] == 1


def test_http_metrics_endpoint():
    reg = telemetry.MetricsRegistry()
    reg.counter("up_total").inc()
    host, port = reg.serve(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10).read().decode()
        assert "up_total 1" in body
        snap = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/metrics.json", timeout=10).read())
        assert snap["counters"]["up_total"] == 1
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{host}:{port}/nope",
                                   timeout=10)
    finally:
        reg.stop_serving()


def test_disabled_fast_path_is_inert():
    telemetry.disable()
    assert not telemetry.enabled()
    m = telemetry.metrics()
    # shared no-op handles: no state, no allocation per call site
    assert m.counter("a") is m.counter("b") is m.gauge("c")
    m.counter("a").inc()
    m.histogram("h").observe(1.0)
    assert m.snapshot()["counters"] == {}
    with telemetry.span("x", k=1) as s:
        inner = s
    assert inner is telemetry.span("y")  # the one shared no-op span
    telemetry.instant("e")
    assert telemetry.tracer().events() == []


# ---- tracer / Perfetto format -----------------------------------------

def check_perfetto_valid(trace: dict) -> None:
    """The validity contract: required ``ph``/``ts``/``pid``/``tid``
    fields on every timed event, non-negative durations, per-thread
    monotone completion timestamps (events append at span exit), a
    thread-name metadata record per thread track, and flow-event
    pairing — every flow-end ("f") matches exactly ONE flow-start
    ("s") by (name, cat, id).  Orphan starts are legal: a chaos-eaten
    message has a sender but never reaches a handler."""
    import collections

    events = trace["traceEvents"]
    assert events, "empty trace"
    named_tids = {e["tid"] for e in events
                  if e.get("ph") == "M"
                  and e.get("name") == "thread_name"}
    ends: dict[int, float] = {}
    flow_starts: collections.Counter = collections.Counter()
    flow_ends = []
    for e in events:
        assert e.get("ph") in ("X", "i", "M", "s", "f"), e
        assert isinstance(e.get("pid"), int)
        assert isinstance(e.get("tid"), int)
        if e["ph"] == "M":
            continue
        assert isinstance(e["ts"], float) and e["ts"] >= 0
        assert e["tid"] in named_tids
        if e["ph"] == "X":
            assert e["dur"] >= 0
            end = e["ts"] + e["dur"]
            assert end >= ends.get(e["tid"], 0.0)
            ends[e["tid"]] = end
        elif e["ph"] in ("s", "f"):
            assert isinstance(e.get("id"), str) and e.get("cat"), e
            key = (e["name"], e["cat"], e["id"])
            if e["ph"] == "s":
                flow_starts[key] += 1
            else:
                assert e.get("bp") == "e", e
                flow_ends.append(key)
    for key in flow_ends:
        assert flow_starts.get(key, 0) == 1, (
            f"flow-end {key} has {flow_starts.get(key, 0)} matching "
            f"starts (want exactly 1)")
    json.loads(json.dumps(trace))  # serializable as-is


def test_tracer_ring_bound_and_span_args(tel):
    small = telemetry.Tracer(capacity=4)
    for i in range(10):
        with small.span("s", i=i):
            pass
    evs = small.events()
    assert len(evs) == 4 and [e["args"]["i"] for e in evs] == \
        [6, 7, 8, 9]
    with pytest.raises(RuntimeError):
        with tel.span("fails"):
            raise RuntimeError("boom")
    err = [e for e in tel.tracer.events() if e["name"] == "fails"]
    assert err[0]["args"]["error"] == "RuntimeError"


def test_chrome_trace_multithreaded_perfetto_validity(tmp_path, tel):
    def work(i):
        for k in range(5):
            with tel.span("outer", worker=i):
                with tel.span("inner", k=k):
                    pass
            tel.instant("tick", worker=i)

    threads = [threading.Thread(target=work, args=(i,),
                                name=f"worker-{i}") for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with tel.span("main"):
        pass
    path = tel.tracer.write_chrome_trace(tmp_path / "trace.json")
    trace = json.load(open(path))
    check_perfetto_valid(trace)
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"worker-0", "worker-1", "worker-2"} <= names
    spans_by_tid = {}
    for e in trace["traceEvents"]:
        if e.get("ph") == "X":
            spans_by_tid.setdefault(e["tid"], []).append(e)
    assert len(spans_by_tid) == 4  # 3 workers + main


# ---- acceptance: host-PS socket run on one timeline -------------------

def test_host_ps_socket_run_single_perfetto_trace(tmp_path, tel):
    """One async host-PS training run (socket fidelity) -> one
    Perfetto-loadable trace with PS commit spans and per-worker round
    spans on DISTINCT thread tracks, plus commit-rate counter and
    staleness histogram in the same registry."""
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(1024, (8,), 4, seed=0)
    t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                 num_workers=3, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.01,
                 worker_optimizer="adam")
    t.train(data)

    path = tel.tracer.write_chrome_trace(tmp_path / "host_ps.json")
    trace = json.load(open(path))
    check_perfetto_valid(trace)

    commit_tids = {e["tid"] for e in trace["traceEvents"]
                   if e.get("ph") == "X" and e["name"] == "ps_commit"}
    round_spans = [e for e in trace["traceEvents"]
                   if e.get("ph") == "X"
                   and e["name"] == "worker_round"]
    round_tids = {e["tid"] for e in round_spans}
    # every worker thread has its own round track...
    assert {e["args"]["worker"] for e in round_spans} == {0, 1, 2}
    assert len(round_tids) == 3
    # ...and socket commits run on PS handler threads, not on them
    assert commit_tids and commit_tids.isdisjoint(round_tids)

    n_rounds = len(t.history["round_loss"])
    assert tel.metrics.counter("ps_commits_total").value == n_rounds
    assert tel.metrics.histogram("ps_commit_staleness").count == \
        n_rounds
    assert tel.metrics.counter("ps_wire_bytes_total",
                               direction="rx").value > 0
    assert tel.metrics.counter("ps_wire_bytes_total",
                               direction="tx").value > 0
    # the trainer's history stayed intact alongside (the view reads
    # the trainer's own registry, not the global one)
    assert len(t.history["staleness"][-1]) == n_rounds


# ---- acceptance: DecodeEngine metrics snapshot ------------------------

def _lm(max_len=32, vocab=37):
    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config("transformer_lm", (max_len,),
                        input_dtype="int32", vocab_size=vocab,
                        num_layers=1, d_model=32, num_heads=2,
                        max_len=max_len, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, max_len), jnp.int32))
    return model, variables


def test_engine_mixed_run_metrics_snapshot_and_derived_keys(tel):
    """Mixed-length DecodeEngine run -> snapshot holds queue-depth and
    slot-occupancy gauges, a TTFT histogram, and per-bucket compile
    counters; results carry engine-owned ``ttft``/``latency`` derived
    from the unified clock (meta keys of the same name lose)."""
    from distkeras_tpu.serving import DecodeEngine

    model, variables = _lm()
    eng = DecodeEngine(model, variables, slots=2, buckets=[16, 32],
                       prefill_align=4, max_new_tokens=4)
    rng = np.random.default_rng(3)
    reqs = [{"prompt": rng.integers(0, 37, (t,)).astype(np.int32),
             "ttft": "meta-must-lose", "i": i}
            for i, t in enumerate([5, 9, 3, 14, 7])]
    results = list(eng.run(reqs))
    assert len(results) == 5
    for r in results:
        assert isinstance(r["ttft"], float)      # engine key wins
        assert r["i"] in range(5)                # other meta survives
        assert r["t_submit"] <= r["t_first"] <= r["t_finish"]
        assert r["ttft"] == pytest.approx(r["t_first"] - r["t_submit"])
        assert r["latency"] == pytest.approx(
            r["t_finish"] - r["t_submit"])
        assert 0 <= r["ttft"] <= r["latency"]

    snap = tel.metrics.snapshot()
    for env in (16, 32):
        assert f'serving_queue_depth{{bucket="{env}"}}' \
            in snap["gauges"]
        assert f'serving_slot_occupancy{{bucket="{env}"}}' \
            in snap["gauges"]
        # drained engine: both levels ended at zero
        assert snap["gauges"][
            f'serving_slot_occupancy{{bucket="{env}"}}'] == 0
        assert tel.metrics.counter("compiles_total", kind="step",
                                   bucket=env).value == 1
        assert tel.metrics.sum_counter("compiles_total",
                                       kind="prefill",
                                       bucket=env) >= 1
    ttft = snap["histograms"]["serving_ttft_seconds"]
    assert ttft["count"] == 5
    lat = snap["histograms"]["serving_latency_seconds"]
    assert lat["count"] == 5 and lat["sum"] >= ttft["sum"]
    assert tel.metrics.sum_counter("serving_tokens_total") == \
        sum(len(r["tokens"]) for r in results)
    # timeline side: prefill/decode_step spans + evict instants
    names = {e["name"] for e in tel.tracer.events()}
    assert {"prefill", "decode_step", "evict"} <= names


def test_engine_timing_fields_without_telemetry_enabled():
    """The unified clock + derived keys are engine contract, not a
    telemetry feature: with telemetry DISABLED the timing fields are
    still present, ordered, and on one clock."""
    telemetry.disable()
    from distkeras_tpu.serving import DecodeEngine

    model, variables = _lm()
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=3)
    (r,) = list(eng.run([np.arange(5, dtype=np.int32)]))
    assert r["t_submit"] <= r["t_first"] <= r["t_finish"]
    assert r["ttft"] == pytest.approx(r["t_first"] - r["t_submit"])
    assert r["latency"] == pytest.approx(r["t_finish"] - r["t_submit"])


# ---- label escaping / bound port / healthz (ISSUE 6 satellites) -------

def test_prometheus_label_value_escaping():
    """Hostile label values (quotes, backslashes, newlines) must not
    corrupt the exposition format — and plain values must render
    byte-identically to before."""
    reg = telemetry.MetricsRegistry()
    reg.counter("reqs_total", bucket=16).inc(3)
    reg.counter("errs_total", path='say "hi"\\n').inc()
    reg.counter("errs_total", path="a\nb").inc(2)
    txt = reg.prometheus_text()
    assert 'reqs_total{bucket="16"} 3' in txt  # plain path unchanged
    assert 'errs_total{path="say \\"hi\\"\\\\n"} 1' in txt
    assert 'errs_total{path="a\\nb"} 2' in txt
    # one line per sample: the raw newline never split a line
    for line in txt.splitlines():
        if line and not line.startswith("#"):
            assert line.rsplit(" ", 1)[1].replace(".", "").isdigit()


def test_serve_bound_port_error_names_port():
    reg = telemetry.MetricsRegistry()
    host, port = reg.serve(port=0)
    other = telemetry.MetricsRegistry()
    try:
        with pytest.raises(OSError, match=f"{port}.*already in use"):
            other.serve(host=host, port=port)
        # ...and the recovery path the message recommends works
        h2, p2 = other.serve(port=0)
        assert p2 != port
    finally:
        other.stop_serving()
        reg.stop_serving()


def _read(url):
    try:
        resp = urllib.request.urlopen(url, timeout=10)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz_endpoint_reports_slo_state():
    reg = telemetry.MetricsRegistry()
    reg.counter("serving_requests_total", bucket=16).inc(100)
    host, port = reg.serve(port=0)
    try:
        status, verdict = _read(f"http://{host}:{port}/healthz")
        assert status == 200 and verdict["state"] == "ok"
        # 30% sheds >= the 25% critical threshold -> HTTP 503
        reg.counter("serving_shed_total", reason="queue_full",
                    bucket=16).inc(30)
        status, verdict = _read(f"http://{host}:{port}/healthz")
        assert status == 503 and verdict["state"] == "critical"
        assert verdict["breaches"]["shed_rate"]["level"] == "critical"
    finally:
        reg.stop_serving()


# ---- SLO watchdog ------------------------------------------------------

def test_slo_watchdog_thresholds_and_transitions(tel):
    reg = telemetry.MetricsRegistry()
    with pytest.raises(ValueError, match="unknown SLO signal"):
        telemetry.SLOWatchdog(reg, thresholds={"nope": (1, 2)})
    with pytest.raises(ValueError, match="must not exceed"):
        telemetry.SLOWatchdog(reg,
                              thresholds={"retry_rate": (2.0, 1.0)})

    w = telemetry.SLOWatchdog(reg)
    assert w.evaluate() == {"state": "ok", "raw_state": "ok",
                            "signals": {},
                            "breaches": {}}  # no traffic != outage
    h = reg.histogram("ps_commit_staleness",
                      buckets=telemetry.STALENESS_BUCKETS)
    for _ in range(100):
        h.observe(20)  # p99 = 20 >= degraded_at 16, < critical 64
    v = w.evaluate()
    assert v["state"] == "degraded"
    assert v["breaches"]["staleness_p99"]["level"] == "degraded"
    for _ in range(900):
        h.observe(100)
    v = w.evaluate()
    assert v["state"] == "critical" and w.state == "critical"
    # state CHANGES drop slo_state instants on the trace (2 flips)
    flips = [e for e in tel.tracer.events()
             if e["name"] == "slo_state"]
    assert [e["args"]["state"] for e in flips] == ["degraded",
                                                  "critical"]
    assert w.last() == v

    # idle fraction needs the registered-workers denominator
    reg2 = telemetry.MetricsRegistry()
    reg2.gauge("ps_registered_workers").set(4)
    reg2.gauge("ps_idle_workers").set(3)
    v2 = telemetry.SLOWatchdog(reg2).evaluate()
    assert v2["signals"]["idle_worker_fraction"] == 0.75
    assert v2["state"] == "critical"

    # background loop + attach: registry.health() uses the attached
    # watchdog (custom thresholds visible through /healthz's path)
    w3 = telemetry.SLOWatchdog(reg2, thresholds={
        "idle_worker_fraction": (0.9, 0.95)}, interval_s=0.01)
    reg2.attach_watchdog(w3)
    assert reg2.health()["state"] == "ok"
    w3.start()
    assert w3.start() is w3  # idempotent
    final = w3.stop()
    assert final["state"] == "ok"


def test_prefix_hit_rate_slo_signal_breaches_low(tel):
    """ISSUE 8: ``prefix_hit_rate`` is an INVERTED signal — a LOW
    rate (store thrash / post-swap cold start) is the breach, never a
    high one — with the threshold validation inverted to match."""
    reg = telemetry.MetricsRegistry()
    w = telemetry.SLOWatchdog(reg)
    assert w.evaluate()["state"] == "ok"  # no lookups != outage
    hits = reg.counter("serving_prefix_hits_total", bucket=32)
    miss = reg.counter("serving_prefix_misses_total", bucket=32)
    hits.inc(90)
    miss.inc(10)  # 0.90 hit rate: healthy
    v = w.evaluate()
    assert v["signals"]["prefix_hit_rate"] == pytest.approx(0.90)
    assert "prefix_hit_rate" not in v["breaches"]
    miss.inc(900)  # rate collapses to 0.09 <= degraded_at 0.10
    v = w.evaluate()
    assert v["breaches"]["prefix_hit_rate"]["level"] == "degraded"
    miss.inc(8000)  # ~0.01 <= critical_at 0.01
    v = w.evaluate()
    assert v["state"] == "critical"
    assert v["breaches"]["prefix_hit_rate"]["level"] == "critical"
    # custom thresholds: inverted pairs validate the inverted way
    telemetry.SLOWatchdog(reg, thresholds={
        "prefix_hit_rate": (0.5, 0.2)})  # degraded ABOVE critical: ok
    with pytest.raises(ValueError, match="breaches LOW"):
        telemetry.SLOWatchdog(reg, thresholds={
            "prefix_hit_rate": (0.2, 0.5)})


def test_mfu_gap_slo_signal():
    """ISSUE 17: ``mfu_gap`` = 1 - observed/roofline off the driver's
    attribution gauges — a big gap (round running far below its
    roofline floor) degrades the verdict."""
    reg = telemetry.MetricsRegistry()
    w = telemetry.SLOWatchdog(reg)
    assert "mfu_gap" not in w.evaluate()["signals"]  # gauges absent
    obs = reg.gauge("mfu_observed")
    roof = reg.gauge("mfu_roofline")
    obs.set(0.40)
    roof.set(0.50)  # gap 0.2 < degraded_at 0.5: healthy
    v = w.evaluate()
    assert v["signals"]["mfu_gap"] == pytest.approx(0.2)
    assert "mfu_gap" not in v["breaches"]
    obs.set(0.20)  # gap 0.6 >= 0.5: degraded
    v = w.evaluate()
    assert v["breaches"]["mfu_gap"]["level"] == "degraded"
    obs.set(0.02)  # gap 0.96 >= critical_at 0.9
    v = w.evaluate()
    assert v["breaches"]["mfu_gap"]["level"] == "critical"
    obs.set(0.60)  # observed ABOVE the roofline estimate: clamped to 0
    assert w.evaluate()["signals"]["mfu_gap"] == 0.0
    roof.set(0.0)  # degenerate roofline: signal absent, not fabricated
    assert "mfu_gap" not in w.evaluate()["signals"]


# ---- trace context + wire header --------------------------------------

def test_trace_context_nesting_and_wire_header(tel):
    from distkeras_tpu.parallel import transport

    assert telemetry.current_trace() is None
    assert transport.trace_header() == b""  # tracing off: ZERO bytes
    with telemetry.span("root") as root:
        trace_id, span_id = telemetry.current_trace()
        assert trace_id == span_id == root.span_id  # root id IS trace
        with telemetry.span("child") as child:
            t2, s2 = telemetry.current_trace()
            assert t2 == trace_id and s2 == child.span_id != span_id
            hdr = transport.trace_header()
            assert len(hdr) == transport.TRACE_HEADER_LEN == 17
            link, rest = transport.split_trace_header(
                hdr + b"c" + b"payload")
            assert link == (t2, s2) and bytes(rest) == b"cpayload"
        assert telemetry.current_trace() == (trace_id, span_id)
    assert telemetry.current_trace() is None
    # an untraced body passes through unmodified
    link, rest = transport.split_trace_header(b"p")
    assert link is None and rest == b"p"
    # span ids are process-unique and stamped into exported args
    evs = {e["name"]: e for e in tel.tracer.events()}
    assert evs["child"]["args"]["trace_id"] == \
        evs["root"]["args"]["span_id"]
    assert evs["child"]["args"]["span_id"] != \
        evs["root"]["args"]["span_id"]


def test_merge_traces_clock_shift_and_pid_collision():
    def tr(pid, wall, mono, ts):
        return {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"p{pid}"}},
            {"name": "s1", "ph": "X", "ts": ts, "dur": 5.0,
             "pid": pid, "tid": 1, "args": {}}],
            "wallAnchor": {"wall_s": wall, "mono_s": mono,
                           "pid": pid}}

    # same wall instant, different perf_counter origins: process B's
    # mono clock reads 2s lower, so its events shift +2s in the merge
    merged = telemetry.merge_traces(tr(1, 1000.0, 50.0, 50.0 * 1e6),
                                    tr(1, 1000.0, 48.0, 48.0 * 1e6))
    spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert spans[0]["ts"] == pytest.approx(spans[1]["ts"])
    # colliding pid: the second dump got a synthetic process track
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert len(pids) == 2 and 1 in pids
    # metadata sorts first so Perfetto names tracks before events
    assert merged["traceEvents"][0]["ph"] == "M"


# ---- flight recorder ---------------------------------------------------

def test_flight_recorder_rotation_retention_and_torn_tail(tmp_path):
    from distkeras_tpu.flight_recorder import FlightRecorder

    with pytest.raises(ValueError, match=">= 1"):
        FlightRecorder(tmp_path, segment_events=0)
    fr = FlightRecorder(tmp_path / "ring", segment_events=4,
                        segments=2)
    for i in range(20):
        fr.record("tick", i=i)
    fr.close()
    fr.close()  # idempotent
    # ring bound: 2 sealed segments x 4 events survive of the 20
    events = fr.read_events()
    assert [e["i"] for e in events] == list(range(12, 20))
    assert all(e["kind"] == "tick" and "wall_s" in e and "pid" in e
               for e in events)
    # the caller's own fields never collide with recorder stamps
    fr2 = FlightRecorder(tmp_path / "ring2")
    fr2.record("commit", seq=41)
    assert fr2.read_events()[0]["seq"] == 41
    # torn final line (crashed writer): parsed up to the tear
    with open(fr2._open_path(fr2._segment_n), "a") as f:
        f.write('{"kind": "torn", "wal')
    assert [e["kind"] for e in fr2.read_events()] == ["commit"]
    # windowing: last N seconds ending at the newest event
    assert fr2.last(60.0) == fr2.read_events()
    assert fr2.last(0.0, until_wall_s=0.0) == []


def test_flight_recorder_module_globals_and_disabled_noop(tmp_path):
    from distkeras_tpu import flight_recorder

    flight_recorder.stop()
    assert flight_recorder.active() is None
    flight_recorder.record("ignored", x=1)  # no recorder: no-op
    flight_recorder.flush()
    fr = flight_recorder.start(tmp_path / "fdr")
    try:
        assert flight_recorder.active() is fr
        flight_recorder.record("seen", x=2)
        flight_recorder.flush(fsync=True)
        assert [e["kind"] for e in fr.read_events()] == ["seen"]
    finally:
        flight_recorder.stop()
    assert flight_recorder.active() is None
    # stopping sealed the live segment atomically
    assert list((tmp_path / "fdr").glob("*.jsonl"))
    assert not list((tmp_path / "fdr").glob("*.open"))


# ---- acceptance: chaos + kill/restart, traced and flight-recorded -----

def test_chaos_kill_restart_traced_flight_and_postmortem(tmp_path, tel):
    """THE observability acceptance scenario (ISSUE 6): a chaos-enabled
    socket training run whose external PS is killed and warm-restarted
    mid-stream, observed end to end —

    * the Perfetto trace validates WITH flow-event pairing: every
      surviving commit's server ``ps_rpc`` handler span carries a
      ``link_span`` that resolves to exactly one client-side wire span
      (chaos-eaten sends leave legal orphan flow-starts).  The genuine
      cross-PROCESS merge of the same arrows is proven by
      ``scripts/trace_merge.py --smoke`` (tier-1 via test_examples);
    * the flight recorder survives the crash with the whole story —
      commits, snapshots, chaos injections, client retries, the
      ``ps_kill`` marker, the ``ps_restart`` marker — and the max
      commit seq per worker it recorded up to the restart marker
      equals the restarted server's dedupe state exactly;
    * ``scripts/postmortem.py``'s reconstruction finds the kill as the
      crash marker (its exact snapshot ``acked_match`` law on a fully
      sequential schedule is proven by ``postmortem.py --smoke``);
    * the trainer's history carries the run's SLO verdict.
    """
    import importlib.util
    import pathlib
    import time

    from distkeras_tpu import flight_recorder
    from distkeras_tpu.data import datasets
    from distkeras_tpu.flight_recorder import FlightRecorder
    from distkeras_tpu.models import ModelSpec, model_config
    from distkeras_tpu.parallel.faults import ChaosTransport
    from distkeras_tpu.parallel.host_ps import (HostParameterServer,
                                                PSServer)
    from distkeras_tpu.parallel.update_rules import DownpourRule
    from distkeras_tpu.trainers import DOWNPOUR

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(1024, (8,), 4, seed=0)
    model = ModelSpec.from_config(mlp).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    center = jax.tree_util.tree_map(np.asarray, variables["params"])

    flight_dir = tmp_path / "flight"
    snap = tmp_path / "ps.snap"
    flight_recorder.start(flight_dir)
    ps = HostParameterServer(DownpourRule(), center,
                             snapshot_path=snap, snapshot_every=1)
    srv = PSServer(ps, center).start()
    port = srv.address[1]
    box = {}

    def killer():
        while srv.ps.num_commits < 5:
            time.sleep(0.002)
        srv.kill()
        # Let any commit already inside the handler finish its apply +
        # snapshot before the restart loads the file: every commit
        # RECORDED before the restart marker is then durably in the
        # snapshot the restart resumes from.  (A commit CAN race the
        # kill marker itself — real crash semantics — which is why the
        # cross-check below anchors at the restart, not the kill.)
        time.sleep(0.25)
        for _ in range(50):
            try:
                box["srv2"] = PSServer.restart_from(
                    snap, DownpourRule(), center, port=port)
                return
            except OSError:
                time.sleep(0.05)
        raise OSError(f"could not rebind port {port}")

    k = threading.Thread(target=killer)
    k.start()
    try:
        with ChaosTransport(seed=7, reset_rate=0.05, max_injections=2,
                            skip_ops=8):
            t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                         num_workers=1, communication_window=2,
                         batch_size=16, num_epoch=1,
                         learning_rate=0.01, worker_optimizer="adam",
                         worker_retries=12,
                         ps_address=("127.0.0.1", port))
            t.train(data, initial_variables=variables)
    finally:
        k.join()
        flight_recorder.stop()
    srv2 = box["srv2"]
    srv2.stop()

    # the outage really happened, the worker rode through it, and the
    # run closed with an SLO verdict in the history
    assert srv2.ps.num_commits > 5
    assert t.history.get("worker_round_retries"), (
        "the kill was invisible to the worker — test proved nothing")
    assert t.history["slo_health"][-1] in ("ok", "degraded", "critical")

    # -- trace: flow pairing + server->client span linking --------------
    path = tel.tracer.write_chrome_trace(tmp_path / "trace.json")
    trace = json.load(open(path))
    check_perfetto_valid(trace)  # includes the flow-pairing contract
    evs = trace["traceEvents"]
    client_spans = {e["args"]["span_id"] for e in evs
                    if e.get("ph") == "X"
                    and e["name"] in ("ps_client_pull",
                                      "ps_client_commit")}
    rpc = [e for e in evs if e.get("ph") == "X"
           and e["name"] == "ps_rpc"]
    linked = [e for e in rpc if "link_span" in e["args"]]
    assert linked, "no handler span recorded a client link"
    for e in linked:
        assert e["args"]["link_span"] in client_spans, e
    assert any(e.get("ph") == "f" for e in evs)  # arrows really drawn

    # -- flight recorder: the whole crash story survived ----------------
    events = FlightRecorder(flight_dir).read_events()
    kinds = {e["kind"] for e in events}
    assert {"commit", "snapshot", "retry",
            "ps_kill", "ps_restart"} <= kinds, kinds
    assert "chaos" in kinds, "no chaos injection fired"

    # the postmortem law, anchored at the restart marker: the max seq
    # the flight ring recorded per worker up to the restart equals the
    # dedupe state the restarted server resumed with
    restart_ev = [e for e in events if e["kind"] == "ps_restart"][-1]
    acked: dict = {}
    for e in events:
        if e["kind"] in ("commit", "commit_dedup") \
                and e["wall_s"] <= restart_ev["wall_s"]:
            w = str(e["worker"])
            acked[w] = max(acked.get(w, -1), int(e["seq"]))
    assert acked == {w: int(s)
                     for w, s in restart_ev["last_acked"].items()}

    # -- scripts/postmortem.py reconstructs the same crash --------------
    pm_path = (pathlib.Path(__file__).resolve().parent.parent
               / "scripts" / "postmortem.py")
    spec = importlib.util.spec_from_file_location("_dkt_pm", pm_path)
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    report = pm.reconstruct(str(flight_dir), seconds=300.0)
    assert report["crash"]["kind"] == "ps_kill"
    assert report["kinds"].get("commit", 0) >= 5
    # flight-acked at the KILL can trail the restart state by whatever
    # was mid-handler when the crash hit, but can never lead it
    for w, s in report["flight_last_acked"].items():
        assert int(s) <= acked[w]
    assert "postmortem" in pm.render(report)


def test_slo_violation_seconds_accrue_by_state(tel):
    """ISSUE 18: every evaluation closes out the time spent in the
    previously committed non-ok state onto
    ``slo_violation_seconds_total{state}`` — the drill's
    violation-minutes metric is a pure time integral, testable with an
    injected clock."""
    reg = telemetry.MetricsRegistry()
    w = telemetry.SLOWatchdog(
        reg, thresholds={"queue_depth": (3.0, 10.0)},
        sustain_secs=0.0)  # edge-trigger: transitions commit at once
    q = reg.gauge("serving_queue_depth", bucket=16)

    def acc(state):
        return reg.counter("slo_violation_seconds_total",
                           state=state).value

    assert w.evaluate(now_s=0.0)["state"] == "ok"
    q.set(5.0)
    assert w.evaluate(now_s=10.0)["state"] == "degraded"
    assert acc("degraded") == 0.0  # the 0..10 span was spent ok
    assert w.evaluate(now_s=12.0)["state"] == "degraded"
    assert acc("degraded") == pytest.approx(2.0)
    q.set(20.0)
    assert w.evaluate(now_s=15.0)["state"] == "critical"
    assert acc("degraded") == pytest.approx(5.0)  # closed on the flip
    assert w.evaluate(now_s=18.0)["state"] == "critical"
    q.set(0.0)
    assert w.evaluate(now_s=20.0)["state"] == "ok"
    assert acc("critical") == pytest.approx(5.0)
    assert w.evaluate(now_s=25.0)["state"] == "ok"
    # ok time never accrues; the totals are final
    assert acc("degraded") == pytest.approx(5.0)
    assert acc("critical") == pytest.approx(5.0)

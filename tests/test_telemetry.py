"""Unified telemetry (ISSUE 2): registry thread-safety under racing
PS-style threads, Perfetto-format trace validity, the opt-in /metrics
endpoint, and the two acceptance runs — an async host-PS (socket)
training producing ONE Perfetto-loadable trace with PS commit spans and
per-worker round spans on distinct thread tracks, and a mixed-length
``DecodeEngine`` run whose metrics snapshot holds queue-depth /
slot-occupancy gauges, a TTFT histogram, and per-bucket compile
counters."""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import telemetry

jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def tel():
    t = telemetry.enable(ring_capacity=100_000)
    yield t
    telemetry.disable()


# ---- registry ----------------------------------------------------------

def test_registry_get_or_create_and_kind_conflicts():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("a_total", bucket=16)
    assert reg.counter("a_total", bucket=16) is c
    assert reg.counter("a_total", bucket=32) is not c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("a_total", bucket=16)
    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    s = reg.series("loss")
    s.append(1.0)
    s.extend([0.5, 0.25])
    assert s.values() == [1.0, 0.5, 0.25] and len(s) == 3


def test_histogram_buckets_percentiles_and_validation():
    h = telemetry.Histogram(buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4 and snap["min"] == 0.005 \
        and snap["max"] == 5.0
    assert snap["buckets"] == {0.01: 1, 0.1: 2, 1.0: 3}
    assert h.percentile(0.5) == 0.1
    assert h.percentile(1.0) == 5.0  # beyond the last edge -> max
    assert telemetry.Histogram(buckets=(1, 2, 3)).percentile(0.5) \
        is None
    with pytest.raises(ValueError, match="strictly increasing"):
        telemetry.Histogram(buckets=(1.0, 1.0))
    with pytest.raises(ValueError, match="strictly increasing"):
        telemetry.Histogram(buckets=())


def test_registry_thread_safety_racing_ps_arm_shape():
    """The racing host-PS access pattern: N 'worker' threads and N
    'handler' threads hammer one counter, one histogram, and one
    series while a reader concurrently snapshots — final totals must
    be exact (no lost updates), snapshots must never crash."""
    reg = telemetry.MetricsRegistry()
    n_threads, n_ops = 8, 500
    stop = threading.Event()
    snaps = []

    def writer(i):
        c = reg.counter("commits_total")
        h = reg.histogram("staleness",
                          buckets=telemetry.STALENESS_BUCKETS)
        for k in range(n_ops):
            c.inc()
            h.observe(k % 7)
            reg.series("round_loss").append((i, k))
            # half the threads also race the get-or-create path
            if i % 2:
                reg.counter("wire_bytes", direction="rx").inc(10)

    def reader():
        while not stop.is_set():
            snaps.append(reg.snapshot())
            reg.prometheus_text()

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    total = n_threads * n_ops
    assert reg.counter("commits_total").value == total
    assert reg.histogram("staleness").count == total
    assert len(reg.series("round_loss")) == total
    assert reg.counter("wire_bytes", direction="rx").value == \
        (n_threads // 2) * n_ops * 10
    # concurrent snapshots were internally consistent and monotone
    counts = [s["counters"].get("commits_total", 0) for s in snaps]
    assert counts == sorted(counts)


def test_prometheus_text_and_jsonl_export(tmp_path):
    reg = telemetry.MetricsRegistry()
    reg.counter("reqs_total", bucket=16).inc(3)
    reg.gauge("depth").set(2)
    reg.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.05)
    reg.series("epoch_loss").append(0.5)
    txt = reg.prometheus_text()
    assert "# TYPE reqs_total counter" in txt
    assert 'reqs_total{bucket="16"} 3' in txt
    assert 'lat_seconds_bucket{le="0.1"} 1' in txt
    assert 'lat_seconds_bucket{le="+Inf"} 1' in txt
    assert "lat_seconds_count 1" in txt
    assert "epoch_loss_observations 1" in txt
    path = reg.write_jsonl(tmp_path / "m.jsonl")
    recs = {r["key"]: r for r in map(json.loads, open(path))}
    assert recs['reqs_total{bucket="16"}']["value"] == 3
    assert recs["epoch_loss"]["values"] == [0.5]
    assert recs["lat_seconds"]["count"] == 1


def test_http_metrics_endpoint():
    reg = telemetry.MetricsRegistry()
    reg.counter("up_total").inc()
    host, port = reg.serve(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10).read().decode()
        assert "up_total 1" in body
        snap = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/metrics.json", timeout=10).read())
        assert snap["counters"]["up_total"] == 1
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{host}:{port}/nope",
                                   timeout=10)
    finally:
        reg.stop_serving()


def test_disabled_fast_path_is_inert():
    telemetry.disable()
    assert not telemetry.enabled()
    m = telemetry.metrics()
    # shared no-op handles: no state, no allocation per call site
    assert m.counter("a") is m.counter("b") is m.gauge("c")
    m.counter("a").inc()
    m.histogram("h").observe(1.0)
    assert m.snapshot()["counters"] == {}
    with telemetry.span("x", k=1) as s:
        inner = s
    assert inner is telemetry.span("y")  # the one shared no-op span
    telemetry.instant("e")
    assert telemetry.tracer().events() == []


# ---- tracer / Perfetto format -----------------------------------------

def check_perfetto_valid(trace: dict) -> None:
    """The validity contract: required ``ph``/``ts``/``pid``/``tid``
    fields on every timed event, non-negative durations, per-thread
    monotone completion timestamps (events append at span exit), and a
    thread-name metadata record per thread track."""
    events = trace["traceEvents"]
    assert events, "empty trace"
    named_tids = {e["tid"] for e in events
                  if e.get("ph") == "M"
                  and e.get("name") == "thread_name"}
    ends: dict[int, float] = {}
    for e in events:
        assert e.get("ph") in ("X", "i", "M"), e
        assert isinstance(e.get("pid"), int)
        assert isinstance(e.get("tid"), int)
        if e["ph"] == "M":
            continue
        assert isinstance(e["ts"], float) and e["ts"] >= 0
        assert e["tid"] in named_tids
        if e["ph"] == "X":
            assert e["dur"] >= 0
            end = e["ts"] + e["dur"]
            assert end >= ends.get(e["tid"], 0.0)
            ends[e["tid"]] = end
    json.loads(json.dumps(trace))  # serializable as-is


def test_tracer_ring_bound_and_span_args(tel):
    small = telemetry.Tracer(capacity=4)
    for i in range(10):
        with small.span("s", i=i):
            pass
    evs = small.events()
    assert len(evs) == 4 and [e["args"]["i"] for e in evs] == \
        [6, 7, 8, 9]
    with pytest.raises(RuntimeError):
        with tel.span("fails"):
            raise RuntimeError("boom")
    err = [e for e in tel.tracer.events() if e["name"] == "fails"]
    assert err[0]["args"]["error"] == "RuntimeError"


def test_chrome_trace_multithreaded_perfetto_validity(tmp_path, tel):
    def work(i):
        for k in range(5):
            with tel.span("outer", worker=i):
                with tel.span("inner", k=k):
                    pass
            tel.instant("tick", worker=i)

    threads = [threading.Thread(target=work, args=(i,),
                                name=f"worker-{i}") for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with tel.span("main"):
        pass
    path = tel.tracer.write_chrome_trace(tmp_path / "trace.json")
    trace = json.load(open(path))
    check_perfetto_valid(trace)
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"worker-0", "worker-1", "worker-2"} <= names
    spans_by_tid = {}
    for e in trace["traceEvents"]:
        if e.get("ph") == "X":
            spans_by_tid.setdefault(e["tid"], []).append(e)
    assert len(spans_by_tid) == 4  # 3 workers + main


# ---- acceptance: host-PS socket run on one timeline -------------------

def test_host_ps_socket_run_single_perfetto_trace(tmp_path, tel):
    """One async host-PS training run (socket fidelity) -> one
    Perfetto-loadable trace with PS commit spans and per-worker round
    spans on DISTINCT thread tracks, plus commit-rate counter and
    staleness histogram in the same registry."""
    from distkeras_tpu.data import datasets
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import DOWNPOUR

    mlp = model_config("mlp", (8,), num_classes=4, hidden=(16,))
    data = datasets.synthetic_classification(1024, (8,), 4, seed=0)
    t = DOWNPOUR(mlp, fidelity="host", transport="socket",
                 num_workers=3, communication_window=2, batch_size=16,
                 num_epoch=1, learning_rate=0.01,
                 worker_optimizer="adam")
    t.train(data)

    path = tel.tracer.write_chrome_trace(tmp_path / "host_ps.json")
    trace = json.load(open(path))
    check_perfetto_valid(trace)

    commit_tids = {e["tid"] for e in trace["traceEvents"]
                   if e.get("ph") == "X" and e["name"] == "ps_commit"}
    round_spans = [e for e in trace["traceEvents"]
                   if e.get("ph") == "X"
                   and e["name"] == "worker_round"]
    round_tids = {e["tid"] for e in round_spans}
    # every worker thread has its own round track...
    assert {e["args"]["worker"] for e in round_spans} == {0, 1, 2}
    assert len(round_tids) == 3
    # ...and socket commits run on PS handler threads, not on them
    assert commit_tids and commit_tids.isdisjoint(round_tids)

    n_rounds = len(t.history["round_loss"])
    assert tel.metrics.counter("ps_commits_total").value == n_rounds
    assert tel.metrics.histogram("ps_commit_staleness").count == \
        n_rounds
    assert tel.metrics.counter("ps_wire_bytes_total",
                               direction="rx").value > 0
    assert tel.metrics.counter("ps_wire_bytes_total",
                               direction="tx").value > 0
    # the trainer's history stayed intact alongside (the view reads
    # the trainer's own registry, not the global one)
    assert len(t.history["staleness"][-1]) == n_rounds


# ---- acceptance: DecodeEngine metrics snapshot ------------------------

def _lm(max_len=32, vocab=37):
    from distkeras_tpu.models import ModelSpec, model_config

    spec = model_config("transformer_lm", (max_len,),
                        input_dtype="int32", vocab_size=vocab,
                        num_layers=1, d_model=32, num_heads=2,
                        max_len=max_len, dtype="float32")
    model = ModelSpec.from_config(spec).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((2, max_len), jnp.int32))
    return model, variables


def test_engine_mixed_run_metrics_snapshot_and_derived_keys(tel):
    """Mixed-length DecodeEngine run -> snapshot holds queue-depth and
    slot-occupancy gauges, a TTFT histogram, and per-bucket compile
    counters; results carry engine-owned ``ttft``/``latency`` derived
    from the unified clock (meta keys of the same name lose)."""
    from distkeras_tpu.serving import DecodeEngine

    model, variables = _lm()
    eng = DecodeEngine(model, variables, slots=2, buckets=[16, 32],
                       prefill_align=4, max_new_tokens=4)
    rng = np.random.default_rng(3)
    reqs = [{"prompt": rng.integers(0, 37, (t,)).astype(np.int32),
             "ttft": "meta-must-lose", "i": i}
            for i, t in enumerate([5, 9, 3, 14, 7])]
    results = list(eng.run(reqs))
    assert len(results) == 5
    for r in results:
        assert isinstance(r["ttft"], float)      # engine key wins
        assert r["i"] in range(5)                # other meta survives
        assert r["t_submit"] <= r["t_first"] <= r["t_finish"]
        assert r["ttft"] == pytest.approx(r["t_first"] - r["t_submit"])
        assert r["latency"] == pytest.approx(
            r["t_finish"] - r["t_submit"])
        assert 0 <= r["ttft"] <= r["latency"]

    snap = tel.metrics.snapshot()
    for env in (16, 32):
        assert f'serving_queue_depth{{bucket="{env}"}}' \
            in snap["gauges"]
        assert f'serving_slot_occupancy{{bucket="{env}"}}' \
            in snap["gauges"]
        # drained engine: both levels ended at zero
        assert snap["gauges"][
            f'serving_slot_occupancy{{bucket="{env}"}}'] == 0
        assert tel.metrics.counter("compiles_total", kind="step",
                                   bucket=env).value == 1
        assert tel.metrics.sum_counter("compiles_total",
                                       kind="prefill",
                                       bucket=env) >= 1
    ttft = snap["histograms"]["serving_ttft_seconds"]
    assert ttft["count"] == 5
    lat = snap["histograms"]["serving_latency_seconds"]
    assert lat["count"] == 5 and lat["sum"] >= ttft["sum"]
    assert tel.metrics.sum_counter("serving_tokens_total") == \
        sum(len(r["tokens"]) for r in results)
    # timeline side: prefill/decode_step spans + evict instants
    names = {e["name"] for e in tel.tracer.events()}
    assert {"prefill", "decode_step", "evict"} <= names


def test_engine_timing_fields_without_telemetry_enabled():
    """The unified clock + derived keys are engine contract, not a
    telemetry feature: with telemetry DISABLED the timing fields are
    still present, ordered, and on one clock."""
    telemetry.disable()
    from distkeras_tpu.serving import DecodeEngine

    model, variables = _lm()
    eng = DecodeEngine(model, variables, slots=2, prefill_align=4,
                       max_new_tokens=3)
    (r,) = list(eng.run([np.arange(5, dtype=np.int32)]))
    assert r["t_submit"] <= r["t_first"] <= r["t_finish"]
    assert r["ttft"] == pytest.approx(r["t_first"] - r["t_submit"])
    assert r["latency"] == pytest.approx(r["t_finish"] - r["t_submit"])

"""Pallas flash-attention kernels vs dense attention — forward
exactness, gradients through the hand-written backward kernels, block
shape validation, and the ``TransformerLM(flash_attn=True)`` spelling.

Runs on the Pallas interpreter off-TPU (``interpret`` auto-detection),
so numerics are exact f32 and the tolerances can be tight; on real TPU
the same code compiles to Mosaic (A/B'd in PERF.md §17).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import ModelSpec, model_config
from distkeras_tpu.models.transformer import dense_causal_attention
from distkeras_tpu.ops.attention import flash_attention

jax.config.update("jax_platforms", "cpu")


def _qkv(b=2, t=64, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32)
                 for k in ks)


def _dense_full(q, k, v, *, scale):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_k", [(16, 16), (16, 32),
                                             (32, 16), (64, 64)])
def test_forward_matches_dense(causal, block_q, block_k):
    q, k, v = _qkv()
    scale = q.shape[-1] ** -0.5
    got = flash_attention(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k)
    ref = dense_causal_attention if causal else _dense_full
    want = ref(q, k, v, scale=scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(t=32)
    scale = q.shape[-1] ** -0.5
    probe = jax.random.normal(jax.random.key(7), q.shape, jnp.float32)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) * probe)

    gf = jax.grad(lambda *a: loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16),
        *a), (0, 1, 2))(q, k, v)
    ref = dense_causal_attention if causal else _dense_full
    gr = jax.grad(lambda *a: loss(
        lambda q, k, v: ref(q, k, v, scale=scale), *a),
        (0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5, err_msg=name)


@pytest.mark.parametrize("t", [8, 48, 96])
def test_default_blocks_adapt_to_any_length(t):
    # default (None) blocks clamp to the largest divisor of T, so
    # short and awkward lengths (reviewer case: T not a power of two)
    # work without configuration
    q, k, v = _qkv(t=t)
    got = flash_attention(q, k, v)
    want = dense_causal_attention(q, k, v, scale=q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_explicit_indivisible_block_rejected():
    q, k, v = _qkv(t=48)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=32, block_k=32)


def test_transformer_flash_attn_spelling():
    """flash_attn=True trains: same loss trajectory shape as dense and
    close numerics at init (f32 interpret path)."""
    spec = model_config("transformer_lm", (16,), input_dtype="int32",
                        vocab_size=64, num_layers=1, d_model=32,
                        num_heads=2, max_len=16, dtype="float32",
                        flash_attn=True)
    model = ModelSpec.from_config(spec).build()
    tokens = jax.random.randint(jax.random.key(0), (2, 16), 0, 64)
    variables = model.init(jax.random.key(1), tokens)
    out = model.apply(variables, tokens)

    dense_spec = dict(spec)
    dense_spec["kwargs"] = {k: v for k, v in spec["kwargs"].items()
                            if k != "flash_attn"}
    dense_model = ModelSpec.from_config(dense_spec).build()
    want = dense_model.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_blockwise_and_flash_mutually_exclusive():
    spec = model_config("transformer_lm", (16,), input_dtype="int32",
                        vocab_size=64, num_layers=1, d_model=32,
                        num_heads=2, max_len=16, dtype="float32",
                        flash_attn=True, blockwise_attn=True)
    model = ModelSpec.from_config(spec).build()
    tokens = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="mutually exclusive"):
        model.init(jax.random.key(0), tokens)


def test_flash_lm_trains_through_async_ps():
    """Integration: the emulated async-PS family trains a
    flash-kernel TransformerLM (vmapped worker states over the Pallas
    custom VJP) — the kernel path composes with every trainer arm."""
    from distkeras_tpu.data import datasets
    from distkeras_tpu.trainers import ADAG

    data = datasets.lm_synth(256, seq_len=16, vocab_size=32, seed=0)
    spec = model_config("transformer_lm", (16,), input_dtype="int32",
                        vocab_size=32, num_layers=1, d_model=32,
                        num_heads=4, max_len=16, dtype="float32",
                        flash_attn=True)
    t = ADAG(spec, loss="sparse_categorical_crossentropy",
             num_workers=4, communication_window=2, batch_size=8,
             num_epoch=2, learning_rate=3e-3, worker_optimizer="adam",
             seed=0)
    t.train(data)
    h = t.history["epoch_loss"]
    assert np.isfinite(h).all()
    assert h[-1] < h[0], h


def test_flash_lm_trains_tensor_parallel():
    """Integration: flash_attn under a (workers, model) TP mesh — the
    Pallas call must compile and train under GSPMD sharding."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    from distkeras_tpu.data import datasets
    from distkeras_tpu.trainers import SyncTrainer

    data = datasets.lm_synth(64, seq_len=16, vocab_size=64, seed=0)
    spec = model_config("transformer_lm", (16,), input_dtype="int32",
                        vocab_size=64, num_layers=1, d_model=32,
                        num_heads=2, max_len=16, dtype="float32",
                        flash_attn=True)
    t = SyncTrainer(spec, loss="sparse_categorical_crossentropy",
                    worker_optimizer="adam", learning_rate=3e-3,
                    batch_size=16, num_epoch=2, num_workers=2,
                    model_parallel=2, seed=0)
    t.train(data)
    h = t.history["epoch_loss"]
    assert np.isfinite(h).all()
    assert h[-1] < h[0], h


def test_flash_with_seq_axis_rejected_loudly():
    """Device-local flash_attn must not be silently swallowed by the
    ring-attention path when seq_axis is set."""
    from distkeras_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=64, num_layers=1, d_model=32,
                          num_heads=2, max_len=16, dtype="float32",
                          flash_attn=True, seq_axis="seq")
    tokens = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="ring attention"):
        model.init(jax.random.key(0), tokens)


def test_unaligned_auto_block_raises_descriptive_error_when_compiled():
    """ADVICE r4: for lengths with no MXU-friendly divisor the auto
    block picker degrades toward unaligned blocks that compiled Mosaic
    rejects with an opaque tiling error — the compiled path must catch
    that up front with an actionable ValueError (the interpreter
    accepts any block, so only interpret=False checks)."""
    from distkeras_tpu.ops.attention import flash_attention

    q = jnp.zeros((1, 257, 2, 8), jnp.float32)  # 257 prime -> bq=257
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, q, q, interpret=False)
    # the interpreter still takes it (tests run anywhere)
    out = flash_attention(q, q, q, interpret=True)
    assert out.shape == q.shape

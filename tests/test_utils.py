import jax.numpy as jnp
import numpy as np

from distkeras_tpu import utils


def _tree():
    return {"a": jnp.arange(4.0), "b": {"w": jnp.ones((2, 2))}}


def test_tree_arithmetic():
    t = _tree()
    z = utils.tree_zeros_like(t)
    s = utils.tree_add(t, z)
    np.testing.assert_allclose(s["a"], t["a"])
    d = utils.tree_sub(t, t)
    assert float(utils.tree_l2_norm(d)) == 0.0
    scaled = utils.tree_scale(t, 2.0)
    np.testing.assert_allclose(scaled["b"]["w"], 2 * np.ones((2, 2)))
    lerped = utils.tree_lerp(z, t, 0.5)
    np.testing.assert_allclose(lerped["a"], 0.5 * np.arange(4.0))


def test_tree_size_and_dot():
    t = _tree()
    assert utils.tree_size(t) == 8
    assert float(utils.tree_dot(t, t)) == float(
        np.sum(np.arange(4.0) ** 2) + 4.0)


def test_params_serialization_roundtrip():
    t = _tree()
    data = utils.serialize_params(t)
    assert isinstance(data, bytes)
    restored = utils.deserialize_params(utils.tree_zeros_like(t), data)
    np.testing.assert_allclose(restored["a"], t["a"])
    np.testing.assert_allclose(restored["b"]["w"], t["b"]["w"])


def test_model_config_roundtrip():
    cfg = {"name": "mlp", "hidden": [64, 32], "classes": 10}
    assert utils.deserialize_model_config(
        utils.serialize_model_config(cfg)) == cfg


def test_to_dense_vector():
    v = utils.to_dense_vector(2, 4)
    np.testing.assert_allclose(v, [0, 0, 1, 0])
    m = utils.to_dense_vector([0, 3], 4)
    assert m.shape == (2, 4)
    assert m[1, 3] == 1.0


def test_shuffle_keeps_alignment():
    cols = {"x": np.arange(10), "y": np.arange(10) * 2}
    out = utils.shuffle(cols, seed=1)
    np.testing.assert_allclose(out["y"], out["x"] * 2)
    assert not np.array_equal(out["x"], cols["x"])  # actually permuted


def test_batch_iterator_and_padding():
    cols = {"x": np.arange(10), "y": np.arange(10)}
    batches = list(utils.batch_iterator(cols, 4))
    assert len(batches) == 2 and batches[1]["x"][0] == 4
    padded = utils.pad_to_multiple(np.ones((10, 3)), 8)
    assert padded.shape == (16, 3)


def test_tree_ops_numpy_fast_path_semantics():
    """The numpy fast path (host PS apply, PERF.md §12) must preserve
    the jnp path's semantics: float leaves stay their dtype, int and
    python-scalar leaves keep the promoting jnp behavior (a leaf-dtype
    scalar would truncate int32(0.5) -> 0)."""
    import numpy as np

    f32 = {"a": np.full((4,), 2.0, np.float32)}
    out = utils.tree_add(f32, f32)
    assert isinstance(out["a"], np.ndarray)
    assert out["a"].dtype == np.float32
    out = utils.tree_lerp(f32, {"a": np.full((4,), 4.0, np.float32)},
                          0.5)
    assert out["a"].dtype == np.float32
    np.testing.assert_allclose(out["a"], 3.0)
    # int leaves: promote like jnp, never truncate the coefficient
    ints = {"a": np.array([10, 10])}
    out = utils.tree_lerp(ints, {"a": np.array([20, 20])}, 0.5)
    np.testing.assert_allclose(np.asarray(out["a"]), 15.0)
    out = utils.tree_axpy(0.5, ints, {"a": np.array([1, 1])})
    np.testing.assert_allclose(np.asarray(out["a"]), 6.0)
    # python scalar leaves still work (jnp path)
    out = utils.tree_lerp({"a": 1.0}, {"a": 3.0}, 0.5)
    np.testing.assert_allclose(float(out["a"]), 2.0)

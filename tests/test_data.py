"""Data layer: Dataset verbs, transformer semantics (the reference's
transformers.py surface), synthetic generators."""

import numpy as np
import pytest

from distkeras_tpu.data import (
    Dataset,
    DenseTransformer,
    HashBucketTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    Pipeline,
    ReshapeTransformer,
    StandardScaleTransformer,
    datasets,
)


def _ds():
    return Dataset({"x": np.arange(12, dtype=np.float32),
                    "y": np.arange(12) % 3})


class TestDataset:
    def test_mismatched_lengths_raise(self):
        with pytest.raises(ValueError):
            Dataset({"a": np.arange(3), "b": np.arange(4)})

    def test_verbs(self):
        ds = _ds()
        assert len(ds) == 12
        assert ds.select(["x"]).column_names == ["x"]
        ds2 = ds.with_column("z", ds["x"] * 2)
        np.testing.assert_allclose(ds2["z"], ds["x"] * 2)
        assert "z" not in ds  # immutability
        assert len(ds.filter(ds["y"] == 0)) == 4
        assert ds.rename({"x": "w"}).column_names[0] == "w"
        assert len(ds.take(5)) == 5
        assert len(ds.concat(ds)) == 24
        assert ds.drop("y").column_names == ["x"]

    def test_shuffle_alignment(self):
        ds = Dataset({"x": np.arange(100),
                      "y": np.arange(100) * 3}).shuffle(seed=7)
        np.testing.assert_array_equal(ds["y"], ds["x"] * 3)
        assert not np.array_equal(ds["x"], np.arange(100))

    def test_shard_and_repartition(self):
        ds = _ds()
        shards = ds.repartition(3)
        assert [len(s) for s in shards] == [4, 4, 4]
        back = np.concatenate([s["x"] for s in shards])
        np.testing.assert_array_equal(back, ds["x"])
        with pytest.raises(ValueError):
            ds.shard(3, 5)
        with pytest.raises(ValueError):
            Dataset({"x": np.arange(2)}).shard(3, 0)

    def test_batches(self):
        ds = _ds()
        bs = list(ds.batches(5))
        assert len(bs) == 2 and len(bs[0]["x"]) == 5
        assert ds.num_batches(5) == 2
        assert ds.num_batches(5, drop_remainder=False) == 3


class TestTransformers:
    def test_label_index(self):
        ds = Dataset({"label": np.array(["b", "a", "b", "c"])})
        t = LabelIndexTransformer("label").fit(ds)
        out = t.transform(ds)
        np.testing.assert_array_equal(out["label_index"], [1, 0, 1, 2])
        unseen = Dataset({"label": np.array(["z"])})
        with pytest.raises(ValueError, match="unseen"):
            t.transform(unseen)

    def test_one_hot(self):
        ds = Dataset({"y": np.array([0, 2, 1])})
        out = OneHotTransformer("y", 3).transform(ds)
        np.testing.assert_allclose(out["y_onehot"],
                                   np.eye(3)[[0, 2, 1]])
        with pytest.raises(ValueError):
            OneHotTransformer("y", 2).transform(ds)

    def test_min_max(self):
        ds = Dataset({"f": np.array([[0., 10.], [5., 20.]])})
        out = MinMaxTransformer("f").fit_transform(ds)
        np.testing.assert_allclose(out["f"], [[0, 0], [1, 1]])
        # constant column doesn't divide by zero
        const = Dataset({"f": np.ones((4, 2))})
        np.testing.assert_allclose(
            MinMaxTransformer("f").fit_transform(const)["f"], 0.0)

    def test_standard_scale(self):
        ds = Dataset({"f": np.random.default_rng(0).normal(
            5.0, 3.0, size=(1000, 4))})
        out = StandardScaleTransformer("f").fit_transform(ds)
        assert abs(out["f"].mean()) < 0.01
        assert abs(out["f"].std() - 1.0) < 0.01

    def test_reshape(self):
        ds = Dataset({"f": np.arange(24, dtype=np.float32).reshape(2, 12)})
        out = ReshapeTransformer("f", (3, 4)).transform(ds)
        assert out["f"].shape == (2, 3, 4)

    def test_dense(self):
        ds = Dataset({"idx": np.array([[0, 3], [1, -1]]),
                      "val": np.array([[1., 2.], [5., 9.]])})
        out = DenseTransformer("idx", "val", dim=4).transform(ds)
        np.testing.assert_allclose(out["features"],
                                   [[1, 0, 0, 2], [0, 5, 0, 0]])

    def test_hash_bucket_deterministic(self):
        ds = Dataset({"c": np.array(["a", "b", "a"])})
        out = HashBucketTransformer("c", 16).transform(ds)
        assert out["c_bucket"][0] == out["c_bucket"][2]
        out2 = HashBucketTransformer("c", 16).transform(ds)
        np.testing.assert_array_equal(out["c_bucket"], out2["c_bucket"])
        assert out["c_bucket"].max() < 16

    def test_pipeline(self):
        ds = Dataset({"label": np.array(["x", "y", "x", "y"]),
                      "f": np.array([[1.], [2.], [3.], [4.]])})
        pipe = Pipeline([
            LabelIndexTransformer("label"),
            MinMaxTransformer("f"),
            OneHotTransformer("label_index", 2),
        ])
        out = pipe.fit(ds).transform(ds)
        assert out["label_index_onehot"].shape == (4, 2)
        np.testing.assert_allclose(out["f"].ravel(),
                                   [0, 1 / 3, 2 / 3, 1], atol=1e-6)

    def test_unfitted_raises(self):
        ds = Dataset({"f": np.ones((2, 2))})
        with pytest.raises(RuntimeError):
            MinMaxTransformer("f").transform(ds)


class TestSyntheticDatasets:
    def test_shapes(self):
        assert datasets.mnist_synth(64)["features"].shape == (64, 28, 28, 1)
        assert datasets.cifar10_synth(32)["features"].shape == (32, 32, 32, 3)
        imdb = datasets.imdb_synth(16, seq_len=32)
        assert imdb["features"].shape == (16, 32)
        criteo = datasets.criteo_synth(32, num_dense=5, num_categorical=3)
        assert criteo["dense"].shape == (32, 5)
        assert "c2" in criteo
        lm = datasets.lm_synth(8, seq_len=16, vocab_size=64)
        assert lm["features"].shape == lm["label"].shape == (8, 16)
        # next-token structure: label is features shifted by one
        np.testing.assert_array_equal(lm["features"][:, 1:],
                                      lm["label"][:, :-1])

    def test_labels_learnable_and_balanced(self):
        ds = datasets.synthetic_classification(2000, (8,), 4, seed=0)
        counts = np.bincount(ds["label"], minlength=4)
        assert counts.min() > 100  # no collapsed class
        # deterministic given seed
        ds2 = datasets.synthetic_classification(2000, (8,), 4, seed=0)
        np.testing.assert_array_equal(ds["label"], ds2["label"])


def test_hash_bucket_vectorized_matches_scalar():
    values = np.array(["", "a", "cat_123", "日本語", "x" * 40])
    t = HashBucketTransformer("c", 1 << 20)
    vec = t._fnv1a_vectorized(values)
    for v, h in zip(values, vec):
        assert int(h) == t._fnv1a(str(v).encode("utf-8")), v


def test_dataset_csv_roundtrip_and_typing(tmp_path):
    """CSV ingestion (the reference's Spark-reader surface): numeric
    columns auto-type (int64 / f32), strings stay strings."""
    from distkeras_tpu.data.dataset import Dataset

    p = tmp_path / "t.csv"
    p.write_text("id,score,cat\n1,0.5,a\n2,1.5,b\n3,-2.0,a\n")
    ds = Dataset.from_csv(p)
    assert ds.column_names == ["id", "score", "cat"]
    assert ds["id"].dtype == np.int64
    assert ds["score"].dtype == np.float32
    assert ds["cat"].dtype.kind in ("U", "S")
    np.testing.assert_allclose(ds["score"], [0.5, 1.5, -2.0])

    # headerless TSV with explicit names
    q = tmp_path / "t.tsv"
    q.write_text("1\tx\n2\ty\n")
    ds2 = Dataset.from_csv(q, delimiter="\t", header=False,
                           names=["n", "s"])
    assert len(ds2) == 2 and list(ds2["s"]) == ["x", "y"]

    # npz round trip (the --data-npz example format)
    out = ds.drop("cat").to_npz(tmp_path / "t.npz")
    back = Dataset.from_npz(out)
    np.testing.assert_array_equal(back["id"], ds["id"])


def test_dataset_csv_errors(tmp_path):
    from distkeras_tpu.data.dataset import Dataset

    bad = tmp_path / "bad.csv"
    bad.write_text("a,b\n1,2\n3\n")
    with pytest.raises(ValueError, match="fields"):
        Dataset.from_csv(bad)
    with pytest.raises(ValueError, match="names"):
        Dataset.from_csv(bad, header=False)
    empty = tmp_path / "empty.csv"
    empty.write_text("")
    with pytest.raises(ValueError, match="empty"):
        Dataset.from_csv(empty)


def test_csv_to_training_pipeline(tmp_path):
    """CSV -> ETL -> trainer end-to-end (the reference's notebook
    flow: read file, transform, train)."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.data.transformers import (AssembleTransformer,
                                                 LabelIndexTransformer)
    from distkeras_tpu.models import model_config
    from distkeras_tpu.trainers import SingleTrainer

    rng = np.random.default_rng(0)
    lines = ["f0,f1,f2,f3,label"]
    for i in range(256):
        cls = "pos" if rng.normal() > 0 else "neg"
        feats = rng.normal(size=4) + (1.0 if cls == "pos" else -1.0)
        lines.append(",".join(f"{v:.4f}" for v in feats) + "," + cls)
    p = tmp_path / "train.csv"
    p.write_text("\n".join(lines) + "\n")

    ds = Dataset.from_csv(p)
    ds = LabelIndexTransformer("label").fit_transform(ds)
    ds = AssembleTransformer(
        ["f0", "f1", "f2", "f3"], output_col="features")(ds)
    ds = ds.drop("label").rename({"label_index": "label"})
    t = SingleTrainer(model_config("mlp", (4,), num_classes=2,
                                   hidden=(8,)),
                      worker_optimizer="adam", learning_rate=1e-2,
                      batch_size=32, num_epoch=3)
    t.train(ds)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0] * 0.8, h


def test_csv_edge_cases(tmp_path):
    from distkeras_tpu.data.dataset import Dataset

    # duplicate header names rejected (would silently drop a column)
    dup = tmp_path / "dup.csv"
    dup.write_text("a,a\n1,2\n")
    with pytest.raises(ValueError, match="duplicate"):
        Dataset.from_csv(dup)
    # int64 overflow falls through to float, not a crash
    big = tmp_path / "big.csv"
    big.write_text("id\n12345678901234567890123\n1\n")
    ds = Dataset.from_csv(big)
    assert ds["id"].dtype == np.float32
    # to_npz appends .npz and returns the real path
    out = Dataset({"x": np.ones(3)}).to_npz(tmp_path / "plain")
    assert out.endswith("plain.npz")
    assert len(Dataset.from_npz(out)) == 3
    # reserved column name
    with pytest.raises(ValueError, match="file"):
        Dataset({"file": np.ones(2)}).to_npz(tmp_path / "f")


def test_train_test_split():
    ds = Dataset({"x": np.arange(100), "y": np.arange(100) % 3})
    train, test = ds.train_test_split(0.25, seed=1)
    assert len(train) == 75 and len(test) == 25
    # disjoint, exhaustive, rows stay aligned across columns
    assert sorted(np.concatenate([train["x"], test["x"]])) == list(
        range(100))
    np.testing.assert_array_equal(train["y"], train["x"] % 3)
    # deterministic per seed
    t2, _ = ds.train_test_split(0.25, seed=1)
    np.testing.assert_array_equal(train["x"], t2["x"])
    with pytest.raises(ValueError, match="test_fraction"):
        ds.train_test_split(1.5)
    with pytest.raises(ValueError, match="empty part"):
        Dataset({"x": np.arange(2)}).train_test_split(0.1)

"""Host-side concurrent PS (design 5a) — transport framing, serial
equivalence against the emulator's scan path, convergence of the
threaded faithful arm, the socket protocol end to end, and the
fault-tolerance layer: resilient client retry/backoff/dedupe, PS
snapshot + warm restart, and the kill-and-restart-mid-training
integration (docs/API.md "Fault tolerance")."""

import socket
import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.data import datasets
from distkeras_tpu.evaluators import evaluate_model
from distkeras_tpu.models import model_config
from distkeras_tpu.parallel import transport
from distkeras_tpu.parallel.host_ps import (
    HostParameterServer,
    PSClient,
    PSRetryExhausted,
    PSServer,
    ResilientPSClient,
)
from distkeras_tpu.parallel.update_rules import (
    AdagRule,
    DynSGDRule,
    ElasticRule,
    apply_commit_round,
)
from distkeras_tpu.trainers import ADAG, AEASGD, DOWNPOUR
from distkeras_tpu.utils import tree_sub

MLP = model_config("mlp", (8,), num_classes=4, hidden=(16,))
DATA = datasets.synthetic_classification(2048, (8,), 4, seed=0)


def _params(seed=0, shapes=((3, 4), (4,))):
    rng = np.random.default_rng(seed)
    return {f"w{i}": rng.normal(size=s).astype(np.float32)
            for i, s in enumerate(shapes)}


def test_transport_framing_roundtrip():
    a, b = socket.socketpair()
    try:
        transport.send_msg(a, b"c", b"x" * 100_000)
        msg = transport.recv_msg(b)
        assert msg[:1] == b"c" and len(msg) == 100_001
        transport.send_msg(b, b"")
        assert transport.recv_msg(a) == b""
    finally:
        a.close()
        b.close()


@pytest.mark.parametrize("rule", [AdagRule(), DynSGDRule(),
                                  ElasticRule(alpha=0.3)])
def test_host_ps_serial_matches_scan_round(rule):
    """The emulator's round scenario replayed through the threaded
    server — every worker pulls at round start, then commits land in
    order (so commit i has staleness i): the center and staleness
    sequence must match the scan path exactly (same UpdateRule code on
    both sides, so any divergence would be a transport/ordering bug)."""
    center = _params(0)
    payloads = [_params(i + 1) for i in range(4)]

    ps = HostParameterServer(rule, center)
    for w in range(4):
        ps.pull(w)
    for w, p in enumerate(payloads):
        ps.commit(w, p, p)

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *payloads)
    state, _, _ = apply_commit_round(
        rule, rule.init_state(center), stacked)
    for k in center:
        np.testing.assert_allclose(ps.center[k],
                                   np.asarray(state.center[k]),
                                   rtol=1e-6, atol=1e-6)
    # the i-th commit of the round observed i intervening commits
    assert ps.staleness_log == [0, 1, 2, 3]


def test_host_ps_concurrent_staleness_and_consistency():
    """N racing threads: commits all land (clock == total), staleness is
    emergent but bounded, center stays finite."""
    rule = AdagRule()
    center = _params(0)
    ps = HostParameterServer(rule, center)
    n_threads, n_commits = 4, 8

    def run(w):
        ps.pull(w)
        for i in range(n_commits):
            ps.commit(w, _params(w * 100 + i),)

    threads = [threading.Thread(target=run, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ps.num_commits == n_threads * n_commits
    assert len(ps.staleness_log) == n_threads * n_commits
    assert max(ps.staleness_log) <= n_threads * n_commits
    assert all(np.isfinite(v).all() for v in ps.center.values())


def test_adag_host_fidelity_converges_and_matches_emulator():
    """The faithful host arm must reach the emulated arm's quality on
    the same budget — the convergence-equivalence evidence SURVEY.md §7
    hard part #1 calls for."""
    kwargs = dict(num_workers=4, communication_window=2, batch_size=16,
                  num_epoch=3, learning_rate=5e-3,
                  worker_optimizer="adam")
    host = ADAG(MLP, fidelity="host", **kwargs)
    host.train(DATA)
    emu = ADAG(MLP, fidelity="faithful", **kwargs)
    emu.train(DATA)

    acc_host = evaluate_model(host.model, host.trained_variables,
                              DATA)["accuracy"]
    acc_emu = evaluate_model(emu.model, emu.trained_variables,
                             DATA)["accuracy"]
    assert acc_host > 0.7, (acc_host, host.history["epoch_loss"])
    assert abs(acc_host - acc_emu) < 0.15, (acc_host, acc_emu)
    # emergent staleness was recorded
    stal = host.history["staleness"][-1]
    assert len(stal) == len(host.history["round_loss"])


def test_aeasgd_host_fidelity_converges():
    """Elastic family through the host arm (exercises the
    pull-uses-local path and params payload kind)."""
    t = AEASGD(MLP, fidelity="host", num_workers=4,
               communication_window=2, batch_size=16, num_epoch=3,
               rho=2.5, learning_rate=0.02)
    t.train(DATA)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0], h


def test_downpour_socket_transport_end_to_end():
    """Full TCP path: workers talk to the PS over the L1 framing."""
    t = DOWNPOUR(MLP, fidelity="host", transport="socket",
                 num_workers=3, communication_window=2, batch_size=16,
                 num_epoch=2, learning_rate=0.01,
                 worker_optimizer="adam")
    t.train(DATA)
    h = t.history["epoch_loss"]
    assert h[-1] < h[0] * 1.05, h
    assert t.parameter_server_state.num_commits == \
        sum(1 for _ in t.history["round_loss"])


def test_ps_server_client_protocol():
    """Socket protocol unit: pull returns center; commit applies and
    returns the pulled params."""
    rule = ElasticRule(alpha=0.5)
    center = _params(3)
    ps = HostParameterServer(rule, center)
    with PSServer(ps, center) as server:
        client = PSClient(*server.address, worker_id=7,
                          template=center)
        got = client.pull()
        for k in center:
            np.testing.assert_allclose(got[k], center[k])
        local = _params(4)
        pulled = client.commit(local, local)
        want = jax.tree_util.tree_map(
            lambda l, c: l + 0.5 * (c - l), local, center)
        for k in center:
            np.testing.assert_allclose(pulled[k], np.asarray(want[k]),
                                       rtol=1e-6)
        client.close()
    assert ps.num_commits == 1


class _Bomb(Exception):
    pass


def test_worker_round_retry_is_exactly_once():
    """A transiently failing round is retried after a fresh pull; every
    commit lands exactly once (the correct form of the Spark-retry
    semantic hazard, SURVEY.md §5)."""
    boom = {"armed": True}

    def injector(w, epoch, r):
        if w == 1 and epoch == 0 and r == 1 and boom.pop("armed", False):
            raise _Bomb("transient")

    t = DOWNPOUR(MLP, fidelity="host", num_workers=3,
                 communication_window=2, batch_size=16, num_epoch=2,
                 learning_rate=0.01, worker_optimizer="adam",
                 worker_retries=2, fault_injector=injector)
    t.train(DATA)
    assert t.history["worker_round_retries"] == [[(1, 0, 1)]]
    assert "worker_failures" not in t.history
    # every recorded round committed exactly once
    assert t.parameter_server_state.num_commits == \
        len(t.history["round_loss"])
    h = t.history["epoch_loss"]
    assert h[-1] < h[0] * 1.05, h


def test_dead_worker_tolerated_when_elastic():
    """A worker that exhausts retries dies; training continues on the
    survivors when max_worker_failures allows it."""
    def injector(w, epoch, r):
        if w == 2:
            raise _Bomb("hard failure")

    t = ADAG(MLP, fidelity="host", num_workers=4,
             communication_window=2, batch_size=16, num_epoch=2,
             learning_rate=5e-3, worker_optimizer="adam",
             max_worker_failures=1, fault_injector=injector)
    t.train(DATA)
    [(dead, err)] = t.history["worker_failures"][-1]
    assert dead == 2 and "_Bomb" in err
    assert t.parameter_server_state.num_commits == \
        len(t.history["round_loss"]) > 0
    h = t.history["epoch_loss"]
    assert h[-1] < h[0] * 1.05, h


def test_dead_worker_fatal_by_default():
    def injector(w, epoch, r):
        if w == 0:
            raise _Bomb("hard failure")

    t = DOWNPOUR(MLP, fidelity="host", num_workers=2,
                 communication_window=2, batch_size=16, num_epoch=1,
                 learning_rate=0.01, fault_injector=injector)
    with pytest.raises(_Bomb):
        t.train(DATA)


def test_all_workers_dead_raises_even_when_elastic():
    def injector(w, epoch, r):
        raise _Bomb("everyone")

    t = DOWNPOUR(MLP, fidelity="host", num_workers=2,
                 communication_window=2, batch_size=16, num_epoch=1,
                 learning_rate=0.01, max_worker_failures=5,
                 fault_injector=injector)
    with pytest.raises(_Bomb):
        t.train(DATA)


def test_idle_worker_detection():
    """The PS detects silent workers via the contact heartbeat."""
    ps = HostParameterServer(AdagRule(), _params(0))
    ps.pull(0)
    ps.pull(1)
    delta = jax.tree_util.tree_map(np.zeros_like, _params(0))
    ps.commit(0, delta)
    ps._last_seen[1] -= 10.0  # backdate: worker 1 went silent
    assert ps.idle_workers(timeout=5.0) == [1]
    assert ps.idle_workers(timeout=3600.0) == []


def test_commit_seq_dedupes_lost_ack_retry():
    """A retried commit with the same seq (ack lost) is not re-applied:
    the server returns the cached reply — at-most-once application."""
    rule = AdagRule()
    center = _params(7)
    ps = HostParameterServer(rule, center)
    ps.pull(0)
    delta = jax.tree_util.tree_map(np.ones_like, center)
    first = ps.commit(0, delta, seq=0)
    center_after = jax.tree_util.tree_map(np.copy, ps.center)
    again = ps.commit(0, delta, seq=0)  # retry of the same commit
    assert ps.num_commits == 1
    for a, b in zip(jax.tree_util.tree_leaves(first),
                    jax.tree_util.tree_leaves(again)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree_util.tree_leaves(center_after),
                    jax.tree_util.tree_leaves(ps.center)):
        np.testing.assert_array_equal(a, b)
    # a new seq applies normally
    ps.commit(0, delta, seq=1)
    assert ps.num_commits == 2
    # a straggler OLDER than the last applied seq is also a duplicate
    ps.commit(0, delta, seq=0)
    assert ps.num_commits == 2
    # seq=None never dedupes (the in-process arm)
    ps.commit(0, delta)
    ps.commit(0, delta)
    assert ps.num_commits == 4


def test_startup_connect_failure_consumes_retry_budget():
    """A transient failure at first contact retries instead of killing
    the worker (recorded as epoch/round -1)."""
    calls = {"n": 0}
    orig_pull = HostParameterServer.pull

    def flaky_pull(self, worker_id):
        if worker_id == 1 and calls["n"] == 0:
            calls["n"] += 1
            raise ConnectionError("PS warming up")
        return orig_pull(self, worker_id)

    HostParameterServer.pull = flaky_pull
    try:
        t = DOWNPOUR(MLP, fidelity="host", num_workers=2,
                     communication_window=2, batch_size=16, num_epoch=1,
                     learning_rate=0.01, worker_retries=1)
        t.train(DATA)
    finally:
        HostParameterServer.pull = orig_pull
    assert (1, -1, -1) in t.history["worker_round_retries"][-1]
    assert "worker_failures" not in t.history


def test_retire_removes_liveness_and_reply_cache():
    ps = HostParameterServer(AdagRule(), _params(0))
    ps.pull(0)
    delta = jax.tree_util.tree_map(np.zeros_like, _params(0))
    ps.commit(0, delta, seq=0)
    ps._last_seen[0] -= 100.0
    assert ps.idle_workers(timeout=50.0) == [0]
    ps.retire(0)
    assert ps.idle_workers(timeout=0.0) == []
    assert ps._last_reply == {}
    # retry kwargs are host-arm only
    with pytest.raises(ValueError, match="fidelity='host'"):
        DOWNPOUR(MLP, worker_retries=2)


def test_watchdog_detects_stalled_worker():
    """worker_timeout arms the liveness watchdog: a worker stalled
    mid-round shows up in history['detected_idle_workers']."""
    import time as _time

    stalled = {"armed": True}

    def injector(w, epoch, r):
        if w == 1 and epoch == 0 and r == 1 and stalled.pop("armed",
                                                            False):
            _time.sleep(2.5)

    t = DOWNPOUR(MLP, fidelity="host", num_workers=3,
                 communication_window=2, batch_size=16, num_epoch=1,
                 learning_rate=0.01, worker_timeout=0.5,
                 fault_injector=injector)
    t.train(DATA)
    detected = t.history.get("detected_idle_workers", [[]])[-1]
    assert any(1 in idle for idle in detected), detected
    # the stall was transient: training still completed every round
    assert t.parameter_server_state.num_commits == \
        len(t.history["round_loss"])


def test_worker_timeout_host_only_and_positive():
    with pytest.raises(ValueError, match="fidelity='host'"):
        DOWNPOUR(MLP, worker_timeout=5.0)
    with pytest.raises(ValueError, match="positive"):
        DOWNPOUR(MLP, fidelity="host", worker_timeout=0.0)


# ---- fault-tolerance layer (ISSUE 3) ---------------------------------


def test_connect_clears_timeout_and_survives_slow_replies():
    """Regression (ISSUE 3 satellite): ``transport.connect`` used to
    leave the connect timeout armed on the socket, so any reply slower
    than it raised ``socket.timeout`` MID-frame and desynced the
    length-prefix stream.  Now the timeout bounds establishment only —
    a reply slower than the connect timeout still arrives whole."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen()

    def slow_echo():
        conn, _ = srv.accept()
        with conn:
            time.sleep(0.6)  # slower than the connect timeout below
            transport.send_msg(conn, b"x" * 100_000)

    t = threading.Thread(target=slow_echo, daemon=True)
    t.start()
    try:
        sock = transport.connect(*srv.getsockname(), timeout=0.25)
        assert sock.gettimeout() is None  # cleared after establishment
        assert transport.recv_msg(sock) == b"x" * 100_000
        sock.close()
    finally:
        t.join()
        srv.close()


def test_oversized_length_header_rejected_before_allocation(monkeypatch):
    """A garbage/hostile length header is rejected by the sanity bound
    BEFORE ``_recvall`` allocates; the bound is env-configurable
    (``DKT_MAX_MSG_BYTES``, default 1 GB — down from the old 1 TB)."""
    monkeypatch.setattr(transport, "MAX_MSG_BYTES", 1 << 20)
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">Q", 1 << 50))  # desynced-stream header
        with pytest.raises(ValueError, match="sanity bound"):
            transport.recv_msg(b)
        # at the bound is fine; one past it is not
        assert transport.MAX_MSG_BYTES == 1 << 20
    finally:
        a.close()
        b.close()
    monkeypatch.setenv("DKT_MAX_MSG_BYTES", "12345")
    assert transport._max_msg_bytes() == 12345
    monkeypatch.delenv("DKT_MAX_MSG_BYTES")
    assert transport._max_msg_bytes() == 1 << 30


class _AlwaysFail:
    def pull(self):
        raise ConnectionError("dead PS")

    def close(self):
        pass


def test_resilient_client_retry_budget_and_deterministic_backoff():
    """The extracted retry core: transient failures are retried with
    rebuilt connections; the budget exhausts into ``PSRetryExhausted``
    (cause preserved); jittered backoff is deterministic per seed;
    KeyboardInterrupt is never retried."""
    calls = {"n": 0, "built": 0}

    class Flaky:
        def pull(self):
            if calls["n"] < 2:
                calls["n"] += 1
                raise ConnectionError("transient")
            return {"ok": 1}

        def close(self):
            pass

    def factory():
        calls["built"] += 1
        return Flaky()

    c = ResilientPSClient(factory, retries=3, backoff_base=1e-4,
                          seed=0)
    assert c.pull() == {"ok": 1}
    assert c.retry_count == 2
    assert calls["built"] == 3  # the connection is rebuilt per failure

    c2 = ResilientPSClient(lambda: _AlwaysFail(), retries=2,
                           backoff_base=1e-4)
    with pytest.raises(PSRetryExhausted) as ei:
        c2.pull()
    assert isinstance(ei.value.__cause__, ConnectionError)
    assert c2.retry_count == 3  # initial + 2 retries

    a = ResilientPSClient(lambda: None, retries=0, seed=5)
    b = ResilientPSClient(lambda: None, retries=0, seed=5)
    da = [a._backoff_delay(i) for i in range(1, 6)]
    assert da == [b._backoff_delay(i) for i in range(1, 6)]
    assert max(da) <= a.backoff_max

    class Interrupted:
        def pull(self):
            raise KeyboardInterrupt

        def close(self):
            pass

    with pytest.raises(KeyboardInterrupt):
        ResilientPSClient(lambda: Interrupted(), retries=5,
                          backoff_base=1e-4).pull()


def test_resilient_client_lost_ack_commit_is_exactly_once():
    """The lost-ack shape end to end at the client: a commit that was
    APPLIED but whose reply died is internally retried with the same
    seq and deduped server-side — applied exactly once."""
    ps = HostParameterServer(AdagRule(), _params(0))
    armed = {"on": True}

    class LostAck:
        def pull(self):
            return ps.pull(0)

        def commit(self, payload, local=None, seq=None):
            out = ps.commit(0, payload, local, seq=seq)
            if armed.pop("on", False):
                raise ConnectionError("ack lost")  # AFTER the apply
            return out

        def close(self):
            pass

    c = ResilientPSClient(lambda: LostAck(), retries=2,
                          backoff_base=1e-4)
    c.pull()
    delta = jax.tree_util.tree_map(np.ones_like, _params(0))
    c.commit(delta)
    assert ps.num_commits == 1  # retried, deduped, applied once
    c.commit(delta)
    assert ps.num_commits == 2  # the next seq applies normally


def test_ps_snapshot_roundtrip_preserves_dedupe(tmp_path):
    """Snapshot → restore keeps center, clocks, staleness AND the
    commit-seq dedupe table: a lost-ack retry against the RESTORED
    server still gets the cached reply instead of a second apply."""
    ps = HostParameterServer(AdagRule(), _params(0))
    ps.pull(0)
    d1 = jax.tree_util.tree_map(np.ones_like, _params(0))
    ps.commit(0, d1, seq=0)
    reply = ps.commit(0, d1, seq=1)
    path = ps.save_snapshot(tmp_path / "ps.snap")

    ps2 = HostParameterServer.from_snapshot(AdagRule(), path)
    assert ps2.num_commits == 2 and ps2._clock == ps._clock
    assert ps2.staleness_log == ps.staleness_log
    for k in ps.center:
        np.testing.assert_array_equal(ps2.center[k], ps.center[k])
    center_before = jax.tree_util.tree_map(np.copy, ps2.center)
    again = ps2.commit(0, d1, seq=1)  # the retry a crash orphaned
    assert ps2.num_commits == 2
    for a, b in zip(jax.tree_util.tree_leaves(reply),
                    jax.tree_util.tree_leaves(again)):
        np.testing.assert_array_equal(a, b)
    for k in ps2.center:
        np.testing.assert_array_equal(ps2.center[k], center_before[k])
    ps2.commit(0, d1, seq=2)
    assert ps2.num_commits == 3


def test_periodic_snapshots_written_under_commits(tmp_path):
    path = tmp_path / "ps.snap"
    ps = HostParameterServer(AdagRule(), _params(0),
                             snapshot_path=path, snapshot_every=2)
    ps.pull(0)
    delta = jax.tree_util.tree_map(np.ones_like, _params(0))
    for s in range(5):
        ps.commit(0, delta, seq=s)
    assert ps.num_snapshots == 2 and path.exists()
    restored = HostParameterServer.from_snapshot(AdagRule(), path)
    assert restored.num_commits == 4  # the last multiple of 2
    with pytest.raises(ValueError, match="snapshot_path"):
        HostParameterServer(AdagRule(), _params(0), snapshot_every=2)


def test_fault_tolerance_kwargs_validation(tmp_path):
    with pytest.raises(ValueError, match="transport='socket'"):
        DOWNPOUR(MLP, fidelity="host", ps_address=("127.0.0.1", 1))
    with pytest.raises(ValueError, match="fidelity='host'"):
        DOWNPOUR(MLP, ps_snapshot_path=str(tmp_path / "s"),
                 ps_snapshot_every=1)
    with pytest.raises(ValueError, match="ps_snapshot_path"):
        DOWNPOUR(MLP, fidelity="host", ps_snapshot_every=2)
    with pytest.raises(ValueError, match="externally created"):
        DOWNPOUR(MLP, fidelity="host", transport="socket",
                 ps_address=("127.0.0.1", 1),
                 ps_snapshot_path=str(tmp_path / "s"),
                 ps_snapshot_every=1)


def test_trainer_periodic_ps_snapshot_and_history_key(tmp_path):
    """``ps_snapshot_every`` on the trainer writes warm-restart
    snapshots through training and records ``history['ps_snapshots']``;
    the file warm-restarts a server whose bookkeeping matches."""
    path = tmp_path / "ps.snap"
    t = DOWNPOUR(MLP, fidelity="host", num_workers=2,
                 communication_window=2, batch_size=16, num_epoch=1,
                 learning_rate=0.01, worker_optimizer="adam",
                 ps_snapshot_path=str(path), ps_snapshot_every=4)
    t.train(DATA)
    ps = t.parameter_server_state
    assert t.history["ps_snapshots"][-1] == ps.num_snapshots > 0
    restored = HostParameterServer.from_snapshot(type(ps.rule)(), path)
    assert restored.num_commits == (ps.num_commits // 4) * 4
    if restored.num_commits == ps.num_commits:
        for a, b in zip(jax.tree_util.tree_leaves(restored.center),
                        jax.tree_util.tree_leaves(ps.center)):
            np.testing.assert_array_equal(a, b)


def test_ps_kill_restart_mid_training_byte_identical(tmp_path):
    """THE acceptance scenario: an externally managed PS is killed
    mid-training (snapshot_every=1) and warm-restarted on the same
    port; the single worker's resilient client rides its backoff
    through the outage, the commit-seq dedupe table proves at-most-once
    across the crash, and the final center is byte-identical to an
    uninterrupted run at the same commit schedule."""
    from distkeras_tpu.models import ModelSpec
    from distkeras_tpu.parallel.update_rules import DownpourRule

    model = ModelSpec.from_config(MLP).build()
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.float32))
    center = jax.tree_util.tree_map(np.asarray, variables["params"])
    kwargs = dict(fidelity="host", transport="socket", num_workers=1,
                  communication_window=2, batch_size=16, num_epoch=1,
                  learning_rate=0.01, worker_optimizer="adam",
                  worker_retries=12)

    # uninterrupted baseline against an external server
    ps_a = HostParameterServer(DownpourRule(), center)
    with PSServer(ps_a, center) as srv_a:
        base = DOWNPOUR(MLP, ps_address=srv_a.address, **kwargs)
        base.train(DATA, initial_variables=variables)
    n_rounds = len(base.history["round_loss"])
    assert ps_a.num_commits == n_rounds

    # the kill/restart run: same schedule, crash after the 5th commit
    snap = tmp_path / "ps.snap"
    ps_b = HostParameterServer(DownpourRule(), center,
                               snapshot_path=snap, snapshot_every=1)
    srv_b = PSServer(ps_b, center).start()
    port = srv_b.address[1]
    box = {}

    def killer():
        while srv_b.ps.num_commits < 5:
            time.sleep(0.002)
        srv_b.kill()  # listening socket AND live conns die mid-run
        # warm restart on the SAME port so the reconnecting client
        # finds it (bind may need a beat for the dead socket to clear)
        for _ in range(50):
            try:
                box["srv2"] = PSServer.restart_from(
                    snap, DownpourRule(), center, port=port)
                return
            except OSError:
                time.sleep(0.05)
        raise OSError(f"could not rebind port {port}")

    k = threading.Thread(target=killer)
    k.start()
    t = DOWNPOUR(MLP, ps_address=("127.0.0.1", port), **kwargs)
    t.train(DATA, initial_variables=variables)
    k.join()
    srv2 = box["srv2"]
    try:
        # the outage really happened and the client retried through it
        assert srv2.ps.num_commits > 5
        assert t.history.get("worker_round_retries"), (
            "the kill was invisible to the worker — test proved "
            "nothing")
        # at-most-once across the crash: total applied commits ==
        # rounds (the dedupe table absorbed any lost-ack retry)
        assert srv2.ps.num_commits == n_rounds
        # byte-identical center vs. the uninterrupted run
        for a, b in zip(jax.tree_util.tree_leaves(srv2.ps.center),
                        jax.tree_util.tree_leaves(ps_a.center)):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
                jax.tree_util.tree_leaves(base.trained_variables),
                jax.tree_util.tree_leaves(t.trained_variables)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))
    finally:
        srv2.stop()
